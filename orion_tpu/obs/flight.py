"""Flight recorder: bounded postmortem ring + auto-dump (ISSUE 9).

Every degradation the fault-tolerance layers (PR 6/7) can survive —
watchdog stall, consecutive step faults, NaN quarantine, speculation
auto-disable, training anomaly rollback — now ships a postmortem artifact:
a JSON dump of the fault-adjacent window of tracer spans, the recorder's
own engine-event ring (dispatch faults, fallbacks, preemptions, injected
faults), and a metrics snapshot, written to ``inference.flight_dir`` /
``train.flight_dir`` at the moment the trigger fires. The dump is what
``tools/obs_report.py`` renders into a terminal timeline.
"""

from __future__ import annotations

import json
import logging
import os
import time
from collections import deque
from typing import Any, Callable, Optional

from orion_tpu.obs.trace import (
    NULL_TRACER,
    NullTracer,
    Tracer,
    serialize_events,
)

log = logging.getLogger("orion_tpu.obs")


class FlightRecorder:
    """Bounded ring of engine events riding a (possibly shared) tracer.

    ``note(kind, **fields)`` appends to the event ring (cheap; called from
    fault paths only, never per token). ``dump(reason, **context)`` writes
    one self-contained JSON artifact:

      - ``reason`` / ``context``: why this dump exists (the trigger).
      - ``spans``: the tracer ring's recent window (``window_s`` seconds
        back from the dump — the fault-adjacent timeline).
      - ``events``: the recorder's own ring (faults, fallbacks, notes).
      - ``metrics``: the registry snapshot at dump time, when a
        ``snapshot`` callable was provided.

    Dumps are best-effort: a full disk must degrade the postmortem, never
    the serving/training process (callers catch OSError).
    """

    def __init__(
        self,
        tracer: Tracer | NullTracer,
        directory: str,
        capacity: int = 2048,
        window_s: float = 60.0,
        snapshot: Optional[Callable[[], dict]] = None,
        min_interval_s: float = 10.0,
        max_dumps: int = 256,
    ):
        self.tracer = tracer
        self.directory = directory
        self.window_s = window_s
        self._events: deque[dict] = deque(maxlen=capacity)
        self._snapshot = snapshot
        self.dumps: list[str] = []   # paths written, oldest first
        # Dump throttle: per-occurrence triggers (a watchdog stall fires
        # every stalled step of a persistently slow engine; one poisoned
        # step can quarantine N requests) must not turn a long incident
        # into an unbounded stream of multi-MB writes inside the step
        # loop. Repeats of a reason within min_interval_s are counted,
        # not written; max_dumps caps the recorder's lifetime disk use.
        self.min_interval_s = min_interval_s
        self.max_dumps = max_dumps
        self.throttled = 0           # dumps suppressed by the throttle
        self._last_dump: dict[str, float] = {}   # reason -> monotonic t

    def note(self, kind: str, **fields) -> None:
        """Record one engine event in the postmortem ring (and as a tracer
        instant, so it also lands in the Chrome timeline)."""
        self._events.append(
            {"t": time.monotonic(), "kind": kind, **fields}
        )
        self.tracer.instant(kind, **fields)

    def dump(self, reason: str, **context) -> Optional[str]:
        """Write the postmortem artifact; returns its path, or None when
        the throttle suppressed it (same reason within ``min_interval_s``,
        or ``max_dumps`` lifetime cap reached — suppressions are counted
        in ``throttled``). File names carry the reason and a nanosecond
        stamp, so repeated triggers in one process never clobber each
        other."""
        now = time.monotonic()
        last = self._last_dump.get(reason)
        if (last is not None and now - last < self.min_interval_s) \
                or len(self.dumps) >= self.max_dumps:
            self.throttled += 1
            return None
        self._last_dump[reason] = now
        os.makedirs(self.directory, exist_ok=True)
        spans = serialize_events([
            e for e in self.tracer.events()
            if e[3] >= now - self.window_s
        ])
        doc: dict[str, Any] = {
            "reason": reason,
            "wall_time": time.strftime("%Y-%m-%dT%H:%M:%S%z"),
            "t_dump": now,
            "window_s": self.window_s,
            "context": context,
            "events": list(self._events),
            "spans": spans,
        }
        if self._snapshot is not None:
            try:
                doc["metrics"] = self._snapshot()
            except Exception as e:   # a metrics read must never kill a dump
                doc["metrics"] = {"error": f"{type(e).__name__}: {e}"}
        path = os.path.join(
            self.directory, f"flight_{reason}_{time.time_ns()}.json"
        )
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            # default=str: a non-primitive tag/metric value (np scalars
            # from user-registered providers) must degrade to its repr,
            # never TypeError out of a postmortem write.
            json.dump(doc, f, default=str)
        os.replace(tmp, path)
        self.dumps.append(path)
        log.error("flight recorder: %s -> %s", reason, path)
        return path

    def try_dump(self, reason: str, **context) -> Optional[str]:
        """``dump`` with the degradation contract applied: ANY failure to
        write the artifact (full disk, permissions, a pathological value)
        is logged and swallowed — the engine/trainer the recorder is
        observing must never die of its own postmortem."""
        try:
            return self.dump(reason, **context)
        except Exception as e:
            log.error("flight recorder dump failed (%s): %s", reason, e)
            return None


def init_obs(
    *,
    trace: bool,
    trace_ring: int,
    flight_dir: Optional[str],
    trace_path: Optional[str] = None,
    snapshot: Optional[Callable[[], dict]] = None,
    injector: Optional[Any] = None,
):
    """The ONE obs wiring both the engine and the trainer share: build the
    tracer (NULL only when NOTHING asks for recording — a configured
    ``trace_path`` or ``flight_dir`` implies recording even with the
    ``trace`` flag off, since an export/dump needs a ring to read; a bare
    trace_path silently producing no file would be a foot-gun), the
    flight recorder, and hook a FaultInjector's ``on_fire`` observer so
    injected faults land in the postmortem ring. Returns
    ``(tracer, flight_or_None)``."""
    obs_on = trace or trace_path is not None or flight_dir is not None
    tracer = Tracer(capacity=trace_ring) if obs_on else NULL_TRACER
    flight = None
    if flight_dir is not None:
        flight = FlightRecorder(tracer, flight_dir, snapshot=snapshot)
        if injector is not None and injector.on_fire is None:
            injector.on_fire = (
                lambda kind, step, path, fl=flight: fl.note(
                    "injected_fault", fault=kind, step=step,
                    path=path or "",
                )
            )
    return tracer, flight
