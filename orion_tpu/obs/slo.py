"""SLO monitor: per-priority-class latency objectives + windowed burn
rate (ISSUE 14 tentpole part 4).

PRs 6/8 landed the *mechanics* of multi-tenant serving — priorities,
deadlines, per-class TTFT/ITL percentiles — but nothing ever JUDGED the
latency: the fleet measured per-class p99s and drew no conclusion. This
module closes that loop with the standard SRE construction:

  - an **objective** is "fraction ``goal`` of class-``cls`` requests must
    see ``metric`` (ttft | itl) <= ``target_s``" (``SLOConfig``:
    fleet-wide ``slo.ttft_ms``/``slo.itl_ms`` defaults plus per-class
    overrides via ``slo.per_class``);
  - observations accumulate in per-(metric, class) ``LatencyStats``
    collectors (the PR 8 percentile machinery, reused — not a parallel
    histogram implementation) over a rolling window of ``slo.window_s``;
  - at each window close the **burn rate** is computed per objective:
    ``(violating fraction) / (1 - goal)`` — 1.0 means the error budget is
    burning exactly at the allowed rate, 2.0 means twice as fast; a
    window whose burn exceeds ``slo.burn_threshold`` (with at least
    ``slo.min_events`` observations — an EMPTY class window says nothing
    and must never breach) is a typed **``slo_breach``**.

The monitor is deliberately passive: ``observe()`` + ``sweep()`` are
driven by whoever owns the serving loop (the Router, today), breaches
surface through the ``on_breach`` callback (the router turns them into
tracer instants, flight-recorder notes + dumps, and a RouterStats
counter), and ``metrics()`` is a registry provider (the ``slo`` section:
per-objective burn gauges + last-window per-class percentiles — the
fleet's merged per-class latency surface).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Callable, Optional

from orion_tpu.metrics import LatencyStats

METRICS = ("ttft", "itl")


@dataclass(frozen=True)
class SLOObjective:
    """One judged objective. ``cls`` is a priority class, or None for the
    fleet-wide objective (every class counts toward it)."""

    metric: str                 # "ttft" | "itl"
    target_s: float             # latency objective, seconds
    cls: Optional[int] = None   # priority class; None = all classes
    goal: float = 0.99          # fraction that must meet target_s

    @property
    def key(self) -> str:
        """Identifier-shaped gauge suffix: ``ttft_all`` / ``itl_c2`` /
        ``ttft_cneg1`` (negative classes spell the sign out — registry
        keys stay Prometheus-sanitizable)."""
        if self.cls is None:
            tag = "all"
        elif self.cls < 0:
            tag = f"cneg{-self.cls}"
        else:
            tag = f"c{self.cls}"
        return f"{self.metric}_{tag}"


def build_objectives(slo_cfg) -> list[SLOObjective]:
    """SLOConfig -> objectives: the fleet-wide ttft_ms/itl_ms defaults
    plus per-class overrides from the ``slo.per_class`` spec (parsed by
    ``config.parse_per_class``; validated at config construction)."""
    from orion_tpu.config import parse_per_class

    out: list[SLOObjective] = []
    if slo_cfg.ttft_ms is not None:
        out.append(SLOObjective(
            "ttft", slo_cfg.ttft_ms / 1e3, goal=slo_cfg.goal,
        ))
    if slo_cfg.itl_ms is not None:
        out.append(SLOObjective(
            "itl", slo_cfg.itl_ms / 1e3, goal=slo_cfg.goal,
        ))
    for cls, targets in parse_per_class(slo_cfg.per_class).items():
        for metric, target_ms in targets.items():
            out.append(SLOObjective(
                metric, target_ms / 1e3, cls=cls, goal=slo_cfg.goal,
            ))
    return out


class SLOMonitor:
    """Windowed burn-rate monitor over a set of objectives.

    ``observe(metric, cls, seconds)`` records one event into the current
    window's per-(metric, class) ``LatencyStats``; ``sweep(now)`` closes
    the window once ``window_s`` has elapsed, judges every objective, and
    returns the breaches (also delivered to ``on_breach``, one call per
    breach). All host-side and allocation-light: the serving loop calls
    observe() per emitted token at most, sweep() per step.
    """

    def __init__(
        self,
        objectives: list[SLOObjective],
        window_s: float = 5.0,
        burn_threshold: float = 1.0,
        min_events: int = 1,
        on_breach: Optional[Callable[[dict], None]] = None,
    ):
        self.objectives = list(objectives)
        self.window_s = window_s
        self.burn_threshold = burn_threshold
        self.min_events = min_events
        self.on_breach = on_breach
        self.breaches = 0           # lifetime breach count (gauge)
        self.windows = 0            # windows judged
        self._window_start: Optional[float] = None
        # (metric, cls) -> LatencyStats for the CURRENT window.
        self._window: dict[tuple[str, int], LatencyStats] = {}
        # objective.key -> burn rate of the last JUDGED window (with
        # >= min_events observations; unjudged windows keep the previous
        # value so the gauge never flaps to zero on an idle lull).
        self.last_burn: dict[str, float] = {
            o.key: 0.0 for o in self.objectives
        }
        self._last_window: dict[str, dict[str, float]] = {}

    @classmethod
    def from_config(cls, slo_cfg, on_breach=None) -> Optional["SLOMonitor"]:
        """Build from a ``config.SLOConfig``; None when no objective is
        configured (the monitor then costs nothing — callers hold None
        and skip the observe/sweep calls entirely)."""
        objectives = build_objectives(slo_cfg)
        if not objectives:
            return None
        return cls(
            objectives,
            window_s=slo_cfg.window_s,
            burn_threshold=slo_cfg.burn_threshold,
            min_events=slo_cfg.min_events,
            on_breach=on_breach,
        )

    def observe(self, metric: str, cls: int, seconds: float,
                now: float) -> None:
        """Record one latency event (``metric`` in {"ttft", "itl"}) for
        priority class ``cls`` at monotonic time ``now``. The first
        observation opens the window."""
        if self._window_start is None:
            self._window_start = now
        st = self._window.get((metric, cls))
        if st is None:
            st = self._window[(metric, cls)] = LatencyStats()
        st.record(seconds)

    def sweep(self, now: float, force: bool = False) -> list[dict]:
        """Close + judge the window when ``window_s`` has elapsed since
        it opened; returns the breach records (possibly empty). A window
        with no observations never opens (``_window_start`` stays None),
        so an idle fleet is never judged against a zero-event window.
        ``force`` judges a still-open window immediately — the shutdown
        path's final sweep, so a serve shorter than ``window_s`` still
        gets one verdict (burn is fraction-based, so a partial window's
        math is unchanged)."""
        if self._window_start is None or (
            not force and now - self._window_start < self.window_s
        ):
            return []
        window, self._window = self._window, {}
        start, self._window_start = self._window_start, None
        self.windows += 1
        self._last_window = self._summarize(window)
        breaches: list[dict] = []
        for obj in self.objectives:
            if obj.cls is None:
                stats = [
                    st for (m, _c), st in window.items() if m == obj.metric
                ]
            else:
                st = window.get((obj.metric, obj.cls))
                stats = [st] if st is not None else []
            samples = [s for st in stats for s in st.samples]
            total = len(samples)
            if total < self.min_events:
                # Empty-class (or too-thin) window: no evidence, no
                # verdict — the burn gauge keeps its last judged value.
                continue
            bad = sum(1 for s in samples if s > obj.target_s)
            budget = max(1.0 - obj.goal, 1e-9)
            burn = (bad / total) / budget
            self.last_burn[obj.key] = burn
            if burn > self.burn_threshold:
                self.breaches += 1
                breach = {
                    "objective": obj.key,
                    "metric": obj.metric,
                    "cls": obj.cls,
                    "target_ms": round(obj.target_s * 1e3, 3),
                    "goal": obj.goal,
                    "burn": round(burn, 3),
                    "events": total,
                    "violations": bad,
                    "window_s": round(now - start, 3),
                    "worst_ms": round(max(samples) * 1e3, 3),
                }
                breaches.append(breach)
                if self.on_breach is not None:
                    self.on_breach(breach)
        return breaches

    @staticmethod
    def _summarize(window) -> dict[str, dict[str, float]]:
        """Per-(metric, class) percentile summary of a closed window —
        the fleet's merged per-class latency, exposed as gauges."""
        out: dict[str, dict[str, float]] = {}
        for (metric, cls), st in window.items():
            tag = f"cneg{-cls}" if cls < 0 else f"c{cls}"
            s = st.summary()
            out[f"{metric}_{tag}"] = {
                "count": s["count"],
                "p50_ms": round(s["p50"] * 1e3, 3),
                "p95_ms": round(s["p95"] * 1e3, 3),
                "p99_ms": round(s["p99"] * 1e3, 3),
            }
        return out

    def metrics(self) -> dict:
        """Registry provider (the ``slo`` section): lifetime breach and
        window counters, per-objective burn gauges from the last judged
        window, and the last window's per-class percentiles."""
        out: dict = {
            "breaches": self.breaches,
            "windows": self.windows,
            "objectives": len(self.objectives),
        }
        for key, burn in self.last_burn.items():
            out[f"burn_{key}"] = round(burn, 4)
        for key, summ in self._last_window.items():
            for k, v in summ.items():
                out[f"{key}_{k}"] = v
        return out
