"""Observability: span tracing, flight recording, metrics registry, SLO
burn-rate monitoring.

The serving engine, the trainer, and the multi-replica router all thread
through this package (ISSUEs 9 + 14): ``Tracer`` is the host-side
span/event ring (Chrome trace-event export with overflow accounting,
``jax.profiler`` annotation passthrough for device-profile alignment;
``merge_chrome`` merges the router's ring plus N replica rings into one
Perfetto timeline on a shared clock), ``FlightRecorder`` the bounded
postmortem ring that auto-dumps on degradation triggers,
``MetricsRegistry`` the named-snapshot surface unifying the
per-subsystem Stats dataclasses (metrics.py) with pool occupancy and
live-HBM gauges, exportable as Prometheus textfiles and JSONL time
series, and ``SLOMonitor`` (obs/slo.py) the per-priority-class TTFT/ITL
objective judge emitting typed ``slo_breach`` events off windowed burn
rates.
"""

from orion_tpu.obs.flight import FlightRecorder, init_obs
from orion_tpu.obs.registry import (
    MetricsRegistry,
    bench_metrics_block,
    live_hbm_metrics,
)
from orion_tpu.obs.slo import SLOMonitor, SLOObjective, build_objectives
from orion_tpu.obs.trace import (
    NULL_TRACER,
    NullTracer,
    Tracer,
    export_chrome_safe,
    merge_chrome,
    merge_chrome_safe,
    namespaced_path,
)

__all__ = [
    "FlightRecorder",
    "MetricsRegistry",
    "NULL_TRACER",
    "NullTracer",
    "SLOMonitor",
    "SLOObjective",
    "Tracer",
    "bench_metrics_block",
    "build_objectives",
    "export_chrome_safe",
    "init_obs",
    "live_hbm_metrics",
    "merge_chrome",
    "merge_chrome_safe",
    "namespaced_path",
]
