"""Observability: span tracing, flight recording, metrics registry.

The serving engine and the trainer both thread through this package
(ISSUE 9): ``Tracer`` is the host-side span/event ring (Chrome trace-event
export, ``jax.profiler`` annotation passthrough for device-profile
alignment), ``FlightRecorder`` the bounded postmortem ring that auto-dumps
on degradation triggers, and ``MetricsRegistry`` the named-snapshot surface
unifying the per-subsystem Stats dataclasses (metrics.py) with pool
occupancy and live-HBM gauges, exportable as Prometheus textfiles and
JSONL time series.
"""

from orion_tpu.obs.flight import FlightRecorder, init_obs
from orion_tpu.obs.registry import (
    MetricsRegistry,
    bench_metrics_block,
    live_hbm_metrics,
)
from orion_tpu.obs.trace import (
    NULL_TRACER,
    NullTracer,
    Tracer,
    export_chrome_safe,
)

__all__ = [
    "FlightRecorder",
    "MetricsRegistry",
    "NULL_TRACER",
    "NullTracer",
    "Tracer",
    "bench_metrics_block",
    "export_chrome_safe",
    "init_obs",
    "live_hbm_metrics",
]
