"""Low-overhead host-side span tracer (ISSUE 9 tentpole).

A monotonic-clock ring buffer of spans and instant events. Design
constraints, in order:

  1. **~Zero cost when disabled.** Callers hold a ``NULL_TRACER`` whose
     every method is a no-op returning a shared null context manager — no
     clock reads, no allocation, no branch beyond the attribute lookup.
     Compiled programs are never touched in either mode: the tracer is
     pure host-side bookkeeping around dispatches, not inside them.
  2. **Bounded.** The ring is a ``deque(maxlen=capacity)``; a serving
     engine that runs for a week holds the most recent ``capacity``
     events, which is exactly what the flight recorder wants to dump when
     something degrades.
  3. **Profiler-aligned.** ``annotation()`` / ``step_annotation()`` wrap
     ``jax.profiler.TraceAnnotation`` / ``StepTraceAnnotation`` so host
     spans emitted around device dispatches land in the SAME xprof
     timeline as the device trace captured by ``train.profile_steps`` —
     line the Chrome export up with the device profile by name.

Export is Chrome trace-event JSON (``export_chrome``), loadable in
Perfetto / ``chrome://tracing``; timestamps are microseconds relative to
tracer construction.
"""

from __future__ import annotations

import json
import time
from collections import deque
from typing import Any, Optional

import jax

# Event tuples in the ring: (kind, name, t_start, t_end, tags) with kind
# "span" (t_end > t_start) or "instant" (t_end == t_start). Times are
# time.monotonic() seconds — wall-clock jumps (NTP) must never produce
# negative spans in a postmortem artifact.
Event = tuple[str, str, float, float, dict]


class _NullCtx:
    """Shared reusable no-op context manager (the disabled-tracer span)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_CTX = _NullCtx()


class NullTracer:
    """The disabled tracer: every call is a no-op. One shared instance
    (``NULL_TRACER``) serves every disabled engine/trainer, so the
    tracing-off host path is today's code plus one attribute lookup and
    a no-op ``with`` per dispatch."""

    enabled = False

    def span(self, name: str, annotate: bool = False, **tags) -> _NullCtx:
        return _NULL_CTX

    def instant(self, name: str, **tags) -> None:
        return None

    def record_span(self, name: str, t_start: float, t_end: float,
                    **tags) -> None:
        return None

    def annotation(self, name: str) -> _NullCtx:
        return _NULL_CTX

    def step_annotation(self, name: str, step: int) -> _NullCtx:
        return _NULL_CTX

    def events(self) -> list[Event]:
        return []

    def export_chrome(self, path: str) -> int:
        return 0

    def clear(self) -> None:
        return None


NULL_TRACER = NullTracer()


class _Span:
    """One live span: context manager that stamps monotonic start/end and
    appends to the owning tracer's ring on exit (exit always records —
    a span interrupted by an exception is exactly the span a postmortem
    wants to see)."""

    __slots__ = ("_tracer", "name", "tags", "t0", "t1", "_ann")

    def __init__(self, tracer: "Tracer", name: str, annotate: bool,
                 tags: dict):
        self._tracer = tracer
        self.name = name
        self.tags = tags
        self.t0 = 0.0
        self.t1 = 0.0
        self._ann = (
            jax.profiler.TraceAnnotation(name) if annotate else None
        )

    def __enter__(self) -> "_Span":
        if self._ann is not None:
            self._ann.__enter__()
        self.t0 = time.monotonic()
        return self

    def __exit__(self, *exc) -> bool:
        self.t1 = time.monotonic()
        if self._ann is not None:
            self._ann.__exit__(*exc)
        self._tracer._ring.append(
            ("span", self.name, self.t0, self.t1, self.tags)
        )
        return False

    @property
    def elapsed(self) -> float:
        return self.t1 - self.t0


class Tracer:
    """The enabled tracer: bounded ring of spans + instants.

    Thread-notes: ``deque.append`` is atomic under the GIL and the
    watchdog/async-checkpoint threads only ever ``instant()``, so no lock
    is needed on the hot path; ``events()`` snapshots with ``list()``.
    """

    enabled = True

    def __init__(self, capacity: int = 16384):
        if capacity < 1:
            raise ValueError(f"tracer capacity={capacity} must be >= 1")
        self.capacity = capacity
        self._ring: deque[Event] = deque(maxlen=capacity)
        self.t0 = time.monotonic()

    # -- recording ---------------------------------------------------------

    def span(self, name: str, annotate: bool = False, **tags) -> _Span:
        """Context manager recording a [enter, exit) span. With
        ``annotate``, also enters a ``jax.profiler.TraceAnnotation`` of
        the same name so the span shows up in a concurrently-captured
        device profile (train.profile_steps window)."""
        return _Span(self, name, annotate, tags)

    def instant(self, name: str, **tags) -> None:
        t = time.monotonic()
        self._ring.append(("instant", name, t, t, tags))

    def record_span(self, name: str, t_start: float, t_end: float,
                    **tags) -> None:
        """Append an already-measured span (times on the time.monotonic
        clock) — for call sites that cannot wrap their body in a ``with``
        without restructuring (e.g. the engine's whole-step span)."""
        self._ring.append(("span", name, t_start, t_end, tags))

    def annotation(self, name: str):
        """Bare ``jax.profiler.TraceAnnotation`` context (device-profile
        alignment only; records nothing in the host ring)."""
        return jax.profiler.TraceAnnotation(name)

    def step_annotation(self, name: str, step: int):
        """``jax.profiler.StepTraceAnnotation`` context: marks a train
        step boundary in the device profile, so xprof's step view lines
        up with the host spans recorded around the same dispatch."""
        return jax.profiler.StepTraceAnnotation(name, step_num=step)

    # -- reading / export --------------------------------------------------

    def events(self) -> list[Event]:
        """Snapshot of the ring, oldest first."""
        return list(self._ring)

    def clear(self) -> None:
        self._ring.clear()

    def export_chrome(self, path: str) -> int:
        """Write the ring as Chrome trace-event JSON (Perfetto /
        chrome://tracing loadable); returns the number of events written.
        Spans are "X" (complete) events, instants "i"; ``ts``/``dur`` are
        microseconds relative to tracer construction; tags ride ``args``.
        """
        evs: list[dict[str, Any]] = [
            {"name": "process_name", "ph": "M", "pid": 0, "tid": 0,
             "args": {"name": "orion-tpu host"}},
        ]
        base = self.t0
        for kind, name, t_start, t_end, tags in self.events():
            ev: dict[str, Any] = {
                "name": name,
                "ts": (t_start - base) * 1e6,
                "pid": 0,
                "tid": 0,
                "args": dict(tags),
            }
            if kind == "span":
                ev["ph"] = "X"
                ev["dur"] = (t_end - t_start) * 1e6
            else:
                ev["ph"] = "i"
                ev["s"] = "t"
            evs.append(ev)
        # tmp + atomic rename, like every other obs artifact writer: a
        # poller watching trace_path (or a mid-write crash) must never see
        # a torn multi-MB JSON. default=str: a non-primitive tag value
        # degrades to its repr, never TypeErrors a shutdown-path export.
        import os

        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(
                {"traceEvents": evs, "displayTimeUnit": "ms"}, f,
                default=str,
            )
        os.replace(tmp, path)
        return len(evs) - 1  # metadata event excluded


def export_chrome_safe(tracer, path: Optional[str]) -> int:
    """Chrome export with the shared error contract (engine.close and
    Trainer.fit both end with this): no-op when tracing is off or no path
    is configured, and an export failure is logged, never raised — a full
    disk must not fail a clean shutdown. Returns events written."""
    import logging

    log = logging.getLogger("orion_tpu.obs")
    if not path or not tracer.enabled:
        return 0
    try:
        n = tracer.export_chrome(path)
        log.info("exported %d trace events to %s (load in Perfetto)",
                 n, path)
        return n
    except OSError as e:
        log.error("trace export to %s failed: %s", path, e)
        return 0


def serialize_events(events: list[Event]) -> list[dict[str, Any]]:
    """Ring events as JSON-ready dicts (the flight-recorder dump format;
    times stay monotonic seconds so dump consumers can window on them)."""
    return [
        {"kind": kind, "name": name, "t_start": t_start, "t_end": t_end,
         "dur_ms": (t_end - t_start) * 1e3, **({"tags": tags} if tags else {})}
        for kind, name, t_start, t_end, tags in events
    ]
