"""Low-overhead host-side span tracer (ISSUE 9 tentpole).

A monotonic-clock ring buffer of spans and instant events. Design
constraints, in order:

  1. **~Zero cost when disabled.** Callers hold a ``NULL_TRACER`` whose
     every method is a no-op returning a shared null context manager — no
     clock reads, no allocation, no branch beyond the attribute lookup.
     Compiled programs are never touched in either mode: the tracer is
     pure host-side bookkeeping around dispatches, not inside them.
  2. **Bounded.** The ring is a ``deque(maxlen=capacity)``; a serving
     engine that runs for a week holds the most recent ``capacity``
     events, which is exactly what the flight recorder wants to dump when
     something degrades.
  3. **Profiler-aligned.** ``annotation()`` / ``step_annotation()`` wrap
     ``jax.profiler.TraceAnnotation`` / ``StepTraceAnnotation`` so host
     spans emitted around device dispatches land in the SAME xprof
     timeline as the device trace captured by ``train.profile_steps`` —
     line the Chrome export up with the device profile by name.

Export is Chrome trace-event JSON (``export_chrome``), loadable in
Perfetto / ``chrome://tracing``; timestamps are microseconds relative to
tracer construction. ``merge_chrome`` (ISSUE 14) merges N tracers —
the router's plus one per replica engine — into ONE timeline on a
shared clock: ring events carry absolute ``time.monotonic()`` stamps,
so reconciling per-tracer construction offsets is a single re-base
against the earliest tracer, and each source becomes its own Perfetto
process (``pid`` + ``process_name`` metadata).
"""

from __future__ import annotations

import json
import time
from collections import deque
from typing import Any, Optional

import jax

# Event tuples in the ring: (kind, name, t_start, t_end, tags) with kind
# "span" (t_end > t_start) or "instant" (t_end == t_start). Times are
# time.monotonic() seconds — wall-clock jumps (NTP) must never produce
# negative spans in a postmortem artifact.
Event = tuple[str, str, float, float, dict]


class _NullCtx:
    """Shared reusable no-op context manager (the disabled-tracer span)."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_CTX = _NullCtx()


class NullTracer:
    """The disabled tracer: every call is a no-op. One shared instance
    (``NULL_TRACER``) serves every disabled engine/trainer, so the
    tracing-off host path is today's code plus one attribute lookup and
    a no-op ``with`` per dispatch."""

    enabled = False
    dropped = 0
    capacity = 0

    def span(self, name: str, annotate: bool = False, **tags) -> _NullCtx:
        return _NULL_CTX

    def instant(self, name: str, **tags) -> None:
        return None

    def record_span(self, name: str, t_start: float, t_end: float,
                    **tags) -> None:
        return None

    def annotation(self, name: str) -> _NullCtx:
        return _NULL_CTX

    def step_annotation(self, name: str, step: int) -> _NullCtx:
        return _NULL_CTX

    def events(self) -> list[Event]:
        return []

    def export_chrome(self, path: str) -> int:
        return 0

    def clear(self) -> None:
        return None


NULL_TRACER = NullTracer()


class _Span:
    """One live span: context manager that stamps monotonic start/end and
    appends to the owning tracer's ring on exit (exit always records —
    a span interrupted by an exception is exactly the span a postmortem
    wants to see)."""

    __slots__ = ("_tracer", "name", "tags", "t0", "t1", "_ann")

    def __init__(self, tracer: "Tracer", name: str, annotate: bool,
                 tags: dict):
        self._tracer = tracer
        self.name = name
        self.tags = tags
        self.t0 = 0.0
        self.t1 = 0.0
        self._ann = (
            jax.profiler.TraceAnnotation(name) if annotate else None
        )

    def __enter__(self) -> "_Span":
        if self._ann is not None:
            self._ann.__enter__()
        self.t0 = time.monotonic()
        return self

    def __exit__(self, *exc) -> bool:
        self.t1 = time.monotonic()
        if self._ann is not None:
            self._ann.__exit__(*exc)
        self._tracer._append(
            ("span", self.name, self.t0, self.t1, self.tags)
        )
        return False

    @property
    def elapsed(self) -> float:
        return self.t1 - self.t0


class Tracer:
    """The enabled tracer: bounded ring of spans + instants.

    Thread-notes: ``deque.append`` is atomic under the GIL and the
    watchdog/async-checkpoint threads only ever ``instant()``, so no lock
    is needed on the hot path; ``events()`` snapshots with ``list()``.
    The ``dropped`` overflow counter's check-then-append pair is not
    atomic, so concurrent appends at the ring boundary can undercount by
    a few — acceptable for a truncation FLAG (zero stays exactly zero:
    no append ever drops before the ring is full).
    """

    enabled = True

    def __init__(self, capacity: int = 16384):
        if capacity < 1:
            raise ValueError(f"tracer capacity={capacity} must be >= 1")
        self.capacity = capacity
        self._ring: deque[Event] = deque(maxlen=capacity)
        self.t0 = time.monotonic()
        # Ring-overflow accounting (ISSUE 14 satellite): a deque(maxlen)
        # silently evicts the oldest event on overflow, which means a
        # long run's export is a TRUNCATED timeline — count evictions so
        # the registry can gauge it and obs_report can flag the export
        # instead of rendering a hole as if nothing happened.
        self.dropped = 0

    # -- recording ---------------------------------------------------------

    def _append(self, event: Event) -> None:
        if len(self._ring) == self.capacity:
            self.dropped += 1
        self._ring.append(event)

    def span(self, name: str, annotate: bool = False, **tags) -> _Span:
        """Context manager recording a [enter, exit) span. With
        ``annotate``, also enters a ``jax.profiler.TraceAnnotation`` of
        the same name so the span shows up in a concurrently-captured
        device profile (train.profile_steps window)."""
        return _Span(self, name, annotate, tags)

    def instant(self, name: str, **tags) -> None:
        t = time.monotonic()
        self._append(("instant", name, t, t, tags))

    def record_span(self, name: str, t_start: float, t_end: float,
                    **tags) -> None:
        """Append an already-measured span (times on the time.monotonic
        clock) — for call sites that cannot wrap their body in a ``with``
        without restructuring (e.g. the engine's whole-step span)."""
        self._append(("span", name, t_start, t_end, tags))

    def annotation(self, name: str):
        """Bare ``jax.profiler.TraceAnnotation`` context (device-profile
        alignment only; records nothing in the host ring)."""
        return jax.profiler.TraceAnnotation(name)

    def step_annotation(self, name: str, step: int):
        """``jax.profiler.StepTraceAnnotation`` context: marks a train
        step boundary in the device profile, so xprof's step view lines
        up with the host spans recorded around the same dispatch."""
        return jax.profiler.StepTraceAnnotation(name, step_num=step)

    # -- reading / export --------------------------------------------------

    def events(self) -> list[Event]:
        """Snapshot of the ring, oldest first."""
        return list(self._ring)

    def clear(self) -> None:
        self._ring.clear()
        self.dropped = 0

    def metrics(self) -> dict[str, int]:
        """Ring gauges for the metrics registry ("trace" section): event
        count, capacity and the overflow-drop counter — a nonzero
        ``dropped`` means any export from this ring is a truncated
        timeline."""
        return {
            "events": len(self._ring),
            "capacity": self.capacity,
            "dropped": self.dropped,
        }

    def export_chrome(self, path: str) -> int:
        """Write the ring as Chrome trace-event JSON (Perfetto /
        chrome://tracing loadable); returns the number of events written.
        Spans are "X" (complete) events, instants "i"; ``ts``/``dur`` are
        microseconds relative to tracer construction; tags ride ``args``.
        The top-level ``metadata`` block carries the monotonic clock base
        (so merged/compared exports can reconcile offsets) and the
        ring-overflow drop count (so consumers can flag truncation).
        """
        evs: list[dict[str, Any]] = [
            {"name": "process_name", "ph": "M", "pid": 0, "tid": 0,
             "args": {"name": "orion-tpu host"}},
        ]
        evs.extend(_chrome_events(self.events(), self.t0, pid=0))
        meta = {
            "clock_base_monotonic_s": self.t0,
            "dropped_events": self.dropped,
            "ring_capacity": self.capacity,
        }
        _write_chrome(path, evs, meta)
        return len(evs) - 1  # metadata event excluded


def _chrome_events(
    events: list[Event], base: float, pid: int
) -> list[dict[str, Any]]:
    """Ring events as Chrome trace-event dicts: ``ts``/``dur`` in
    microseconds re-based against ``base`` (a monotonic-clock origin),
    under process id ``pid``. Shared by the single-tracer export and the
    multi-source merge, so both emit identical event shapes."""
    out: list[dict[str, Any]] = []
    for kind, name, t_start, t_end, tags in events:
        ev: dict[str, Any] = {
            "name": name,
            "ts": (t_start - base) * 1e6,
            "pid": pid,
            "tid": 0,
            "args": dict(tags),
        }
        if kind == "span":
            ev["ph"] = "X"
            ev["dur"] = (t_end - t_start) * 1e6
        else:
            ev["ph"] = "i"
            ev["s"] = "t"
        out.append(ev)
    return out


def _write_chrome(path: str, evs: list, meta: dict) -> None:
    # tmp + atomic rename, like every other obs artifact writer: a
    # poller watching trace_path (or a mid-write crash) must never see
    # a torn multi-MB JSON. default=str: a non-primitive tag value
    # degrades to its repr, never TypeErrors a shutdown-path export.
    import os

    tmp = path + ".tmp"
    with open(tmp, "w") as f:
        json.dump(
            {"traceEvents": evs, "displayTimeUnit": "ms",
             "metadata": meta},
            f, default=str,
        )
    os.replace(tmp, path)


def merge_chrome(
    path: str, sources: list[tuple[str, Any]]
) -> int:
    """Merge N tracers into ONE Perfetto timeline (ISSUE 14 tentpole):
    ``sources`` is ``[(name, tracer)]`` — e.g. the router's tracer plus
    one per replica engine. Each source becomes its own Perfetto process
    (``pid`` = source index, ``process_name``/``thread_name`` metadata =
    the source name); every event is re-based onto the SHARED clock (the
    earliest tracer's construction origin — ring events carry absolute
    ``time.monotonic()`` stamps, so per-tracer offsets reconcile by
    subtraction, no cross-process clock sync needed for in-process
    replicas). Disabled (Null) tracers contribute an empty process, so
    the process list always names the whole fleet. Returns the number of
    events written (metadata rows excluded); the top-level ``metadata``
    block carries per-process event/drop counts so a truncated replica
    ring is visible in the artifact itself."""
    enabled = [tr for _, tr in sources if tr.enabled]
    base = min((tr.t0 for tr in enabled), default=0.0)
    evs: list[dict[str, Any]] = []
    procs: dict[str, Any] = {}
    total = 0
    for pid, (name, tr) in enumerate(sources):
        evs.append({"name": "process_name", "ph": "M", "pid": pid,
                    "tid": 0, "args": {"name": name}})
        evs.append({"name": "thread_name", "ph": "M", "pid": pid,
                    "tid": 0, "args": {"name": name}})
        rows = _chrome_events(tr.events(), base, pid=pid)
        evs.extend(rows)
        total += len(rows)
        procs[name] = {
            "pid": pid,
            "events": len(rows),
            "dropped": tr.dropped,
            "clock_offset_us": (
                (tr.t0 - base) * 1e6 if tr.enabled else None
            ),
        }
    meta = {
        "merged": True,
        "clock_base_monotonic_s": base,
        "dropped_events": sum(tr.dropped for _, tr in sources),
        "processes": procs,
    }
    _write_chrome(path, evs, meta)
    return total


def merge_chrome_safe(
    path: Optional[str], sources: list[tuple[str, Any]]
) -> int:
    """``merge_chrome`` under the shared shutdown-path error contract
    (the fleet analog of ``export_chrome_safe``): no-op when no path is
    configured or every source is disabled; a write failure is logged,
    never raised. Returns events written."""
    import logging

    log = logging.getLogger("orion_tpu.obs")
    if not path or not any(tr.enabled for _, tr in sources):
        return 0
    try:
        n = merge_chrome(path, sources)
        log.info(
            "merged %d trace events from %d processes to %s "
            "(load in Perfetto)", n, len(sources), path,
        )
        return n
    except OSError as e:
        log.error("merged trace export to %s failed: %s", path, e)
        return 0


def namespaced_path(path: str, tag: str) -> str:
    """Per-replica sink path: insert ``tag`` before the extension —
    ``("/tmp/trace.json", "replica-0")`` -> ``/tmp/trace.replica-0.json``
    — so N replicas exporting the "same" configured target never clobber
    one file (ISSUE 14; PR 11 stripped replica targets instead)."""
    import os

    root, ext = os.path.splitext(path)
    return f"{root}.{tag}{ext}" if ext else f"{path}.{tag}"


def export_chrome_safe(tracer, path: Optional[str]) -> int:
    """Chrome export with the shared error contract (engine.close and
    Trainer.fit both end with this): no-op when tracing is off or no path
    is configured, and an export failure is logged, never raised — a full
    disk must not fail a clean shutdown. Returns events written."""
    import logging

    log = logging.getLogger("orion_tpu.obs")
    if not path or not tracer.enabled:
        return 0
    try:
        n = tracer.export_chrome(path)
        log.info("exported %d trace events to %s (load in Perfetto)",
                 n, path)
        return n
    except OSError as e:
        log.error("trace export to %s failed: %s", path, e)
        return 0


def serialize_events(events: list[Event]) -> list[dict[str, Any]]:
    """Ring events as JSON-ready dicts (the flight-recorder dump format;
    times stay monotonic seconds so dump consumers can window on them)."""
    return [
        {"kind": kind, "name": name, "t_start": t_start, "t_end": t_end,
         "dur_ms": (t_end - t_start) * 1e3, **({"tags": tags} if tags else {})}
        for kind, name, t_start, t_end, tags in events
    ]
