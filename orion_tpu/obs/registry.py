"""Named-snapshot metrics registry + Prometheus/JSONL exporters (ISSUE 9).

Before this module, serving/training counters lived in seven ad-hoc Stats
dataclasses (metrics.py) drained through ``reset_timing`` / MetricsLogger
extras, with no export surface and no gauges (pool occupancy, live HBM).
The registry unifies them behind one API:

    reg = MetricsRegistry()
    reg.register("prefix", lambda: engine.prefix_stats.as_timing())
    reg.register("pool", engine_pool_provider)
    reg.snapshot()                      # {"prefix.hits": 3, "pool.free_pages": 12, ...}
    reg.export_prometheus("/run/metrics/orion.prom")
    reg.export_jsonl("/var/log/orion_metrics.jsonl")

Providers are zero-arg callables returning flat mappings; they are read
lazily at snapshot time, so registering costs nothing on the hot path and
a provider reading live engine state always reports the CURRENT window —
``reset_timing``'s drain-and-zero semantics are unchanged, the registry
just reads whichever stats object is live right now.

The engine and trainer each own a registry (``engine.registry`` /
``trainer.registry``); the bench tools emit a standard ``"metrics"`` block
built from it (``bench_metrics_block``), so every bench JSON line carries
a comparable counter set across rounds.
"""

from __future__ import annotations

import json
import os
import re
import time
from typing import Any, Callable, Mapping, Optional, Sequence

import jax

Provider = Callable[[], Mapping[str, Any]]

_NAME_RE = re.compile(r"^[A-Za-z_][A-Za-z0-9_]*$")
_PROM_SANITIZE = re.compile(r"[^a-zA-Z0-9_]")

# JSONL time-series stamps must be wall-clock (cross-host comparable) but
# may never step backwards within a process — an NTP slew mid-run would
# reorder the series a dashboard diffs. Anchor the wall clock once and
# advance it on the monotonic clock (obs clock discipline, tools/lint.py
# `clock` rule).
_T0_WALL = time.time()  # orion: allow[clock] one-off wall anchor; stamps advance monotonically from it
_T0_MONO = time.monotonic()


def _wall_now() -> float:
    """Monotonic-within-process wall-clock seconds."""
    return _T0_WALL + (time.monotonic() - _T0_MONO)


def live_hbm_metrics(device: Optional[jax.Device] = None) -> dict[str, int]:
    """Live device-memory gauges from the backend allocator, or {} when
    the backend exposes none (CPU test runs). Keys follow the backend's
    own naming (bytes_in_use / peak_bytes_in_use / bytes_limit)."""
    d = device if device is not None else jax.devices()[0]
    stats_fn = getattr(d, "memory_stats", None)
    if not callable(stats_fn):
        return {}
    try:
        stats = stats_fn()
    except Exception:
        return {}
    if not stats:
        return {}
    out = {}
    for key in ("bytes_in_use", "peak_bytes_in_use", "bytes_limit",
                "largest_alloc_size"):
        if key in stats:
            out[key] = int(stats[key])
    return out


class MetricsRegistry:
    """Named sections of lazily-read metric providers."""

    def __init__(self):
        self._providers: dict[str, Provider] = {}

    def register(self, name: str, provider: Provider) -> None:
        """Register (or replace) the provider for a section. Section names
        are identifier-shaped; snapshot keys are ``section.key``."""
        if not _NAME_RE.match(name):
            raise ValueError(f"bad metrics section name {name!r}")
        self._providers[name] = provider

    def unregister(self, name: str) -> None:
        self._providers.pop(name, None)

    def sections(self) -> list[str]:
        return sorted(self._providers)

    def snapshot(
        self, sections: Optional[Sequence[str]] = None
    ) -> dict[str, Any]:
        """One flat name-spaced read of every (or the named) section(s).
        A provider that raises contributes a ``<section>.error`` string
        instead of taking the caller down — metrics reads run inside
        serving loops and postmortem dumps."""
        out: dict[str, Any] = {}
        names = self.sections() if sections is None else sections
        for name in names:
            fn = self._providers.get(name)
            if fn is None:
                continue
            try:
                vals = fn() or {}
            except Exception as e:
                out[f"{name}.error"] = f"{type(e).__name__}: {e}"
                continue
            for k, v in vals.items():
                out[f"{name}.{k}"] = v
        return out

    # -- exporters ---------------------------------------------------------

    def export_prometheus(
        self,
        path: str,
        prefix: str = "orion",
        snapshot: Optional[Mapping[str, Any]] = None,
    ) -> int:
        """Write the snapshot as a Prometheus textfile (node_exporter
        textfile-collector format: ``<prefix>_<flattened_key> <value>``),
        atomically (tmp + rename — the collector must never read a torn
        file). Non-numeric values are skipped (Prometheus has no string
        samples); returns the number of samples written."""
        snap = self.snapshot() if snapshot is None else snapshot
        lines = []
        for key in sorted(snap):
            v = snap[key]
            if isinstance(v, bool):
                v = int(v)
            if not isinstance(v, (int, float)):
                continue
            metric = f"{prefix}_{_PROM_SANITIZE.sub('_', key)}"
            lines.append(f"{metric} {float(v):.17g}")
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            f.write("\n".join(lines) + ("\n" if lines else ""))
        os.replace(tmp, path)
        return len(lines)

    def export_jsonl(
        self,
        path: str,
        snapshot: Optional[Mapping[str, Any]] = None,
    ) -> dict[str, Any]:
        """Append one time-series row ({"ts": unix_seconds, **snapshot})
        to a JSONL file; returns the row. The serving engine calls this
        from ``reset_timing`` when ``inference.metrics_jsonl`` is set, so
        every drain window becomes one comparable row. The stamp is the
        monotonic-anchored wall clock (``_wall_now``): comparable across
        hosts, never backwards within the process."""
        row = {"ts": _wall_now()}
        row.update(self.snapshot() if snapshot is None else snapshot)
        with open(path, "a") as f:
            f.write(json.dumps(row, default=str) + "\n")
        return row


def bench_metrics_block(
    engine, timing: Optional[Mapping[str, Any]] = None
) -> dict[str, Any]:
    """The standard ``"metrics"`` block for tools/*_bench.py JSON lines:
    the engine registry's gauge sections (pool occupancy, live HBM) plus a
    drained ``reset_timing`` window, name-spaced ``serve.*`` like registry
    snapshots. Pass ``timing`` when the bench already drained the window
    itself (reset_timing zeroes — draining twice would report zeros)."""
    block = engine.registry.snapshot(sections=("pool", "hbm"))
    src = timing if timing is not None else engine.reset_timing()
    block.update({f"serve.{k}": v for k, v in src.items()})
    return block
