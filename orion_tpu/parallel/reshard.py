"""Cross-layout array redistribution (PAPERS.md:8).

The reference world reshapes a training job's parallelism by tearing down
one NCCL process group and hand-coding gather/scatter into the next layout;
the portable-collectives paper (PAPERS.md:8) frames redistribution as a
first-class operation. On TPU the whole problem collapses into sharding
annotations: XLA already knows how to move any `NamedSharding` layout to any
other with a minimal collective schedule (all-to-all / collective-permute
over ICI), so redistribution is one `device_put` (eager) or an identity jit
with `out_shardings` (compiled, fusable with surrounding work).

Two consumers:
  - live layout migration: `reshard(state, new_shardings)` moves a training
    state between e.g. fsdp- and tp-major layouts without a checkpoint
    round-trip (tests/test_parallel.py cross-layout tests);
  - checkpoint portability: Orbax restores directly into *any* target
    layout via the abstract-state template (`Trainer.abstract_state`), so a
    checkpoint written under one parallelism config restores under another
    with no conversion step (tests/test_train.py).
"""

from __future__ import annotations

from typing import Any

import jax


def reshard(tree: Any, target_shardings: Any, *, donate: bool = False) -> Any:
    """Redistribute every array in ``tree`` to ``target_shardings``.

    ``target_shardings`` is a matching pytree of ``jax.sharding.Sharding``s
    (build one with ``parallel.param_shardings`` / ``train.state_shardings``
    over the destination mesh). The result never aliases the source
    (``may_alias=False``): a leaf whose layout already matches would
    otherwise share buffers, and a later donating step on the source state
    (every train step donates) would delete it out from under the migrated
    copy. With ``donate=True`` the source buffers are consumed instead —
    pass it when migrating a state the caller won't touch again (halves
    peak memory for same-mesh moves).
    """
    flat_t, treedef_t = jax.tree.flatten(tree)
    flat_s, treedef_s = jax.tree.flatten(
        target_shardings,
        is_leaf=lambda x: isinstance(x, jax.sharding.Sharding),
    )
    if treedef_t != treedef_s:
        raise ValueError(
            f"tree/shardings structure mismatch: {treedef_t} vs {treedef_s}"
        )
    out = jax.device_put(
        flat_t, flat_s, donate=donate, may_alias=False if not donate else None
    )
    return jax.tree.unflatten(treedef_t, out)
