"""Pipeline parallelism over the ``pp`` mesh axis.

The reference pipelines layers across devices with NCCL p2p activation
transfers and a microbatch schedule (SURVEY.md §3 "PP"; PAPERS.md:7). The
TPU-native formulation here is SPMD, not MPMD: the stacked per-layer params
[L, ...] are sharded contiguously over ``pp`` (rule "layers" -> "pp", so each
device owns L/pp stage layers), and a ``shard_map`` that is *manual over pp
only* runs the classic GPipe fill/drain schedule — each tick every stage
applies its layers to its current microbatch and ``ppermute``s the activation
one hop down the ring. All other mesh axes (dp/fsdp/tp/sp) stay in XLA's
auto-sharding mode inside the pipeline body, so pipeline composes with data,
ZeRO-3, tensor and sequence sharding without any manual collectives.

Schedule notes: with M microbatches over S stages the bubble fraction is
(S-1)/(M+S-1) — raise ``parallel.pp_microbatches`` to amortize. Bubble ticks
compute on garbage and are masked out (uniform SPMD control flow beats a
per-stage cond that would have to carry collectives). Three schedules:

GPIPE (``pp_schedule='gpipe'``): the classic fill/drain. Backward is just
``jax.grad`` through the scan: ppermute transposes into the reverse-direction
ring, giving the synchronous GPipe backward schedule. The forward scan's
autodiff residuals grow with the TICK count — every per-layer interior of
every tick (bubble ticks included, whose garbage compute still gets stashed)
stays live from the forward pass until its backward tick, so peak activation
memory scales with M (or with remat='full', M+S-1 boundary carries plus
1.33x executed FLOPs).

1F1B (``pp_schedule='1f1b'``; PAPERS.md 2412.14374 schedule family): the
hand-written pipeline VJP the round-3 note said this would need (jax.grad
through a schedule that reorders fwd/bwd ticks does not fall out of a scan).
The forward tick loop stashes exactly ONE [mb, S, D] stage-INPUT per real
microbatch (M slots — no garbage-tick stash, no per-layer interiors); the
custom-vjp backward runs the reverse-direction ring: each tick re-linearizes
the stage body at its stashed input (``jax.vjp`` inside the tick — the
recompute lives and dies within one tick) and ppermutes the input-cotangent
UP the ring while parameter cotangents accumulate per stage. Peak in-flight
interior activations are therefore ONE stage body per device — bounded by
the stage count, never by M — and the boundary stash is M·(B/M) = B rows
total, also M-independent. The loss lives outside the pipelined region, so
its cotangent only exists after every microbatch has drained: the classic
steady-state "one forward, one backward per tick" interleaving of fwd and
bwd of the SAME optimizer step collapses to fwd-phase-then-bwd-phase here
(same tick count, T = M+S-1 each way); what 1F1B contributes in this
formulation is its stash discipline. Cost model per backward tick:
relinearize (F) + pullback (B) — GPipe's remat='full' pays the same FLOPs
while stashing M+S-1 carries incl. bubbles; GPipe's remat='none' skips the
relinearize but stashes every interior of every tick. Bitwise: forward is
tick-for-tick GPipe's, and backward contributions accumulate in the same
reverse-microbatch order jax.grad's transposed scan uses, so losses AND
grads are bitwise-equal to the GPipe path (pinned,
tests/test_pipeline_1f1b.py).

The INTERLEAVED (Megatron virtual-pipeline-class) schedule attacks the
bubble where raising M cannot: each device owns V non-contiguous layer
chunks (chunk c on device c mod pp), ticks advance at CHUNK granularity,
and a microbatch laps the device ring V times. Per-batch overhead drops
from GPipe's (M+pp-1)/M to (M+V*pp-1)/(V*M): at M=pp, V=4 that is
~1.25x vs GPipe's ~2x — and, crucially, V raises utilization WITHOUT
shrinking the microbatch, so it composes with small global batches where
GPipe's only lever (more, smaller microbatches) starves the MXU.
Scheduling constraint: M <= pp keeps at most ONE of a device's V chunks
active per tick, which is what lets the schedule stay a uniform SPMD scan
that ``jax.grad`` differentiates (the reverse scan IS the interleaved
backward). Cost: the round-robin chunk layout is a one-gather-per-step
resharding of the stage params (volume comparable to the param
all-gather every ZeRO-3 step already pays). Select via
``parallel.pp_schedule='interleaved'`` + ``parallel.pp_virtual_stages``.

Measured (round 5, tools/pp_bubble_bench.py, 8-fake-CPU-device mesh,
8-layer model, uncontended rows; step time vs the pp=1 layout):
pp=2 interleaved M=2,V=4 -> 1.14x (predicted 1.12x); pp=4 GPipe
M=2/4/8 -> 2.75x/1.78x/1.33x (predicted 2.5/1.75/1.38 — the model
tracks); pp=4 interleaved M=4,V=2 -> 1.12x, i.e. BETTER occupancy than
GPipe at M=8 while using half the microbatches (2x the per-microbatch
MXU shape) — exactly the regime the schedule exists for.

Runtime compatibility: on the jax-0.4.x boxes where ``jax.shard_map`` is
the adapter over ``jax.experimental.shard_map`` (orion_tpu.__init__), the
SPMD partitioner cannot lower three things a partial-auto (manual over pp
only) region wants to do: ``lax.axis_index`` (PartitionId HLO rejected),
``lax.ppermute`` (manual-subgroup CollectivePermute check-fails), and the
transposed while loop ``jax.grad`` makes of a scanned tick loop (the
replicated output cotangent entering the loop check-fails the same way).
Every schedule therefore routes through three seams that keep ONE code
path semantically: the stage index arrives as a P(pp)-sharded iota input
(``_stage_ids``) instead of axis_index; ring hops go through ``_make_hop``
(ppermute on modern jax; a one-hot ``psum_scatter`` emulation with a
custom-vjp reverse hop on compat runtimes, so jax never transposes the
collective itself); and the differentiated schedules drive their ticks
through ``_run_ticks`` (lax.scan on modern jax, python-unrolled on compat
runtimes). The 1F1B schedule hand-writes its VJP, so its tick loops stay
``lax.scan`` everywhere — only its replicated per-tick reads move out of
the loop body (pre-gathered scan xs), which is the remaining compat rule.
"""

from __future__ import annotations

import functools
from typing import Any, Callable, Optional, Tuple

import numpy as np

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

BlockFn = Callable[[jax.Array, Any], Tuple[jax.Array, jax.Array]]


def _compat_runtime() -> bool:
    """True on runtimes running the orion_tpu shard_map adapter (jax
    0.4.x), whose SPMD partitioner needs the compat formulations above."""
    return bool(getattr(jax.shard_map, "_orion_compat", False))


def _stage_ids(pp: int) -> jax.Array:
    """P(axis)-sharded iota input: each device's slice IS its stage index
    (the axis_index replacement that lowers everywhere)."""
    return jnp.arange(pp, dtype=jnp.int32)


def _rs_hop(x, stage, npp: int, axis: str, reverse: bool, wrap: bool):
    """One ring hop as a one-hot reduce-scatter: every device contributes
    ``x`` at its destination's slot (zeros elsewhere) and psum_scatter
    hands slot d to device d — unmatched receivers get zeros, exactly
    ppermute's semantics. ~npp x the wire volume of a p2p permute, which
    the fake-device mesh (and any compat box) doesn't care about."""
    iota = jnp.arange(npp, dtype=jnp.int32).reshape((npp,) + (1,) * x.ndim)
    dest = stage + (-1 if reverse else 1)
    if wrap:
        sel = iota == jnp.remainder(dest, npp)
    else:
        sel = (iota == dest) & (dest >= 0) & (dest < npp)
    buf = jnp.where(sel, x[None], jnp.zeros_like(x)[None])
    return lax.psum_scatter(
        buf, axis, scatter_dimension=0, tiled=True
    ).reshape(x.shape)


def _make_hop(npp: int, axis: str, wrap: bool = False):
    """``hop(x, stage, reverse=False)``: one ring hop along ``axis``.

    Modern jax: ``lax.ppermute`` (whose transpose is the reverse permute,
    natively). Compat runtimes: the ``_rs_hop`` emulation under a
    custom-vjp whose backward is the reverse hop — the mathematically
    exact transpose, expressed again as a psum_scatter so jax.grad of a
    differentiated schedule never asks the old partitioner to transpose
    a manual-subgroup collective."""
    if not _compat_runtime():
        if wrap:
            fperm = [(i, (i + 1) % npp) for i in range(npp)]
            rperm = [((i + 1) % npp, i) for i in range(npp)]
        else:
            fperm = [(i, i + 1) for i in range(npp - 1)]
            rperm = [(i + 1, i) for i in range(npp - 1)]

        def hop(x, stage, reverse: bool = False):
            return lax.ppermute(x, axis, rperm if reverse else fperm)

        return hop

    @functools.partial(jax.custom_vjp, nondiff_argnums=(2,))
    def send(x, stage, reverse):
        return _rs_hop(x, stage, npp, axis, reverse, wrap)

    def send_fwd(x, stage, reverse):
        return send(x, stage, reverse), stage

    def send_bwd(reverse, stage, g):
        return (
            _rs_hop(g, stage, npp, axis, not reverse, wrap),
            np.zeros((), jax.dtypes.float0),
        )

    send.defvjp(send_fwd, send_bwd)

    def hop(x, stage, reverse: bool = False):
        return send(x, stage, reverse)

    return hop


def _run_ticks(tick, carry, xs, T: int):
    """Drive a differentiated schedule's tick loop: ``lax.scan`` on modern
    jax; python-unrolled on compat runtimes, where the transposed while
    loop jax.grad would make of the scan breaks the old SPMD partitioner.
    ``xs`` is a pytree of [T, ...] per-tick arrays."""
    if not _compat_runtime():
        carry, _ = lax.scan(tick, carry, xs)
        return carry
    for t in range(T):
        carry, _ = tick(carry, jax.tree.map(lambda a: a[t], xs))
    return carry


def validate_row_state(row_state: Any, batch: int, num_microbatches: int):
    """Normalize per-row state for microbatch slicing (ADVICE r5).

    The non-pp block_fn accepts row-state leaves with a broadcast [1, ...]
    leading dim; pipelining slices leaves to [M, B/M, ...], so lift the
    broadcast to the full batch up front and reject any other leading dim
    loudly instead of dying in an opaque reshape."""
    def _leaf(a):
        a = jnp.asarray(a)
        if a.ndim >= 1 and a.shape[0] == 1 and batch != 1:
            return jnp.broadcast_to(a, (batch,) + a.shape[1:])
        if a.ndim < 1 or a.shape[0] != batch:
            raise ValueError(
                f"pipeline row_state leaf has shape {a.shape}: leading dim "
                f"must equal the batch ({batch}) — or 1 to broadcast — so "
                f"it can be sliced into {num_microbatches} microbatches"
            )
        return a

    return jax.tree.map(_leaf, row_state)


def pipeline_forward(
    x: jax.Array,                 # [B, S, D] (batch auto-sharded on dp/fsdp)
    blocks: Any,                  # stacked per-layer params, leaves [L, ...]
    block_fn: BlockFn,            # (x [b,S,D], layer_params[, row_state])
    mesh: Mesh,
    *,
    axis: str = "pp",
    num_microbatches: int = 1,
    schedule: str = "gpipe",
    virtual_stages: int = 1,
    row_state: Any = None,        # pytree of [B, ...] per-row arrays
) -> tuple[jax.Array, jax.Array]:
    """Run the layer stack as a GPipe pipeline; returns (x_out, aux_sum).

    Requirements (validated by the trainer): L % pp == 0, B % M == 0.

    ``row_state`` carries per-row batch state (packed segment_ids, custom
    positions) through microbatching: leaves are [B, ...] arrays sliced to
    [M, mb, ...], and each tick's stage LOOKS UP its active microbatch's
    slice by index — row state never rides the ppermute ring (it is a
    static input, unlike the activation). With row_state, ``block_fn`` is
    called as ``block_fn(x, layer_params, rs)``.

    ``schedule='interleaved'`` runs the virtual-stage schedule (module
    docstring): ``virtual_stages`` chunks per device, M <= pp required.
    ``schedule='1f1b'`` runs the hand-written-VJP schedule (module
    docstring): stage-input stash bounded by the stage count, explicit
    reverse-ring backward; bitwise-equal losses and grads to 'gpipe'.
    """
    if schedule not in ("gpipe", "interleaved", "1f1b"):
        raise ValueError(
            f"unknown pp_schedule {schedule!r}; expected 'gpipe', "
            f"'interleaved' or '1f1b'"
        )

    def call(c, bp, rs):
        return block_fn(c, bp) if row_state is None else block_fn(c, bp, rs)

    pp = mesh.shape.get(axis, 1)
    if pp == 1:
        def scan_fn(c, bp):
            y, aux = call(c, bp, row_state)
            return y, aux
        x, aux = lax.scan(scan_fn, x, blocks)
        return x, aux.sum()

    B, S, D = x.shape
    M = num_microbatches
    if B % M:
        raise ValueError(f"batch {B} not divisible by pp_microbatches {M}")
    L = jax.tree.leaves(blocks)[0].shape[0]
    if L % pp:
        raise ValueError(f"n_layers {L} not divisible by pp {pp}")

    row_state = validate_row_state(row_state, B, M)
    rs_mb = jax.tree.map(
        lambda a: a.reshape(M, B // M, *a.shape[1:]), row_state
    )
    if schedule == "interleaved":
        return _interleaved_pipeline(
            x, blocks, call, mesh, axis, M, virtual_stages, rs_mb
        )
    if schedule == "1f1b":
        return _pipeline_1f1b(x, blocks, call, mesh, axis, M, rs_mb)
    mb = B // M

    # [L, ...] -> [pp, L/pp, ...]: contiguous stage chunks, so this reshape
    # is local for params sharded "layers" -> "pp".
    staged = jax.tree.map(
        lambda a: a.reshape(pp, L // pp, *a.shape[1:]), blocks
    )
    x_mb = x.reshape(M, mb, S, D)

    def local(stage_ids, x_mb, staged, rs_mb):
        stage_params = jax.tree.map(lambda a: a[0], staged)  # [L/pp, ...]
        stage = stage_ids[0]
        is_last = stage == pp - 1
        T = M + pp - 1
        hop = _make_hop(pp, axis)

        def run_stage(c, rs):
            def scan_fn(h, bp):
                y, aux = call(h, bp, rs)
                return y, aux
            y, aux = lax.scan(scan_fn, c, stage_params)
            return y, aux.sum()

        def tick(carry, t):
            state, outputs, aux_acc = carry
            inject = x_mb[jnp.clip(t, 0, M - 1)]
            cur = jnp.where(stage == 0, inject, state)
            # Row state is looked up by this stage's active microbatch
            # index (t - stage) — static input, never on the ring.
            rs = jax.tree.map(
                lambda a: a[jnp.clip(t - stage, 0, M - 1)], rs_mb
            )
            # Bubble ticks run on garbage and are masked below: uniform
            # control flow keeps the auto-axis collectives unconditional.
            out, aux_t = run_stage(cur, rs)
            active = (t >= stage) & (t - stage < M)
            aux_acc = aux_acc + jnp.where(active, aux_t, 0.0)
            out_idx = jnp.clip(t - (pp - 1), 0, M - 1)
            outputs = outputs.at[out_idx].set(
                jnp.where(is_last & active, out, outputs[out_idx])
            )
            state = hop(out, stage)
            return (state, outputs, aux_acc), None

        # The carries become device-varying over pp after the first tick, so
        # their (replicated-zero) initial values must be cast to varying.
        carry0 = jax.tree.map(
            lambda a: lax.pcast(a, (axis,), to="varying"),
            (
                jnp.zeros_like(x_mb[0]),
                jnp.zeros_like(x_mb),
                jnp.zeros((), jnp.float32),
            ),
        )
        _, outputs, aux_acc = _run_ticks(tick, carry0, jnp.arange(T), T)
        # Only the last stage holds real outputs; broadcast them (and the
        # per-stage aux partial sums) to every stage. Per-layer aux values
        # are batch means (e.g. the MoE balance loss), so average over the M
        # microbatches to match the single-batch scan semantics.
        outputs = lax.psum(
            jnp.where(is_last, outputs, jnp.zeros_like(outputs)), axis
        )
        aux = lax.psum(aux_acc, axis) / M
        return outputs, aux

    outputs, aux = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis), P(), P(axis), jax.tree.map(lambda _: P(), rs_mb)),
        out_specs=(P(), P()),
        axis_names={axis},
        check_vma=False,
    )(_stage_ids(pp), x_mb, staged, rs_mb)
    return outputs.reshape(B, S, D), aux


def _zero_cotangent(a):
    """Cotangent for a non-differentiated pipeline input: float zeros for
    float leaves, float0 for integer leaves (row-state positions /
    segment_ids — the custom-vjp contract for int primals)."""
    a = jnp.asarray(a)
    if jnp.issubdtype(a.dtype, jnp.floating):
        return jnp.zeros_like(a)
    return np.zeros(a.shape, jax.dtypes.float0)


def _pipeline_1f1b(
    x: jax.Array,
    blocks: Any,
    call,                  # call(x, layer_params, rs) -> (y, aux)
    mesh: Mesh,
    axis: str,
    M: int,
    rs_mb: Any = None,     # row-state leaves [M, mb, ...] (see caller)
) -> tuple[jax.Array, jax.Array]:
    """The 1F1B schedule as a hand-written pipeline VJP (module docstring).

    Forward: GPipe's fill/drain tick loop, additionally saving each
    stage's INPUT activation per real microbatch into an [M, mb, S, D]
    per-device stash (masked writes — bubble ticks never stash garbage).
    Backward (``jax.custom_vjp``): a reverse-direction tick loop of the
    same length; tick u at stage s re-linearizes the stage body at the
    stashed input of microbatch M-1-(u-(pp-1-s)) via ``jax.vjp`` (the
    recompute is transient within the tick — no interior ever crosses a
    tick boundary), accumulates the parameter cotangent, and ppermutes
    the input-cotangent one hop UP the ring. Losses and grads are
    bitwise-equal to the 'gpipe' schedule: the forward is tick-for-tick
    identical and the backward accumulates per-stage contributions in
    the same reverse-microbatch order as jax.grad's transposed scan
    (masked-zero bubble contributions are exact +0.0 either way).
    """
    pp = mesh.shape[axis]
    B, S, D = x.shape
    L = jax.tree.leaves(blocks)[0].shape[0]
    if L % pp:
        raise ValueError(f"n_layers {L} not divisible by pp {pp}")
    mb = B // M

    staged = jax.tree.map(
        lambda a: a.reshape(pp, L // pp, *a.shape[1:]), blocks
    )
    x_mb = x.reshape(M, mb, S, D)
    rs_specs = jax.tree.map(lambda _: P(), rs_mb)

    def run_stage(c, sp, rs):
        def scan_fn(h, bp):
            y, aux = call(h, bp, rs)
            return y, aux

        y, aux = lax.scan(scan_fn, c, sp)
        return y, aux.sum()

    def make_fwd_local(with_stash: bool):
        """The forward tick loop; ``with_stash`` statically selects
        whether the stage-input stash is carried and returned (the VJP
        forward needs it; the no-grad primal skips its writes and
        footprint entirely — GPipe's forward cost exactly)."""
        def fwd_local(stage_ids, x_mb, staged, rs_mb):
            stage_params = jax.tree.map(lambda a: a[0], staged)
            stage = stage_ids[0]
            is_last = stage == pp - 1
            T = M + pp - 1
            hop = _make_hop(pp, axis)
            ts = jnp.arange(T)
            # Per-tick reads of the replicated inputs happen HERE,
            # outside the scan (compat rule, module docstring): the
            # injected microbatch stream and this stage's row-state
            # slices ride in as scan xs instead of being indexed inside
            # the loop body.
            injects = x_mb[jnp.clip(ts, 0, M - 1)]
            rs_seq = jax.tree.map(
                lambda a: a[jnp.clip(ts - stage, 0, M - 1)], rs_mb
            )

            def tick(carry, xs):
                t, inject, rs = xs
                if with_stash:
                    state, outputs, stash, aux_acc = carry
                else:
                    state, outputs, aux_acc = carry
                cur = jnp.where(stage == 0, inject, state)
                midx = jnp.clip(t - stage, 0, M - 1)
                active = (t >= stage) & (t - stage < M)
                if with_stash:
                    # The 1F1B stash: this stage's input for microbatch
                    # midx — the backward's re-linearization point.
                    # Masked so bubble ticks can't clobber a real slot.
                    stash = stash.at[midx].set(
                        jnp.where(active, cur, stash[midx])
                    )
                out, aux_t = run_stage(cur, stage_params, rs)
                aux_acc = aux_acc + jnp.where(active, aux_t, 0.0)
                out_idx = jnp.clip(t - (pp - 1), 0, M - 1)
                outputs = outputs.at[out_idx].set(
                    jnp.where(is_last & active, out, outputs[out_idx])
                )
                state = hop(out, stage)
                carry = (
                    (state, outputs, stash, aux_acc) if with_stash
                    else (state, outputs, aux_acc)
                )
                return carry, None

            init = [
                jnp.zeros_like(x_mb[0]),
                jnp.zeros_like(x_mb),
                jnp.zeros((), jnp.float32),
            ]
            if with_stash:
                init.insert(2, jnp.zeros_like(x_mb))  # stage-input stash
            carry0 = jax.tree.map(
                lambda a: lax.pcast(a, (axis,), to="varying"), tuple(init)
            )
            carry, _ = lax.scan(tick, carry0, (ts, injects, rs_seq))
            outputs, aux_acc = carry[1], carry[-1]
            outputs = lax.psum(
                jnp.where(is_last, outputs, jnp.zeros_like(outputs)), axis
            )
            aux = lax.psum(aux_acc, axis) / M
            if with_stash:
                return outputs, aux, carry[2]
            return outputs, aux

        return fwd_local

    fwd_sm = jax.shard_map(
        make_fwd_local(True),
        mesh=mesh,
        in_specs=(P(axis), P(), P(axis), rs_specs),
        out_specs=(P(), P(), P(axis)),
        axis_names={axis},
        check_vma=False,
    )
    fwd_nostash_sm = jax.shard_map(
        make_fwd_local(False),
        mesh=mesh,
        in_specs=(P(axis), P(), P(axis), rs_specs),
        out_specs=(P(), P()),
        axis_names={axis},
        check_vma=False,
    )

    def bwd_local(stage_ids, g_out, g_aux, stash, staged, rs_mb):
        stage_params = jax.tree.map(lambda a: a[0], staged)
        stage = stage_ids[0]
        is_last = stage == pp - 1
        is_first = stage == 0
        T = M + pp - 1
        hop = _make_hop(pp, axis)
        us = jnp.arange(T)
        # The last stage injects output-cotangents, microbatch M-1 first
        # (the reverse of emission order); pre-gathered outside the scan
        # like the forward's injects, and this stage's row-state slices
        # for its backward microbatch schedule likewise.
        g_seq = g_out[jnp.clip(M - 1 - us, 0, M - 1)]
        rs_seq = jax.tree.map(
            lambda a: a[jnp.clip(M - 1 - (us - (pp - 1 - stage)),
                                 0, M - 1)],
            rs_mb,
        )
        # d(aux)/d(aux_t) = 1/M for every active (stage, microbatch) tick
        # (fwd: aux = psum(sum_t aux_t) / M).
        gaux_term = (g_aux / M).astype(jnp.float32)

        def tick(carry, xs):
            u, ginj, rs = xs
            gstate, dparams, dx = carry
            d = u - (pp - 1 - stage)
            active = (d >= 0) & (d < M)
            midx = jnp.clip(M - 1 - d, 0, M - 1)
            gcur = jnp.where(is_last, ginj, gstate)
            a_in = stash[midx]
            # Re-linearize the stage body at its stashed input: the
            # recompute (and every interior it briefly materializes)
            # lives entirely within this tick.
            _, pull = jax.vjp(
                lambda a_, p_: run_stage(a_, p_, rs), a_in, stage_params
            )
            da, dp = pull((gcur, gaux_term))
            da = jnp.where(active, da, jnp.zeros_like(da))
            dparams = jax.tree.map(
                lambda acc, g: acc + jnp.where(active, g,
                                               jnp.zeros_like(g)),
                dparams, dp,
            )
            dx = dx.at[midx].set(
                jnp.where(is_first & active, da, dx[midx])
            )
            gstate = hop(da, stage, reverse=True)
            return (gstate, dparams, dx), None

        zero_dp = jax.tree.map(jnp.zeros_like, stage_params)
        carry0 = jax.tree.map(
            lambda a: lax.pcast(a, (axis,), to="varying"),
            (jnp.zeros_like(g_out[0]), zero_dp, jnp.zeros_like(g_out)),
        )
        (_, dparams, dx), _ = lax.scan(
            tick, carry0, (us, g_seq, rs_seq)
        )
        dx = lax.psum(
            jnp.where(is_first, dx, jnp.zeros_like(dx)), axis
        )
        # Re-lead with the stage dim so the out_spec P(axis) reassembles
        # the [pp, L/pp, ...] staged layout.
        dparams = jax.tree.map(lambda g: g[None], dparams)
        return dx, dparams

    bwd_sm = jax.shard_map(
        bwd_local,
        mesh=mesh,
        in_specs=(P(axis), P(), P(), P(axis), P(axis), rs_specs),
        out_specs=(P(), jax.tree.map(lambda _: P(axis), staged)),
        axis_names={axis},
        check_vma=False,
    )

    sids = _stage_ids(pp)

    @jax.custom_vjp
    def run(x_mb, staged, rs_mb):
        # The no-grad primal (eval / forward-only callers): no stash
        # writes, no stash footprint — GPipe's forward, tick for tick.
        return fwd_nostash_sm(sids, x_mb, staged, rs_mb)

    def run_fwd(x_mb, staged, rs_mb):
        outputs, aux, stash = fwd_sm(sids, x_mb, staged, rs_mb)
        return (outputs, aux), (stash, staged, rs_mb)

    def run_bwd(res, ct):
        stash, staged, rs_mb = res
        g_out, g_aux = ct
        dx, dstaged = bwd_sm(sids, g_out, g_aux, stash, staged, rs_mb)
        return dx, dstaged, jax.tree.map(_zero_cotangent, rs_mb)

    run.defvjp(run_fwd, run_bwd)
    outputs, aux = run(x_mb, staged, rs_mb)
    return outputs.reshape(B, S, D), aux


def _interleaved_pipeline(
    x: jax.Array,
    blocks: Any,
    call,                  # call(x, layer_params, rs) -> (y, aux)
    mesh: Mesh,
    axis: str,
    M: int,
    V: int,
    rs_mb: Any = None,     # row-state leaves [M, mb, ...] (see caller)
) -> tuple[jax.Array, jax.Array]:
    """Virtual-stage (interleaved) schedule: chunk c of V*pp lives on device
    c mod pp; tick t runs chunk s on microbatch t-s; ppermute is the full
    ring (the wrap link carries a microbatch into its next lap). M <= pp
    keeps exactly one of a device's V chunks active per tick, so the
    schedule is a uniform SPMD scan and ``jax.grad`` of it IS the
    interleaved backward. See the module docstring for the bubble math.
    """
    pp = mesh.shape[axis]
    B, S, D = x.shape
    L = jax.tree.leaves(blocks)[0].shape[0]
    if V < 1:
        raise ValueError(f"pp_virtual_stages={V} must be >= 1")
    if L % (V * pp):
        raise ValueError(
            f"n_layers {L} not divisible by pp*pp_virtual_stages "
            f"({pp}*{V})"
        )
    if M > pp:
        raise ValueError(
            f"interleaved schedule needs pp_microbatches ({M}) <= pp "
            f"({pp}): a device may only have one active chunk per tick; "
            f"raise pp_virtual_stages (not M) to amortize the bubble"
        )
    mb = B // M
    Lc = L // (V * pp)

    # Round-robin chunk layout: device d owns chunks {j*pp + d}. The
    # stacked params are sharded contiguously on the layer dim, so this
    # static gather is a per-step resharding of the stage params (cost ~
    # one ZeRO-3 param all-gather; see module docstring).
    perm = jnp.asarray(
        [
            (j * pp + d) * Lc + i
            for d in range(pp)
            for j in range(V)
            for i in range(Lc)
        ],
        jnp.int32,
    )
    staged = jax.tree.map(
        lambda a: jnp.take(a, perm, axis=0).reshape(
            pp, V, Lc, *a.shape[1:]
        ),
        blocks,
    )
    x_mb = x.reshape(M, mb, S, D)

    def local(stage_ids, x_mb, staged, rs_mb):
        chunks = jax.tree.map(lambda a: a[0], staged)   # [V, Lc, ...]
        stage = stage_ids[0]
        T = M + V * pp - 1
        hop = _make_hop(pp, axis, wrap=True)
        is_last = stage == pp - 1

        def run_chunk(c, j, rs):
            cp = jax.tree.map(
                lambda a: lax.dynamic_index_in_dim(a, j, 0, keepdims=False),
                chunks,
            )

            def scan_fn(h, bp):
                y, aux = call(h, bp, rs)
                return y, aux

            y, aux = lax.scan(scan_fn, c, cp)
            return y, aux.sum()

        def tick(carry, t):
            state, outputs, aux_acc = carry
            dt = t - stage
            j = jnp.clip(dt // pp, 0, V - 1)        # this device's chunk lap
            active = (dt >= 0) & (dt % pp < M) & (dt // pp < V)
            # Chunk 0 (device 0, lap 0) injects fresh microbatches; every
            # other (device, lap) consumes the ring.
            inject = x_mb[jnp.clip(t, 0, M - 1)]
            cur = jnp.where((stage == 0) & (t < M), inject, state)
            # Active microbatch index: dt mod pp (lap-invariant); row
            # state is a static lookup, never on the ring.
            rs = jax.tree.map(
                lambda a: a[jnp.clip(dt % pp, 0, M - 1)], rs_mb
            )
            out, aux_t = run_chunk(cur, j, rs)
            aux_acc = aux_acc + jnp.where(active, aux_t, 0.0)
            # The final chunk (device pp-1, lap V-1) emits mb m at tick
            # t = m + V*pp - 1.
            out_idx = jnp.clip(t - (V * pp - 1), 0, M - 1)
            emit = is_last & active & (j == V - 1)
            outputs = outputs.at[out_idx].set(
                jnp.where(emit, out, outputs[out_idx])
            )
            state = hop(out, stage)
            return (state, outputs, aux_acc), None

        carry0 = jax.tree.map(
            lambda a: lax.pcast(a, (axis,), to="varying"),
            (
                jnp.zeros_like(x_mb[0]),
                jnp.zeros_like(x_mb),
                jnp.zeros((), jnp.float32),
            ),
        )
        _, outputs, aux_acc = _run_ticks(tick, carry0, jnp.arange(T), T)
        outputs = lax.psum(
            jnp.where(is_last, outputs, jnp.zeros_like(outputs)), axis
        )
        aux = lax.psum(aux_acc, axis) / M
        return outputs, aux

    outputs, aux = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(axis), P(), P(axis), jax.tree.map(lambda _: P(), rs_mb)),
        out_specs=(P(), P()),
        axis_names={axis},
        check_vma=False,
    )(_stage_ids(pp), x_mb, staged, rs_mb)
    return outputs.reshape(B, S, D), aux
