"""Pipeline parallelism over the ``pp`` mesh axis.

The reference pipelines layers across devices with NCCL p2p activation
transfers and a microbatch schedule (SURVEY.md §3 "PP"; PAPERS.md:7). The
TPU-native formulation here is SPMD, not MPMD: the stacked per-layer params
[L, ...] are sharded contiguously over ``pp`` (rule "layers" -> "pp", so each
device owns L/pp stage layers), and a ``shard_map`` that is *manual over pp
only* runs the classic GPipe fill/drain schedule — each tick every stage
applies its layers to its current microbatch and ``ppermute``s the activation
one hop down the ring. All other mesh axes (dp/fsdp/tp/sp) stay in XLA's
auto-sharding mode inside the pipeline body, so pipeline composes with data,
ZeRO-3, tensor and sequence sharding without any manual collectives.

Schedule notes: with M microbatches over S stages the bubble fraction is
(S-1)/(M+S-1) — raise ``parallel.pp_microbatches`` to amortize. Bubble ticks
compute on garbage and are masked out (uniform SPMD control flow beats a
per-stage cond that would have to carry collectives). Backward is just
``jax.grad`` through the scan: ppermute transposes into the reverse-direction
ring, giving the synchronous GPipe backward schedule; combine with
``model.remat='full'`` to keep activation memory at O(stage).

Why GPipe and not 1F1B (measured, round 3): 1F1B has the SAME bubble
fraction as GPipe — its benefit is peak activation memory (S in-flight
microbatches instead of M). Here that memory is already bounded by
``remat='full'``: the scan saves only the [mb, S, D] stage-boundary carry
per tick (M+S-1 of them), so the 1F1B win shrinks to (M+S-1)/S boundary
buffers — negligible next to ZeRO-3-sharded params/optimizer at the judged
configs — while its interleaved forward/backward cannot be expressed
through ``jax.grad`` of a scan at all; it needs a hand-written pipeline VJP
with a manual schedule, a large correctness surface for no bubble change.
Measured on the 8-fake-device mesh (pp=2, 4-layer tiny-llama): 694 ms/step
at M=2 -> 490 at M=4 -> 435 at M=8, tracking the predicted 1.50x / 1.25x /
1.12x compute inflation — i.e. the bubble is governed by M exactly as the
formula says, and M is cheap to raise. Revisit only if a config appears
where boundary-activation memory, not params, is the binding constraint.

The INTERLEAVED (Megatron virtual-pipeline-class) schedule attacks the
bubble where raising M cannot: each device owns V non-contiguous layer
chunks (chunk c on device c mod pp), ticks advance at CHUNK granularity,
and a microbatch laps the device ring V times. Per-batch overhead drops
from GPipe's (M+pp-1)/M to (M+V*pp-1)/(V*M): at M=pp, V=4 that is
~1.25x vs GPipe's ~2x — and, crucially, V raises utilization WITHOUT
shrinking the microbatch, so it composes with small global batches where
GPipe's only lever (more, smaller microbatches) starves the MXU.
Scheduling constraint: M <= pp keeps at most ONE of a device's V chunks
active per tick, which is what lets the schedule stay a uniform SPMD scan
that ``jax.grad`` differentiates (the reverse scan IS the interleaved
backward). Cost: the round-robin chunk layout is a one-gather-per-step
resharding of the stage params (volume comparable to the param
all-gather every ZeRO-3 step already pays). Select via
``parallel.pp_schedule='interleaved'`` + ``parallel.pp_virtual_stages``.

Measured (round 5, tools/pp_bubble_bench.py, 8-fake-CPU-device mesh,
8-layer model, uncontended rows; step time vs the pp=1 layout):
pp=2 interleaved M=2,V=4 -> 1.14x (predicted 1.12x); pp=4 GPipe
M=2/4/8 -> 2.75x/1.78x/1.33x (predicted 2.5/1.75/1.38 — the model
tracks); pp=4 interleaved M=4,V=2 -> 1.12x, i.e. BETTER occupancy than
GPipe at M=8 while using half the microbatches (2x the per-microbatch
MXU shape) — exactly the regime the schedule exists for.
"""

from __future__ import annotations

from typing import Any, Callable, Optional, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

BlockFn = Callable[[jax.Array, Any], Tuple[jax.Array, jax.Array]]


def validate_row_state(row_state: Any, batch: int, num_microbatches: int):
    """Normalize per-row state for microbatch slicing (ADVICE r5).

    The non-pp block_fn accepts row-state leaves with a broadcast [1, ...]
    leading dim; pipelining slices leaves to [M, B/M, ...], so lift the
    broadcast to the full batch up front and reject any other leading dim
    loudly instead of dying in an opaque reshape."""
    def _leaf(a):
        a = jnp.asarray(a)
        if a.ndim >= 1 and a.shape[0] == 1 and batch != 1:
            return jnp.broadcast_to(a, (batch,) + a.shape[1:])
        if a.ndim < 1 or a.shape[0] != batch:
            raise ValueError(
                f"pipeline row_state leaf has shape {a.shape}: leading dim "
                f"must equal the batch ({batch}) — or 1 to broadcast — so "
                f"it can be sliced into {num_microbatches} microbatches"
            )
        return a

    return jax.tree.map(_leaf, row_state)


def pipeline_forward(
    x: jax.Array,                 # [B, S, D] (batch auto-sharded on dp/fsdp)
    blocks: Any,                  # stacked per-layer params, leaves [L, ...]
    block_fn: BlockFn,            # (x [b,S,D], layer_params[, row_state])
    mesh: Mesh,
    *,
    axis: str = "pp",
    num_microbatches: int = 1,
    schedule: str = "gpipe",
    virtual_stages: int = 1,
    row_state: Any = None,        # pytree of [B, ...] per-row arrays
) -> tuple[jax.Array, jax.Array]:
    """Run the layer stack as a GPipe pipeline; returns (x_out, aux_sum).

    Requirements (validated by the trainer): L % pp == 0, B % M == 0.

    ``row_state`` carries per-row batch state (packed segment_ids, custom
    positions) through microbatching: leaves are [B, ...] arrays sliced to
    [M, mb, ...], and each tick's stage LOOKS UP its active microbatch's
    slice by index — row state never rides the ppermute ring (it is a
    static input, unlike the activation). With row_state, ``block_fn`` is
    called as ``block_fn(x, layer_params, rs)``.

    ``schedule='interleaved'`` runs the virtual-stage schedule (module
    docstring): ``virtual_stages`` chunks per device, M <= pp required.
    """
    if schedule not in ("gpipe", "interleaved"):
        raise ValueError(
            f"unknown pp_schedule {schedule!r}; expected 'gpipe' or "
            f"'interleaved'"
        )

    def call(c, bp, rs):
        return block_fn(c, bp) if row_state is None else block_fn(c, bp, rs)

    pp = mesh.shape.get(axis, 1)
    if pp == 1:
        def scan_fn(c, bp):
            y, aux = call(c, bp, row_state)
            return y, aux
        x, aux = lax.scan(scan_fn, x, blocks)
        return x, aux.sum()

    B, S, D = x.shape
    M = num_microbatches
    if B % M:
        raise ValueError(f"batch {B} not divisible by pp_microbatches {M}")
    L = jax.tree.leaves(blocks)[0].shape[0]
    if L % pp:
        raise ValueError(f"n_layers {L} not divisible by pp {pp}")

    row_state = validate_row_state(row_state, B, M)
    rs_mb = jax.tree.map(
        lambda a: a.reshape(M, B // M, *a.shape[1:]), row_state
    )
    if schedule == "interleaved":
        return _interleaved_pipeline(
            x, blocks, call, mesh, axis, M, virtual_stages, rs_mb
        )
    mb = B // M

    # [L, ...] -> [pp, L/pp, ...]: contiguous stage chunks, so this reshape
    # is local for params sharded "layers" -> "pp".
    staged = jax.tree.map(
        lambda a: a.reshape(pp, L // pp, *a.shape[1:]), blocks
    )
    x_mb = x.reshape(M, mb, S, D)

    def local(x_mb, staged, rs_mb):
        stage_params = jax.tree.map(lambda a: a[0], staged)  # [L/pp, ...]
        stage = lax.axis_index(axis)
        npp = lax.axis_size(axis)
        is_last = stage == npp - 1
        T = M + npp - 1
        fwd_perm = [(i, i + 1) for i in range(npp - 1)]

        def run_stage(c, rs):
            def scan_fn(h, bp):
                y, aux = call(h, bp, rs)
                return y, aux
            y, aux = lax.scan(scan_fn, c, stage_params)
            return y, aux.sum()

        def tick(carry, t):
            state, outputs, aux_acc = carry
            inject = x_mb[jnp.clip(t, 0, M - 1)]
            cur = jnp.where(stage == 0, inject, state)
            # Row state is looked up by this stage's active microbatch
            # index (t - stage) — static input, never ppermuted.
            rs = jax.tree.map(
                lambda a: a[jnp.clip(t - stage, 0, M - 1)], rs_mb
            )
            # Bubble ticks run on garbage and are masked below: uniform
            # control flow keeps the auto-axis collectives unconditional.
            out, aux_t = run_stage(cur, rs)
            active = (t >= stage) & (t - stage < M)
            aux_acc = aux_acc + jnp.where(active, aux_t, 0.0)
            out_idx = jnp.clip(t - (npp - 1), 0, M - 1)
            outputs = outputs.at[out_idx].set(
                jnp.where(is_last & active, out, outputs[out_idx])
            )
            state = lax.ppermute(out, axis, fwd_perm)
            return (state, outputs, aux_acc), None

        # The carries become device-varying over pp after the first tick, so
        # their (replicated-zero) initial values must be cast to varying.
        carry0 = jax.tree.map(
            lambda a: lax.pcast(a, (axis,), to="varying"),
            (
                jnp.zeros_like(x_mb[0]),
                jnp.zeros_like(x_mb),
                jnp.zeros((), jnp.float32),
            ),
        )
        (_, outputs, aux_acc), _ = lax.scan(tick, carry0, jnp.arange(T))
        # Only the last stage holds real outputs; broadcast them (and the
        # per-stage aux partial sums) to every stage. Per-layer aux values
        # are batch means (e.g. the MoE balance loss), so average over the M
        # microbatches to match the single-batch scan semantics.
        outputs = lax.psum(
            jnp.where(is_last, outputs, jnp.zeros_like(outputs)), axis
        )
        aux = lax.psum(aux_acc, axis) / M
        return outputs, aux

    outputs, aux = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), P(axis), jax.tree.map(lambda _: P(), rs_mb)),
        out_specs=(P(), P()),
        axis_names={axis},
    )(x_mb, staged, rs_mb)
    return outputs.reshape(B, S, D), aux


def _interleaved_pipeline(
    x: jax.Array,
    blocks: Any,
    call,                  # call(x, layer_params, rs) -> (y, aux)
    mesh: Mesh,
    axis: str,
    M: int,
    V: int,
    rs_mb: Any = None,     # row-state leaves [M, mb, ...] (see caller)
) -> tuple[jax.Array, jax.Array]:
    """Virtual-stage (interleaved) schedule: chunk c of V*pp lives on device
    c mod pp; tick t runs chunk s on microbatch t-s; ppermute is the full
    ring (the wrap link carries a microbatch into its next lap). M <= pp
    keeps exactly one of a device's V chunks active per tick, so the
    schedule is a uniform SPMD scan and ``jax.grad`` of it IS the
    interleaved backward. See the module docstring for the bubble math.
    """
    pp = mesh.shape[axis]
    B, S, D = x.shape
    L = jax.tree.leaves(blocks)[0].shape[0]
    if V < 1:
        raise ValueError(f"pp_virtual_stages={V} must be >= 1")
    if L % (V * pp):
        raise ValueError(
            f"n_layers {L} not divisible by pp*pp_virtual_stages "
            f"({pp}*{V})"
        )
    if M > pp:
        raise ValueError(
            f"interleaved schedule needs pp_microbatches ({M}) <= pp "
            f"({pp}): a device may only have one active chunk per tick; "
            f"raise pp_virtual_stages (not M) to amortize the bubble"
        )
    mb = B // M
    Lc = L // (V * pp)

    # Round-robin chunk layout: device d owns chunks {j*pp + d}. The
    # stacked params are sharded contiguously on the layer dim, so this
    # static gather is a per-step resharding of the stage params (cost ~
    # one ZeRO-3 param all-gather; see module docstring).
    perm = jnp.asarray(
        [
            (j * pp + d) * Lc + i
            for d in range(pp)
            for j in range(V)
            for i in range(Lc)
        ],
        jnp.int32,
    )
    staged = jax.tree.map(
        lambda a: jnp.take(a, perm, axis=0).reshape(
            pp, V, Lc, *a.shape[1:]
        ),
        blocks,
    )
    x_mb = x.reshape(M, mb, S, D)

    def local(x_mb, staged, rs_mb):
        chunks = jax.tree.map(lambda a: a[0], staged)   # [V, Lc, ...]
        stage = lax.axis_index(axis)
        npp = lax.axis_size(axis)
        T = M + V * npp - 1
        ring = [(i, (i + 1) % npp) for i in range(npp)]
        is_last = stage == npp - 1

        def run_chunk(c, j, rs):
            cp = jax.tree.map(
                lambda a: lax.dynamic_index_in_dim(a, j, 0, keepdims=False),
                chunks,
            )

            def scan_fn(h, bp):
                y, aux = call(h, bp, rs)
                return y, aux

            y, aux = lax.scan(scan_fn, c, cp)
            return y, aux.sum()

        def tick(carry, t):
            state, outputs, aux_acc = carry
            dt = t - stage
            j = jnp.clip(dt // npp, 0, V - 1)       # this device's chunk lap
            active = (dt >= 0) & (dt % npp < M) & (dt // npp < V)
            # Chunk 0 (device 0, lap 0) injects fresh microbatches; every
            # other (device, lap) consumes the ppermuted activation.
            inject = x_mb[jnp.clip(t, 0, M - 1)]
            cur = jnp.where((stage == 0) & (t < M), inject, state)
            # Active microbatch index: dt mod npp (lap-invariant); row
            # state is a static lookup, never ppermuted.
            rs = jax.tree.map(
                lambda a: a[jnp.clip(dt % npp, 0, M - 1)], rs_mb
            )
            out, aux_t = run_chunk(cur, j, rs)
            aux_acc = aux_acc + jnp.where(active, aux_t, 0.0)
            # The final chunk (device pp-1, lap V-1) emits mb m at tick
            # t = m + V*pp - 1.
            out_idx = jnp.clip(t - (V * npp - 1), 0, M - 1)
            emit = is_last & active & (j == V - 1)
            outputs = outputs.at[out_idx].set(
                jnp.where(emit, out, outputs[out_idx])
            )
            state = lax.ppermute(out, axis, ring)
            return (state, outputs, aux_acc), None

        carry0 = jax.tree.map(
            lambda a: lax.pcast(a, (axis,), to="varying"),
            (
                jnp.zeros_like(x_mb[0]),
                jnp.zeros_like(x_mb),
                jnp.zeros((), jnp.float32),
            ),
        )
        (_, outputs, aux_acc), _ = lax.scan(tick, carry0, jnp.arange(T))
        outputs = lax.psum(
            jnp.where(is_last, outputs, jnp.zeros_like(outputs)), axis
        )
        aux = lax.psum(aux_acc, axis) / M
        return outputs, aux

    outputs, aux = jax.shard_map(
        local,
        mesh=mesh,
        in_specs=(P(), P(axis), jax.tree.map(lambda _: P(), rs_mb)),
        out_specs=(P(), P()),
        axis_names={axis},
    )(x_mb, staged, rs_mb)
    return outputs.reshape(B, S, D), aux
