"""Logical-axis -> mesh-axis sharding rules.

One rule table keyed by logical axis names (the torchtitan/MaxText-style
solution to composable dp×fsdp×tp×sp×ep sharding, SURVEY.md §8) replaces the
reference's per-strategy wrapper code paths. Model code annotates parameters
with logical names (models.transformer.param_logical_axes); this module turns
them into ``NamedSharding``s; jit + XLA turn those into collectives:

  - grads psum over dp          == DDP all-reduce       (BASELINE.json:8)
  - param gather-on-use on fsdp == FSDP/ZeRO-3          (BASELINE.json:9)
  - heads/mlp matmul split on tp == megatron-style TP
  - expert dispatch on ep       == MoE all-to-all        (BASELINE.json:10)
"""

from __future__ import annotations

from typing import Any, Callable, Mapping, Optional, Sequence, Union

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

MeshAxes = Union[None, str, tuple[str, ...]]

# Logical axis name -> mesh axis (or axes). None = replicated along that dim.
DEFAULT_RULES: dict[str, MeshAxes] = {
    # Activations.
    "batch": ("dp", "fsdp"),   # fsdp shards the batch too (ZeRO data-parallel)
    "seq": "sp",
    # Parameters.
    "embed": "fsdp",           # ZeRO-3: gather-on-use along the embed axis
    "heads": "tp",
    "kv_heads": "tp",
    "mlp": "tp",
    "vocab": "tp",
    "expert": "ep",
    # Stacked-layer scan axis: contiguous L/pp chunks per pipeline stage
    # (parallel.pipeline strips the stage dim inside its shard_map). With
    # pp=1 the axis is elided and this is a no-op.
    "layers": "pp",
    "pos": None,
}


def logical_to_spec(
    logical_axes: Sequence[str],
    rules: Mapping[str, MeshAxes] = DEFAULT_RULES,
    mesh: Optional[Mesh] = None,
) -> P:
    """Map a tuple of logical axis names to a PartitionSpec.

    If ``mesh`` is given, mesh axes of size 1 are elided (cosmetic: P(None)
    instead of P('tp') when tp=1) and duplicate mesh-axis use across dims
    raises (a logical tree bug).
    """
    spec: list[MeshAxes] = []
    used: set[str] = set()
    for name in logical_axes:
        if name not in rules:
            raise ValueError(f"no sharding rule for logical axis {name!r}")
        target = rules[name]
        if target is None:
            spec.append(None)
            continue
        axes = (target,) if isinstance(target, str) else tuple(target)
        if mesh is not None:
            axes = tuple(a for a in axes if mesh.shape.get(a, 1) > 1)
        live = []
        for a in axes:
            if a in used:
                raise ValueError(
                    f"mesh axis {a!r} used twice in logical axes {logical_axes}"
                )
            used.add(a)
            live.append(a)
        if not live:
            spec.append(None)
        elif len(live) == 1:
            spec.append(live[0])
        else:
            spec.append(tuple(live))
    return P(*spec)


def param_shardings(
    mesh: Mesh,
    logical_tree: Any,
    rules: Mapping[str, MeshAxes] = DEFAULT_RULES,
) -> Any:
    """Pytree of NamedShardings matching a logical-axes pytree."""
    def leaf(axes):
        return NamedSharding(mesh, logical_to_spec(axes, rules, mesh))

    return jax.tree.map(
        leaf, logical_tree, is_leaf=lambda x: isinstance(x, tuple)
    )


def zero1_update_dim(
    shape: Sequence[int], spec: P, n: int
) -> Optional[int]:
    """Pick the dimension a leaf's weight update shards over for ZeRO-1
    (PAPERS.md 2004.13336): the largest dim divisible by the axis size
    ``n`` among dims no other mesh axis already shards (ties break to the
    lowest index, so the choice is deterministic across processes). None
    when no dim qualifies — that leaf's update stays replicated (norm
    scales / biases; the wire and memory saving there is nil anyway)."""
    entries = tuple(spec) + (None,) * (len(shape) - len(tuple(spec)))
    best: Optional[int] = None
    for d, size in enumerate(shape):
        if entries[d] is not None or size == 0 or size % n:
            continue
        if best is None or size > shape[best]:
            best = d
    return best


def zero1_shardings(
    mesh: Mesh,
    logical_tree: Any,
    shapes: Any,
    rules: Mapping[str, MeshAxes] = DEFAULT_RULES,
    *,
    axis: str = "dp",
) -> tuple[Any, Any]:
    """(shardings, dims) pytrees for ZeRO-1 dp-sharded optimizer state.

    Each leaf's base spec comes from its logical axes (exactly
    ``param_shardings``); ``axis`` is then inserted at the dim
    ``zero1_update_dim`` picks, so master params and Adam moments live
    1/|axis| per replica while composing with fsdp/tp sharding on the
    other dims. ``dims`` records the chosen dim per leaf (-1 =
    replicated; an int sentinel, not None, because None leaves vanish
    from a pytree) — the explicit shard_map wire path needs it to place
    the reduce-scatter/all-gather on the right dimension.
    """
    n = mesh.shape.get(axis, 1)

    def leaf(axes, shape_leaf):
        shape = tuple(shape_leaf.shape)
        base = logical_to_spec(axes, rules, mesh)
        if n <= 1:
            return NamedSharding(mesh, base), -1
        d = zero1_update_dim(shape, base, n)
        if d is None:
            return NamedSharding(mesh, base), -1
        entries = list(tuple(base)) + [None] * (len(shape) - len(tuple(base)))
        entries[d] = axis
        return NamedSharding(mesh, P(*entries)), d

    pairs = jax.tree.map(
        leaf, logical_tree, shapes,
        is_leaf=lambda x: isinstance(x, tuple),
    )
    is_pair = lambda x: (
        isinstance(x, tuple) and len(x) == 2
        and isinstance(x[0], NamedSharding)
    )
    shardings = jax.tree.map(lambda t: t[0], pairs, is_leaf=is_pair)
    dims = jax.tree.map(lambda t: t[1], pairs, is_leaf=is_pair)
    return shardings, dims


def batch_sharding(
    mesh: Mesh,
    rules: Mapping[str, MeshAxes] = DEFAULT_RULES,
    *,
    shard_seq: bool = True,
) -> NamedSharding:
    """Sharding for [B, S] token batches (and [B, S] masks/positions)."""
    seq = "seq" if shard_seq else "pos"
    spec = logical_to_spec(("batch", seq), {**rules, "pos": None}, mesh)
    return NamedSharding(mesh, spec)


def shard_init(
    init_fn: Callable[[], Any],
    shardings: Any,
) -> Any:
    """Run an initializer with outputs materialized directly into shardings.

    jit with out_shardings means each device only ever materializes its own
    shard — required to init 70B-class models without host OOM
    (SURVEY.md §4 stack A, model.build).
    """
    return jax.jit(init_fn, out_shardings=shardings)()
