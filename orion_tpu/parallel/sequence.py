"""Sequence / context parallelism: ring attention and Ulysses.

The reference reaches long contexts by sharding the sequence axis over
devices and moving KV blocks (ring, NCCL p2p) or resharding heads<->sequence
(Ulysses, NCCL all-to-all) around attention (SURVEY.md §3 "SP / CP / ring
attention", "Ulysses"). TPU-native equivalents, per SURVEY.md §6
"Long-context":

  - **ring attention** — activations stay sequence-sharded on the ``sp`` mesh
    axis; inside a ``shard_map``, KV blocks rotate around the ``sp`` ring via
    ``lax.ppermute`` while each device accumulates blockwise-stable softmax
    (log-sum-exp merge) for its local queries. Communication is O(S/sp) per
    step and overlaps with the block matmuls under XLA latency hiding.
  - **Ulysses** — a head<->sequence ``lax.all_to_all`` gives every device the
    full sequence for a 1/sp slice of heads; plain (flash) attention runs
    locally, then the inverse all-to-all restores sequence sharding.

Both compose with the batch (dp/fsdp) and head (tp) mesh axes: all specs
below carry those axes through the shard_map. Everything is differentiable
(ppermute/all_to_all have exact transposes), so the same code path serves
training and inference.

Causal load balance: with contiguous sequence blocks, device i only attends
ring blocks src <= i, so later devices do more work than earlier ones; the
fully-masked blocks are skipped via lax.cond (no wasted matmuls), but the
skew remains. ``method="ring_striped"`` fixes it: a striped block-to-device
assignment (the zigzag-class layout) gives every device sp evenly-spaced
slices of the sequence, equalizing work per ring step — see
``_ring_striped_local``.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from orion_tpu.ops.attention import NEG_INF, _gqa_expand

BatchAxes = Tuple[str, ...]


# ---------------------------------------------------------------------------
# Blockwise attention with log-sum-exp state (the ring accumulation unit)
# ---------------------------------------------------------------------------


def _block_attend(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    q_offset: jax.Array,
    kv_offset: jax.Array,
    causal: bool,
    q_segment_ids: Optional[jax.Array],
    kv_segment_ids: Optional[jax.Array],
    logit_softcap: Optional[float],
    q_positions: Optional[jax.Array] = None,
    kv_positions: Optional[jax.Array] = None,
    window: Optional[int] = None,
) -> tuple[jax.Array, jax.Array]:
    """Attention of local queries against one KV block.

    q: [b, sq, n, h]; k, v: [b, skv, kv, h]. Returns (out [b, sq, n, h] f32,
    normalized within the block, and lse [b, n, sq] f32, the log-sum-exp of
    the block's logits; -inf rows mean "nothing attended here").
    Causal masking uses explicit ``q_positions``/``kv_positions`` ([sq]/
    [skv]) when given (striped layouts), else index + offset. ``window``
    (requires causal) keeps only pairs with 0 <= q_pos - kv_pos < window.
    """
    n_heads, head_dim = q.shape[2], q.shape[3]
    k = _gqa_expand(k, n_heads)
    v = _gqa_expand(v, n_heads)

    scale = head_dim ** -0.5
    logits = jnp.einsum(
        "bqnh,bknh->bnqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if logit_softcap is not None:
        logits = logit_softcap * jnp.tanh(logits / logit_softcap)

    mask = None
    if causal:
        if q_positions is not None:
            q_pos, kv_pos = q_positions, kv_positions
        else:
            q_pos = q_offset + jnp.arange(q.shape[1])
            kv_pos = kv_offset + jnp.arange(k.shape[1])
        dist = q_pos[:, None] - kv_pos[None, :]           # [sq, skv]
        mask = dist >= 0
        if window is not None:
            mask &= dist < window
        mask = mask[None, None]                           # [1, 1, sq, skv]
    elif window is not None:
        raise ValueError("window requires causal attention")
    if q_segment_ids is not None:
        seg = q_segment_ids[:, None, :, None] == kv_segment_ids[:, None, None, :]
        mask = seg if mask is None else (mask & seg)
    if mask is not None:
        logits = jnp.where(mask, logits, NEG_INF)

    lse = jax.nn.logsumexp(logits, axis=-1)               # [b, n, sq]
    # Rows with every position masked have lse == NEG_INF-ish; zero them out.
    dead = lse <= NEG_INF / 2
    safe_lse = jnp.where(dead, 0.0, lse)
    probs = jnp.exp(logits - safe_lse[..., None])
    probs = jnp.where(dead[..., None], 0.0, probs)
    out = jnp.einsum(
        "bnqk,bknh->bqnh", probs, v, preferred_element_type=jnp.float32
    )
    lse = jnp.where(dead, -jnp.inf, lse)
    return out, lse


def _merge_blocks(
    o1: jax.Array, l1: jax.Array, o2: jax.Array, l2: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """Combine two normalized partial attentions via their log-sum-exps.

    o*: [b, sq, n, h] f32; l*: [b, n, sq] f32 (may be -inf).
    """
    m = jnp.maximum(l1, l2)
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    w1 = jnp.where(jnp.isfinite(l1), jnp.exp(l1 - m_safe), 0.0)
    w2 = jnp.where(jnp.isfinite(l2), jnp.exp(l2 - m_safe), 0.0)
    denom = w1 + w2
    lse = jnp.where(denom > 0, m_safe + jnp.log(jnp.maximum(denom, 1e-37)),
                    -jnp.inf)
    scale1 = jnp.where(denom > 0, w1 / jnp.maximum(denom, 1e-37), 0.0)
    scale2 = jnp.where(denom > 0, w2 / jnp.maximum(denom, 1e-37), 0.0)
    # [b, n, sq] -> [b, sq, n, 1] for broadcasting against [b, sq, n, h].
    b1 = scale1.transpose(0, 2, 1)[..., None]
    b2 = scale2.transpose(0, 2, 1)[..., None]
    return o1 * b1 + o2 * b2, lse


# ---------------------------------------------------------------------------
# Ring attention
# ---------------------------------------------------------------------------


def _ring_scan(k, v, seg0, has_seg, axis, sp, idx, attend, n_steps=None):
    """Shared ring skeleton: attend the local block, then ``n_steps``
    (default sp-1) rotate->attend->merge steps (no trailing rotation whose
    result is discarded). ``attend(k, v, seg, src, is_first)`` returns
    (o_f32, lse); ``is_first`` is static (True only for the local step-0
    block, where src == idx by construction).

    ``n_steps < sp-1`` statically truncates the ring: with a sliding window
    over contiguous blocks, every device's step-t source block sits exactly
    t*s_loc positions back, so steps wholly behind the window are dead for
    ALL devices at once — dropping them removes their ppermutes entirely
    (O(window) communication, not O(S)), not just their matmuls."""
    n_steps = sp - 1 if n_steps is None else n_steps
    perm = [(i, (i + 1) % sp) for i in range(sp)]
    o_acc, l_acc = attend(k, v, seg0, idx, True)

    def step(carry, t):
        k_cur, v_cur, seg_cur, o, l = carry
        k_cur = lax.ppermute(k_cur, axis, perm)
        v_cur = lax.ppermute(v_cur, axis, perm)
        if has_seg:
            seg_cur = lax.ppermute(seg_cur, axis, perm)
        src = jnp.mod(idx - t, sp)
        o_blk, l_blk = attend(k_cur, v_cur, seg_cur, src, False)
        o, l = _merge_blocks(o, l, o_blk, l_blk)
        return (k_cur, v_cur, seg_cur, o, l), None

    if n_steps > 0:
        (_, _, _, o_acc, _), _ = lax.scan(
            step, (k, v, seg0, o_acc, l_acc), jnp.arange(1, n_steps + 1)
        )
    return o_acc


def _ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    q_seg: Optional[jax.Array],
    kv_seg: Optional[jax.Array],
    *,
    axis: str,
    causal: bool,
    logit_softcap: Optional[float],
    impl: str = "xla",
    block_q: Optional[int] = None,
    block_kv: Optional[int] = None,
    window: Optional[int] = None,
    debug_asserts: bool = False,
) -> jax.Array:
    """Per-device ring attention body (runs inside shard_map).

    Under ``impl='pallas'`` the blockwise unit is the fused flash kernel via
    ``flash_attention_with_lse`` (the lse output feeds the ring merge); under
    'xla' it is the jnp math in _block_attend. Without a window, every ring
    position needs only a *static* mask config — the local diagonal block is
    causal at relative offset 0, fully-past blocks are unmasked, fully-future
    blocks are skipped — so the kernel never needs a traced q_offset.

    With ``window`` (sliding-window / Mistral long-context), past blocks
    carry their true global positions (idx/src * s_loc + iota) so the kernel
    masks by real sequence distance, and the ring scan is statically
    truncated to the steps that can reach the window at all (see _ring_scan):
    both the compute and the ppermute traffic become O(window), independent
    of the global sequence length.
    """
    from orion_tpu.ops._dispatch import resolve_impl

    use_pallas, interpret = resolve_impl(impl)
    sp = lax.axis_size(axis)
    idx = lax.axis_index(axis)
    s_loc = q.shape[1]
    has_seg = q_seg is not None
    windowed = causal and window is not None

    def block(k_, v_, seg_, src, diag: bool):
        """Attend local q against one KV block; diag => causally masked.

        Past blocks (diag=False) are unmasked — unless a window is active,
        in which case they attend causally by explicit global positions
        (causality is vacuous there since every kv precedes every q; the
        positions exist to measure the window distance).
        """
        qpos = kvpos = None
        if windowed and not diag:
            iota = jnp.arange(s_loc, dtype=jnp.int32)
            qpos = idx * s_loc + iota
            kvpos = src * s_loc + iota
            # Sanitizer hook (SURVEY.md §6): the ring's source/position
            # arithmetic runs where checkify cannot reach; a wrong src
            # would silently mask the wrong window. No-op unless
            # model.debug_asserts.
            from orion_tpu.runtime.asserts import device_assert

            device_assert(
                debug_asserts,
                (kvpos >= 0).all() & (kvpos < sp * s_loc).all()
                & (src >= 0) & (src < sp),
                "ring_positions",
                "ring step source/global positions out of range",
            )
        blk_causal = causal and (diag or windowed)
        if use_pallas:
            from orion_tpu.ops.pallas.flash_attention import (
                flash_attention_with_lse,
            )

            o, lse = flash_attention_with_lse(
                q, k_, v_,
                causal=blk_causal,
                q_segment_ids=q_seg if has_seg else None,
                kv_segment_ids=seg_ if has_seg else None,
                logit_softcap=logit_softcap,
                block_q=block_q,
                block_kv=block_kv,
                interpret=interpret,
                q_positions=qpos,
                kv_positions=kvpos,
                window=window if windowed else None,
            )
            return o.astype(jnp.float32), lse
        zero = jnp.zeros((), jnp.int32)
        return _block_attend(
            q, k_, v_,
            q_offset=zero, kv_offset=zero, causal=blk_causal,
            q_segment_ids=q_seg if has_seg else None,
            kv_segment_ids=seg_ if has_seg else None,
            logit_softcap=logit_softcap,
            q_positions=qpos, kv_positions=kvpos,
            window=window if windowed else None,
        )

    def empty(kv):
        b, sq, n, h = q.shape
        return (
            jnp.zeros((b, sq, n, h), jnp.float32),
            jnp.full((b, n, sq), -jnp.inf, jnp.float32),
        )

    def attend(k_, v_, seg_, src, is_first):
        # Step 0 (src == idx) is the causal diagonal. In the scan steps,
        # blocks entirely in the masked future (src > idx) contribute
        # nothing — skip their matmuls instead of masking them to -inf.
        # (The compute skew this leaves across the ring is what
        # method="ring_striped" fixes.)
        if is_first or not causal:
            return block(k_, v_, seg_, src, is_first and causal)
        return lax.cond(
            src < idx,
            lambda kv: block(*kv, src, False),
            empty,
            (k_, v_, seg_),
        )

    n_steps = None
    if windowed:
        # Step t's source block ends at global position (idx-t+1)*s_loc - 1;
        # its nearest pair distance to local q is (t-1)*s_loc + 1. Steps with
        # (t-1)*s_loc + 1 >= window are dead for every device: truncate.
        n_steps = min(sp - 1, max(0, (window - 2) // s_loc + 1))

    seg0 = kv_seg if has_seg else jnp.zeros((), jnp.int32)
    o_acc = _ring_scan(k, v, seg0, has_seg, axis, sp, idx, attend, n_steps)
    return o_acc.astype(q.dtype)


def _ring_striped_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    q_seg: Optional[jax.Array],
    kv_seg: Optional[jax.Array],
    *,
    axis: str,
    causal: bool,
    logit_softcap: Optional[float],
    impl: str = "xla",
    block_q: Optional[int] = None,
    block_kv: Optional[int] = None,
    window: Optional[int] = None,
    debug_asserts: bool = False,
) -> jax.Array:
    """Load-balanced ("zigzag-class") ring attention body.

    Contiguous sequence blocks skew causal ring work: device i attends i+1
    of sp blocks, so the ring's wall-clock is the LAST device's full-
    attention cost. This body first reshards to the STRIPED layout — one
    tiled all_to_all splits each contiguous shard into sp stripes and gives
    device d stripe d of every shard, i.e. sp evenly-spaced slices of the
    global sequence — so every device sees the same mix of early and late
    positions and does the same work each ring step (the striped-attention
    formulation of the zigzag fix planned in the module docstring).

    Masking can no longer be block-static: stripes carry their true global
    positions, and the blockwise unit masks/skips by explicit position
    arrays (flash kernel ``q_positions``/``kv_positions``; the dynamic
    min/max block-skip preserves the 2x causal saving). One inverse
    all_to_all restores the contiguous layout afterwards, so callers see
    identical semantics to plain ring.

    ``window`` composes naturally here: the explicit positions already
    measure true sequence distance, so it passes straight to the blockwise
    unit, and behind-window stripes fall out via the kernel's dynamic
    block-skip. (Unlike plain ring, no ring STEP can be truncated — every
    step's stripes span the whole sequence — so windowed long-context
    training prefers method="ring"; this path keeps the load balance.)
    """
    from orion_tpu.ops._dispatch import resolve_impl

    use_pallas, interpret = resolve_impl(impl)
    sp = lax.axis_size(axis)
    idx = lax.axis_index(axis)
    s_loc = q.shape[1]
    if s_loc % sp:
        raise ValueError(
            f"striped ring needs local seq {s_loc} divisible by sp={sp} "
            f"(global seq % sp^2 == 0)"
        )
    c = s_loc // sp
    has_seg = q_seg is not None

    def to_striped(t, seq_axis=1):
        return lax.all_to_all(
            t, axis, split_axis=seq_axis, concat_axis=seq_axis, tiled=True
        )

    q = to_striped(q)
    k = to_striped(k)
    v = to_striped(v)
    if has_seg:
        q_seg = to_striped(q_seg)
        kv_seg = to_striped(kv_seg)

    # Global positions of the local stripes: stripe a of device d covers
    # [a*s_loc + d*c, a*s_loc + (d+1)*c).
    base = (jnp.arange(sp, dtype=jnp.int32) * s_loc)[:, None]
    off = jnp.arange(c, dtype=jnp.int32)[None, :]
    qpos = (base + idx * c + off).reshape(-1)            # [s_loc]

    def attend(k_, v_, seg_, src, is_first):
        kvpos = (base + src * c + off).reshape(-1)
        # Sanitizer hook — see _ring_attention_local.block.
        from orion_tpu.runtime.asserts import device_assert

        device_assert(
            debug_asserts,
            (kvpos >= 0).all() & (kvpos < sp * s_loc).all()
            & (src >= 0) & (src < sp),
            "ring_striped_positions",
            "striped ring step source/global positions out of range",
        )
        if use_pallas:
            from orion_tpu.ops.pallas.flash_attention import (
                flash_attention_with_lse,
            )

            o, lse = flash_attention_with_lse(
                q, k_, v_,
                causal=causal,
                q_segment_ids=q_seg if has_seg else None,
                kv_segment_ids=seg_ if has_seg else None,
                logit_softcap=logit_softcap,
                # Clamp tiles to the stripe length so the dynamic causal
                # block-skip works at stripe granularity (but never below
                # the 128-lane tile the hardware wants).
                block_q=min(block_q or 1024, max(c, 128)),
                block_kv=min(block_kv or 1024, max(c, 128)),
                interpret=interpret,
                q_positions=qpos if causal else None,
                kv_positions=kvpos if causal else None,
                window=window if causal else None,
            )
            return o.astype(jnp.float32), lse
        zero = jnp.zeros((), jnp.int32)
        return _block_attend(
            q, k_, v_,
            q_offset=zero, kv_offset=zero, causal=causal,
            q_segment_ids=q_seg if has_seg else None,
            kv_segment_ids=seg_ if has_seg else None,
            logit_softcap=logit_softcap,
            q_positions=qpos if causal else None,
            kv_positions=kvpos if causal else None,
            window=window if causal else None,
        )

    seg0 = kv_seg if has_seg else jnp.zeros((), jnp.int32)
    o_acc = _ring_scan(k, v, seg0, has_seg, axis, sp, idx, attend)
    # Inverse a2a (the stripe exchange is an involution): back to the
    # caller's contiguous layout.
    return to_striped(o_acc.astype(q.dtype))


# ---------------------------------------------------------------------------
# Ulysses attention
# ---------------------------------------------------------------------------


def _ulysses_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    q_seg: Optional[jax.Array],
    kv_seg: Optional[jax.Array],
    *,
    axis: str,
    causal: bool,
    logit_softcap: Optional[float],
    impl: str = "xla",
    block_q: Optional[int] = None,
    block_kv: Optional[int] = None,
    window: Optional[int] = None,
    debug_asserts: bool = False,   # accepted for body-signature uniformity;
    #                                ulysses has no index arithmetic to check
) -> jax.Array:
    """Per-device Ulysses body: a2a to full-seq / sharded-heads, attend, a2a
    back (runs inside shard_map). ``impl`` selects the local attention kernel
    (the Pallas flash kernel under impl='pallas'); ``window`` passes straight
    to it (the local view is the full sequence, so index distance is true
    sequence distance)."""
    from orion_tpu.ops.attention import attention

    sp = lax.axis_size(axis)
    # [b, s_loc, n_loc, h] -> [b, S, n_loc/sp, h]
    qg = lax.all_to_all(q, axis, split_axis=2, concat_axis=1, tiled=True)
    kg = lax.all_to_all(k, axis, split_axis=2, concat_axis=1, tiled=True)
    vg = lax.all_to_all(v, axis, split_axis=2, concat_axis=1, tiled=True)
    if q_seg is not None:
        q_seg = lax.all_gather(q_seg, axis, axis=1, tiled=True)   # [b, S]
        kv_seg = lax.all_gather(kv_seg, axis, axis=1, tiled=True)
    out = attention(
        qg, kg, vg,
        causal=causal,
        q_segment_ids=q_seg,
        kv_segment_ids=kv_seg,
        logit_softcap=logit_softcap,
        window=window,
        block_q=block_q,
        block_kv=block_kv,
        impl=impl,
    )
    # [b, S, n_loc/sp, h] -> [b, s_loc, n_loc, h]
    return lax.all_to_all(out, axis, split_axis=1, concat_axis=2, tiled=True)


# ---------------------------------------------------------------------------
# Public entry points (build the shard_map around the local bodies)
# ---------------------------------------------------------------------------


def _specs(axis: str, batch_axes: BatchAxes, head_axis: Optional[str]):
    qkv = P(batch_axes, axis, head_axis, None)
    seg = P(batch_axes, axis)
    return qkv, seg


def sequence_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    method: str = "ring",
    axis: str = "sp",
    causal: bool = True,
    q_segment_ids: Optional[jax.Array] = None,
    kv_segment_ids: Optional[jax.Array] = None,
    logit_softcap: Optional[float] = None,
    batch_axes: BatchAxes = ("dp", "fsdp"),
    head_axis: Optional[str] = "tp",
    impl: str = "xla",
    block_q: Optional[int] = None,
    block_kv: Optional[int] = None,
    window: Optional[int] = None,
    debug_asserts: bool = False,
) -> jax.Array:
    """Sequence-parallel grouped-query causal attention.

    q: [B, S, N, H]; k, v: [B, S, K, H] (global shapes; jit keeps them
    sequence-sharded on ``axis``). Semantics match ``ops.attention``; the
    method picks the communication pattern:

      - "ring":         ppermute KV rotation, O(S/sp) comm per step.
      - "ring_striped": ring over the load-balanced striped layout (one
                        head-preserving seq all_to_all each way); equalizes
                        the causal skew across devices. Needs S % sp^2 == 0.
      - "ulysses":      head<->sequence all_to_all; K % (sp*tp) == 0.

    ``window`` (sliding-window / Mistral-family, requires causal) composes
    with every method; under "ring" both compute and ppermute traffic shrink
    to O(window) via static ring-step truncation (see _ring_attention_local).
    """
    if method not in ("ring", "ring_striped", "ulysses"):
        raise ValueError(f"unknown sequence method {method!r}")
    if window is not None and (not causal or window < 1):
        raise ValueError(
            f"window={window} requires causal attention and window >= 1"
        )
    sp = mesh.shape.get(axis, 1)
    if method == "ulysses":
        tp = mesh.shape.get(head_axis, 1) if head_axis else 1
        n_heads, n_kv = q.shape[2], k.shape[2]
        if n_heads % (sp * tp):
            raise ValueError(
                f"ulysses needs n_heads ({n_heads}) divisible by sp*tp "
                f"({sp}*{tp})"
            )
        if n_kv % (sp * tp):
            # The head<->seq all_to_all moves whole heads; replicate grouped
            # KV heads up to a divisible count (costs comm volume, like every
            # Ulysses implementation under GQA).
            reps = (sp * tp) // n_kv
            if n_kv * reps != sp * tp or n_heads % (n_kv * reps):
                raise ValueError(
                    f"ulysses cannot expand kv_heads ({n_kv}) to a multiple "
                    f"of sp*tp ({sp}*{tp}) compatible with n_heads {n_heads}"
                )
            k = jnp.repeat(k, reps, axis=2)
            v = jnp.repeat(v, reps, axis=2)
    if q.shape[1] % sp:
        raise ValueError(f"seq len {q.shape[1]} not divisible by {axis}={sp}")

    body = {
        "ring": _ring_attention_local,
        "ring_striped": _ring_striped_local,
        "ulysses": _ulysses_local,
    }[method]
    fn = partial(
        body, axis=axis, causal=causal, logit_softcap=logit_softcap, impl=impl,
        block_q=block_q, block_kv=block_kv, window=window,
        debug_asserts=debug_asserts,
    )
    qkv_spec, seg_spec = _specs(axis, batch_axes, head_axis)

    if q_segment_ids is None:
        mapped = jax.shard_map(
            lambda q_, k_, v_: fn(q_, k_, v_, None, None),
            mesh=mesh,
            in_specs=(qkv_spec, qkv_spec, qkv_spec),
            out_specs=qkv_spec,
            check_vma=False,
        )
        return mapped(q, k, v)

    mapped = jax.shard_map(
        fn,
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, seg_spec, seg_spec),
        out_specs=qkv_spec,
        check_vma=False,
    )
    return mapped(q, k, v, q_segment_ids, kv_segment_ids)


def ring_attention(q, k, v, mesh, **kw) -> jax.Array:
    """Ring attention over the ``sp`` axis (see sequence_attention)."""
    return sequence_attention(q, k, v, mesh, method="ring", **kw)


def ulysses_attention(q, k, v, mesh, **kw) -> jax.Array:
    """Ulysses attention over the ``sp`` axis (see sequence_attention)."""
    return sequence_attention(q, k, v, mesh, method="ulysses", **kw)
