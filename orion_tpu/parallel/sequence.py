"""Sequence / context parallelism: ring attention and Ulysses.

The reference reaches long contexts by sharding the sequence axis over
devices and moving KV blocks (ring, NCCL p2p) or resharding heads<->sequence
(Ulysses, NCCL all-to-all) around attention (SURVEY.md §3 "SP / CP / ring
attention", "Ulysses"). TPU-native equivalents, per SURVEY.md §6
"Long-context":

  - **ring attention** — activations stay sequence-sharded on the ``sp`` mesh
    axis; inside a ``shard_map``, KV blocks rotate around the ``sp`` ring via
    ``lax.ppermute`` while each device accumulates blockwise-stable softmax
    (log-sum-exp merge) for its local queries. Communication is O(S/sp) per
    step and overlaps with the block matmuls under XLA latency hiding.
  - **Ulysses** — a head<->sequence ``lax.all_to_all`` gives every device the
    full sequence for a 1/sp slice of heads; plain (flash) attention runs
    locally, then the inverse all-to-all restores sequence sharding.

Both compose with the batch (dp/fsdp) and head (tp) mesh axes: all specs
below carry those axes through the shard_map. Everything is differentiable
(ppermute/all_to_all have exact transposes), so the same code path serves
training and inference.

Causal load balance: with contiguous sequence blocks, device i only attends
ring blocks src <= i, so later devices do more work than earlier ones; the
fully-masked blocks are skipped via lax.cond (no wasted matmuls), but the
skew remains — a striped ("zigzag") block-to-device assignment that equalizes
work per device is the planned follow-up and only changes the position
bookkeeping here, not the callers.
"""

from __future__ import annotations

from functools import partial
from typing import Optional, Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from orion_tpu.ops.attention import NEG_INF, _gqa_expand

BatchAxes = Tuple[str, ...]


# ---------------------------------------------------------------------------
# Blockwise attention with log-sum-exp state (the ring accumulation unit)
# ---------------------------------------------------------------------------


def _block_attend(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    q_offset: jax.Array,
    kv_offset: jax.Array,
    causal: bool,
    q_segment_ids: Optional[jax.Array],
    kv_segment_ids: Optional[jax.Array],
    logit_softcap: Optional[float],
) -> tuple[jax.Array, jax.Array]:
    """Attention of local queries against one KV block.

    q: [b, sq, n, h]; k, v: [b, skv, kv, h]. Returns (out [b, sq, n, h] f32,
    normalized within the block, and lse [b, n, sq] f32, the log-sum-exp of
    the block's logits; -inf rows mean "nothing attended here").
    """
    n_heads, head_dim = q.shape[2], q.shape[3]
    k = _gqa_expand(k, n_heads)
    v = _gqa_expand(v, n_heads)

    scale = head_dim ** -0.5
    logits = jnp.einsum(
        "bqnh,bknh->bnqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if logit_softcap is not None:
        logits = logit_softcap * jnp.tanh(logits / logit_softcap)

    mask = None
    if causal:
        q_pos = q_offset + jnp.arange(q.shape[1])
        kv_pos = kv_offset + jnp.arange(k.shape[1])
        mask = q_pos[:, None] >= kv_pos[None, :]          # [sq, skv]
        mask = mask[None, None]                           # [1, 1, sq, skv]
    if q_segment_ids is not None:
        seg = q_segment_ids[:, None, :, None] == kv_segment_ids[:, None, None, :]
        mask = seg if mask is None else (mask & seg)
    if mask is not None:
        logits = jnp.where(mask, logits, NEG_INF)

    lse = jax.nn.logsumexp(logits, axis=-1)               # [b, n, sq]
    # Rows with every position masked have lse == NEG_INF-ish; zero them out.
    dead = lse <= NEG_INF / 2
    safe_lse = jnp.where(dead, 0.0, lse)
    probs = jnp.exp(logits - safe_lse[..., None])
    probs = jnp.where(dead[..., None], 0.0, probs)
    out = jnp.einsum(
        "bnqk,bknh->bqnh", probs, v, preferred_element_type=jnp.float32
    )
    lse = jnp.where(dead, -jnp.inf, lse)
    return out, lse


def _merge_blocks(
    o1: jax.Array, l1: jax.Array, o2: jax.Array, l2: jax.Array
) -> tuple[jax.Array, jax.Array]:
    """Combine two normalized partial attentions via their log-sum-exps.

    o*: [b, sq, n, h] f32; l*: [b, n, sq] f32 (may be -inf).
    """
    m = jnp.maximum(l1, l2)
    m_safe = jnp.where(jnp.isfinite(m), m, 0.0)
    w1 = jnp.where(jnp.isfinite(l1), jnp.exp(l1 - m_safe), 0.0)
    w2 = jnp.where(jnp.isfinite(l2), jnp.exp(l2 - m_safe), 0.0)
    denom = w1 + w2
    lse = jnp.where(denom > 0, m_safe + jnp.log(jnp.maximum(denom, 1e-37)),
                    -jnp.inf)
    scale1 = jnp.where(denom > 0, w1 / jnp.maximum(denom, 1e-37), 0.0)
    scale2 = jnp.where(denom > 0, w2 / jnp.maximum(denom, 1e-37), 0.0)
    # [b, n, sq] -> [b, sq, n, 1] for broadcasting against [b, sq, n, h].
    b1 = scale1.transpose(0, 2, 1)[..., None]
    b2 = scale2.transpose(0, 2, 1)[..., None]
    return o1 * b1 + o2 * b2, lse


# ---------------------------------------------------------------------------
# Ring attention
# ---------------------------------------------------------------------------


def _ring_attention_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    q_seg: Optional[jax.Array],
    kv_seg: Optional[jax.Array],
    *,
    axis: str,
    causal: bool,
    logit_softcap: Optional[float],
    impl: str = "xla",
    block_q: Optional[int] = None,
    block_kv: Optional[int] = None,
) -> jax.Array:
    """Per-device ring attention body (runs inside shard_map).

    Under ``impl='pallas'`` the blockwise unit is the fused flash kernel via
    ``flash_attention_with_lse`` (the lse output feeds the ring merge); under
    'xla' it is the jnp math in _block_attend. Every ring position needs only
    a *static* mask config — the local diagonal block is causal at relative
    offset 0, fully-past blocks are unmasked, fully-future blocks are skipped
    — so the kernel never needs a traced q_offset.
    """
    from orion_tpu.ops._dispatch import resolve_impl

    use_pallas, interpret = resolve_impl(impl)
    sp = lax.axis_size(axis)
    idx = lax.axis_index(axis)
    perm = [(i, (i + 1) % sp) for i in range(sp)]
    has_seg = q_seg is not None

    def block(k_, v_, seg_, diag: bool):
        """Attend local q against one KV block; diag => causally masked."""
        if use_pallas:
            from orion_tpu.ops.pallas.flash_attention import (
                flash_attention_with_lse,
            )

            o, lse = flash_attention_with_lse(
                q, k_, v_,
                causal=causal and diag,
                q_segment_ids=q_seg if has_seg else None,
                kv_segment_ids=seg_ if has_seg else None,
                logit_softcap=logit_softcap,
                block_q=block_q,
                block_kv=block_kv,
                interpret=interpret,
            )
            return o.astype(jnp.float32), lse
        zero = jnp.zeros((), jnp.int32)
        return _block_attend(
            q, k_, v_,
            q_offset=zero, kv_offset=zero, causal=causal and diag,
            q_segment_ids=q_seg if has_seg else None,
            kv_segment_ids=seg_ if has_seg else None,
            logit_softcap=logit_softcap,
        )

    # Step 0 attends the local (diagonal) KV block; the scan then does
    # exactly sp-1 rotate->attend steps (no trailing rotation whose result
    # is discarded).
    seg0 = kv_seg if has_seg else jnp.zeros((), jnp.int32)
    o_acc, l_acc = block(k, v, seg0, True)

    def empty(kv):
        b, sq, n, h = q.shape
        return (
            jnp.zeros((b, sq, n, h), jnp.float32),
            jnp.full((b, n, sq), -jnp.inf, jnp.float32),
        )

    def step(carry, t):
        k_cur, v_cur, seg_cur, o, l = carry
        k_cur = lax.ppermute(k_cur, axis, perm)
        v_cur = lax.ppermute(v_cur, axis, perm)
        if has_seg:
            seg_cur = lax.ppermute(seg_cur, axis, perm)
        src = jnp.mod(idx - t, sp)
        if causal:
            # Blocks entirely in the masked future (src > idx) contribute
            # nothing; skip their matmuls instead of masking them to -inf.
            # (The compute skew this leaves across the ring is resolved the
            # standard way — see the module docstring on striping.)
            o_blk, l_blk = lax.cond(
                src < idx,
                lambda kv: block(*kv, False),
                empty,
                (k_cur, v_cur, seg_cur),
            )
        else:
            o_blk, l_blk = block(k_cur, v_cur, seg_cur, False)
        o, l = _merge_blocks(o, l, o_blk, l_blk)
        return (k_cur, v_cur, seg_cur, o, l), None

    if sp > 1:
        (_, _, _, o_acc, _), _ = lax.scan(
            step, (k, v, seg0, o_acc, l_acc), jnp.arange(1, sp)
        )
    return o_acc.astype(q.dtype)


# ---------------------------------------------------------------------------
# Ulysses attention
# ---------------------------------------------------------------------------


def _ulysses_local(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    q_seg: Optional[jax.Array],
    kv_seg: Optional[jax.Array],
    *,
    axis: str,
    causal: bool,
    logit_softcap: Optional[float],
    impl: str = "xla",
    block_q: Optional[int] = None,
    block_kv: Optional[int] = None,
) -> jax.Array:
    """Per-device Ulysses body: a2a to full-seq / sharded-heads, attend, a2a
    back (runs inside shard_map). ``impl`` selects the local attention kernel
    (the Pallas flash kernel under impl='pallas')."""
    from orion_tpu.ops.attention import attention

    sp = lax.axis_size(axis)
    # [b, s_loc, n_loc, h] -> [b, S, n_loc/sp, h]
    qg = lax.all_to_all(q, axis, split_axis=2, concat_axis=1, tiled=True)
    kg = lax.all_to_all(k, axis, split_axis=2, concat_axis=1, tiled=True)
    vg = lax.all_to_all(v, axis, split_axis=2, concat_axis=1, tiled=True)
    if q_seg is not None:
        q_seg = lax.all_gather(q_seg, axis, axis=1, tiled=True)   # [b, S]
        kv_seg = lax.all_gather(kv_seg, axis, axis=1, tiled=True)
    out = attention(
        qg, kg, vg,
        causal=causal,
        q_segment_ids=q_seg,
        kv_segment_ids=kv_seg,
        logit_softcap=logit_softcap,
        block_q=block_q,
        block_kv=block_kv,
        impl=impl,
    )
    # [b, S, n_loc/sp, h] -> [b, s_loc, n_loc, h]
    return lax.all_to_all(out, axis, split_axis=1, concat_axis=2, tiled=True)


# ---------------------------------------------------------------------------
# Public entry points (build the shard_map around the local bodies)
# ---------------------------------------------------------------------------


def _specs(axis: str, batch_axes: BatchAxes, head_axis: Optional[str]):
    qkv = P(batch_axes, axis, head_axis, None)
    seg = P(batch_axes, axis)
    return qkv, seg


def sequence_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    mesh: Mesh,
    *,
    method: str = "ring",
    axis: str = "sp",
    causal: bool = True,
    q_segment_ids: Optional[jax.Array] = None,
    kv_segment_ids: Optional[jax.Array] = None,
    logit_softcap: Optional[float] = None,
    batch_axes: BatchAxes = ("dp", "fsdp"),
    head_axis: Optional[str] = "tp",
    impl: str = "xla",
    block_q: Optional[int] = None,
    block_kv: Optional[int] = None,
) -> jax.Array:
    """Sequence-parallel grouped-query causal attention.

    q: [B, S, N, H]; k, v: [B, S, K, H] (global shapes; jit keeps them
    sequence-sharded on ``axis``). Semantics match ``ops.attention``; the
    method picks the communication pattern:

      - "ring":    ppermute KV rotation, O(S/sp) comm per step.
      - "ulysses": head<->sequence all_to_all; requires K % (sp*tp) == 0.
    """
    if method not in ("ring", "ulysses"):
        raise ValueError(f"unknown sequence method {method!r}")
    sp = mesh.shape.get(axis, 1)
    if method == "ulysses":
        tp = mesh.shape.get(head_axis, 1) if head_axis else 1
        n_heads, n_kv = q.shape[2], k.shape[2]
        if n_heads % (sp * tp):
            raise ValueError(
                f"ulysses needs n_heads ({n_heads}) divisible by sp*tp "
                f"({sp}*{tp})"
            )
        if n_kv % (sp * tp):
            # The head<->seq all_to_all moves whole heads; replicate grouped
            # KV heads up to a divisible count (costs comm volume, like every
            # Ulysses implementation under GQA).
            reps = (sp * tp) // n_kv
            if n_kv * reps != sp * tp or n_heads % (n_kv * reps):
                raise ValueError(
                    f"ulysses cannot expand kv_heads ({n_kv}) to a multiple "
                    f"of sp*tp ({sp}*{tp}) compatible with n_heads {n_heads}"
                )
            k = jnp.repeat(k, reps, axis=2)
            v = jnp.repeat(v, reps, axis=2)
    if q.shape[1] % sp:
        raise ValueError(f"seq len {q.shape[1]} not divisible by {axis}={sp}")

    body = _ring_attention_local if method == "ring" else _ulysses_local
    fn = partial(
        body, axis=axis, causal=causal, logit_softcap=logit_softcap, impl=impl,
        block_q=block_q, block_kv=block_kv,
    )
    qkv_spec, seg_spec = _specs(axis, batch_axes, head_axis)

    if q_segment_ids is None:
        mapped = jax.shard_map(
            lambda q_, k_, v_: fn(q_, k_, v_, None, None),
            mesh=mesh,
            in_specs=(qkv_spec, qkv_spec, qkv_spec),
            out_specs=qkv_spec,
            check_vma=False,
        )
        return mapped(q, k, v)

    mapped = jax.shard_map(
        fn,
        mesh=mesh,
        in_specs=(qkv_spec, qkv_spec, qkv_spec, seg_spec, seg_spec),
        out_specs=qkv_spec,
        check_vma=False,
    )
    return mapped(q, k, v, q_segment_ids, kv_segment_ids)


def ring_attention(q, k, v, mesh, **kw) -> jax.Array:
    """Ring attention over the ``sp`` axis (see sequence_attention)."""
    return sequence_attention(q, k, v, mesh, method="ring", **kw)


def ulysses_attention(q, k, v, mesh, **kw) -> jax.Array:
    """Ulysses attention over the ``sp`` axis (see sequence_attention)."""
    return sequence_attention(q, k, v, mesh, method="ulysses", **kw)
