"""Parallelism strategies as sharding rules over the named mesh.

TPU-native replacement for the reference's ``orion.parallel`` wrapper modules
(``orion.parallel.ddp``, ``orion.parallel.fsdp``; BASELINE.json:8-9) and the
brief's TP/PP/SP/CP/EP/ring/Ulysses strategies: instead of per-strategy
wrapper classes, each strategy is a set of entries in one logical-axis ->
mesh-axis rule table (SURVEY.md §8 "hard parts" #3). DDP = batch on dp;
ZeRO-3 = params' embed axis on fsdp (XLA gathers on use); TP = heads/mlp/vocab
on tp; EP = expert axis on ep; SP/ring/Ulysses = sequence on sp (see
orion_tpu.parallel.ring / ulysses); PP = layer stages on pp (parallel.pipeline).
"""

from orion_tpu.parallel.sharding import (
    DEFAULT_RULES,
    batch_sharding,
    logical_to_spec,
    param_shardings,
    shard_init,
    zero1_shardings,
    zero1_update_dim,
)
from orion_tpu.parallel.pipeline import pipeline_forward
from orion_tpu.parallel.reshard import reshard
from orion_tpu.parallel.sequence import (
    ring_attention,
    sequence_attention,
    ulysses_attention,
)

__all__ = [
    "DEFAULT_RULES",
    "batch_sharding",
    "logical_to_spec",
    "param_shardings",
    "shard_init",
    "zero1_shardings",
    "zero1_update_dim",
    "pipeline_forward",
    "reshard",
    "ring_attention",
    "sequence_attention",
    "ulysses_attention",
]
