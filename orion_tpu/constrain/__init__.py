"""Grammar-constrained decoding (ISSUE 16).

A token-level constraint subsystem: regex / JSON-schema frontends
compile to a character-level DFA, lifted to a token-level DFA over the
tokenizer vocab (per-state legal-token bitmasks, memoized by constraint
hash). Per-request :class:`ConstraintState` walks the DFA as tokens are
emitted; the mask is composed into ``sampling.filter_logits`` — the one
filter shared by greedy, sampled, and spec-decode verify paths — so
constrained speculation needs no new acceptance math, and FSM states
with a single legal continuation become free multi-token drafts.
"""

from __future__ import annotations

import json
from dataclasses import dataclass
from typing import Optional, Tuple

from orion_tpu.constrain.dfa import ConstraintState, TokenDFA, \
    cache_clear, compile_token_dfa
from orion_tpu.constrain.regex import CharDFA, ConstraintError, \
    compile_regex
from orion_tpu.constrain.schema import schema_to_regex

__all__ = [
    "CharDFA", "ConstraintError", "ConstraintSpec", "ConstraintState",
    "TokenDFA", "cache_clear", "compile_constraint", "compile_regex",
    "compile_token_dfa", "schema_to_regex",
]


@dataclass(frozen=True)
class ConstraintSpec:
    """What a request asks to be constrained BY: exactly one frontend.

    ``regex`` is a pattern in the anchored subset ``constrain.regex``
    documents; ``json_schema`` is JSON text (kept as text so the spec
    stays hashable — it is parsed and canonicalized at compile time).
    """

    regex: Optional[str] = None
    json_schema: Optional[str] = None

    def __post_init__(self):
        have = [n for n, v in (("regex", self.regex),
                               ("json_schema", self.json_schema))
                if v is not None]
        if len(have) != 1:
            raise ConstraintError(
                f"ConstraintSpec needs exactly one of regex/json_schema,"
                f" got {have or 'neither'}"
            )
        picked = self.regex if self.regex is not None else \
            self.json_schema
        if not isinstance(picked, str) or not picked:
            raise ConstraintError(
                f"constraint {have[0]} must be a non-empty string, "
                f"got {picked!r}"
            )

    def pattern(self) -> str:
        """The anchored regex this spec denotes (schema frontends lower
        through :func:`schema_to_regex`)."""
        if self.regex is not None:
            return self.regex
        return schema_to_regex(self.json_schema)

    def canonical(self) -> str:
        if self.regex is not None:
            return f"regex:{self.regex}"
        parsed = json.loads(self.json_schema)
        return "schema:" + json.dumps(parsed, sort_keys=True,
                                      separators=(",", ":"))


def compile_constraint(
    spec: ConstraintSpec,
    vocab_size: int,
    *,
    max_states: int = 4096,
    cache_size: int = 32,
) -> Tuple[TokenDFA, bool]:
    """Compile a spec to its token DFA; ``(dfa, cache_hit)``. Raises
    :class:`ConstraintError` when the pattern is malformed or when no
    token in the vocab can begin a conforming emission (start-state dead
    end — the constraint is unserveable for this tokenizer)."""
    dfa, hit = compile_token_dfa(
        spec.pattern(), vocab_size,
        max_states=max_states, cache_size=cache_size,
    )
    if int(dfa.legal_count[dfa.start]) == 0 and \
            not bool(dfa.accepting[dfa.start]):
        raise ConstraintError(
            f"constraint {spec.canonical()[:80]!r} has no legal first "
            f"token in a vocab of {vocab_size} — unserveable here"
        )
    return dfa, hit
