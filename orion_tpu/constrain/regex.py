"""Regex frontend: a full-match-anchored regex subset compiled to a
character-level DFA over the byte alphabet.

Supported syntax (the subset structured-output schemas actually need):
literals (non-ASCII encoded as their UTF-8 byte sequence), escapes
(``\\d \\D \\w \\W \\s \\S \\n \\t \\r \\f \\v \\0 \\xHH`` and
escaped metacharacters), character classes ``[...]`` with ranges and
``^`` negation, ``.`` (any byte except newline), alternation ``|``,
groups ``(...)``, and quantifiers ``* + ? {m} {m,} {m,n}``.

Patterns are implicitly anchored at both ends — constrained decoding
matches the WHOLE emission, so ``a+`` means "the output is one or more
'a' bytes", not "contains". Bounded repeats are expanded (Thompson
construction has no counters); the expansion is capped so a hostile
``{1,100000}`` fails fast instead of building a million states.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Set, Tuple

__all__ = ["ConstraintError", "compile_regex", "CharDFA"]

# One bounded repeat may expand to at most this many copies of its body;
# the DFA state cap (inference.constraint_max_states) bounds the rest.
_MAX_REPEAT = 1024

_ALL_BYTES = frozenset(range(256))
_DOT = frozenset(b for b in range(256) if b != 0x0A)
_DIGIT = frozenset(range(0x30, 0x3A))
_WORD = frozenset(
    list(range(0x30, 0x3A)) + list(range(0x41, 0x5B))
    + list(range(0x61, 0x7B)) + [0x5F]
)
_SPACE = frozenset(b" \t\n\r\f\v")

_CLASS_ESCAPES = {
    "d": _DIGIT, "D": _ALL_BYTES - _DIGIT,
    "w": _WORD, "W": _ALL_BYTES - _WORD,
    "s": _SPACE, "S": _ALL_BYTES - _SPACE,
}
_CHAR_ESCAPES = {
    "n": 0x0A, "t": 0x09, "r": 0x0D, "f": 0x0C, "v": 0x0B, "0": 0x00,
    "a": 0x07, "b": 0x08, "e": 0x1B,
}


class ConstraintError(ValueError):
    """Typed compile/validation error for the constraint subsystem —
    malformed pattern, unsupported schema, state-cap blowout, or a
    constraint no token in the vocab can ever satisfy."""


# --------------------------------------------------------------------------
# AST
# --------------------------------------------------------------------------


@dataclass(frozen=True)
class _Lit:
    bytes_: frozenset  # set of legal byte values for ONE position


@dataclass(frozen=True)
class _Concat:
    parts: tuple


@dataclass(frozen=True)
class _Alt:
    options: tuple


@dataclass(frozen=True)
class _Star:
    inner: object


@dataclass(frozen=True)
class _Repeat:
    inner: object
    lo: int
    hi: Optional[int]  # None = unbounded


class _Parser:
    """Recursive-descent parser for the subset above."""

    def __init__(self, pattern: str):
        self.p = pattern
        self.i = 0

    def _err(self, msg: str) -> ConstraintError:
        return ConstraintError(
            f"regex parse error at offset {self.i}: {msg} "
            f"(pattern {self.p!r})"
        )

    def _peek(self) -> Optional[str]:
        return self.p[self.i] if self.i < len(self.p) else None

    def _next(self) -> str:
        if self.i >= len(self.p):
            raise self._err("unexpected end of pattern")
        c = self.p[self.i]
        self.i += 1
        return c

    def parse(self):
        node = self._alternation()
        if self.i != len(self.p):
            raise self._err(f"unexpected {self.p[self.i]!r}")
        return node

    def _alternation(self):
        options = [self._concat()]
        while self._peek() == "|":
            self._next()
            options.append(self._concat())
        if len(options) == 1:
            return options[0]
        return _Alt(tuple(options))

    def _concat(self):
        parts = []
        while self._peek() is not None and self._peek() not in "|)":
            parts.append(self._repeat())
        if len(parts) == 1:
            return parts[0]
        return _Concat(tuple(parts))

    def _repeat(self):
        node = self._atom()
        while True:
            c = self._peek()
            if c == "*":
                self._next()
                node = _Star(node)
            elif c == "+":
                self._next()
                node = _Concat((node, _Star(node)))
            elif c == "?":
                self._next()
                node = _Repeat(node, 0, 1)
            elif c == "{":
                node = self._braces(node)
            else:
                return node

    def _braces(self, node):
        save = self.i
        self._next()  # '{'
        digits = ""
        while self._peek() is not None and self._peek().isdigit():
            digits += self._next()
        if not digits:
            # A literal '{' (e.g. in a JSON pattern) — backtrack.
            self.i = save
            self._next()
            return _Concat((node, _Lit(frozenset([0x7B]))))
        lo = int(digits)
        hi: Optional[int] = lo
        if self._peek() == ",":
            self._next()
            digits = ""
            while self._peek() is not None and self._peek().isdigit():
                digits += self._next()
            hi = int(digits) if digits else None
        if self._next() != "}":
            raise self._err("unterminated {m,n} quantifier")
        if hi is not None and hi < lo:
            raise self._err(f"bad repeat bounds {{{lo},{hi}}}")
        if max(lo, hi or 0) > _MAX_REPEAT:
            raise self._err(
                f"repeat bound exceeds cap {_MAX_REPEAT} (expanded "
                f"construction; tighten the pattern)"
            )
        return _Repeat(node, lo, hi)

    def _atom(self):
        c = self._next()
        if c == "(":
            node = self._alternation()
            if self._peek() != ")":
                raise self._err("unterminated group")
            self._next()
            return node
        if c == "[":
            return _Lit(self._char_class())
        if c == ".":
            return _Lit(_DOT)
        if c == "\\":
            return _Lit(self._escape(in_class=False))
        if c in "*+?)":
            raise self._err(f"dangling {c!r}")
        # Multi-byte UTF-8 literals become a byte-sequence concat.
        enc = c.encode("utf-8")
        if len(enc) == 1:
            return _Lit(frozenset([enc[0]]))
        return _Concat(tuple(_Lit(frozenset([b])) for b in enc))

    def _escape(self, in_class: bool) -> frozenset:
        c = self._next()
        if c in _CLASS_ESCAPES:
            return _CLASS_ESCAPES[c]
        if c in _CHAR_ESCAPES and not (in_class and c == "b"):
            return frozenset([_CHAR_ESCAPES[c]])
        if c == "x":
            hex_ = self._next() + self._next()
            try:
                return frozenset([int(hex_, 16)])
            except ValueError:
                raise self._err(f"bad \\x escape {hex_!r}")
        enc = c.encode("utf-8")
        if len(enc) != 1:
            raise self._err(f"cannot escape multi-byte char {c!r}")
        return frozenset([enc[0]])

    def _char_class(self) -> frozenset:
        negate = False
        if self._peek() == "^":
            self._next()
            negate = True
        members: Set[int] = set()
        first = True
        while True:
            c = self._peek()
            if c is None:
                raise self._err("unterminated character class")
            if c == "]" and not first:
                self._next()
                break
            first = False
            self._next()
            if c == "\\":
                got = self._escape(in_class=True)
                if len(got) > 1:
                    members |= got  # \d-style class escape: no ranges
                    continue
                lo = next(iter(got))
            else:
                enc = c.encode("utf-8")
                if len(enc) != 1:
                    raise self._err(
                        f"multi-byte char {c!r} in class (use \\xHH "
                        f"byte ranges for non-ASCII)"
                    )
                lo = enc[0]
            if self._peek() == "-" and self.i + 1 < len(self.p) \
                    and self.p[self.i + 1] != "]":
                self._next()  # '-'
                hc = self._next()
                if hc == "\\":
                    got = self._escape(in_class=True)
                    if len(got) != 1:
                        raise self._err("class escape cannot end a range")
                    hi = next(iter(got))
                else:
                    enc = hc.encode("utf-8")
                    if len(enc) != 1:
                        raise self._err("multi-byte char ends a range")
                    hi = enc[0]
                if hi < lo:
                    raise self._err(f"reversed range {chr(lo)}-{chr(hi)}")
                members |= set(range(lo, hi + 1))
            else:
                members.add(lo)
        out = frozenset(members)
        return frozenset(_ALL_BYTES - out) if negate else out


# --------------------------------------------------------------------------
# Thompson NFA + subset construction
# --------------------------------------------------------------------------


class _NFA:
    def __init__(self):
        self.edges: List[List[Tuple[frozenset, int]]] = []
        self.eps: List[List[int]] = []

    def state(self) -> int:
        self.edges.append([])
        self.eps.append([])
        return len(self.edges) - 1


def _build(nfa: _NFA, node) -> Tuple[int, int]:
    """Thompson fragment: returns (start, accept) state ids."""
    if isinstance(node, _Lit):
        s, e = nfa.state(), nfa.state()
        if node.bytes_:
            nfa.edges[s].append((node.bytes_, e))
        else:
            raise ConstraintError("empty character class matches nothing")
        return s, e
    if isinstance(node, _Concat):
        if not node.parts:
            s = nfa.state()
            return s, s
        s, e = _build(nfa, node.parts[0])
        for part in node.parts[1:]:
            s2, e2 = _build(nfa, part)
            nfa.eps[e].append(s2)
            e = e2
        return s, e
    if isinstance(node, _Alt):
        s, e = nfa.state(), nfa.state()
        for opt in node.options:
            os_, oe = _build(nfa, opt)
            nfa.eps[s].append(os_)
            nfa.eps[oe].append(e)
        return s, e
    if isinstance(node, _Star):
        s, e = nfa.state(), nfa.state()
        is_, ie = _build(nfa, node.inner)
        nfa.eps[s] += [is_, e]
        nfa.eps[ie] += [is_, e]
        return s, e
    if isinstance(node, _Repeat):
        lo, hi = node.lo, node.hi
        if lo == 0 and hi == 1:
            s, e = nfa.state(), nfa.state()
            is_, ie = _build(nfa, node.inner)
            nfa.eps[s] += [is_, e]
            nfa.eps[ie].append(e)
            return s, e
        parts: List[object] = [node.inner] * lo
        if hi is None:
            parts.append(_Star(node.inner))
        else:
            parts += [_Repeat(node.inner, 0, 1)] * (hi - lo)
        if not parts:  # {0,0}
            s = nfa.state()
            return s, s
        return _build(nfa, _Concat(tuple(parts)))
    raise ConstraintError(f"unknown AST node {node!r}")


@dataclass
class CharDFA:
    """Character-level DFA over the byte alphabet: ``trans[s]`` maps a
    byte value to the next state (absent = illegal), state 0 is the
    start."""

    trans: List[dict]
    accepting: List[bool]

    @property
    def n_states(self) -> int:
        return len(self.trans)


def _eps_closure(nfa: _NFA, states: frozenset) -> frozenset:
    seen = set(states)
    stack = list(states)
    while stack:
        s = stack.pop()
        for t in nfa.eps[s]:
            if t not in seen:
                seen.add(t)
                stack.append(t)
    return frozenset(seen)


def compile_regex(pattern: str, max_states: int = 4096) -> CharDFA:
    """Parse ``pattern`` and subset-construct its byte-level DFA. Raises
    :class:`ConstraintError` on syntax errors or when the DFA exceeds
    ``max_states`` (the inference.constraint_max_states knob)."""
    ast = _Parser(pattern).parse()
    nfa = _NFA()
    start, accept = _build(nfa, ast)

    d0 = _eps_closure(nfa, frozenset([start]))
    ids = {d0: 0}
    order = [d0]
    trans: List[dict] = [{}]
    accepting = [accept in d0]
    i = 0
    while i < len(order):
        cur = order[i]
        move: dict = {}
        for s in cur:
            for byteset, tgt in nfa.edges[s]:
                for b in byteset:
                    move.setdefault(b, set()).add(tgt)
        for b, tgts in move.items():
            nxt = _eps_closure(nfa, frozenset(tgts))
            if nxt not in ids:
                if len(ids) >= max_states:
                    raise ConstraintError(
                        f"constraint DFA exceeds max_states="
                        f"{max_states}; raise inference."
                        f"constraint_max_states or simplify the pattern"
                    )
                ids[nxt] = len(order)
                order.append(nxt)
                trans.append({})
                accepting.append(accept in nxt)
            trans[i][b] = ids[nxt]
        i += 1
    return CharDFA(trans=trans, accepting=accepting)
