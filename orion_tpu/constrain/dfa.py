"""Token-level DFA: lift the character DFA to the tokenizer vocab.

The tokenizer here is the stack's byte-level one (token id == byte
value, ``model.vocab_size <= 256``), so a token is one byte and the
lift is a direct table read; ``token_bytes`` generalizes to multi-byte
vocabularies (walk each token's bytes through the char DFA; any token
whose walk falls off the DFA is illegal in that state).

Compiled artifacts are memoized by constraint hash + vocab size in a
module-level LRU, so N requests carrying the same JSON schema share one
compile (the compile is the expensive part: subset construction plus an
S x V table build).
"""

from __future__ import annotations

import hashlib
from collections import OrderedDict
from dataclasses import dataclass, field
from threading import Lock
from typing import Callable, List, Optional, Tuple

import numpy as np

from orion_tpu.constrain.regex import CharDFA, ConstraintError, \
    compile_regex

__all__ = ["TokenDFA", "ConstraintState", "compile_token_dfa",
           "cache_clear"]


def _byte_token(t: int) -> Optional[bytes]:
    """Default token->bytes map for the byte tokenizer."""
    return bytes([t]) if t < 256 else None


@dataclass
class TokenDFA:
    """Per-state legal-token tables. ``next_state[s, t] < 0`` means
    token ``t`` is illegal in state ``s``; ``legal`` is the bitmask the
    sampler consumes; ``only_token[s]`` is the forced continuation when
    ``legal_count[s] == 1`` (the free-draft states)."""

    next_state: np.ndarray   # int32 [S, V]
    accepting: np.ndarray    # bool  [S]
    start: int
    pattern_sha: str
    legal: np.ndarray = field(init=False)        # bool  [S, V]
    legal_count: np.ndarray = field(init=False)  # int32 [S]
    only_token: np.ndarray = field(init=False)   # int32 [S]

    def __post_init__(self):
        self.legal = self.next_state >= 0
        self.legal_count = self.legal.sum(axis=1).astype(np.int32)
        self.only_token = self.legal.argmax(axis=1).astype(np.int32)

    @property
    def n_states(self) -> int:
        return int(self.next_state.shape[0])

    @property
    def vocab_size(self) -> int:
        return int(self.next_state.shape[1])


def _lift(cdfa: CharDFA, vocab_size: int,
          token_bytes: Optional[Callable[[int], Optional[bytes]]],
          pattern_sha: str) -> TokenDFA:
    token_bytes = token_bytes or _byte_token
    S = cdfa.n_states
    next_state = np.full((S, vocab_size), -1, np.int32)
    walks: List[Optional[Tuple[int, ...]]] = []
    for t in range(vocab_size):
        bs = token_bytes(t)
        walks.append(tuple(bs) if bs else None)
    for s in range(S):
        for t, bs in enumerate(walks):
            if bs is None:
                continue
            cur: Optional[int] = s
            for b in bs:
                cur = cdfa.trans[cur].get(b)
                if cur is None:
                    break
            if cur is not None:
                next_state[s, t] = cur
    return TokenDFA(
        next_state=next_state,
        accepting=np.asarray(cdfa.accepting, bool),
        start=0,
        pattern_sha=pattern_sha,
    )


# --------------------------------------------------------------------------
# Memoized compile
# --------------------------------------------------------------------------

_CACHE: "OrderedDict[tuple, TokenDFA]" = OrderedDict()
_CACHE_LOCK = Lock()


def cache_clear() -> None:
    with _CACHE_LOCK:
        _CACHE.clear()


def compile_token_dfa(
    pattern: str,
    vocab_size: int,
    *,
    max_states: int = 4096,
    cache_size: int = 32,
    token_bytes: Optional[Callable[[int], Optional[bytes]]] = None,
) -> Tuple[TokenDFA, bool]:
    """Compile ``pattern`` to a token DFA; returns ``(dfa, cache_hit)``.

    Memoized by sha256(pattern) + vocab size so repeated schemas across
    requests share one artifact. A ``token_bytes`` override bypasses the
    cache (the key has no way to identify the callable's behavior).
    """
    sha = hashlib.sha256(pattern.encode("utf-8")).hexdigest()
    key = (sha, vocab_size, max_states)
    if token_bytes is None:
        with _CACHE_LOCK:
            hit = _CACHE.get(key)
            if hit is not None:
                _CACHE.move_to_end(key)
                return hit, True
    cdfa = compile_regex(pattern, max_states=max_states)
    dfa = _lift(cdfa, vocab_size, token_bytes, sha)
    if token_bytes is None:
        with _CACHE_LOCK:
            _CACHE[key] = dfa
            while len(_CACHE) > max(1, cache_size):
                _CACHE.popitem(last=False)
    return dfa, False


# --------------------------------------------------------------------------
# Per-request runtime state
# --------------------------------------------------------------------------


class ConstraintState:
    """One request's walk through the token DFA. Pure host state riding
    the Request, so it survives preemption/rollback (the re-prefill
    replays prompt + generated; ``sync`` re-walks ``generated`` if the
    advance count ever disagrees, e.g. after a router resubmission)."""

    __slots__ = ("dfa", "eos_id", "state", "n_advanced")

    def __init__(self, dfa: TokenDFA, eos_id: Optional[int] = None):
        self.dfa = dfa
        self.eos_id = eos_id if eos_id is not None and \
            eos_id < dfa.vocab_size else None
        self.state = dfa.start
        self.n_advanced = 0

    # -- masks -------------------------------------------------------------

    def mask_row(self, state: Optional[int] = None) -> np.ndarray:
        """Legal-token bitmask at ``state`` (default: current), with eos
        added once the constraint is satisfiable-complete (accepting
        states may either continue the pattern or stop)."""
        s = self.state if state is None else state
        row = self.dfa.legal[s].copy()
        if self.eos_id is not None and self.dfa.accepting[s]:
            row[self.eos_id] = True
        return row

    def mask_choices(self, state: Optional[int] = None) -> int:
        """How many tokens the mask at ``state`` admits (legal
        continuations plus the eos alternative in accepting states)."""
        s = self.state if state is None else state
        c = int(self.dfa.legal_count[s])
        if self.eos_id is not None and self.dfa.accepting[s] \
                and not (self.dfa.legal[s, self.eos_id]):
            c += 1
        return c

    # -- walking -----------------------------------------------------------

    def peek(self, tok: int, state: Optional[int] = None) -> int:
        """Next DFA state after ``tok`` (or -1 illegal) without moving."""
        s = self.state if state is None else state
        if tok == self.eos_id and self.dfa.accepting[s]:
            return s  # eos closes an accepting walk in place
        if 0 <= tok < self.dfa.vocab_size:
            return int(self.dfa.next_state[s, tok])
        return -1

    def advance(self, tok: int) -> bool:
        """Consume one emitted token; returns False if it was illegal
        (the caller quarantines — this only happens when something
        upstream bypassed the mask)."""
        nxt = self.peek(tok)
        if nxt < 0:
            return False
        self.state = nxt
        self.n_advanced += 1
        return True

    def walk(self, toks, state: Optional[int] = None) -> int:
        """End state after consuming ``toks`` from ``state`` (default:
        current) without moving the cursor; -1 once any step is illegal."""
        s = self.state if state is None else state
        for tok in toks:
            if s < 0:
                return -1
            s = self.peek(int(tok), s)
        return s

    def sync(self, generated) -> bool:
        """Re-walk ``generated`` from the start state when the advance
        count disagrees (failover/replay safety). Returns False if the
        replay hits an illegal token."""
        if self.n_advanced == len(generated):
            return True
        self.state = self.dfa.start
        self.n_advanced = 0
        for tok in generated:
            if not self.advance(int(tok)):
                return False
        return True

    # -- terminal classification -------------------------------------------

    def is_complete(self) -> bool:
        """Accepting with no legal continuation: the only move is to
        stop — the engine finishes the request without burning a step."""
        return bool(self.dfa.accepting[self.state]) and \
            int(self.dfa.legal_count[self.state]) == 0

    def is_dead(self) -> bool:
        """Non-accepting with no legal continuation: no emission can
        ever satisfy the constraint (vocab can't spell the pattern)."""
        return not self.dfa.accepting[self.state] and \
            int(self.dfa.legal_count[self.state]) == 0

    # -- speculation hooks -------------------------------------------------

    def forced_run(self, limit: int,
                   state: Optional[int] = None) -> List[int]:
        """The run of single-choice continuations from ``state``
        (default: current): states whose mask admits exactly one token
        emit that token for free (guaranteed acceptance — the masked
        target probability is exactly 1.0). Does not move the state."""
        out: List[int] = []
        s = self.state if state is None else state
        while len(out) < limit and self.mask_choices(s) == 1:
            if int(self.dfa.legal_count[s]) == 1:
                tok = int(self.dfa.only_token[s])
                out.append(tok)
                s = int(self.dfa.next_state[s, tok])
            else:
                # Accepting dead end whose single choice is eos.
                out.append(self.eos_id)  # type: ignore[arg-type]
                break
        return out

    def branch_tokens(self, width: int,
                      state: Optional[int] = None) -> List[int]:
        """Up to ``width`` legal tokens at an ambiguous state — the FSM
        branch points that feed ``spec_decode.build_tree``."""
        s = self.state if state is None else state
        toks = np.flatnonzero(self.dfa.legal[s])[:width]
        return [int(t) for t in toks]
