"""JSON-schema frontend: compile a (restricted) JSON schema to a regex
over the byte alphabet, which the regex frontend then turns into a DFA.

The translation is the outlines-style one (PAPERS.md 2307.09702): every
schema node becomes a regex fragment describing the exact byte sequence
of a conforming JSON value. Deliberate simplifications, documented in
README "Constrained decoding":

- Emitted JSON is COMPACT (no whitespace between tokens) — the grammar
  admits one canonical serialization, which keeps the DFA small and the
  forced-token runs long (punctuation like ``","`` and ``":"`` is a
  single legal continuation, i.e. a free draft).
- Object properties are emitted in declared order and all are required;
  ``required`` narrowing / optional-property combinatorics are out of
  scope for this pass.
- ``$ref``, ``allOf``, ``patternProperties`` and unconstrained
  ``additionalProperties`` objects are rejected with a typed error
  rather than silently accepted.
"""

from __future__ import annotations

import json
from typing import Optional

from orion_tpu.constrain.regex import ConstraintError

__all__ = ["schema_to_regex", "STRING_INNER"]

# One JSON string character: anything but '"', '\' or a control byte,
# or a short escape, or a \uXXXX escape.
STRING_INNER = (
    r'([^"\\\x00-\x1f]|\\["\\/bfnrt]|\\u[0-9a-fA-F]{4})'
)
_STRING = f'"{STRING_INNER}*"'
_INTEGER = r"-?(0|[1-9][0-9]*)"
_NUMBER = r"-?(0|[1-9][0-9]*)(\.[0-9]+)?([eE][+\-]?[0-9]+)?"
_BOOLEAN = r"(true|false)"
_NULL = r"null"

_META = set("\\.[]{}()*+?|^$-")


def _quote(text: str) -> str:
    """Escape a literal string for the regex frontend."""
    out = []
    for ch in text:
        if ch in _META:
            out.append("\\" + ch)
        elif ch == "\n":
            out.append(r"\n")
        elif ch == "\t":
            out.append(r"\t")
        elif ch == "\r":
            out.append(r"\r")
        else:
            out.append(ch)
    return "".join(out)


def _const(value) -> str:
    return _quote(json.dumps(value, separators=(",", ":"),
                             ensure_ascii=True))


def _string_fragment(node: dict) -> str:
    lo = node.get("minLength")
    hi = node.get("maxLength")
    if lo is None and hi is None:
        return _STRING
    lo = 0 if lo is None else int(lo)
    rep = f"{{{lo},{int(hi)}}}" if hi is not None else f"{{{lo},}}"
    return f'"{STRING_INNER}{rep}"'


def _array_fragment(node: dict, depth: int) -> str:
    item = _fragment(node.get("items", {}), depth + 1) \
        if "items" in node else f"({_NUMBER}|{_STRING}|{_BOOLEAN}|{_NULL})"
    lo = int(node.get("minItems", 0))
    hi = node.get("maxItems")
    if hi is not None:
        hi = int(hi)
        if hi < lo:
            raise ConstraintError(
                f"array maxItems={hi} < minItems={lo}"
            )
        if hi == 0:
            return r"\[\]"
    if lo == 0:
        tail = f"({item}(,{item})*)?" if hi is None else (
            f"({item}(,{item}){{0,{hi - 1}}})?"
        )
    else:
        tail = f"{item}(,{item}){{{lo - 1},}}" if hi is None else (
            f"{item}(,{item}){{{lo - 1},{hi - 1}}}"
        )
    return r"\[" + tail + r"\]"


def _object_fragment(node: dict, depth: int) -> str:
    props = node.get("properties")
    if not props:
        if node.get("additionalProperties") is False or props == {}:
            return r"\{\}"
        raise ConstraintError(
            "object schema without 'properties' is unbounded; declare "
            "the properties (or additionalProperties: false for {})"
        )
    parts = []
    for key, sub in props.items():
        parts.append(f'"{_quote(key)}":{_fragment(sub, depth + 1)}')
    return r"\{" + ",".join(parts) + r"\}"


def _fragment(node, depth: int = 0) -> str:
    if depth > 32:
        raise ConstraintError("schema nesting exceeds depth cap 32")
    if node is True or node == {}:
        # Permissive node: any scalar JSON value (containers need an
        # explicit schema to stay bounded).
        return f"({_NUMBER}|{_STRING}|{_BOOLEAN}|{_NULL})"
    if not isinstance(node, dict):
        raise ConstraintError(f"schema node must be an object: {node!r}")
    for bad in ("$ref", "allOf", "patternProperties"):
        if bad in node:
            raise ConstraintError(f"unsupported schema keyword {bad!r}")
    if "const" in node:
        return _const(node["const"])
    if "enum" in node:
        opts = node["enum"]
        if not opts:
            raise ConstraintError("empty enum matches nothing")
        return "(" + "|".join(_const(v) for v in opts) + ")"
    for key in ("anyOf", "oneOf"):
        if key in node:
            opts = node[key]
            if not opts:
                raise ConstraintError(f"empty {key} matches nothing")
            return "(" + "|".join(
                _fragment(o, depth + 1) for o in opts
            ) + ")"
    ty = node.get("type")
    if isinstance(ty, list):
        return "(" + "|".join(
            _fragment({**node, "type": t}, depth + 1) for t in ty
        ) + ")"
    if ty == "string":
        if "pattern" in node:
            # The schema's own regex, anchored by our full-match
            # semantics, quoted inside JSON string delimiters.
            return f'"{node["pattern"]}"'
        return _string_fragment(node)
    if ty == "integer":
        return _INTEGER
    if ty == "number":
        return _NUMBER
    if ty == "boolean":
        return _BOOLEAN
    if ty == "null":
        return _NULL
    if ty == "array":
        return _array_fragment(node, depth)
    if ty == "object":
        return _object_fragment(node, depth)
    raise ConstraintError(f"unsupported schema type {ty!r}")


def schema_to_regex(schema) -> str:
    """Compile a JSON schema (dict, or JSON text) to an anchored regex
    accepting exactly the compact serializations of conforming values."""
    if isinstance(schema, (str, bytes)):
        try:
            schema = json.loads(schema)
        except ValueError as e:
            raise ConstraintError(f"json_schema is not valid JSON: {e}")
    return _fragment(schema)
