"""Declarative contracts over compiled orion programs (ISSUE 15 tentpole).

Every compiled-program invariant the stack depends on used to live as a
one-off HLO pin inside some test file: donation fully aliased
(``Trainer.memory_report``), guard-off traces free of finiteness ops
(test_train_fault), the grouped scan's stacked-DUS shrink
(test_scan_remat), ZeRO-1's reduce-scatter/all-gather pair (test_zero1).
This module centralizes them as a *contract registry*: each contract
names a program builder (the train step at a parallel layout; an engine
dispatch program per kernel path) and a tuple of predicates over the
compiled artifact. ``tools/contract_check.py`` sweeps contracts across a
layout grid in subprocesses; tests call :func:`check` directly and prove
every predicate live with injected violations (tests/test_contracts.py).

Three artifact views, all static (no program is ever executed):

- **jaxpr** (``jax.jit(f).trace``): primitive census — host callbacks,
  finiteness ops, dtype-upcast sites (counted per *staged* site, so a
  scanned layer body counts once, not per layer);
- **StableHLO** (``lower().as_text()``): textual matchers — f64 tensors,
  custom_call targets, the executed-stacked-DUS counter;
- **optimized HLO** (``compile().as_text()`` + ``memory_analysis()``):
  what XLA actually scheduled — collective inventory (SPMD partitioning
  inserts collectives only at compile time) and donation aliasing.
"""

from __future__ import annotations

import math
import re
import time
from collections import Counter
from dataclasses import dataclass, field
from typing import Any, Callable, Iterator, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

__all__ = [
    "ProgramArtifact", "Contract", "ContractResult", "Violation",
    "CONTRACTS", "check", "check_artifact", "artifact_from_fn",
    "build_program", "iter_eqns", "primitive_census", "count_bf16_upcasts",
    "collective_census", "executed_stacked_dus", "donation_report",
    "smoke_contracts", "grid_contracts",
]


class ContractError(RuntimeError):
    """A contract could not be evaluated (bad layout / missing program) —
    distinct from a contract *violation*."""


# ---------------------------------------------------------------------------
# Artifact
# ---------------------------------------------------------------------------


@dataclass
class ProgramArtifact:
    """Lazy views over one lowered program.

    ``lowered``/``traced`` come from the builder; the StableHLO text,
    compiled executable, optimized-HLO text and memory analysis are
    derived on first use (compiling is the expensive step — predicates
    that only need the trace never pay for it). Tests can also construct
    artifacts directly from raw text (``stablehlo=``/``optimized=``) to
    exercise a matcher on synthetic input.
    """

    name: str
    lowered: Any = None
    traced: Any = None            # jax Traced (jaxpr access), optional
    donated: tuple = ()           # abstract donated input leaves
    meta: dict = field(default_factory=dict)
    stablehlo_text: Optional[str] = None
    optimized_text: Optional[str] = None
    _compiled: Any = None

    @property
    def jaxpr(self):
        if self.traced is None:
            return None
        return self.traced.jaxpr

    @property
    def stablehlo(self) -> str:
        if self.stablehlo_text is None:
            if self.lowered is None:
                raise ContractError(f"{self.name}: no lowered module")
            self.stablehlo_text = self.lowered.as_text()
        return self.stablehlo_text

    def compiled(self):
        if self._compiled is None:
            if self.lowered is None:
                raise ContractError(f"{self.name}: no lowered module")
            self._compiled = self.lowered.compile()
        return self._compiled

    @property
    def optimized_hlo(self) -> str:
        if self.optimized_text is None:
            self.optimized_text = self.compiled().as_text()
        return self.optimized_text

    def memory_analysis(self):
        return self.compiled().memory_analysis()


def artifact_from_fn(
    name: str, fn, *args, donate_argnums: tuple = (), **jit_kw
) -> ProgramArtifact:
    """Build an artifact from a plain callable — the injected-violation
    fixture path (tests) and ad-hoc matcher runs."""
    jitted = jax.jit(fn, donate_argnums=donate_argnums, **jit_kw)
    donated = tuple(
        leaf
        for i in donate_argnums
        for leaf in jax.tree.leaves(args[i])
    )
    return ProgramArtifact(
        name=name,
        lowered=jitted.lower(*args),
        traced=_try_trace(jitted, args),
        donated=donated,
    )


def _try_trace(jitted, args, kwargs=None):
    """jaxpr access is best-effort: every predicate that walks the jaxpr
    falls back to a text matcher when tracing is unavailable (older jit
    wrappers, checkify closures)."""
    try:
        return jitted.trace(*args, **(kwargs or {}))
    except Exception:
        return None


# ---------------------------------------------------------------------------
# jaxpr walker
# ---------------------------------------------------------------------------


def _sub_jaxprs(eqn) -> Iterator:
    for v in eqn.params.values():
        vs = v if isinstance(v, (tuple, list)) else (v,)
        for x in vs:
            if isinstance(x, jax.core.ClosedJaxpr):
                yield x.jaxpr
            elif isinstance(x, jax.core.Jaxpr):
                yield x


def iter_eqns(jaxpr) -> Iterator:
    """Depth-first over every equation, descending into sub-jaxprs
    (scan/while/cond/pjit bodies) — a census over *staged sites*, not
    executions: a scanned layer body contributes each primitive once."""
    if isinstance(jaxpr, jax.core.ClosedJaxpr):
        jaxpr = jaxpr.jaxpr
    for eqn in jaxpr.eqns:
        yield eqn
        for sub in _sub_jaxprs(eqn):
            yield from iter_eqns(sub)


def primitive_census(jaxpr) -> Counter:
    return Counter(eqn.primitive.name for eqn in iter_eqns(jaxpr))


def count_bf16_upcasts(jaxpr) -> int:
    """Staged ``convert_element_type`` sites bf16 -> f32 — the silent-
    upcast budget (each is a whitelisted site: norms compute in f32,
    logits/loss promote; anything beyond the budget is a new full-width
    f32 activation sneaking into a bf16 model)."""
    n = 0
    for eqn in iter_eqns(jaxpr):
        if eqn.primitive.name != "convert_element_type":
            continue
        inv = eqn.invars[0]
        out = eqn.outvars[0]
        if (
            getattr(inv, "aval", None) is not None
            and inv.aval.dtype == jnp.bfloat16
            and out.aval.dtype == jnp.float32
        ):
            n += 1
    return n


_HOST_CALLBACK_PRIMS = frozenset({
    "pure_callback", "io_callback", "debug_callback", "callback",
})
# StableHLO fallback: jax lowers every host callback flavor to a
# custom_call against the cpu/tpu callback runtime.
_CALLBACK_RE = re.compile(
    r"custom_call\s+@(xla_python_cpu_callback\w*|xla_ffi_python_cpu_callback"
    r"\w*|xla_python_gpu_callback\w*|tpu_callback\w*)"
)


# ---------------------------------------------------------------------------
# HLO matchers
# ---------------------------------------------------------------------------

# A scan writing per-iteration slices lowers to a while whose body does one
# dynamic_update_slice of a [1, ...]-leading update into a [trip, ...]-
# leading buffer (migrated from tests/test_scan_remat.py — ISSUE 15).
_DUS_RE = re.compile(
    r"stablehlo\.dynamic_update_slice[^\n]*:\s*"
    r"\(tensor<(\d+)x[^>]*>,\s*tensor<(\d+)x"
)


def executed_stacked_dus(stablehlo_text: str) -> int:
    """Executed stacked-buffer DUS writes in a lowered module: each
    unit-leading update into a [trip_count, ...] buffer EXECUTES
    trip_count slice writes — exactly the fwd stash + bwd stacked-grad
    traffic the grouped scan (model.scan_group) shrinks G-fold."""
    total = 0
    for m in _DUS_RE.finditer(stablehlo_text):
        target_lead, update_lead = int(m.group(1)), int(m.group(2))
        if update_lead == 1 and target_lead > 1:
            total += target_lead
    return total


COLLECTIVE_OPS = (
    "all-reduce", "all-gather", "reduce-scatter", "collective-permute",
    "all-to-all",
)
# Optimized-HLO instruction form: `%name = ty[...] all-gather(...)`, or the
# async `-start(` pair whose result is a TUPLE type with spaces
# (`%s = (f32[1,8], f32[8,8]) all-gather-start(...)`); `-done(` carries no
# new collective (the trailing `\(` rejects it: after the op name a done
# line continues `-done(`).
_COLL_RE = re.compile(
    r"=\s+(?:\([^)]*\)|\S+)\s+(" + "|".join(COLLECTIVE_OPS)
    + r")(?:-start)?\("
)


def collective_census(optimized_hlo: str) -> dict[str, int]:
    """Count scheduled collective instructions per op kind — what the SPMD
    partitioner actually inserted (StableHLO carries only sharding
    annotations; collectives exist after compile)."""
    census = {op: 0 for op in COLLECTIVE_OPS}
    for m in _COLL_RE.finditer(optimized_hlo):
        census[m.group(1)] += 1
    return census


_F64_RE = re.compile(r"tensor<(?:\d+x)*f64>|xf64[>x]|\bf64\[")


def _leaf_chip_bytes(leaf) -> int:
    """Per-device bytes of one abstract leaf (replicated dims count in
    full — the same accounting as Trainer.memory_report)."""
    sharding = getattr(leaf, "sharding", None)
    shape = (
        sharding.shard_shape(leaf.shape) if sharding is not None
        else leaf.shape
    )
    return math.prod(shape) * jnp.dtype(leaf.dtype).itemsize


def donation_report(artifact: ProgramArtifact) -> dict:
    """Donated-vs-aliased accounting off XLA's compiled memory analysis.
    A donated buffer that failed to alias silently DOUBLES its footprint
    for the step — the exact headroom regression class memory_report
    guards in the trainer, generalized to any program."""
    ma = artifact.memory_analysis()
    donated = sum(_leaf_chip_bytes(leaf) for leaf in artifact.donated)
    report = {"donated_bytes": donated, "available": ma is not None}
    if ma is not None:
        report["alias_bytes"] = int(ma.alias_size_in_bytes)
        report["leaked_bytes"] = max(
            0, donated - int(ma.alias_size_in_bytes)
        )
    return report


# ---------------------------------------------------------------------------
# Predicates
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Violation:
    contract: str
    predicate: str
    detail: str

    def __str__(self):
        return f"{self.contract}/{self.predicate}: {self.detail}"


@dataclass(frozen=True)
class Predicate:
    """A named check over one artifact; returns a list of violation
    detail strings (empty = holds)."""

    name: str
    fn: Callable[[ProgramArtifact], list]

    def __call__(self, artifact: ProgramArtifact) -> list:
        return self.fn(artifact)


def predicate(name: str):
    def wrap(fn) -> Predicate:
        return Predicate(name, fn)
    return wrap


@predicate("no_f64")
def no_f64(art: ProgramArtifact) -> list:
    """No float64 anywhere: an f64 tensor on TPU software-emulates (and on
    any backend doubles bytes) — always an accidental promotion here."""
    if art.jaxpr is not None:
        hits = sorted({
            str(v.aval.dtype)
            for eqn in iter_eqns(art.jaxpr)
            for v in eqn.outvars
            if getattr(v, "aval", None) is not None
            and getattr(v.aval, "dtype", None) is not None
            and v.aval.dtype == jnp.float64
        })
        if hits:
            return [f"float64 values staged in jaxpr ({len(hits)} dtypes)"]
        return []
    m = _F64_RE.search(art.stablehlo)
    return [f"f64 tensor in StableHLO: ...{m.group(0)}..."] if m else []


@predicate("no_host_callbacks")
def no_host_callbacks(art: ProgramArtifact) -> list:
    """No host callbacks staged: a pure/debug/io callback in a dispatch
    program is a per-step host round-trip (and a donation barrier) —
    only model.debug_asserts may stage them, and it is off here."""
    out = []
    if art.jaxpr is not None:
        census = primitive_census(art.jaxpr)
        prims = sorted(_HOST_CALLBACK_PRIMS & set(census))
        if prims:
            out.append(f"host-callback primitives staged: {prims}")
    m = _CALLBACK_RE.search(art.stablehlo)
    if m and not out:
        out.append(f"host-callback custom_call in StableHLO: @{m.group(1)}")
    return out


def _finiteness_staged(art: ProgramArtifact) -> bool:
    if art.jaxpr is not None:
        return "is_finite" in primitive_census(art.jaxpr)
    txt = art.stablehlo
    return "is_finite" in txt or "is-finite" in txt


@predicate("no_finiteness_ops")
def no_finiteness_ops(art: ProgramArtifact) -> list:
    """Guard-off purity: with nan_guard / anomaly_guard off, the compiled
    program must be the pre-guard trace — zero is_finite ops (the PR 6/7
    bit-identical-when-off promise, migrated from test_train_fault)."""
    if _finiteness_staged(art):
        return ["is_finite ops staged in a guard-off program"]
    return []


@predicate("finiteness_staged")
def finiteness_staged(art: ProgramArtifact) -> list:
    """Positive control: the guard-ON program must actually stage the
    finiteness check (a contract that can only pass vacuously is dead)."""
    if not _finiteness_staged(art):
        return ["guard on, but no is_finite ops staged"]
    return []


@predicate("donation_complete")
def donation_complete(art: ProgramArtifact) -> list:
    """Every donated input byte aliases into an output buffer."""
    rep = donation_report(art)
    if not rep["available"]:
        return ["memory_analysis unavailable on this backend"]
    if rep["donated_bytes"] == 0:
        return ["nothing donated: donation contract is vacuous here"]
    if rep["leaked_bytes"] > 0:
        return [
            f"donation leaked {rep['leaked_bytes']} of "
            f"{rep['donated_bytes']} donated per-chip bytes "
            f"(alias_size={rep['alias_bytes']})"
        ]
    return []


def n_param_leaves(art: ProgramArtifact) -> int:
    """Weight-leaf count of the artifact's model — the per-leaf unit the
    CPU emitter schedules collectives at (no combiner pass: one grad
    all-reduce / ZeRO-1 all-gather per leaf; on-chip XLA combines them,
    so bands expressed in leaves hold on both backends)."""
    from orion_tpu.models import init_params

    cfg = art.meta["cfg"]
    shapes = jax.eval_shape(
        lambda: init_params(cfg.model, jax.random.key(0))
    )
    return len(jax.tree.leaves(shapes))


def collective_inventory(**expect) -> Predicate:
    """Pin the scheduled collective census. ``expect`` maps op name
    (underscored: ``all_gather=1``) to an exact int, a ``(lo, hi)``
    inclusive band, or a callable(artifact) -> int | (lo, hi) for
    layout-derived bounds; unnamed ops are unconstrained."""

    spec = {k.replace("_", "-"): v for k, v in expect.items()}
    unknown = set(spec) - set(COLLECTIVE_OPS)
    if unknown:
        raise ValueError(f"unknown collective ops: {sorted(unknown)}")

    def fn(art: ProgramArtifact) -> list:
        census = collective_census(art.optimized_hlo)
        out = []
        for op, want in spec.items():
            got = census[op]
            if callable(want):
                want = want(art)
            lo, hi = want if isinstance(want, tuple) else (want, want)
            if not (lo <= got <= hi):
                out.append(
                    f"{op} count {got} outside expected "
                    f"[{lo}, {hi}] (census: " + ", ".join(
                        f"{k}={v}" for k, v in census.items() if v
                    ) + ")"
                )
        return out

    return Predicate("collective_inventory", fn)


def dtype_whitelist_budget(art: ProgramArtifact) -> int:
    """Whitelisted staged bf16->f32 convert sites for the tiny-llama
    train step, as a function of layout: ~16 per layer staged in the
    scan body (norm x2 / rotary / softmax / residual-boundary mirrors),
    +4 per layer under a remat policy (the bwd body re-stages the fwd's
    converts), +5 fixed (final norm, logits, loss, schedule), +2 slack.
    Measured exact across scan_group x remat combos
    (tests/test_contracts.py pins the fit)."""
    mcfg = art.meta["cfg"].model
    unit = mcfg.scan_unit if mcfg.scan_layers else mcfg.n_layers
    remat_extra = 4 * unit if mcfg.remat != "none" else 0
    return 5 + 16 * unit + remat_extra + 2


def bf16_upcast_budget(budget) -> Predicate:
    """Dtype discipline: at most ``budget`` (int, or callable(artifact)
    -> int for layout-derived budgets) staged bf16->f32 convert sites —
    the norm/master/logits whitelist. A new full-width f32 activation in
    a bf16 model shows up as a budget overrun."""

    def fn(art: ProgramArtifact) -> list:
        if art.jaxpr is None:
            return ["no jaxpr available for upcast census"]
        b = budget(art) if callable(budget) else budget
        n = count_bf16_upcasts(art.jaxpr)
        if n > b:
            return [
                f"{n} staged bf16->f32 convert sites exceed the "
                f"whitelist budget {b}"
            ]
        return []

    return Predicate("bf16_upcast_budget", fn)


def output_sharded_over(getter: Callable[[Any], Any], axis: str,
                        what: str) -> Predicate:
    """The compiled executable's output shardings place ``what`` over
    ``axis`` — the artifact-level form of test_zero1's physical-sharding
    pin (the memory lever IS the sharding)."""

    def fn(art: ProgramArtifact) -> list:
        try:
            out_sh = art.compiled().output_shardings
        except Exception as e:  # pragma: no cover - jax-version dependent
            return [f"output_shardings unavailable: {type(e).__name__}"]
        leaves = jax.tree.leaves(
            getter(out_sh),
            is_leaf=lambda x: hasattr(x, "spec"),
        )
        if not leaves:
            return [f"{what}: no output sharding leaves found"]
        bad = sum(
            1 for s in leaves
            if axis not in jax.tree.leaves(tuple(s.spec))
        )
        if bad:
            return [
                f"{what}: {bad}/{len(leaves)} output leaves not sharded "
                f"over '{axis}'"
            ]
        return []

    return Predicate("output_sharded_over", fn)


# ---------------------------------------------------------------------------
# Program builders
# ---------------------------------------------------------------------------

# Small enough to lower/compile in seconds on the fake-device CPU mesh,
# big enough that every structural feature (scan, GQA, norms) is staged.
TRAIN_BASE = (
    "runtime.platform=cpu",
    "train.num_steps=4",
    "train.log_interval=1000",
    "optimizer.warmup_steps=1",
)
ENGINE_BASE = (
    "inference.max_seq_len=128",
    "inference.page_size=16",
    "inference.num_pages=32",
    "inference.max_batch_size=4",
    "inference.prefill_chunk=16",
    "inference.max_new_tokens=8",
)

ENGINE_PROGRAMS = (
    "prefill", "decode", "decode_defaults", "mixed", "mixed_defaults",
    "verify", "verify_defaults", "mixed_verify", "mixed_verify_defaults",
    # The grammar-masked verify specialization (inference.constrained):
    # the same _verify_defaults program called with a legal_mask —
    # switching None -> array is a distinct jit specialization, so the
    # masked trace gets its own contract row.
    "verify_masked",
    # The KV-page migration envelope halves (ISSUE 20): the batched
    # gather (export — a pure pool read, NO donation) and the batched
    # scatter (import — donates the destination cache). One dispatch per
    # page batch by construction; the contracts pin that neither half
    # smuggles in host callbacks, f64, finiteness ops or collectives.
    "migrate_gather", "migrate_scatter",
)


def build_train_step(
    overrides: Sequence[str] = (), preset: str = "tiny-llama"
) -> ProgramArtifact:
    """Lower the Trainer's jitted step at a layout — abstract state/batch
    exactly as the hot path runs them (the memory_report shapes)."""
    from orion_tpu.config import get_config
    from orion_tpu.train.trainer import Trainer

    cfg = get_config(preset, list(TRAIN_BASE) + list(overrides))
    t = Trainer(cfg)
    state = t.abstract_state()
    batch = jax.tree.map(
        lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                       sharding=a.sharding),
        t.global_batch(0),
    )
    args: tuple = (state, batch)
    if t.cfg.train.anomaly_guard:
        args = (*args, jax.ShapeDtypeStruct((), jnp.float32))
    return ProgramArtifact(
        name="train_step",
        lowered=t._jit_step.lower(*args),
        traced=_try_trace(t._jit_step, args),
        donated=tuple(jax.tree.leaves(state)),
        meta={"cfg": t.cfg, "mesh": t.mesh},
    )


def _tp_shard_params(cfg, params, tp: int):
    from orion_tpu.config import ParallelConfig
    from orion_tpu.models.transformer import param_logical_axes
    from orion_tpu.parallel.sharding import param_shardings
    from orion_tpu.runtime import build_mesh

    mesh = build_mesh(
        ParallelConfig(tp=tp), devices=jax.devices("cpu")[:tp]
    )
    return jax.device_put(
        params, param_shardings(mesh, param_logical_axes(cfg.model))
    )


def build_engine_program(
    program: str,
    overrides: Sequence[str] = (),
    preset: str = "tiny-llama",
    tp: int = 0,
) -> ProgramArtifact:
    """Lower one engine dispatch program with inputs shaped exactly as the
    engine's call sites assemble them (engine._decode_window_all /
    _prefill_burst / _verify_all / _mixed_decode). The arrays are zeros —
    lowering only cares about shape/dtype — and the cache is the donated
    tree (executor donate_argnums=(1,)). ``tp > 1`` serves tp-sharded
    params over a fake tp mesh (the xla path partitions from the params'
    shardings alone)."""
    from orion_tpu.config import get_config
    from orion_tpu.infer import InferenceEngine
    from orion_tpu.models import init_params

    if program not in ENGINE_PROGRAMS:
        raise ContractError(
            f"unknown engine program {program!r}; have {ENGINE_PROGRAMS}"
        )
    cfg = get_config(preset, list(ENGINE_BASE) + list(overrides))
    params = init_params(cfg.model, jax.random.key(0))
    if tp > 1:
        params = _tp_shard_params(cfg, params, tp)
    eng = InferenceEngine(cfg, params)
    if tp > 1:
        # Steady-state cache layout: on the xla tp path XLA shards the
        # pool over kv heads from the first dispatch on (the same
        # P(None, 'tp') the pallas path places explicitly). Donating the
        # day-0 unsharded cache would measure a one-off reshard, not the
        # hot loop — the contract checks the program the engine re-runs.
        from jax.sharding import NamedSharding, PartitionSpec as P

        mesh = next(iter(jax.tree.leaves(params))).sharding.mesh
        spec = {"k": P(None, "tp"), "v": P(None, "tp"),
                "k_scale": P(None, "tp"), "v_scale": P(None, "tp")}
        eng.cache = {
            name: jax.device_put(arr, NamedSharding(mesh, spec[name]))
            for name, arr in eng.cache.items()
        }
    jitted, args, kwargs = _engine_call(eng, program)
    return ProgramArtifact(
        name=f"engine_{program}",
        lowered=jitted.lower(*args, **kwargs),
        traced=_try_trace(jitted, args, kwargs),
        donated=tuple(
            jax.tree.map(
                lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                               sharding=a.sharding),
                eng.cache,
            ).values()
        ),
        meta={"cfg": cfg, "engine_cfg": eng.icfg, "program": program},
    )


def _engine_call(eng, program: str):
    """Mirror the engine's dispatch-arg assembly for each program (shape/
    dtype only; values are zeros). Drift is loud: a signature change makes
    the lower() here fail, which is the contract run failing."""
    i32, f32 = np.int32, np.float32
    B, pps = eng.max_batch, eng.pages_per_seq
    zB = np.zeros(B, i32)
    mask = np.zeros(B, bool)
    pt = np.zeros((B, pps), i32)
    sampling = (np.zeros(B, f32), np.zeros(B, i32), np.ones(B, f32))
    key = jax.random.key(0)

    if program in ("decode", "decode_defaults"):
        common = (
            eng.params, eng.cache, zB, zB, pt, mask,
            jax.random.split(key, eng.decode_window),
        )
        extra = sampling if program == "decode" else ()
        return getattr(eng, "_" + program), common + extra, {}

    if program in ("migrate_gather", "migrate_scatter"):
        # The migration copy envelope (ISSUE 20): pow2-padded page-id
        # batches, exactly as export_migration_pages / import_pages
        # assemble them. Gather reads the pool (no donation); scatter
        # donates the destination cache (executor donate_argnums=(0,)).
        pages = np.zeros(8, i32)
        if program == "migrate_gather":
            return eng._gather_pages, (eng.cache, pages), {}
        L = eng.mcfg.n_layers
        blocks = {
            name: np.zeros((8, L) + arr.shape[1:], arr.dtype)
            for name, arr in eng.cache.items()
        }
        return eng._scatter_pages, (eng.cache, pages, blocks), {}

    if program == "prefill":
        S = eng.icfg.prefill_chunk
        nb = 2
        args = (
            eng.params, eng.cache,
            np.zeros((nb, S), i32), np.ones(nb, i32),
            np.zeros((nb, S // eng.psz), i32),
            np.zeros(nb, i32), np.zeros((nb, 0), i32),
        )
        return eng._prefill, args, {}

    if program in ("verify", "verify_defaults", "verify_masked"):
        if getattr(eng, "_verify", None) is None:
            raise ContractError(
                "verify programs need inference.speculative=true or "
                "inference.constrained=true in the contract overrides"
            )
        W2 = eng.icfg.speculate_tokens + 1
        common = (
            eng.params, eng.cache, np.zeros((B, W2), i32), zB,
            np.ones(B, i32), pt, mask, key,
        )
        extra = sampling if program == "verify" else ()
        if program == "verify_masked":
            # The masked specialization: all-True rows shape the trace
            # exactly as the engine's host-built FSM masks do.
            kwargs = {
                "legal_mask": np.ones(
                    (B, W2, eng.mcfg.vocab_size), bool
                ),
            }
            return eng._verify_defaults, common, kwargs
        return getattr(eng, "_" + program), common + extra, {}

    # mixed / mixed_verify: one-page chunk rows (the chunk width is a
    # static arg — any page-multiple width traces the same program family).
    if not eng.chunked:
        raise ContractError(
            "mixed programs need inference.chunked_prefill=true in the "
            "contract overrides"
        )
    S = eng.psz
    chunk = (
        np.zeros((1, S), i32), np.ones(1, i32),
        np.zeros((1, S // eng.psz), i32),
        np.zeros(1, i32), np.zeros((1, 0), i32),
    )
    if program in ("mixed", "mixed_defaults"):
        common = (eng.params, eng.cache, zB, zB, pt, mask, key) + chunk
        extra = sampling if program == "mixed" else ()
        return getattr(eng, "_" + program), common + extra, {}

    if getattr(eng, "_mixed_verify", None) is None:
        raise ContractError(
            "mixed_verify programs need inference.speculative=true (or "
            "inference.constrained=true) AND "
            "inference.chunked_prefill=true in the contract overrides"
        )
    W2 = eng.icfg.speculate_tokens + 1
    common = (
        eng.params, eng.cache, np.zeros((B, W2), i32), zB,
        np.ones(B, i32), pt, mask, key,
    ) + chunk
    extra = sampling if program == "mixed_verify" else ()
    return getattr(eng, "_" + program), common + extra, {}


def build_program(
    program: str, overrides: Sequence[str] = (), **kw
) -> ProgramArtifact:
    """The registry's single builder entry point: ``"train"`` or an
    engine program name."""
    if program == "train":
        return build_train_step(overrides, **kw)
    return build_engine_program(program, overrides, **kw)


# ---------------------------------------------------------------------------
# Contract registry
# ---------------------------------------------------------------------------


@dataclass(frozen=True)
class Contract:
    """One declarative contract: a program at a layout plus predicates.

    ``smoke`` marks the cpu-viable fast set (tools/contract_check.py
    --smoke, wired into tier-1); the full grid adds layout compositions
    on top via extra overrides. ``devices`` is the fake-device floor the
    layout needs (the sweeper skips rows the host cannot fake)."""

    name: str
    program: str
    overrides: tuple = ()
    predicates: tuple = ()
    smoke: bool = False
    devices: int = 1
    tp: int = 0
    doc: str = ""


@dataclass
class ContractResult:
    name: str
    ok: bool
    violations: list
    seconds: float
    notes: dict = field(default_factory=dict)

    def as_row(self) -> dict:
        return {
            "contract": self.name,
            "ok": self.ok,
            "violations": [str(v) for v in self.violations],
            "seconds": round(self.seconds, 2),
            **self.notes,
        }


def _registry() -> dict[str, Contract]:
    C: dict[str, Contract] = {}

    def add(name, program, overrides=(), predicates=(), **kw):
        C[name] = Contract(
            name=name, program=program, overrides=tuple(overrides),
            predicates=tuple(predicates), **kw
        )

    # -- train step -------------------------------------------------------
    add(
        "train_hygiene", "train",
        predicates=(no_f64, no_host_callbacks, no_finiteness_ops,
                    donation_complete),
        smoke=True,
        doc="baseline train step: no f64 promotion, no host callbacks "
            "(debug_asserts off), guard-off purity (zero is_finite — the "
            "test_train_fault pin), donation fully aliased "
            "(memory_report's failure class, PR 4/9)",
    )
    add(
        "train_guard_staged", "train",
        overrides=("train.anomaly_guard=true",),
        predicates=(finiteness_staged, donation_complete),
        smoke=True,
        doc="positive control: anomaly_guard=on really stages the "
            "finiteness check AND keeps the donation-safe skip aliased",
    )
    add(
        "train_dtype_discipline", "train",
        overrides=("model.dtype=bfloat16",),
        predicates=(bf16_upcast_budget(dtype_whitelist_budget), no_f64),
        smoke=True,
        doc="bf16 activations stay bf16: staged f32 upcast sites bounded "
            "by the norm/master/logits whitelist",
    )
    add(
        "zero1_collectives", "train",
        overrides=("parallel.dp=8", "data.batch_size=8",
                   "train.zero1=true"),
        predicates=(
            # ONE RS/AG pair per weight-update leaf and nothing more: the
            # updated-param AG leg gathers each leaf exactly once (2x
            # would be a doubled wire bill), the grad reduction costs at
            # most one reduce-scatter-or-all-reduce per leaf (XLA's CPU
            # emitter spells RS as AR + local slice) plus the fused
            # loss/metric scalars — and no ring/a2a traffic at all.
            collective_inventory(
                all_gather=lambda a: (1, n_param_leaves(a)),
                reduce_scatter=lambda a: (0, n_param_leaves(a)),
                all_reduce=lambda a: (0, n_param_leaves(a) + 3),
                collective_permute=0, all_to_all=0,
            ),
            output_sharded_over(
                lambda out: out[0]["opt"]["mu"], "dp", "adam mu moments"
            ),
            donation_complete,
        ),
        smoke=True,
        devices=8,
        doc="ZeRO-1 step: one RS/AG pair per update leaf over dp "
            "(PAPERS.md 2004.13336) and the moments physically "
            "dp-sharded (the test_zero1 pin, artifact-level)",
    )
    add(
        "dp_baseline_collectives", "train",
        overrides=("parallel.dp=8", "data.batch_size=8"),
        predicates=(
            # Plain DP: the grad all-reduce only — ANY all-gather or
            # reduce-scatter here means some state silently stopped
            # being replicated (the footprint regression zero1 makes on
            # purpose and nothing else may).
            collective_inventory(
                all_gather=0, reduce_scatter=0,
                all_reduce=lambda a: (1, n_param_leaves(a) + 3),
                collective_permute=0, all_to_all=0,
            ),
            donation_complete,
        ),
        smoke=True,
        devices=8,
        doc="plain dp=8 step: grads all-reduce only; no gathers/scatters "
            "(state stays replicated)",
    )

    def _pp_hop_band(art: ProgramArtifact) -> tuple:
        """Staged ring-hop band for a pipeline step: the ticks are
        python-unrolled on the compat path (one staged hop per fwd tick
        + one per bwd tick, minus the skipped boundary hops =
        2*(M+pp-1)-2 for the differentiated schedules) and lax.scan'd on
        modern jax / 1f1b (the body stages its hop once) — so the band
        is [2, 2*(M+pp-1)]. Zero means the ring is GONE (stages stopped
        talking); above means a schedule staged extra hops per tick."""
        p = art.meta["cfg"].parallel
        return (2, 2 * (p.pp_microbatches + p.pp - 1))

    add(
        "pp_ring_hops", "train",
        overrides=("parallel.pp=2", "parallel.pp_microbatches=2",
                   "model.scan_layers=true", "model.n_layers=2",
                   "data.batch_size=4"),
        predicates=(
            # Ring hops only: point-to-point traffic spelled as
            # collective-permute (modern jax ppermute) or the one-hot
            # psum_scatter emulation (compat seam -> reduce-scatter).
            # An all-gather here is the failure mode where a stage
            # gathers the whole activation stack instead of ring-hopping
            # its slice; all-reduce belongs to the metric scalars only.
            collective_inventory(
                all_gather=0, all_to_all=0,
                collective_permute=lambda a: (0, _pp_hop_band(a)[1]),
                reduce_scatter=lambda a: (0, _pp_hop_band(a)[1]),
            ),
            Predicate(
                "ring_hops_present",
                lambda a: [] if sum(
                    collective_census(a.optimized_hlo)[op]
                    for op in ("collective-permute", "reduce-scatter")
                ) >= 2 else ["no ring hops staged: the pipeline ring "
                             "is gone (stages not communicating)"],
            ),
            donation_complete,
        ),
        devices=2,
        doc="pp=2 pipeline step: ring-hop count per tick bounded "
            "(2..2*(M+pp-1) staged hops as permute/psum_scatter), no "
            "stage-gather all-gathers",
    )

    # -- engine programs --------------------------------------------------
    eng_hygiene = (no_f64, no_host_callbacks, no_finiteness_ops,
                   donation_complete)
    add(
        "decode_hygiene", "decode_defaults",
        predicates=eng_hygiene, smoke=True,
        doc="fused decode window (greedy-defaults path): guard-off "
            "purity, no callbacks, cache donation aliased",
    )
    add(
        "decode_guard_staged", "decode_defaults",
        overrides=("inference.nan_guard=true",),
        predicates=(finiteness_staged, donation_complete), smoke=True,
        doc="positive control: nan_guard=on decode stages is_finite and "
            "still donates the cache",
    )
    add(
        "prefill_hygiene", "prefill",
        predicates=(no_f64, no_host_callbacks, donation_complete),
        smoke=True,
        doc="batched prefill: no callbacks/f64, cache donation aliased",
    )
    add(
        "verify_hygiene", "verify_defaults",
        overrides=("inference.speculative=true",),
        predicates=eng_hygiene,
        doc="speculative verify dispatch: hygiene + cache donation",
    )
    add(
        "constrained_verify_hygiene", "verify_masked",
        overrides=("inference.constrained=true",),
        predicates=eng_hygiene + (
            # Constrained programs may not grow a wire bill: a single-
            # replica masked verify schedules ZERO collectives, exactly
            # like its unmasked twin — the FSM mask is a pure elementwise
            # where() on the logits.
            collective_inventory(
                all_gather=0, reduce_scatter=0, all_reduce=0,
                collective_permute=0, all_to_all=0,
            ),
        ),
        doc="grammar-masked verify specialization "
            "(inference.constrained): the FSM legal_mask composes into "
            "the verify program with no host callbacks, no f64, no "
            "finiteness ops, zero collectives, cache still donated",
    )
    add(
        "mixed_hygiene", "mixed_defaults",
        overrides=("inference.chunked_prefill=true",),
        predicates=eng_hygiene,
        doc="mixed decode+chunk dispatch: hygiene + cache donation",
    )
    add(
        "long_prefill_hygiene", "mixed_defaults",
        overrides=("inference.chunked_prefill=true",
                   "inference.long_context=true",
                   "inference.host_tier_bytes=1048576",
                   "model.sliding_window=32"),
        predicates=eng_hygiene + (
            # The page walk is scalar metadata, not communication: a
            # single-replica long-context mixed program schedules ZERO
            # collectives, exactly like its short-context twin.
            collective_inventory(
                all_gather=0, reduce_scatter=0, all_reduce=0,
                collective_permute=0, all_to_all=0,
            ),
        ),
        smoke=True,
        doc="long-context serving (ISSUE 19): the mixed chunk+decode "
            "program under long_context + SWA gains no host callbacks, "
            "d2h copies, finiteness ops or collectives from the "
            "per-request paging machinery — demote/restore copies live "
            "in their own dispatches, never in the compiled step; cache "
            "donation still aliased",
    )
    add(
        "mixed_verify_hygiene", "mixed_verify_defaults",
        overrides=("inference.chunked_prefill=true",
                   "inference.speculative=true"),
        predicates=eng_hygiene,
        doc="mixed verify dispatch: hygiene + cache donation",
    )
    add(
        "decode_sampled_hygiene", "decode",
        predicates=eng_hygiene,
        doc="per-request-sampling decode path: same hygiene as defaults",
    )
    add(
        "host_tier_decode_hygiene", "decode_defaults",
        overrides=("inference.prefix_cache=true",
                   "inference.host_tier_bytes=1048576"),
        predicates=eng_hygiene, smoke=True,
        doc="host-tier-enabled decode (ISSUE 18): the tiered cache is "
            "pure host machinery — the compiled decode program gains no "
            "host callbacks or d2h copies, cache donation still aliased "
            "(eviction/restore copies live in their own dispatches, "
            "never on the decode hot path)",
    )
    add(
        "host_tier_verify_hygiene", "verify_defaults",
        overrides=("inference.prefix_cache=true",
                   "inference.host_tier_bytes=1048576",
                   "inference.speculative=true"),
        predicates=eng_hygiene,
        doc="host-tier x speculation: the verify dispatch is equally "
            "untouched by the tier (no callbacks, donation complete)",
    )
    # Zero-collective pin shared by both migration envelope halves: a
    # single-replica page copy is pure pool traffic — ONE dispatch per
    # pow2-padded page batch with no collective fan-out (a per-page
    # dispatch blowup would show up as N gathers in the bench, but a
    # collective sneaking into the copy program would show up HERE).
    _mig_no_collectives = collective_inventory(
        all_gather=0, reduce_scatter=0, all_reduce=0,
        collective_permute=0, all_to_all=0,
    )
    add(
        "migration_hygiene", "migrate_gather",
        predicates=(no_f64, no_host_callbacks, no_finiteness_ops,
                    _mig_no_collectives),
        smoke=True,
        doc="migration export half (ISSUE 20): the batched page gather "
            "feeding a prefill->decode handoff stages no host callbacks/"
            "f64/finiteness ops and zero collectives per page batch. "
            "Deliberately NO donation predicate: the gather is a pure "
            "pool read (the source request keeps serving if the handoff "
            "dies), so nothing is donated by design",
    )
    add(
        "migration_scatter_hygiene", "migrate_scatter",
        predicates=eng_hygiene + (_mig_no_collectives,),
        smoke=True,
        doc="migration import half (ISSUE 20): the batched page scatter "
            "admitting migrated KV into the decode replica's pool — same "
            "hygiene, zero collectives, and the destination cache "
            "donation fully aliased (a leaked alias would double the "
            "decode pool for the copy step)",
    )
    add(
        "tp_decode_collectives", "decode_defaults",
        tp=2, devices=2,
        predicates=(
            # tp decode: row-parallel matmul partials all-reduce; nothing
            # may all-gather a weight matrix (that would serialize tp's
            # whole memory win). The logits unembed may gather the [B, V]
            # activation — bounded, not a param gather.
            collective_inventory(all_gather=(0, 2)),
            no_finiteness_ops, donation_complete,
        ),
        doc="tp=2-sharded decode: no unexpected all-gathers (params stay "
            "sharded; only bounded activation gathers allowed)",
    )
    return C


CONTRACTS: dict[str, Contract] = _registry()


def smoke_contracts() -> list[str]:
    return [c.name for c in CONTRACTS.values() if c.smoke]


def grid_contracts() -> list[str]:
    return list(CONTRACTS)


def check_artifact(
    artifact: ProgramArtifact,
    predicates: Sequence[Predicate],
    contract_name: str = "adhoc",
) -> list:
    """Run predicates over one artifact; returns Violations."""
    out = []
    for pred in predicates:
        for detail in pred(artifact):
            out.append(Violation(contract_name, pred.name, detail))
    return out


def check(
    name: str, extra_overrides: Sequence[str] = ()
) -> ContractResult:
    """Evaluate one registered contract (optionally at a layout variant
    layered on top of its base overrides)."""
    if name not in CONTRACTS:
        raise ContractError(
            f"unknown contract {name!r}; have {sorted(CONTRACTS)}"
        )
    c = CONTRACTS[name]
    t0 = time.perf_counter()
    artifact = build_program(
        c.program, tuple(c.overrides) + tuple(extra_overrides),
        **({"tp": c.tp} if c.tp else {}),
    )
    violations = check_artifact(artifact, c.predicates, name)
    return ContractResult(
        name=name,
        ok=not violations,
        violations=violations,
        seconds=time.perf_counter() - t0,
        notes={"program": c.program,
               "overrides": list(c.overrides) + list(extra_overrides)},
    )
