"""Static analysis for orion-tpu (ISSUE 15).

Two layers, both *static* — nothing here executes a compiled program:

- :mod:`orion_tpu.analysis.contracts` — declarative contracts over the
  compiled artifacts (jaxpr / StableHLO / optimized HLO / memory analysis)
  of the programs the stack actually dispatches: the train step at a given
  parallel layout and the serving engine's prefill/decode/verify/mixed
  programs. ``tools/contract_check.py`` sweeps a layout grid.
- :mod:`orion_tpu.analysis.lint` — an AST pass with repo-specific rules
  (host syncs in dispatch hot paths, wall clocks in obs, unregistered
  Stats classes, validation-free Config dataclasses, overbroad excepts in
  fault envelopes). ``tools/lint.py`` is the CLI.

SANITIZERS.md ("Static contracts & lint") maps each contract and rule to
the failure class it guards.
"""

from orion_tpu.analysis import contracts, lint  # noqa: F401

__all__ = ["contracts", "lint"]
