"""Repo-native AST lint (ISSUE 15, layer 2).

Host-side discipline the compiled-program contracts can't see: no device
syncs in dispatch hot paths, monotonic clocks in obs, every Stats class
riding the ``reset_timing``/registry protocol, Config dataclasses
validating their fields, and fault envelopes that never swallow blindly.
Each finding is typed and suppressible per-site with a comment of the
form ``# orion: allow[<rule>] <reason>`` on the finding's line or the
line above. The reason is mandatory — an allow comment without one is itself a
finding (``bad-allow``), and an allow that suppresses nothing is flagged
(``unused-allow``) so stale suppressions cannot accumulate. CLI:
``tools/lint.py [--diff [REF]]``.
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass
from pathlib import Path
from typing import Callable, Iterable, Optional, Sequence

__all__ = [
    "Finding", "RULES", "lint_source", "lint_paths", "iter_target_files",
    "DEFAULT_TARGETS",
]

# Entry scripts + packages the sweep covers (repo-relative).
DEFAULT_TARGETS = ("orion_tpu", "tools", "train.py", "generate.py",
                   "bench.py")

_ALLOW_RE = re.compile(
    r"#\s*orion:\s*allow\[([a-z0-9_,\s-]+)\]\s*(.*)"
)


@dataclass
class Finding:
    rule: str
    path: str
    line: int
    msg: str
    suppressed: bool = False
    reason: str = ""

    def __str__(self):
        tag = " (suppressed: %s)" % self.reason if self.suppressed else ""
        return f"{self.path}:{self.line}: [{self.rule}] {self.msg}{tag}"


@dataclass
class _Allow:
    line: int
    rules: tuple
    reason: str
    used: bool = False


# ---------------------------------------------------------------------------
# Rule helpers
# ---------------------------------------------------------------------------


def _call_name(node: ast.Call) -> str:
    """Dotted best-effort name of a call target: ``jax.device_get`` /
    ``np.asarray`` / ``.item`` (attribute tail for method calls)."""
    f = node.func
    parts = []
    while isinstance(f, ast.Attribute):
        parts.append(f.attr)
        f = f.value
    if isinstance(f, ast.Name):
        parts.append(f.id)
        return ".".join(reversed(parts))
    return "." + ".".join(reversed(parts)) if parts else ""


def _enclosing_funcs(tree: ast.AST):
    """Yield (func_node, qualname) for every function (nested ones with
    their full dotted qualname)."""
    funcs = []

    def walk(node, stack):
        for child in ast.iter_child_nodes(node):
            if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                funcs.append((child, tuple(stack) + (child.name,)))
                walk(child, stack + [child.name])
            else:
                walk(child, stack)

    walk(tree, [])
    return funcs


def _walk_own_body(func) -> Iterable[ast.AST]:
    """Walk a function's OWN statements, not descending into nested
    function definitions — each nested def is visited separately by
    ``_enclosing_funcs``, so a call inside it must not be reported twice
    (once per enclosing frame)."""
    stack = list(ast.iter_child_nodes(func))
    while stack:
        node = stack.pop()
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            continue
        yield node
        stack.extend(ast.iter_child_nodes(node))


# ---------------------------------------------------------------------------
# Rules
# ---------------------------------------------------------------------------

@dataclass(frozen=True)
class Rule:
    name: str
    doc: str
    fn: Callable[[ast.AST, str, str], list]

    def check(self, tree, src, relpath) -> list:
        return self.fn(tree, src, relpath)


def _rule(name, doc):
    def wrap(fn):
        return Rule(name, doc, fn)
    return wrap


def _is_host_sync(node: ast.Call) -> bool:
    """Host-synchronizing call shapes: ``<x>.item()`` /
    ``<x>.block_until_ready()`` on anything, ``jax.device_get`` /
    ``jax.block_until_ready``, and ``np.asarray`` (forces a device->host
    transfer when handed a device array)."""
    name = _call_name(node)
    tail = name.rsplit(".", 1)[-1]
    if tail in ("item", "block_until_ready"):
        return isinstance(node.func, ast.Attribute) or name.startswith(
            "jax."
        )
    return name in ("jax.device_get", "device_get", "np.asarray",
                    "numpy.asarray")

# Dispatch-body scope per module suffix: None = every function in the
# module is hot (runner/executor are the traced/dispatch layer); a tuple
# of prefixes scopes to the engine's step-loop call tree.
_DISPATCH_SCOPE = {
    "orion_tpu/infer/runner.py": None,
    "orion_tpu/infer/executor.py": None,
    "orion_tpu/infer/engine.py": (
        "step", "_decode", "_mixed", "_verify", "_prefill", "_propose",
        "_accept", "_run_dispatch", "_grow_pages", "_roll_window",
        # Host-tier copy paths (ISSUE 18): the batched d2h/h2d envelopes
        # run from admission/eviction inside step — their single syncs
        # are the documented one-copy points (justified allows).
        "_spill", "_restore", "_resolve_host", "offload_prefix",
        # Per-request KV paging (ISSUE 19): the batched page-in restore
        # is the same documented one-h2d envelope.
        "_page_in",
        # KV-page migration (ISSUE 20): the gather/scatter copy envelopes
        # run from the router's serving loop — one sync per batch each
        # (justified allows).
        "export_migration", "import_pages",
    ),
}


@_rule(
    "host-sync",
    "host-synchronizing call (.item/device_get/block_until_ready/"
    "np.asarray) inside an engine/runner/executor dispatch body — every "
    "sync in the hot path must be the documented ONE-fetch point",
)
def _host_sync(tree, src, relpath) -> list:
    scope = None
    for suffix, names in _DISPATCH_SCOPE.items():
        if relpath.endswith(suffix):
            scope = (True, names)
            break
    if scope is None:
        return []
    _, prefixes = scope
    out = []
    for func, qual in _enclosing_funcs(tree):
        # A nested helper inherits its enclosing dispatch body's scope:
        # any qualname component matching a hot-path prefix puts the
        # whole frame in scope.
        if prefixes is not None and not any(
            part.startswith(p) for part in qual for p in prefixes
        ):
            continue
        for node in _walk_own_body(func):
            if not isinstance(node, ast.Call) or not _is_host_sync(node):
                continue
            out.append(Finding(
                "host-sync", relpath, node.lineno,
                f"{_call_name(node)}() in dispatch body "
                f"{'.'.join(qual)}",
            ))
    return out


@_rule(
    "clock",
    "time.time() inside orion_tpu — span/duration timing must ride "
    "monotonic clocks (perf_counter/monotonic); wall-clock export "
    "stamps need a justifying allow",
)
def _clock(tree, src, relpath) -> list:
    if not relpath.startswith("orion_tpu/"):
        return []
    out = []
    for node in ast.walk(tree):
        if isinstance(node, ast.Call) and _call_name(node) == "time.time":
            out.append(Finding(
                "clock", relpath, node.lineno,
                "time.time() — use time.perf_counter()/monotonic() for "
                "durations",
            ))
    return out


@_rule(
    "stats-timing",
    "a *Stats dataclass without as_timing()/summary() — every Stats "
    "class must ride the reset_timing drain / registry protocol "
    "(PR 8's unification; an unregistered one silently exports nothing)",
)
def _stats_timing(tree, src, relpath) -> list:
    if not relpath.startswith("orion_tpu/"):
        return []
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        if not node.name.endswith("Stats"):
            continue
        is_dc = any(
            (isinstance(d, ast.Name) and d.id == "dataclass")
            or (isinstance(d, ast.Call) and _call_name(d) == "dataclass")
            or (isinstance(d, ast.Attribute) and d.attr == "dataclass")
            for d in node.decorator_list
        )
        if not is_dc:
            continue
        methods = {
            n.name for n in node.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        if not methods & {"as_timing", "summary"}:
            out.append(Finding(
                "stats-timing", relpath, node.lineno,
                f"{node.name} defines neither as_timing() nor summary()",
            ))
    return out


@_rule(
    "config-validation",
    "a *Config dataclass in config.py without __post_init__ — domain "
    "validation at construction is what turns a typo'd knob into a "
    "named error instead of a trace-time stack",
)
def _config_validation(tree, src, relpath) -> list:
    if not relpath.endswith("orion_tpu/config.py"):
        return []
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ClassDef):
            continue
        if not node.name.endswith("Config"):
            continue
        methods = {
            n.name for n in node.body
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        }
        if "__post_init__" not in methods:
            out.append(Finding(
                "config-validation", relpath, node.lineno,
                f"{node.name} has no __post_init__ validation",
            ))
    return out


# Fault-envelope modules: catching Exception here is sometimes the whole
# point (contain ANY dispatch failure) — but each catch-all must say so.
_FAULT_ENVELOPES = (
    "orion_tpu/runtime/fault.py", "orion_tpu/infer/executor.py",
    "orion_tpu/infer/engine.py", "orion_tpu/infer/router.py",
    "orion_tpu/ckpt/checkpoint.py",
)


@_rule(
    "fault-except",
    "bare/overbroad except inside a fault envelope — a blind catch "
    "swallows the typed-outcome discipline (PR 6/7); every intentional "
    "catch-all needs a justifying allow",
)
def _fault_except(tree, src, relpath) -> list:
    in_envelope = any(relpath.endswith(m) for m in _FAULT_ENVELOPES)
    out = []
    for node in ast.walk(tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        if node.type is None:
            out.append(Finding(
                "fault-except", relpath, node.lineno,
                "bare except: catches SystemExit/KeyboardInterrupt too",
            ))
            continue
        if not in_envelope:
            continue
        names = []
        types = (
            node.type.elts if isinstance(node.type, ast.Tuple)
            else [node.type]
        )
        for t in types:
            if isinstance(t, ast.Name):
                names.append(t.id)
            elif isinstance(t, ast.Attribute):
                names.append(t.attr)
        if set(names) & {"Exception", "BaseException"}:
            out.append(Finding(
                "fault-except", relpath, node.lineno,
                f"except {'/'.join(names)} in a fault envelope",
            ))
    return out


RULES: tuple[Rule, ...] = (
    _host_sync, _clock, _stats_timing, _config_validation, _fault_except,
)
RULE_NAMES = tuple(r.name for r in RULES) + (
    "bad-allow", "unused-allow", "parse-error",
)


# ---------------------------------------------------------------------------
# Engine
# ---------------------------------------------------------------------------


def _iter_comments(src: str):
    """(line, text) for every REAL comment token — allow parsing must not
    read allow-shaped text out of string literals (a docstring quoting
    the syntax could silently suppress a neighboring finding)."""
    try:
        for tok in tokenize.generate_tokens(io.StringIO(src).readline):
            if tok.type == tokenize.COMMENT:
                yield tok.start[0], tok.string
    except (tokenize.TokenError, IndentationError, SyntaxError):
        return


def _parse_allows(src: str, relpath: str) -> tuple[list, list]:
    """Collect ``# orion: allow[rule,...] reason`` comments; a missing
    reason is a ``bad-allow`` finding, an unknown rule too."""
    allows: list[_Allow] = []
    findings: list[Finding] = []
    for i, comment in _iter_comments(src):
        m = _ALLOW_RE.search(comment)
        if m is None:
            continue
        rules = tuple(
            r.strip() for r in m.group(1).split(",") if r.strip()
        )
        reason = m.group(2).strip()
        unknown = [r for r in rules if r not in RULE_NAMES]
        if unknown:
            findings.append(Finding(
                "bad-allow", relpath, i,
                f"allow names unknown rule(s) {unknown}; have "
                f"{sorted(set(RULE_NAMES) - {'bad-allow', 'unused-allow'})}",
            ))
            continue
        if not reason:
            findings.append(Finding(
                "bad-allow", relpath, i,
                "allow comment without a reason — justify the site",
            ))
            continue
        allows.append(_Allow(line=i, rules=rules, reason=reason))
    return allows, findings


def lint_source(src: str, relpath: str) -> list:
    """Lint one file's source; returns ALL findings (suppressed ones
    flagged, so callers can render them distinctly)."""
    try:
        tree = ast.parse(src)
    except SyntaxError as e:
        return [Finding("parse-error", relpath, e.lineno or 0,
                        f"unparseable: {e.msg}")]
    allows, findings = _parse_allows(src, relpath)
    for rule in RULES:
        findings.extend(rule.check(tree, src, relpath))
    # Apply suppressions: an allow covers its own line and the line
    # directly below (comment-above style).
    by_line: dict[tuple[int, str], _Allow] = {}
    for a in allows:
        for rule in a.rules:
            by_line[(a.line, rule)] = a
            by_line[(a.line + 1, rule)] = a
    for f in findings:
        a = by_line.get((f.line, f.rule))
        if a is not None:
            f.suppressed = True
            f.reason = a.reason
            a.used = True
    for a in allows:
        if not a.used:
            findings.append(Finding(
                "unused-allow", relpath, a.line,
                f"allow[{','.join(a.rules)}] suppresses nothing — remove "
                f"the stale comment",
            ))
    return sorted(findings, key=lambda f: (f.path, f.line, f.rule))


def iter_target_files(
    root: Path, targets: Sequence[str] = DEFAULT_TARGETS
) -> Iterable[Path]:
    for t in targets:
        p = root / t
        if p.is_file():
            yield p
        elif p.is_dir():
            yield from sorted(
                q for q in p.rglob("*.py") if "__pycache__" not in q.parts
            )


def lint_paths(
    root: Path, paths: Optional[Iterable[Path]] = None
) -> list:
    """Lint files (default: the full target set) and return findings."""
    root = Path(root)
    if paths is None:
        paths = iter_target_files(root)
    findings: list[Finding] = []
    for p in paths:
        p = Path(p)
        if p.suffix != ".py" or not p.exists():
            continue
        rel = str(p.relative_to(root)) if p.is_absolute() else str(p)
        findings.extend(lint_source(p.read_text(), rel))
    return findings
