"""Fused RMSNorm as a Pallas TPU kernel (reference ``orion.ops`` fused norm).

Forward fuses the square-mean reduction, rsqrt, and scale multiply in one
VMEM pass over row blocks. The custom VJP computes dx with a second fused
kernel (recomputing the row rstd instead of storing it); dscale is a single
cross-row reduction left to XLA, which emits an optimal fused reduce.

dx derivation for y = x * r * s with r = rsqrt(mean(x^2) + eps):
  dx = r * (g*s - x * r^2 * mean(g*s*x, axis=-1))
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from orion_tpu.ops.pallas.common import pad_axis, resolve_interpret, round_up


def _fwd_kernel(eps, x_ref, s_ref, o_ref):
    x = x_ref[:].astype(jnp.float32)
    r = jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    o_ref[:] = (x * r * s_ref[0, :].astype(jnp.float32)[None, :]).astype(
        o_ref.dtype
    )


def _dx_kernel(eps, x_ref, s_ref, g_ref, o_ref):
    x = x_ref[:].astype(jnp.float32)
    g = g_ref[:].astype(jnp.float32)
    s = s_ref[0, :].astype(jnp.float32)[None, :]
    r = jax.lax.rsqrt(jnp.mean(x * x, axis=-1, keepdims=True) + eps)
    u = g * s
    o_ref[:] = (r * (u - x * r * r * jnp.mean(u * x, axis=-1, keepdims=True))).astype(
        o_ref.dtype
    )


def _rows_call(kernel, eps, block_rows, interpret, out_dtype, x2d, scale2d, *extra):
    R, D = x2d.shape
    br = min(block_rows, round_up(R, 8))
    Rp = round_up(R, br)
    x2d = pad_axis(x2d, 0, Rp)
    extra = [pad_axis(e, 0, Rp) for e in extra]
    row_spec = pl.BlockSpec((br, D), lambda i: (i, 0))
    out = pl.pallas_call(
        functools.partial(kernel, eps),
        grid=(Rp // br,),
        in_specs=[row_spec, pl.BlockSpec((1, D), lambda i: (0, 0))]
        + [row_spec] * len(extra),
        out_specs=row_spec,
        out_shape=jax.ShapeDtypeStruct((Rp, D), out_dtype),
        interpret=interpret,
    )(x2d, scale2d, *extra)
    return out[:R]


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _rmsnorm(eps, block_rows, interpret, x2d, scale):
    return _rows_call(
        _fwd_kernel, eps, block_rows, interpret, x2d.dtype, x2d, scale[None, :]
    )


def _rmsnorm_fwd(eps, block_rows, interpret, x2d, scale):
    return _rmsnorm(eps, block_rows, interpret, x2d, scale), (x2d, scale)


def _rmsnorm_bwd(eps, block_rows, interpret, res, g):
    x2d, scale = res
    dx = _rows_call(
        _dx_kernel, eps, block_rows, interpret, x2d.dtype, x2d, scale[None, :], g
    )
    xf = x2d.astype(jnp.float32)
    r = jax.lax.rsqrt(jnp.mean(xf * xf, axis=-1, keepdims=True) + eps)
    dscale = jnp.einsum("rd,rd->d", g.astype(jnp.float32), xf * r)
    return dx, dscale.astype(scale.dtype)


_rmsnorm.defvjp(_rmsnorm_fwd, _rmsnorm_bwd)


def rmsnorm_pallas(
    x: jax.Array,
    scale: jax.Array,
    *,
    eps: float = 1e-5,
    block_rows: int = 256,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """RMSNorm over the last axis; x [..., D], scale [D]."""
    D = x.shape[-1]
    x2d = x.reshape(-1, D)
    out = _rmsnorm(eps, block_rows, resolve_interpret(interpret), x2d, scale)
    return out.reshape(x.shape)
