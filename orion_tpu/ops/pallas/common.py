"""Shared helpers for the Pallas TPU kernels (reference ``orion.ops`` L0).

All kernels in this package follow the same conventions:

- Block shapes are static; callers pad to block multiples and the kernels
  mask padded positions (XLA/Mosaic require static shapes, SURVEY.md §8).
- Math is float32 inside the kernel regardless of the activation dtype
  (bf16-safe convention shared with the xla reference ops).
- ``interpret=True`` runs the kernel through the Pallas interpreter so the
  same code is testable on the fake-CPU-device mesh (SURVEY.md §5).
"""

from __future__ import annotations

import jax

NEG_INF = -1e30  # finite -inf stand-in: exp(NEG_INF - m) underflows to 0.


def resolve_interpret(interpret) -> bool:
    """None -> autodetect: compiled on TPU, interpreted elsewhere."""
    if interpret is None:
        return jax.default_backend() != "tpu"
    return bool(interpret)


def round_up(n: int, m: int) -> int:
    return (n + m - 1) // m * m


def pad_axis(x: jax.Array, axis: int, target: int) -> jax.Array:
    """Zero-pad ``axis`` of x up to length ``target``."""
    if x.shape[axis] == target:
        return x
    pads = [(0, 0)] * x.ndim
    pads[axis] = (0, target - x.shape[axis])
    import jax.numpy as jnp

    return jnp.pad(x, pads)


def quantize_kv(x: jax.Array) -> tuple[jax.Array, jax.Array]:
    """Symmetric per-vector int8: x [..., H] -> (q int8 [..., H], scale
    [...] f32) with x ~ q * scale. Scale is per (token, kv-head).

    Lives here (plain jnp, Pallas-kernel-legal) because it is the SINGLE
    definition both the jnp cache paths (infer/kv_cache.py re-exports it)
    and the paged kernels' fused in-kernel writes must share — decode,
    prefill, and speculative verification have to agree bit-for-bit.

    The scale is an explicit multiply by the f32 constant 1/127, NOT a
    division by 127: XLA keeps a true f32 divide on the host path but
    rewrites constant divides to reciprocal multiplies inside compiled /
    interpreted Pallas bodies, and the two round differently by 1 ULP on
    some inputs — enough to flip a greedy argmax between the kernel and
    jnp cache paths. One fixed multiply lowers identically everywhere.
    """
    import jax.numpy as jnp
    import numpy as np

    s = jnp.max(jnp.abs(x.astype(jnp.float32)), axis=-1) * np.float32(
        1.0 / 127.0
    )
    s = jnp.maximum(s, 1e-8)
    q = jnp.round(x.astype(jnp.float32) / s[..., None])
    return q.astype(jnp.int8), s
