"""Shared helpers for the Pallas TPU kernels (reference ``orion.ops`` L0).

All kernels in this package follow the same conventions:

- Block shapes are static; callers pad to block multiples and the kernels
  mask padded positions (XLA/Mosaic require static shapes, SURVEY.md §8).
- Math is float32 inside the kernel regardless of the activation dtype
  (bf16-safe convention shared with the xla reference ops).
- ``interpret=True`` runs the kernel through the Pallas interpreter so the
  same code is testable on the fake-CPU-device mesh (SURVEY.md §5).
"""

from __future__ import annotations

import jax

NEG_INF = -1e30  # finite -inf stand-in: exp(NEG_INF - m) underflows to 0.


def resolve_interpret(interpret) -> bool:
    """None -> autodetect: compiled on TPU, interpreted elsewhere."""
    if interpret is None:
        return jax.default_backend() != "tpu"
    return bool(interpret)


def round_up(n: int, m: int) -> int:
    return (n + m - 1) // m * m


def pad_axis(x: jax.Array, axis: int, target: int) -> jax.Array:
    """Zero-pad ``axis`` of x up to length ``target``."""
    if x.shape[axis] == target:
        return x
    pads = [(0, 0)] * x.ndim
    pads[axis] = (0, target - x.shape[axis])
    import jax.numpy as jnp

    return jnp.pad(x, pads)
