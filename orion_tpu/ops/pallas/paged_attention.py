"""Paged decode attention as a Pallas TPU kernel.

The inference engine's decode step attends one new token per sequence
against that sequence's KV pages (PAPERS.md:9 "ragged paged attention for
TPU LLM inference"; SURVEY.md §3 `ops`: fused attention, "ragged/paged
variant for inference"). The jnp reference path materializes every
sequence's full padded context via a pool gather; this kernel instead walks
the page table directly:

  - ``page_table``/``last_pos`` ride the scalar-prefetch channel, so each
    grid step's k/v BlockSpec index map points the DMA at the NEXT physical
    page while the current one computes — the gather never materializes.
  - Grid is (batch, kv_head, page); the online-softmax state for one
    (batch, kv_head) group lives in VMEM scratch across the page sweep.
  - Pages past a sequence's length are skipped (`pl.when`), so compute is
    proportional to the ragged ACTUAL context lengths, not the padded
    maximum — the "ragged" in ragged paged attention.
  - The grouped query heads of one kv head form the sublane dim (G rows,
    padded to 8), the page size the lane dim: one MXU-shaped block per
    (group, page) pair.

Decode is inference-only; no VJP is defined.
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from orion_tpu.ops.pallas.common import NEG_INF, resolve_interpret, round_up

LANES = 128


def _kernel(
    softcap: Optional[float],
    psz: int,
    pt_ref,        # [B, P] scalar-prefetched page table
    sl_ref,        # [B] scalar-prefetched last valid position per sequence
    q_ref,         # [1, 1, G8, H]
    k_ref,         # [1, psz, 1, H]
    v_ref,         # [1, psz, 1, H]
    o_ref,         # [1, 1, G8, H]
    m_s,           # [G8, LANES] f32 scratch
    l_s,           # [G8, LANES] f32 scratch
    acc_s,         # [G8, H] f32 scratch
):
    b, ip = pl.program_id(0), pl.program_id(2)
    npages = pl.num_programs(2)
    last_pos = sl_ref[b]
    scale = q_ref.shape[-1] ** -0.5

    @pl.when(ip == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    # Ragged skip: pages wholly beyond this sequence's context do nothing.
    @pl.when(ip * psz <= last_pos)
    def _body():
        q = q_ref[0, 0].astype(jnp.float32)          # [G8, H]
        k = k_ref[0, :, 0, :].astype(jnp.float32)    # [psz, H]
        v = v_ref[0, :, 0, :].astype(jnp.float32)
        z = jax.lax.dot_general(
            q * scale, k, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )                                            # [G8, psz]
        if softcap is not None:
            z = softcap * jnp.tanh(z / softcap)
        pos = ip * psz + jax.lax.broadcasted_iota(jnp.int32, z.shape, 1)
        mask = pos <= last_pos
        z = jnp.where(mask, z, NEG_INF)

        m_prev = m_s[:, :1]
        m_new = jnp.maximum(m_prev, z.max(axis=-1, keepdims=True))
        p = jnp.exp(z - m_new) * mask.astype(jnp.float32)
        alpha = jnp.exp(m_prev - m_new)
        l_s[:] = jnp.broadcast_to(
            l_s[:, :1] * alpha + p.sum(axis=-1, keepdims=True), l_s.shape
        )
        acc_s[:] = acc_s[:] * alpha + jax.lax.dot_general(
            p, v, (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_s[:] = jnp.broadcast_to(m_new, m_s.shape)

    @pl.when(ip == npages - 1)
    def _finish():
        l = l_s[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_s[:] / l_safe).astype(o_ref.dtype)


def paged_attention(
    q: jax.Array,            # [B, N, H] (the new token's queries)
    k_pool: jax.Array,       # [num_pages, psz, K, H]
    v_pool: jax.Array,       # [num_pages, psz, K, H]
    page_table: jax.Array,   # [B, P] int32 page ids per sequence
    last_pos: jax.Array,     # [B] int32: highest valid position (inclusive)
    *,
    logit_softcap: Optional[float] = None,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Decode attention over the paged KV pool -> [B, N, H].

    Semantics match gathering each sequence's pages into a [B, P*psz, K, H]
    context and running masked attention (positions <= last_pos attend).
    """
    B, N, H = q.shape
    num_pages, psz, K, _ = k_pool.shape
    P = page_table.shape[1]
    assert N % K == 0, (N, K)
    G = N // K
    G8 = max(round_up(G, 8), 8)

    qg = q.reshape(B, K, G, H)
    if G8 != G:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, G8 - G), (0, 0)))

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=2,
        grid=(B, K, P),
        in_specs=[
            pl.BlockSpec(
                (1, 1, G8, H), lambda b, kh, ip, pt, sl: (b, kh, 0, 0)
            ),
            # The page-table lookup happens IN THE INDEX MAP: the DMA for
            # grid step (b, kh, ip) reads physical page pt[b, ip].
            pl.BlockSpec(
                (1, psz, 1, H), lambda b, kh, ip, pt, sl: (pt[b, ip], 0, kh, 0)
            ),
            pl.BlockSpec(
                (1, psz, 1, H), lambda b, kh, ip, pt, sl: (pt[b, ip], 0, kh, 0)
            ),
        ],
        out_specs=pl.BlockSpec(
            (1, 1, G8, H), lambda b, kh, ip, pt, sl: (b, kh, 0, 0)
        ),
        scratch_shapes=[
            pltpu.VMEM((G8, LANES), jnp.float32),
            pltpu.VMEM((G8, LANES), jnp.float32),
            pltpu.VMEM((G8, H), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(_kernel, logit_softcap, psz),
        grid_spec=grid_spec,
        out_shape=jax.ShapeDtypeStruct((B, K, G8, H), q.dtype),
        interpret=resolve_interpret(interpret),
    )(page_table.astype(jnp.int32), last_pos.astype(jnp.int32),
      qg, k_pool, v_pool)
    return out[:, :, :G, :].reshape(B, N, H)
