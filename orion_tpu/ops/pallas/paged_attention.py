"""Paged decode attention as a Pallas TPU kernel, KV write fused in.

The inference engine's decode step attends one new token per sequence
against that sequence's KV pages (PAPERS.md:9 "ragged paged attention for
TPU LLM inference"; SURVEY.md §3 `ops`: fused attention, "ragged/paged
variant for inference"). The jnp reference path scatters the new token's
K/V into the pool and materializes every sequence's full padded context via
a pool gather; this kernel walks the page table directly and performs the
KV write itself:

  - ``page_table``/``last_pos``/``layer base`` ride the scalar-prefetch
    channel, so each grid step's k/v BlockSpec index map points the DMA at
    the NEXT physical page while the current one computes — the gather
    never materializes. The base offset makes the kernel work on the flat
    [L*num_pages, ...] pool at a *traced* layer index, so the layer scan
    can carry one pool array and update it in place.
  - The new token's K/V is written INSIDE the kernel (on the grid step
    whose page contains ``last_pos``), with the pool passed through via
    ``input_output_aliases``. An external scatter followed by a pallas read
    defeats XLA's in-place buffer analysis — the custom call made XLA
    materialize a fresh multi-GB pool copy per layer (measured 140 ms/step
    vs ~7 ms with the fused write).
  - Pool layout is [rows, K, psz, H]: all K kv-heads of a page form one
    (1, K, psz, H) block whose minor dims (psz, H) are (8, 128)-tiling
    legal, and the head dim is a dot_general *batch* dim — one batched MXU
    op per page instead of a K-step head loop (11x on a v5e) or a
    (batch, head, page) grid of tiny blocks (worse still).
  - Grid is (batch, page). Pages wholly past a sequence's length skip
    their compute (`pl.when`) AND their fetch: the index map clamps them to
    the sequence's first page, so the invalid tail re-requests the block
    already resident and Mosaic elides the copies. Compute and traffic are
    both proportional to the ragged ACTUAL context lengths — the "ragged"
    in ragged paged attention.
  - The grouped query heads of one kv head form a G8-row band of the
    [K*G8, H] q block.

Decode is inference-only; no VJP is defined.

Under chunked prefill (``runner.mixed_step``) this kernel serves the
decode rows of the unified mixed dispatch — same contract, one query
token per sequence with the fused in-place write — while prompt-chunk
rows ride the flash kernel's segment-id path in the same program; the
two in-place pool updates touch disjoint pages (the engine masks
mid-prefill slots' decode rows onto the scratch page).
"""

from __future__ import annotations

import functools
from typing import Optional, Union

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from orion_tpu.ops.pallas.common import (
    NEG_INF,
    quantize_kv,
    resolve_interpret,
    round_up,
)

LANES = 128


def _kernel(
    softcap: Optional[float],
    psz: int,
    K: int,
    G8: int,
    fused_write: bool,
    window: Optional[int],
    quant: bool,
    pt_ref,        # [B, P] scalar-prefetched page table (per-layer-relative)
    base_ref,      # [1] scalar-prefetched flat-pool row base (layer * NP)
    sl_ref,        # [B] scalar-prefetched last valid position per sequence
    *refs,
):
    refs = list(refs)
    q_ref, k_ref, v_ref = refs[:3]
    i = 3
    ks_ref = vs_ref = kn_ref = vn_ref = None
    if quant:
        ks_ref, vs_ref = refs[i], refs[i + 1]
        i += 2
    if fused_write:
        kn_ref, vn_ref = refs[i], refs[i + 1]
        i += 2
    o_ref = refs[i]
    i += 1
    ko_ref = vo_ref = kso_ref = vso_ref = None
    if fused_write:
        ko_ref, vo_ref = refs[i], refs[i + 1]
        i += 2
        if quant:
            kso_ref, vso_ref = refs[i], refs[i + 1]
            i += 2
    m_s, l_s, acc_s = refs[i:]

    b, ip = pl.program_id(0), pl.program_id(1)
    npages = pl.num_programs(1)
    last_pos = sl_ref[b]
    H = q_ref.shape[-1]
    scale = H ** -0.5

    @pl.when(ip == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    if fused_write:
        # Pass the page through (aliased in/out), inserting the new token's
        # K/V on the page that owns position last_pos. The invalid tail is
        # clamped onto that same (last valid) page, and the insert re-runs
        # on every revisit: the revisits re-copy the STALE input block
        # (fetched before any write-back), so a single insert at the owning
        # grid step would be clobbered by the tail's final write-back.
        # The insert is a MASKED full-block merge, not a dynamic-index row
        # store: Mosaic rejects vector stores at runtime-computed sublane /
        # lane offsets ("cannot statically prove the index is a multiple of
        # the tile"), which the round-5 compiled run hit; a select against a
        # sublane iota stores the whole (tiling-legal) block instead.
        off = last_pos % psz
        insert = ip >= last_pos // psz
        row = lax.broadcasted_iota(jnp.int32, (K, psz, 1), 1)
        sel = insert & (row == off)                       # [K, psz, 1]
        if not quant:
            ko_ref[0] = jnp.where(
                sel, kn_ref[0][:, None, :].astype(ko_ref.dtype), k_ref[0]
            )
            vo_ref[0] = jnp.where(
                sel, vn_ref[0][:, None, :].astype(vo_ref.dtype), v_ref[0]
            )
        else:
            # Quantize the new token's K/V in-kernel via the SAME function
            # the jnp prefill path uses (common.quantize_kv) — decode and
            # prefill quantization agree bit-for-bit by construction. The
            # scale pools merge the same way against a lane iota.
            col = lax.broadcasted_iota(jnp.int32, ks_ref[0].shape, 1)
            scol = insert & (col == off)                  # [K, SCALE_LANES]
            for new_ref, in_ref, out_ref, sin_ref, sout_ref in (
                (kn_ref, k_ref, ko_ref, ks_ref, kso_ref),
                (vn_ref, v_ref, vo_ref, vs_ref, vso_ref),
            ):
                qv, s = quantize_kv(new_ref[0])             # [K, H], [K]
                out_ref[0] = jnp.where(
                    sel, qv.astype(out_ref.dtype)[:, None, :], in_ref[0]
                )
                sout_ref[0] = jnp.where(scol, s[:, None], sin_ref[0])

        k_src, v_src = ko_ref, vo_ref
        ks_src, vs_src = kso_ref, vso_ref
    else:
        k_src, v_src = k_ref, v_ref
        ks_src, vs_src = ks_ref, vs_ref

    # Ragged skip: pages wholly beyond this sequence's context do nothing
    # (their fetches were elided by the clamped index map). With a sliding
    # window, pages wholly BEHIND the window skip too (same elision via the
    # index map's lower clamp), so compute and traffic are O(window).
    run = ip * psz <= last_pos
    if window is not None:
        run &= ip * psz + psz - 1 >= last_pos - window + 1

    @pl.when(run)
    def _body():
        q = q_ref[0].reshape(K, G8, H).astype(jnp.float32)
        k = k_src[0].astype(jnp.float32)                 # [K, psz, H]
        v = v_src[0].astype(jnp.float32)
        z = lax.dot_general(
            q * scale, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )                                                # [K, G8, psz]
        if quant:
            # int8 pool: the per-(head, token) K scale applies to the logit
            # COLUMNS after the matmul (cheaper than dequantizing the
            # [K, psz, H] block before it).
            z = z * ks_src[0][:, :psz][:, None, :]
        z = z.reshape(K * G8, psz)
        if softcap is not None:
            z = softcap * jnp.tanh(z / softcap)
        kv_pos = ip * psz + lax.broadcasted_iota(
            jnp.int32, (K * G8, psz), 1
        )
        mask = kv_pos <= last_pos
        if window is not None:
            # q sits at last_pos: attend iff last_pos - kv_pos < window.
            mask &= kv_pos >= last_pos - window + 1
        z = jnp.where(mask, z, NEG_INF)

        m_prev = m_s[:, :1]
        m_new = jnp.maximum(m_prev, z.max(axis=-1, keepdims=True))
        p = jnp.exp(z - m_new) * mask.astype(jnp.float32)
        alpha = jnp.exp(m_prev - m_new)
        l_s[:] = jnp.broadcast_to(
            l_s[:, :1] * alpha + p.sum(axis=-1, keepdims=True), l_s.shape
        )
        pw = p.reshape(K, G8, psz)
        if quant:
            # Fold the V scale into the probabilities (per kv column), so
            # the PV matmul consumes the int8 block directly.
            pw = pw * vs_src[0][:, :psz][:, None, :]
        pv = lax.dot_general(
            pw, v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )                                                # [K, G8, H]
        acc_s[:] = acc_s[:] * alpha + pv.reshape(K * G8, H)
        m_s[:] = jnp.broadcast_to(m_new, m_s.shape)

    @pl.when(ip == npages - 1)
    def _finish():
        l = l_s[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_s[:] / l_safe).astype(o_ref.dtype)


def _call(q, k_pool, v_pool, page_table, last_pos, base, k_new, v_new,
          softcap, window, interpret, k_scale=None, v_scale=None):
    B, N, H = q.shape
    rows_total, K, psz, _ = k_pool.shape
    P = page_table.shape[1]
    G = N // K
    G8 = max(round_up(G, 8), 8)
    fused_write = k_new is not None
    quant = k_scale is not None

    qg = q.reshape(B, K, G, H)
    if G8 != G:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, G8 - G), (0, 0)))
    qg = qg.reshape(B, K * G8, H)

    def kv_index(b, ip, pt, bs, sl):
        # Clamp the invalid tail (pages past the context) to the LAST valid
        # page: consecutive identical block requests elide the DMA, and in
        # fused-write mode the tail's write-backs then re-target the page
        # that received the new token (which re-applies its insert — see
        # _kernel) instead of clobbering some other page. With a sliding
        # window, pages wholly behind the window clamp UP to the window's
        # first page the same way (their write-backs rewrite that page with
        # its own just-fetched data — harmless), eliding their DMAs too.
        valid_ip = jnp.minimum(ip, sl[b] // psz)
        if window is not None:
            first = jnp.maximum(sl[b] - window + 1, 0) // psz
            valid_ip = jnp.maximum(valid_ip, jnp.minimum(first, sl[b] // psz))
        return (bs[0] + pt[b, valid_ip], 0, 0, 0)

    def row_index(b, ip, pt, bs, sl):
        return (b, 0, 0)

    q_spec = pl.BlockSpec((1, K * G8, H), row_index)
    kv_spec = pl.BlockSpec((1, K, psz, H), kv_index)
    in_specs = [q_spec, kv_spec, kv_spec]
    args = [qg, k_pool, v_pool]
    if quant:
        # One page's scales: (1, K, SCALE_LANES) f32 — a full (8, 128)
        # lane tile, same clamped page walk as the data blocks.
        sw = k_scale.shape[-1]
        sc_spec = pl.BlockSpec(
            (1, K, sw), lambda b, ip, pt, bs, sl: kv_index(
                b, ip, pt, bs, sl)[:3]
        )
        in_specs += [sc_spec, sc_spec]
        args += [k_scale, v_scale]
    out_specs = [q_spec]
    out_shape = [jax.ShapeDtypeStruct((B, K * G8, H), q.dtype)]
    aliases = {}
    if fused_write:
        new_spec = pl.BlockSpec((1, K, H), row_index)
        in_specs += [new_spec, new_spec]
        args += [k_new, v_new]
        out_specs += [kv_spec, kv_spec]
        out_shape += [
            jax.ShapeDtypeStruct(k_pool.shape, k_pool.dtype),
            jax.ShapeDtypeStruct(v_pool.shape, v_pool.dtype),
        ]
        # Operand indices count the scalar-prefetch args (pt, base, sl) and
        # q before the pools; without quant the pools are operands 4 and 5
        # -> outputs 1 and 2. With quant the scale pools sit between the
        # data pools and k_new/v_new, and are themselves aliased outputs.
        if quant:
            sw = k_scale.shape[-1]
            out_specs += [sc_spec, sc_spec]
            out_shape += [
                jax.ShapeDtypeStruct(k_scale.shape, k_scale.dtype),
                jax.ShapeDtypeStruct(v_scale.shape, v_scale.dtype),
            ]
            aliases = {4: 1, 5: 2, 6: 3, 7: 4}
        else:
            aliases = {4: 1, 5: 2}

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=3,
        grid=(B, P),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((K * G8, LANES), jnp.float32),
            pltpu.VMEM((K * G8, LANES), jnp.float32),
            pltpu.VMEM((K * G8, H), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _kernel, softcap, psz, K, G8, fused_write, window, quant
        ),
        grid_spec=grid_spec,
        out_shape=out_shape,
        input_output_aliases=aliases,
        interpret=resolve_interpret(interpret),
    )(page_table.astype(jnp.int32), base, last_pos.astype(jnp.int32), *args)
    attn = out[0].reshape(B, K, G8, H)[:, :, :G, :].reshape(B, N, H)
    if fused_write:
        if quant:
            return attn, out[1], out[2], out[3], out[4]
        return attn, out[1], out[2]
    return attn, k_pool, v_pool


def paged_attention(
    q: jax.Array,            # [B, N, H] (the new token's queries)
    k_pool: jax.Array,       # [L*num_pages, K, psz, H] flat pool
    v_pool: jax.Array,       # [L*num_pages, K, psz, H]
    page_table: jax.Array,   # [B, P] int32 per-layer-relative page ids
    last_pos: jax.Array,     # [B] int32: highest valid position (inclusive)
    *,
    layer_base: Union[jax.Array, int] = 0,  # flat-pool row offset (layer*NP)
    k_new: Optional[jax.Array] = None,      # [B, K, H]: K/V of the token at
    v_new: Optional[jax.Array] = None,      #   last_pos, written in-kernel
    logit_softcap: Optional[float] = None,
    window: Optional[int] = None,           # sliding window: attend iff
    #                                         last_pos - kv_pos < window
    interpret: Optional[bool] = None,
    k_scale: Optional[jax.Array] = None,    # [rows, K, SCALE_LANES] f32:
    v_scale: Optional[jax.Array] = None,    #   int8-pool per-token scales
    mesh: Optional[jax.sharding.Mesh] = None,
    tp_axis: str = "tp",
):
    """Decode attention over the paged KV pool.

    Returns [B, N, H] when ``k_new``/``v_new`` are None, else
    ``(out, k_pool', v_pool')`` with the new token's K/V written into row
    ``layer_base + page_table[b, last_pos // psz]`` at column
    ``last_pos % psz`` — in place via input/output aliasing (an external
    scatter feeding this call costs a full pool copy per layer instead).

    Semantics match gathering each sequence's pages (rows ``layer_base +
    page_table``) into a [B, P*psz, K, H] context, applying the scatter,
    and running masked attention (positions <= last_pos attend).
    ``layer_base`` may be traced (it rides the scalar-prefetch channel), so
    the call sits inside a layer scan over one carried flat pool.

    With ``k_scale``/``v_scale`` the pools are int8 (inference.kv_quant):
    the kernel dequantizes in place — K scales multiply the logit columns
    after the QK matmul, V scales fold into the probabilities before PV —
    and the fused write quantizes the new token in-kernel
    (kv_cache.quantize_kv semantics), returning
    ``(out, k_pool', v_pool', k_scale', v_scale')``.
    """
    assert (k_new is None) == (v_new is None)
    assert (k_scale is None) == (v_scale is None)
    if window is not None and window < 1:
        raise ValueError(f"window={window} must be >= 1")
    K = k_pool.shape[1]
    assert q.shape[1] % K == 0, (q.shape, K)
    base = jnp.asarray(layer_base, jnp.int32).reshape(1)

    tp = mesh.shape.get(tp_axis, 1) if mesh is not None else 1
    if tp > 1:
        # Tensor-parallel serving: split the HEAD axes (q heads, pool kv
        # heads, new-token kv heads, scale-pool kv heads) across ``tp_axis``
        # and run the kernel per shard — a bare pallas_call is opaque to
        # XLA's partitioner, so jitting it over a tp-sharded pool would
        # gather the whole multi-GB pool onto every device. The page walk
        # is head-independent (page_table/last_pos/base replicate), and the
        # fused in-place write stays consistent per shard: each device
        # owns its K/tp slice of every page. G = N/K is preserved per
        # shard, so the in-kernel GQA mapping is unchanged.
        N = q.shape[1]
        if N % tp or K % tp:
            raise ValueError(
                f"tp-sharded paged attention needs n_heads ({N}) and "
                f"n_kv_heads ({K}) divisible by {tp_axis}={tp}; lower tp "
                f"or serve with kernels='xla'"
            )
        from jax.sharding import PartitionSpec as P

        qspec = P(None, tp_axis, None)          # [B, N, H]
        poolspec = P(None, tp_axis, None, None)  # [rows, K, psz, H]
        rep2, rep1 = P(None, None), P(None)
        args = [q, k_pool, v_pool, page_table, last_pos, base]
        in_specs = [qspec, poolspec, poolspec, rep2, rep1, rep1]
        out_specs = [qspec]
        have_new, have_scale = k_new is not None, k_scale is not None
        if have_new:
            args += [k_new, v_new]
            in_specs += [qspec, qspec]           # [B, K, H]
            out_specs += [poolspec, poolspec]
        if have_scale:
            scspec = P(None, tp_axis, None)      # [rows, K, SCALE_LANES]
            args += [k_scale, v_scale]
            in_specs += [scspec, scspec]
            if have_new:
                out_specs += [scspec, scspec]

        def body(q_, kp_, vp_, pt_, lp_, base_, *rest):
            kn = vn = ks = vs = None
            rest = list(rest)
            if have_new:
                kn, vn = rest[0], rest[1]
                rest = rest[2:]
            if have_scale:
                ks, vs = rest[0], rest[1]
            res = _call(
                q_, kp_, vp_, pt_, lp_, base_, kn, vn,
                logit_softcap, window, interpret, ks, vs,
            )
            if not have_new:
                return res[0]
            return res[:3] if not have_scale else res

        mapped = jax.shard_map(
            body, mesh=mesh, in_specs=tuple(in_specs),
            out_specs=tuple(out_specs) if have_new else out_specs[0],
            check_vma=False,
        )
        out = mapped(*args)
        if not have_new:
            return out
        return tuple(out)

    out = _call(
        q, k_pool, v_pool, page_table, last_pos, base, k_new, v_new,
        logit_softcap, window, interpret, k_scale, v_scale,
    )
    if k_new is None:
        return out[0]
    if k_scale is None:
        return out[:3]
    return out
