"""Multi-query ragged paged attention: W queries per slot, KV write fused.

Speculative verification (``runner.verify_step`` / ``mixed_verify_step``)
scores each slot's pending token + drafts — W = speculate_tokens + 1 query
positions per slot — in one pass over the weights. The XLA reference body
scatters all W tokens' K/V into the pool and re-materializes every slot's
full padded context via a pool gather, exactly the copy tax the W=1 paged
kernel (``paged_attention.py``) exists to avoid. This kernel is that
kernel generalized from 1 to W ragged queries per slot (PAPERS.md: ragged
paged attention), sharing its design decisions:

  - Same (batch, page) grid, scalar-prefetched page walk, clamped index
    map (invalid tail pages re-request the last valid block and Mosaic
    elides the DMA; behind-window pages clamp UP to the window's first
    page), and [rows, K, psz, H] heads-major pool with the head dim as a
    dot_general batch dim.
  - The W new tokens' K/V are written INSIDE the kernel on the grid steps
    whose pages own their positions (``start + j`` for j < ``lens``),
    via input/output aliasing. The insert is a one-hot matmul merge — a
    [psz, W8] selection matrix built from iotas contracts with the
    [K, W8, H] new-token block — because Mosaic rejects vector stores at
    runtime-computed sublane/lane offsets (the round-5 compiled lesson);
    the one-hot contraction is exact (rows multiply by 1.0/0.0), so
    written pool bytes match an external scatter bit-for-bit. Clamped
    revisits re-apply their target page's merge so the final write-back
    is never the stale pre-insert block.
  - Under ``kv_quant=int8`` the new tokens quantize in-kernel with the
    SAME ``common.quantize_kv`` the jnp paths use — per-(token, kv-head)
    scales merged into the lanes-padded scale pools by the same one-hot
    trick — so acceptance numerics stay bit-identical to sequential
    decode.
  - Causal masking among the W new positions rides the same kv-position
    mask as raggedness: query w at position ``start + w`` attends
    kv_pos <= start + w, which includes the earlier drafts of the same
    dispatch (their K/V is already merged into the block being read).
    Rows shorter than W (``lens``) exclude their padding tokens from the
    merge, so padding never touches the pool (the XLA path parks it on
    scratch instead — both are unobservable); padding QUERIES still
    compute, masked like a real query at ``start + j``, and return
    garbage rows the caller discards — do NOT "fix" them to fully
    masked, the XLA reference's discard semantics are the contract.

Per-query numerics match the W=1 kernel's op-for-op: the extra pages a
non-final query visits (between its own position and the row's last) are
exact no-ops in the online softmax (fully masked blocks contribute p=0,
alpha=1), so greedy acceptance on this path reproduces the sequential
pallas decode stream.

Verification is inference-only; no VJP is defined.
"""

from __future__ import annotations

import functools
from typing import Optional, Union

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from orion_tpu.ops.pallas.common import (
    NEG_INF,
    quantize_kv,
    resolve_interpret,
    round_up,
)

LANES = 128

# Conservative per-kernel VMEM budget for the fit check below: one v5e/v6e
# core has ~16 MiB of VMEM; leave headroom for Mosaic's own buffers.
VMEM_BUDGET_BYTES = 12 * 2 ** 20


def verify_vmem_bytes(
    W: int,
    *,
    n_heads: int,
    n_kv_heads: int,
    head_dim: int,
    page_size: int,
    kv_itemsize: int,
    quant: bool,
) -> int:
    """Estimated VMEM footprint of one ragged-paged-attention grid step.

    Counts the q/out blocks, the double-buffered in+out KV page blocks,
    the new-token blocks, the f32 scratch (m/l/acc), and the scale blocks
    under quant. An estimate (Mosaic's allocator has its own padding),
    used only to reject hopeless configs with an actionable error instead
    of a Mosaic OOM."""
    K = n_kv_heads
    G = n_heads // K
    WG8 = max(round_up(W * G, 8), 8)
    W8 = max(round_up(W, 8), 8)
    q_io = 2 * K * WG8 * head_dim * 4                 # q + out blocks
    kv_io = 2 * 2 * 2 * K * page_size * head_dim * kv_itemsize
    new = 2 * 2 * K * W8 * head_dim * 4               # k_new + v_new
    scratch = K * WG8 * (2 * LANES + head_dim) * 4    # m, l, acc
    scales = (2 * 2 * 2 * K * LANES * 4) if quant else 0
    return q_io + kv_io + new + scratch + scales


def check_verify_fit(
    W: int,
    *,
    n_heads: int,
    n_kv_heads: int,
    head_dim: int,
    page_size: int,
    kv_quant: Optional[str],
    dtype_itemsize: int = 2,
) -> None:
    """Reject a speculative verify width the kernel cannot hold in VMEM.

    Called by the engine at init when ``inference.speculative`` rides the
    pallas kernel path, so the failure is a config error naming the knob,
    not a Mosaic allocation failure mid-serving."""
    quant = kv_quant == "int8"
    need = verify_vmem_bytes(
        W, n_heads=n_heads, n_kv_heads=n_kv_heads, head_dim=head_dim,
        page_size=page_size, kv_itemsize=1 if quant else dtype_itemsize,
        quant=quant,
    )
    if need > VMEM_BUDGET_BYTES:
        raise ValueError(
            f"speculative verify width W={W} "
            f"(inference.speculate_tokens={W - 1}) needs ~"
            f"{need / 2**20:.1f} MiB of VMEM per kernel step, over the "
            f"~{VMEM_BUDGET_BYTES / 2**20:.0f} MiB the ragged "
            f"paged-attention kernel budgets; lower "
            f"inference.speculate_tokens or serve with model.kernels='xla'"
        )


def _kernel(
    softcap: Optional[float],
    psz: int,
    K: int,
    G: int,
    W: int,
    WG8: int,
    W8: int,
    fused_write: bool,
    window: Optional[int],
    quant: bool,
    tree: bool,
    pt_ref,        # [B, P] scalar-prefetched page table (per-layer-relative)
    base_ref,      # [1] scalar-prefetched flat-pool row base (layer * NP)
    st_ref,        # [B] scalar-prefetched cursor (first new position)
    ln_ref,        # [B] scalar-prefetched real query count per row (1..W)
    *refs,
):
    refs = list(refs)
    tm_ref = dp_ref = None
    if tree:
        # Token-tree verification: packed per-column ancestor words and
        # tree depths ride the scalar prefetch like the page table.
        tm_ref, dp_ref = refs[0], refs[1]   # [B, W] i32 each
        refs = refs[2:]
    q_ref, k_ref, v_ref = refs[:3]
    i = 3
    ks_ref = vs_ref = kn_ref = vn_ref = None
    if quant:
        ks_ref, vs_ref = refs[i], refs[i + 1]
        i += 2
    if fused_write:
        kn_ref, vn_ref = refs[i], refs[i + 1]
        i += 2
    o_ref = refs[i]
    i += 1
    ko_ref = vo_ref = kso_ref = vso_ref = None
    if fused_write:
        ko_ref, vo_ref = refs[i], refs[i + 1]
        i += 2
        if quant:
            kso_ref, vso_ref = refs[i], refs[i + 1]
            i += 2
    m_s, l_s, acc_s = refs[i:]

    b, ip = pl.program_id(0), pl.program_id(1)
    npages = pl.num_programs(1)
    start = st_ref[b]
    wlen = ln_ref[b]
    # Highest position this row writes/attends; the clamp keeps a
    # degenerate caller (cursor at the context edge) in-bounds the way
    # the XLA body's scratch redirect does.
    last = jnp.minimum(start + wlen - 1, npages * psz - 1)
    H = q_ref.shape[-1]
    scale = H ** -0.5

    @pl.when(ip == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    if fused_write:
        # Which of the W new tokens land on THIS grid step's DMA-target
        # page: the index map's clamp, replicated, so clamped revisits
        # (invalid tail pages down to the last valid page; behind-window
        # pages up to the window's first) re-apply their target page's
        # merge — a single application would be clobbered by a revisit's
        # stale write-back, exactly the W=1 kernel's insert discipline.
        valid_ip = jnp.minimum(ip, last // psz)
        if window is not None:
            first = jnp.maximum(start - window + 1, 0) // psz
            valid_ip = jnp.maximum(valid_ip, jnp.minimum(first, last // psz))
        tok = lax.broadcasted_iota(jnp.int32, (psz, W8), 1)
        row = lax.broadcasted_iota(jnp.int32, (psz, W8), 0)
        pos = start + tok
        sel = (
            (tok < wlen) & (pos <= last)
            & (pos // psz == valid_ip) & (pos % psz == row)
        )
        # One-hot merge instead of a dynamic-index row store (Mosaic
        # rejects vector stores at runtime-computed sublane offsets —
        # round 5): sel has at most one 1 per page row (the W positions
        # are consecutive, so two tokens sharing an in-page offset are a
        # whole page apart and fail the page test), making the f32
        # contraction below an exact select of the new token's vector.
        selm = sel.astype(jnp.float32)                   # [psz, W8]
        row_has = selm.sum(axis=1) > 0.5                 # [psz]
        sel_k = jnp.broadcast_to(selm[None], (K, psz, W8))
        if not quant:
            for new_ref, in_ref, out_ref in (
                (kn_ref, k_ref, ko_ref), (vn_ref, v_ref, vo_ref),
            ):
                merged = lax.dot_general(
                    sel_k, new_ref[0].astype(jnp.float32),
                    (((2,), (1,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32,
                )                                        # [K, psz, H]
                out_ref[0] = jnp.where(
                    row_has[None, :, None],
                    merged.astype(out_ref.dtype), in_ref[0],
                )
        else:
            # Quantize the W new tokens in-kernel via the SAME function
            # the jnp paths use (common.quantize_kv): pool bytes and
            # scales match a sequential decode's bit-for-bit. The scale
            # pools merge by the same one-hot trick against a lane iota.
            SW = ks_ref.shape[-1]
            tokc = lax.broadcasted_iota(jnp.int32, (SW, W8), 1)
            colc = lax.broadcasted_iota(jnp.int32, (SW, W8), 0)
            posc = start + tokc
            selc = (
                (tokc < wlen) & (posc <= last)
                & (posc // psz == valid_ip) & (posc % psz == colc)
            ).astype(jnp.float32)                        # [SW, W8]
            col_has = selc.sum(axis=1) > 0.5             # [SW]
            sel_c = jnp.broadcast_to(selc[None], (K, SW, W8))
            for new_ref, in_ref, out_ref, sin_ref, sout_ref in (
                (kn_ref, k_ref, ko_ref, ks_ref, kso_ref),
                (vn_ref, v_ref, vo_ref, vs_ref, vso_ref),
            ):
                qv, s = quantize_kv(new_ref[0])    # [K, W8, H], [K, W8]
                merged = lax.dot_general(
                    sel_k, qv.astype(jnp.float32),
                    (((2,), (1,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32,
                )
                out_ref[0] = jnp.where(
                    row_has[None, :, None],
                    merged.astype(out_ref.dtype), in_ref[0],
                )
                s_merged = lax.dot_general(
                    sel_c, s, (((2,), (1,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32,
                )                                        # [K, SW]
                sout_ref[0] = jnp.where(
                    col_has[None, :], s_merged, sin_ref[0]
                )

        k_src, v_src = ko_ref, vo_ref
        ks_src, vs_src = kso_ref, vso_ref
    else:
        k_src, v_src = k_ref, v_ref
        ks_src, vs_src = ks_ref, vs_ref

    # Ragged skip: pages wholly beyond the row's LAST query position do
    # nothing (their fetches were elided by the clamped index map); with a
    # sliding window, pages wholly behind the EARLIEST query's window skip
    # too. Later queries' tighter windows are handled by the mask — their
    # extra visited pages are exact online-softmax no-ops.
    run = ip * psz <= last
    if window is not None:
        run &= ip * psz + psz - 1 >= start - window + 1

    @pl.when(run)
    def _body():
        q = q_ref[0].reshape(K, WG8, H).astype(jnp.float32)
        k = k_src[0].astype(jnp.float32)                 # [K, psz, H]
        v = v_src[0].astype(jnp.float32)
        z = lax.dot_general(
            q * scale, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )                                                # [K, WG8, psz]
        if quant:
            z = z * ks_src[0][:, :psz][:, None, :]
        z = z.reshape(K * WG8, psz)
        if softcap is not None:
            z = softcap * jnp.tanh(z / softcap)
        kv_pos = ip * psz + lax.broadcasted_iota(
            jnp.int32, (K * WG8, psz), 1
        )
        # Row r of a K-band holds query w = r // G (padding rows past
        # W*G clamp to the last query; their outputs are sliced away).
        rowq = lax.broadcasted_iota(jnp.int32, (K * WG8, psz), 0) % WG8
        qw = jnp.minimum(rowq // G, W - 1)
        if not tree:
            q_pos = start + qw
            mask = kv_pos <= q_pos
            if window is not None:
                mask &= kv_pos >= q_pos - window + 1
        else:
            # Token tree: committed context (kv_pos < start) is visible
            # to every query; among the W new slots, query w sees slot i
            # iff bit i of its ancestor word is set (or i == w). Depths
            # replace slot order for logical positions: W static and
            # small, so the per-row word/depth vectors build as W
            # unrolled scalar-SMEM selects (Mosaic has no vector gather
            # from SMEM), noise next to the dot_generals.
            word = jnp.zeros_like(qw)
            qdep = jnp.zeros_like(qw)
            for w in range(W):
                word = jnp.where(qw == w, tm_ref[b, w], word)
                qdep = jnp.where(qw == w, dp_ref[b, w], qdep)
            slot = kv_pos - start
            in_new = (slot >= 0) & (slot < W)
            bit = (
                lax.shift_right_logical(word, jnp.clip(slot, 0, 31)) & 1
            ) == 1
            mask = jnp.where(in_new, bit | (slot == qw), kv_pos < start)
            if window is not None:
                # Window distance among new slots is DEPTH distance
                # (two siblings at one depth are window-equivalent even
                # though their pool slots differ).
                sdep = jnp.zeros_like(slot)
                for w in range(W):
                    sdep = jnp.where(slot == w, dp_ref[b, w], sdep)
                mask &= jnp.where(
                    in_new,
                    sdep >= qdep - window + 1,
                    kv_pos >= start + qdep - window + 1,
                )
        z = jnp.where(mask, z, NEG_INF)

        m_prev = m_s[:, :1]
        m_new = jnp.maximum(m_prev, z.max(axis=-1, keepdims=True))
        p = jnp.exp(z - m_new) * mask.astype(jnp.float32)
        alpha = jnp.exp(m_prev - m_new)
        l_s[:] = jnp.broadcast_to(
            l_s[:, :1] * alpha + p.sum(axis=-1, keepdims=True), l_s.shape
        )
        pw = p.reshape(K, WG8, psz)
        if quant:
            pw = pw * vs_src[0][:, :psz][:, None, :]
        pv = lax.dot_general(
            pw, v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )                                                # [K, WG8, H]
        acc_s[:] = acc_s[:] * alpha + pv.reshape(K * WG8, H)
        m_s[:] = jnp.broadcast_to(m_new, m_s.shape)

    @pl.when(ip == npages - 1)
    def _finish():
        l = l_s[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0] = (acc_s[:] / l_safe).astype(o_ref.dtype)


def _call(q, k_pool, v_pool, page_table, start, lens, base, k_new, v_new,
          softcap, window, interpret, k_scale=None, v_scale=None,
          tree_mask=None, depths=None):
    B, W, N, H = q.shape
    rows_total, K, psz, _ = k_pool.shape
    P = page_table.shape[1]
    G = N // K
    WG = W * G
    WG8 = max(round_up(WG, 8), 8)
    W8 = max(round_up(W, 8), 8)
    fused_write = k_new is not None
    quant = k_scale is not None
    tree = tree_mask is not None

    # Pack the W queries' GQA bands per kv head: [K, W*G] rows, padded to
    # a sublane multiple — the kernel recovers (w, g) from the row index.
    qg = q.reshape(B, W, K, G, H).transpose(0, 2, 1, 3, 4)
    qg = qg.reshape(B, K, WG, H)
    if WG8 != WG:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, WG8 - WG), (0, 0)))
    qg = qg.reshape(B, K * WG8, H)

    def kv_index(b, ip, pt, bs, st, ln, *_):
        # Same clamp discipline as the W=1 kernel's (see its kv_index):
        # tail pages clamp DOWN to the row's last valid page, behind-
        # window pages clamp UP to the window's first — both elide the
        # DMA and keep revisit write-backs self-consistent. (*_ absorbs
        # the tree-mode scalar-prefetch operands; the page walk is
        # tree-agnostic — slots stay cursor-sequential.)
        last = jnp.minimum(st[b] + ln[b] - 1, P * psz - 1)
        valid_ip = jnp.minimum(ip, last // psz)
        if window is not None:
            first = jnp.maximum(st[b] - window + 1, 0) // psz
            valid_ip = jnp.maximum(valid_ip, jnp.minimum(first, last // psz))
        return (bs[0] + pt[b, valid_ip], 0, 0, 0)

    def row_index(b, ip, pt, bs, st, ln, *_):
        return (b, 0, 0)

    q_spec = pl.BlockSpec((1, K * WG8, H), row_index)
    kv_spec = pl.BlockSpec((1, K, psz, H), kv_index)
    in_specs = [q_spec, kv_spec, kv_spec]
    args = [qg, k_pool, v_pool]
    if quant:
        sw = k_scale.shape[-1]
        sc_spec = pl.BlockSpec(
            (1, K, sw), lambda b, ip, pt, bs, st, ln, *_: kv_index(
                b, ip, pt, bs, st, ln)[:3]
        )
        in_specs += [sc_spec, sc_spec]
        args += [k_scale, v_scale]
    out_specs = [q_spec]
    out_shape = [jax.ShapeDtypeStruct((B, K * WG8, H), q.dtype)]
    aliases = {}
    if fused_write:
        # [B, W, K, H] -> [B, K, W8, H]: heads-major like the pool, token
        # dim padded to a sublane multiple for the one-hot contraction.
        kn = k_new.transpose(0, 2, 1, 3)
        vn = v_new.transpose(0, 2, 1, 3)
        if W8 != W:
            kn = jnp.pad(kn, ((0, 0), (0, 0), (0, W8 - W), (0, 0)))
            vn = jnp.pad(vn, ((0, 0), (0, 0), (0, W8 - W), (0, 0)))
        new_spec = pl.BlockSpec(
            (1, K, W8, H), lambda b, ip, pt, bs, st, ln, *_: (b, 0, 0, 0)
        )
        in_specs += [new_spec, new_spec]
        args += [kn, vn]
        out_specs += [kv_spec, kv_spec]
        out_shape += [
            jax.ShapeDtypeStruct(k_pool.shape, k_pool.dtype),
            jax.ShapeDtypeStruct(v_pool.shape, v_pool.dtype),
        ]
        # Operand indices count the scalar-prefetch args (pt, base, st,
        # ln, + tree words/depths in tree mode) and q before the pools;
        # without quant the pools are the next two operands after q ->
        # outputs 1 and 2. With quant the scale pools sit between the
        # data pools and k_new/v_new, aliased alongside.
        n_prefetch = 6 if tree else 4
        base_op = n_prefetch + 1            # q sits right after prefetch
        if quant:
            out_specs += [sc_spec, sc_spec]
            out_shape += [
                jax.ShapeDtypeStruct(k_scale.shape, k_scale.dtype),
                jax.ShapeDtypeStruct(v_scale.shape, v_scale.dtype),
            ]
            aliases = {base_op + i: 1 + i for i in range(4)}
        else:
            aliases = {base_op: 1, base_op + 1: 2}

    prefetch = [
        page_table.astype(jnp.int32), base, start.astype(jnp.int32),
        lens.astype(jnp.int32),
    ]
    if tree:
        prefetch += [
            tree_mask.astype(jnp.int32), depths.astype(jnp.int32)
        ]
    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=len(prefetch),
        grid=(B, P),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((K * WG8, LANES), jnp.float32),
            pltpu.VMEM((K * WG8, LANES), jnp.float32),
            pltpu.VMEM((K * WG8, H), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _kernel, softcap, psz, K, G, W, WG8, W8, fused_write, window,
            quant, tree,
        ),
        grid_spec=grid_spec,
        out_shape=out_shape,
        input_output_aliases=aliases,
        interpret=resolve_interpret(interpret),
    )(*prefetch, *args)
    attn = out[0].reshape(B, K, WG8, H)[:, :, :WG, :]
    attn = attn.reshape(B, K, W, G, H).transpose(0, 2, 1, 3, 4)
    attn = attn.reshape(B, W, N, H)
    if fused_write:
        if quant:
            return attn, out[1], out[2], out[3], out[4]
        return attn, out[1], out[2]
    return attn, k_pool, v_pool


def ragged_paged_attention(
    q: jax.Array,            # [B, W, N, H] the W new positions' queries
    k_pool: jax.Array,       # [L*num_pages, K, psz, H] flat pool
    v_pool: jax.Array,       # [L*num_pages, K, psz, H]
    page_table: jax.Array,   # [B, P] int32 per-layer-relative page ids
    start: jax.Array,        # [B] int32: first new position (the cursor)
    lens: jax.Array,         # [B] int32: real queries this row (1..W)
    *,
    layer_base: Union[jax.Array, int] = 0,  # flat-pool row offset (layer*NP)
    k_new: Optional[jax.Array] = None,      # [B, W, K, H]: K/V of the W
    v_new: Optional[jax.Array] = None,      #   tokens, written in-kernel
    logit_softcap: Optional[float] = None,
    window: Optional[int] = None,           # sliding window per query:
    #                                         attend iff q_pos - kv_pos < w
    interpret: Optional[bool] = None,
    k_scale: Optional[jax.Array] = None,    # [rows, K, SCALE_LANES] f32:
    v_scale: Optional[jax.Array] = None,    #   int8-pool per-token scales
    tree_mask: Optional[jax.Array] = None,  # [B, W] i32 packed ancestor
    #                                         words (bit i of word j: query
    #                                         j may attend new slot i)
    depths: Optional[jax.Array] = None,     # [B, W] i32 tree depth per
    #                                         column (logical position =
    #                                         start + depth)
    mesh: Optional[jax.sharding.Mesh] = None,
    tp_axis: str = "tp",
):
    """W-query ragged decode attention over the paged KV pool.

    Row b holds ``lens[b]`` real queries at positions ``start[b] + j``;
    query j attends every pool position <= its own (earlier same-dispatch
    tokens included) under the optional sliding window. Returns
    [B, W, N, H] when ``k_new``/``v_new`` are None, else ``(out, pools...)``
    with all ``lens[b]`` tokens' K/V written in place (aliased); padding
    queries (j >= lens[b]) write nothing and return garbage rows the
    caller discards. Rows whose page-table entries are 0 (inactive /
    mid-prefill slots) read and write only the reserved scratch page.

    Semantics match ``runner._verify_layer``'s XLA reference: scatter all
    W tokens, gather the padded context, mask per query. With
    ``k_scale``/``v_scale`` the pools are int8 (inference.kv_quant) and
    the fused write quantizes in-kernel (kv_cache.quantize_kv semantics),
    returning ``(out, k_pool', v_pool', k_scale', v_scale')``.

    Token trees (``tree_mask``/``depths``): the intra-dispatch causal
    mask generalizes to an arbitrary ancestor mask — query j attends the
    committed context plus exactly the new slots whose bits are set in
    its packed word (ancestors/root/self), at logical position
    ``start + depths[b, j]``; KV WRITES stay slot-sequential
    (``start + j``), so the page walk, fused write and provisioning are
    unchanged. Chain-shaped words/depths reproduce the positional mask
    bit-for-bit (the degenerate case IS the plain W-query verify). Mask
    words are int32, so tree verification caps W at 31 columns.
    """
    assert (k_new is None) == (v_new is None)
    assert (k_scale is None) == (v_scale is None)
    if (tree_mask is None) != (depths is None):
        raise ValueError("tree_mask and depths must be given together")
    if window is not None and window < 1:
        raise ValueError(f"window={window} must be >= 1")
    if tree_mask is not None and q.shape[1] > 31:
        raise ValueError(
            f"tree verification packs the ancestor mask into int32 words: "
            f"W={q.shape[1]} columns exceed the 31-bit budget; lower "
            f"inference.speculate_tokens"
        )
    K = k_pool.shape[1]
    assert q.shape[2] % K == 0, (q.shape, K)
    base = jnp.asarray(layer_base, jnp.int32).reshape(1)

    tp = mesh.shape.get(tp_axis, 1) if mesh is not None else 1
    if tp > 1:
        # Head-sharded serving, exactly the W=1 kernel's scheme: the page
        # walk is head-independent, each device owns K/tp of every page,
        # and G = N/K is preserved per shard.
        N = q.shape[2]
        if N % tp or K % tp:
            raise ValueError(
                f"tp-sharded ragged paged attention needs n_heads ({N}) "
                f"and n_kv_heads ({K}) divisible by {tp_axis}={tp}; lower "
                f"tp or serve with kernels='xla'"
            )
        from jax.sharding import PartitionSpec as P

        qspec = P(None, None, tp_axis, None)     # [B, W, N, H]
        poolspec = P(None, tp_axis, None, None)  # [rows, K, psz, H]
        rep2, rep1 = P(None, None), P(None)
        args = [q, k_pool, v_pool, page_table, start, lens, base]
        in_specs = [qspec, poolspec, poolspec, rep2, rep1, rep1, rep1]
        out_specs = [qspec]
        have_new, have_scale = k_new is not None, k_scale is not None
        if have_new:
            args += [k_new, v_new]
            in_specs += [qspec, qspec]           # [B, W, K, H]
            out_specs += [poolspec, poolspec]
        if have_scale:
            scspec = P(None, tp_axis, None)      # [rows, K, SCALE_LANES]
            args += [k_scale, v_scale]
            in_specs += [scspec, scspec]
            if have_new:
                out_specs += [scspec, scspec]
        have_tree = tree_mask is not None
        if have_tree:
            # Ancestor words/depths are head-independent: replicated,
            # like the page table.
            args += [tree_mask, depths]
            in_specs += [rep2, rep2]

        def body(q_, kp_, vp_, pt_, st_, ln_, base_, *rest):
            kn = vn = ks = vs = tm = dp = None
            rest = list(rest)
            if have_new:
                kn, vn = rest[0], rest[1]
                rest = rest[2:]
            if have_scale:
                ks, vs = rest[0], rest[1]
                rest = rest[2:]
            if have_tree:
                tm, dp = rest[0], rest[1]
            res = _call(
                q_, kp_, vp_, pt_, st_, ln_, base_, kn, vn,
                logit_softcap, window, interpret, ks, vs, tm, dp,
            )
            if not have_new:
                return res[0]
            return res[:3] if not have_scale else res

        mapped = jax.shard_map(
            body, mesh=mesh, in_specs=tuple(in_specs),
            out_specs=tuple(out_specs) if have_new else out_specs[0],
            check_vma=False,
        )
        out = mapped(*args)
        if not have_new:
            return out
        return tuple(out)

    out = _call(
        q, k_pool, v_pool, page_table, start, lens, base, k_new, v_new,
        logit_softcap, window, interpret, k_scale, v_scale,
        tree_mask, depths,
    )
    if k_new is None:
        return out[0]
    if k_scale is None:
        return out[:3]
    return out
