"""Flash attention as a Pallas TPU kernel, forward + custom VJP.

TPU-native equivalent of the reference's fused CUDA attention in ``orion.ops``
(BASELINE.json:5); semantics match ``orion_tpu.ops.attention.attention_xla``
exactly: grouped-query causal attention, optional segment masking (packed
sequences), logit soft-capping, and a ``q_offset`` for decode steps.

Design (SURVEY.md §8 hard-part #1):

- Layout inside the kernel is [batch, heads, seq, head_dim]; the public
  wrapper transposes from the model's [B, S, N, H].
- Grid is (batch, q_head, q_block, kv_block) with the kv block innermost, so
  the online-softmax state (m, l, acc) lives in VMEM scratch carried across
  the kv iterations of one q block.
- GQA is expressed through the k/v BlockSpec index maps (q head n reads kv
  head n * K // N); the backward dk/dv kernel accumulates over the group.
- Causal skipping: blocks strictly above the diagonal skip their compute via
  ``pl.when`` (DMAs still happen — acceptable; revisit with a kv-bound grid).
- The backward pass recomputes attention probabilities from saved (lse) as in
  the flash-attention-2 formulation: two kernels, one accumulating dq over kv
  blocks, one accumulating dk/dv over (group, q-block).
"""

from __future__ import annotations

import dataclasses
import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from orion_tpu.ops.pallas.common import NEG_INF, pad_axis, resolve_interpret, round_up

LANES = 128


@dataclasses.dataclass(frozen=True)
class _Statics:
    """Hashable static config for the custom-VJP core."""

    causal: bool
    logit_softcap: Optional[float]
    q_offset: int
    # Unpadded kv length: padded kv columns are masked in-kernel. Padded q
    # ROWS are deliberately not masked — they produce garbage that the
    # wrapper slices off, and their cotangents are zero in backward.
    seq_kv: int
    block_q: int
    block_kv: int
    interpret: bool
    # Explicit per-token positions provided (striped/permuted layouts):
    # causal masking compares position ARRAYS instead of index iotas, and
    # the causal block-skip becomes a dynamic min/max test on them.
    has_pos: bool = False
    # Sliding-window attention (Mistral-family): attend only to the last
    # `window` positions, i.e. 0 <= q_pos - kv_pos < window (requires
    # causal). Blocks entirely behind the window skip like causal blocks
    # entirely ahead of the diagonal.
    window: Optional[int] = None
    # Opt-in declaration that segment id 0 means PADDING (the pack_rows /
    # ragged-prefill convention): all-padding blocks then SKIP their
    # compute. Off by default — the base segment semantics allow 0 as a
    # real segment id (0==0 attends), and skipping would change results
    # for such callers.
    seg_pad_zero: bool = False


def _unpack_refs(has_seg: bool, has_pos: bool, refs):
    """(q, k, v, qseg, kseg, qpos, kpos, rest) from a kernel's ref list.

    Input order matches _io_args: q, k, v, [qseg, kseg], [qpos, kpos], then
    the kernel-specific inputs/outputs/scratch in ``rest``.
    """
    i = 3
    qseg = kseg = qpos = kpos = None
    if has_seg:
        qseg, kseg = refs[i], refs[i + 1]
        i += 2
    if has_pos:
        qpos, kpos = refs[i], refs[i + 1]
        i += 2
    return refs[0], refs[1], refs[2], qseg, kseg, qpos, kpos, refs[i:]


def _block_mask(st: _Statics, iq, ik, qseg_ref, kseg_ref, qpos_ref, kpos_ref):
    """[bq, bk] bool mask for grid cell (iq, ik); True = attend.

    qseg/kseg (and qpos/kpos) hold the FULL padded sequence of per-token
    ids (blocked (1, 1, S) — TPU tiling forbids (1, bq) blocks); sliced
    here by grid cell.
    """
    bq, bk = st.block_q, st.block_kv
    kv_idx = ik * bk + jax.lax.broadcasted_iota(jnp.int32, (bq, bk), 1)
    mask = kv_idx < st.seq_kv  # kv padding
    if st.causal:
        if st.has_pos:
            q_ids = qpos_ref[0, 0, pl.ds(iq * bq, bq)]
            kv_ids = kpos_ref[0, 0, pl.ds(ik * bk, bk)]
            dist = q_ids[:, None] - kv_ids[None, :]
        else:
            q_pos = iq * bq + jax.lax.broadcasted_iota(
                jnp.int32, (bq, bk), 0
            )
            dist = (q_pos + st.q_offset) - kv_idx
        mask &= dist >= 0
        if st.window is not None:
            mask &= dist < st.window
    if qseg_ref is not None:
        q_ids = qseg_ref[0, 0, pl.ds(iq * bq, bq)]
        kv_ids = kseg_ref[0, 0, pl.ds(ik * bk, bk)]
        mask &= q_ids[:, None] == kv_ids[None, :]
    return mask


def _block_run(st: _Statics, iq, ik, qpos_ref, kpos_ref,
               qseg_ref=None, kseg_ref=None):
    """Block-skip condition for grid cell (iq, ik).

    Causal — index mode: static-shape comparison on block indices;
    position mode: dynamic — a block is skippable only if its largest q
    position precedes its smallest kv position (stripe layouts make this
    the common case for half the blocks, preserving the 2x causal saving).

    Segments — under ``st.seg_pad_zero`` (the caller declares id 0 =
    padding, the data/loader.pack_rows / infer ragged-prefill convention):
    a block whose q rows or kv columns are ALL padding contributes nothing
    anywhere, so it skips. This is what makes mixed-length prefill bursts
    and packed rows pay actual-length compute instead of bucket-padded
    compute. Without the flag, segment blocks never skip (0 may be a real
    segment id).
    """
    run = True
    bq, bk = st.block_q, st.block_kv
    if st.causal:
        if st.has_pos:
            q_ids = qpos_ref[0, 0, pl.ds(iq * bq, bq)]
            kv_ids = kpos_ref[0, 0, pl.ds(ik * bk, bk)]
            run = jnp.max(q_ids) >= jnp.min(kv_ids)
            if st.window is not None:
                # Skip blocks entirely behind the window: largest kv
                # position within reach of the smallest q position. (kv
                # padding is PAD_POS_KV, so padded blocks stay
                # runnable-but-masked.)
                run &= jnp.max(kv_ids) > jnp.min(q_ids) - st.window
        else:
            q_max = iq * bq + bq - 1 + st.q_offset
            run = ik * bk <= q_max
            if st.window is not None:
                q_min = iq * bq + st.q_offset
                run = run & (ik * bk + bk - 1 > q_min - st.window)
    if st.seg_pad_zero and qseg_ref is not None:
        q_seg = qseg_ref[0, 0, pl.ds(iq * bq, bq)]
        kv_seg = kseg_ref[0, 0, pl.ds(ik * bk, bk)]
        run &= (jnp.max(q_seg) > 0) & (jnp.max(kv_seg) > 0)
    return run


def _scaled_logits(st: _Statics, q, k, scale):
    """Returns (z, dz_dscale_factor) where z is the softcapped logit block.

    The second value is tanh(s/cap) (needed by backward) or None.
    """
    s = jax.lax.dot_general(
        q.astype(jnp.float32) * scale,
        k.astype(jnp.float32),
        (((1,), (1,)), ((), ())),
        preferred_element_type=jnp.float32,
    )
    if st.logit_softcap is not None:
        t = jnp.tanh(s / st.logit_softcap)
        return st.logit_softcap * t, t
    return s, None


def _fwd_kernel(st: _Statics, has_seg, *refs):
    (q_ref, k_ref, v_ref, qseg, kseg, qpos, kpos,
     (o_ref, lse_ref, m_s, l_s, acc_s)) = _unpack_refs(
        has_seg, st.has_pos, refs)

    iq, ik = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)
    scale = q_ref.shape[-1] ** -0.5

    @pl.when(ik == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    # Skip blocks with nothing visible under the causal mask.
    run = _block_run(st, iq, ik, qpos, kpos, qseg, kseg)

    @pl.when(run)
    def _body():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        z, _ = _scaled_logits(st, q, k, scale)
        mask = _block_mask(st, iq, ik, qseg, kseg, qpos, kpos)
        z = jnp.where(mask, z, NEG_INF)

        m_prev = m_s[:, :1]                       # [bq, 1]
        m_new = jnp.maximum(m_prev, z.max(axis=-1, keepdims=True))
        # Masked rows keep m == NEG_INF; exp(z - m) would be exp(0) = 1
        # there, so re-apply the mask multiplicatively.
        p = jnp.exp(z - m_new) * mask.astype(jnp.float32)
        alpha = jnp.exp(m_prev - m_new)           # [bq, 1]
        l_new = l_s[:, :1] * alpha + p.sum(axis=-1, keepdims=True)
        acc_s[:] = acc_s[:] * alpha + jax.lax.dot_general(
            p, v.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        m_s[:] = jnp.broadcast_to(m_new, m_s.shape)
        l_s[:] = jnp.broadcast_to(l_new, l_s.shape)

    @pl.when(ik == nk - 1)
    def _finish():
        l = l_s[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_s[:] / l_safe).astype(o_ref.dtype)
        lse = m_s[:, :1] + jnp.log(l_safe)
        lse = jnp.where(l == 0.0, NEG_INF, lse)
        lse_ref[0, 0] = jnp.broadcast_to(lse, lse_ref.shape[2:])


def _dq_kernel(st: _Statics, has_seg, *refs):
    (q_ref, k_ref, v_ref, qseg, kseg, qpos, kpos,
     (do_ref, lse_ref, delta_ref, dq_ref, dq_s)) = _unpack_refs(
        has_seg, st.has_pos, refs)

    iq, ik = pl.program_id(2), pl.program_id(3)
    nk = pl.num_programs(3)
    scale = q_ref.shape[-1] ** -0.5

    @pl.when(ik == 0)
    def _init():
        dq_s[:] = jnp.zeros_like(dq_s)

    run = _block_run(st, iq, ik, qpos, kpos, qseg, kseg)

    @pl.when(run)
    def _body():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0].astype(jnp.float32)
        z, t = _scaled_logits(st, q, k, scale)
        mask = _block_mask(st, iq, ik, qseg, kseg, qpos, kpos)
        lse = lse_ref[0, 0][:, :1]                # [bq, 1] (lanes-broadcast)
        # Mask INSIDE the exp (as the forward does): a fully-masked q row
        # carries the finite NEG_INF lse stand-in, so exp(z - lse) on its
        # raw logits overflows to inf and inf * 0-mask is NaN (hit by the
        # round-5 compiled ring-merge parity check).
        p = jnp.exp(jnp.where(mask, z - lse, NEG_INF))
        dp = jax.lax.dot_general(
            do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dz = p * (dp - delta_ref[0, 0][:, :1])
        ds = dz if t is None else dz * (1.0 - t * t)
        dq_s[:] += jax.lax.dot_general(
            ds, k.astype(jnp.float32), (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when(ik == nk - 1)
    def _finish():
        dq_ref[0, 0] = dq_s[:].astype(dq_ref.dtype)


def _dkv_kernel(st: _Statics, has_seg, *refs):
    (q_ref, k_ref, v_ref, qseg, kseg, qpos, kpos,
     (do_ref, lse_ref, delta_ref, dk_ref, dv_ref, dk_s, dv_s)) = _unpack_refs(
        has_seg, st.has_pos, refs)

    # grid = (batch, kv_head, kv_block, group, q_block)
    ik, g, iq = pl.program_id(2), pl.program_id(3), pl.program_id(4)
    ng, nq = pl.num_programs(3), pl.num_programs(4)
    scale = q_ref.shape[-1] ** -0.5

    @pl.when((g == 0) & (iq == 0))
    def _init():
        dk_s[:] = jnp.zeros_like(dk_s)
        dv_s[:] = jnp.zeros_like(dv_s)

    run = _block_run(st, iq, ik, qpos, kpos, qseg, kseg)

    @pl.when(run)
    def _body():
        q = q_ref[0, 0]
        k = k_ref[0, 0]
        v = v_ref[0, 0]
        do = do_ref[0, 0].astype(jnp.float32)
        z, t = _scaled_logits(st, q, k, scale)
        mask = _block_mask(st, iq, ik, qseg, kseg, qpos, kpos)
        lse = lse_ref[0, 0][:, :1]
        # Masked inside the exp — see _dq_kernel for the NaN rationale.
        p = jnp.exp(jnp.where(mask, z - lse, NEG_INF))
        dv_s[:] += jax.lax.dot_general(
            p, do, (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dp = jax.lax.dot_general(
            do, v.astype(jnp.float32), (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )
        dz = p * (dp - delta_ref[0, 0][:, :1])
        ds = dz if t is None else dz * (1.0 - t * t)
        dk_s[:] += jax.lax.dot_general(
            ds, q.astype(jnp.float32), (((0,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        ) * scale

    @pl.when((g == ng - 1) & (iq == nq - 1))
    def _finish():
        dk_ref[0, 0] = dk_s[:].astype(dk_ref.dtype)
        dv_ref[0, 0] = dv_s[:].astype(dv_ref.dtype)


def _seg_specs(Sq_p: int, Skv_p: int, batch_index):
    """Full-sequence (1, 1, S) segment-id blocks (TPU tiling-legal); the
    kernels slice the current block's ids with pl.ds."""
    return [
        pl.BlockSpec((1, 1, Sq_p), batch_index),
        pl.BlockSpec((1, 1, Skv_p), batch_index),
    ]


def _fwd_call(st: _Statics, q, k, v, qseg, kseg, qpos=None, kpos=None):
    """q: [B,N,Sq,H]; k,v: [B,K,Skv,H] (padded) -> (o, lse[f32 B,N,Sq])."""
    B, N, Sq, H = q.shape
    K, Skv = k.shape[1], k.shape[2]
    G = N // K
    nq, nk = Sq // st.block_q, Skv // st.block_kv
    grid = (B, N, nq, nk)

    q_spec = pl.BlockSpec((1, 1, st.block_q, H), lambda b, n, iq, ik: (b, n, iq, 0))
    kv_spec = pl.BlockSpec(
        (1, 1, st.block_kv, H), lambda b, n, iq, ik: (b, n // G, ik, 0)
    )
    in_specs = [q_spec, kv_spec, kv_spec]
    args = [q, k, v]
    if qseg is not None:
        in_specs += _seg_specs(Sq, Skv, lambda b, n, iq, ik: (b, 0, 0))
        args += [qseg, kseg]
    if qpos is not None:
        in_specs += _seg_specs(Sq, Skv, lambda b, n, iq, ik: (b, 0, 0))
        args += [qpos, kpos]

    out = pl.pallas_call(
        functools.partial(_fwd_kernel, st, qseg is not None),
        grid=grid,
        in_specs=in_specs,
        out_specs=[
            pl.BlockSpec((1, 1, st.block_q, H), lambda b, n, iq, ik: (b, n, iq, 0)),
            pl.BlockSpec(
                (1, 1, st.block_q, LANES), lambda b, n, iq, ik: (b, n, iq, 0)
            ),
        ],
        out_shape=[
            jax.ShapeDtypeStruct(q.shape, q.dtype),
            # lse is lanes-broadcast [B, N, Sq, 128]: TPU tiling forbids a
            # (1, 1, block_q) block, so the row stat rides a full lane dim.
            jax.ShapeDtypeStruct((B, N, Sq, LANES), jnp.float32),
        ],
        scratch_shapes=[
            pltpu.VMEM((st.block_q, LANES), jnp.float32),
            pltpu.VMEM((st.block_q, LANES), jnp.float32),
            pltpu.VMEM((st.block_q, H), jnp.float32),
        ],
        interpret=st.interpret,
    )(*args)
    return out[0], out[1]


def _bwd_call(st: _Statics, q, k, v, qseg, kseg, o, lse, do, g_lse=None,
              qpos=None, kpos=None):
    B, N, Sq, H = q.shape
    K, Skv = k.shape[1], k.shape[2]
    G = N // K
    nq, nk = Sq // st.block_q, Skv // st.block_kv

    delta = jnp.sum(do.astype(jnp.float32) * o.astype(jnp.float32), axis=-1)
    if g_lse is not None:
        # lse cotangent: d lse_i / d z_ij = p_ij, so the dlse term enters dz
        # as +g_lse_i * p_ij — exactly -g_lse folded into delta, since the
        # kernels compute dz = p * (dp - delta).
        delta = delta - g_lse
    delta = jnp.broadcast_to(delta[..., None], (B, N, Sq, LANES))

    q_spec4 = pl.BlockSpec((1, 1, st.block_q, H), lambda b, n, iq, ik: (b, n, iq, 0))
    kv_spec4 = pl.BlockSpec(
        (1, 1, st.block_kv, H), lambda b, n, iq, ik: (b, n // G, ik, 0)
    )
    row_spec4 = pl.BlockSpec(
        (1, 1, st.block_q, LANES), lambda b, n, iq, ik: (b, n, iq, 0)
    )
    in_specs = [q_spec4, kv_spec4, kv_spec4]
    args = [q, k, v]
    if qseg is not None:
        in_specs += _seg_specs(Sq, Skv, lambda b, n, iq, ik: (b, 0, 0))
        args += [qseg, kseg]
    if qpos is not None:
        in_specs += _seg_specs(Sq, Skv, lambda b, n, iq, ik: (b, 0, 0))
        args += [qpos, kpos]
    in_specs += [q_spec4, row_spec4, row_spec4]
    args += [do, lse, delta]

    dq = pl.pallas_call(
        functools.partial(_dq_kernel, st, qseg is not None),
        grid=(B, N, nq, nk),
        in_specs=in_specs,
        out_specs=q_spec4,
        out_shape=jax.ShapeDtypeStruct(q.shape, q.dtype),
        scratch_shapes=[pltpu.VMEM((st.block_q, H), jnp.float32)],
        interpret=st.interpret,
    )(*args)

    # grid = (batch, kv_head, kv_block, group, q_block): the dk/dv output
    # block for (b, kh, ik) is revisited across the two inner dims, so the
    # accumulator scratch carries over the whole group x q sweep.
    def _q_map5(b, kh, ik, g, iq):
        return (b, kh * G + g, iq, 0)

    def _row_map5(b, kh, ik, g, iq):
        return (b, kh * G + g, iq, 0)

    q_spec5 = pl.BlockSpec((1, 1, st.block_q, H), _q_map5)
    kv_spec5 = pl.BlockSpec(
        (1, 1, st.block_kv, H), lambda b, kh, ik, g, iq: (b, kh, ik, 0)
    )
    row_spec5 = pl.BlockSpec((1, 1, st.block_q, LANES), _row_map5)
    in_specs5 = [q_spec5, kv_spec5, kv_spec5]
    args5 = [q, k, v]
    if qseg is not None:
        in_specs5 += _seg_specs(Sq, Skv, lambda b, kh, ik, g, iq: (b, 0, 0))
        args5 += [qseg, kseg]
    if qpos is not None:
        in_specs5 += _seg_specs(Sq, Skv, lambda b, kh, ik, g, iq: (b, 0, 0))
        args5 += [qpos, kpos]
    in_specs5 += [q_spec5, row_spec5, row_spec5]
    args5 += [do, lse, delta]

    dk, dv = pl.pallas_call(
        functools.partial(_dkv_kernel, st, qseg is not None),
        grid=(B, K, nk, G, nq),
        in_specs=in_specs5,
        out_specs=[kv_spec5, kv_spec5],
        out_shape=[
            jax.ShapeDtypeStruct(k.shape, k.dtype),
            jax.ShapeDtypeStruct(v.shape, v.dtype),
        ],
        scratch_shapes=[
            pltpu.VMEM((st.block_kv, H), jnp.float32),
            pltpu.VMEM((st.block_kv, H), jnp.float32),
        ],
        interpret=st.interpret,
    )(*args5)
    return dq, dk, dv


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash(st: _Statics, q, k, v, qseg, kseg, qpos, kpos):
    o, _ = _fwd_call(st, q, k, v, qseg, kseg, qpos, kpos)
    return o


def _flash_fwd(st, q, k, v, qseg, kseg, qpos, kpos):
    o, lse = _fwd_call(st, q, k, v, qseg, kseg, qpos, kpos)
    return o, (q, k, v, qseg, kseg, qpos, kpos, o, lse)


def _flash_bwd(st, res, do):
    q, k, v, qseg, kseg, qpos, kpos, o, lse = res
    dq, dk, dv = _bwd_call(st, q, k, v, qseg, kseg, o, lse, do,
                           qpos=qpos, kpos=kpos)
    return dq, dk, dv, None, None, None, None


_flash.defvjp(_flash_fwd, _flash_bwd)


@functools.partial(jax.custom_vjp, nondiff_argnums=(0,))
def _flash_lse(st: _Statics, q, k, v, qseg, kseg, qpos, kpos):
    """Like _flash but also returns the lanes-broadcast lse residual as a
    differentiable output (ring attention's block merge needs it)."""
    return _fwd_call(st, q, k, v, qseg, kseg, qpos, kpos)


def _flash_lse_fwd(st, q, k, v, qseg, kseg, qpos, kpos):
    o, lse = _fwd_call(st, q, k, v, qseg, kseg, qpos, kpos)
    return (o, lse), (q, k, v, qseg, kseg, qpos, kpos, o, lse)


def _flash_lse_bwd(st, res, cts):
    q, k, v, qseg, kseg, qpos, kpos, o, lse = res
    do, dlse = cts
    # The primal lse output is lanes-broadcast [B, N, Sq, LANES]; the true
    # scalar-per-row cotangent is the sum over the broadcast lane copies.
    g_lse = dlse.sum(axis=-1)
    dq, dk, dv = _bwd_call(st, q, k, v, qseg, kseg, o, lse, do, g_lse=g_lse,
                           qpos=qpos, kpos=kpos)
    return dq, dk, dv, None, None, None, None


_flash_lse.defvjp(_flash_lse_fwd, _flash_lse_bwd)


def flash_attention_with_lse(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    q_segment_ids: Optional[jax.Array] = None,
    kv_segment_ids: Optional[jax.Array] = None,
    logit_softcap: Optional[float] = None,
    q_offset: int = 0,
    block_q: Optional[int] = None,
    block_kv: Optional[int] = None,
    interpret: Optional[bool] = None,
    q_positions: Optional[jax.Array] = None,
    kv_positions: Optional[jax.Array] = None,
    window: Optional[int] = None,
) -> tuple[jax.Array, jax.Array]:
    """Flash attention returning ``(out, lse)``; the blockwise unit of ring
    attention (parallel/sequence.py merges partial outputs via their lse).

    out: [B, Sq, N, H] in q.dtype; lse: [B, N, Sq] float32, ``-inf`` on rows
    where nothing was attended (fully masked). Differentiable in both
    outputs. ``q_positions``/``kv_positions`` and ``window`` as in
    ``flash_attention`` (ring layouts pass blocks' global positions so the
    sliding window measures true sequence distance).
    """
    st, qt, kt, vt, qseg, kseg, qpos, kpos, Sq = _prep(
        q, k, v, q_segment_ids, kv_segment_ids,
        causal, logit_softcap, q_offset, block_q, block_kv, interpret,
        q_positions, kv_positions, window,
    )
    o, lse = _flash_lse(st, qt, kt, vt, qseg, kseg, qpos, kpos)
    o = o[:, :, :Sq, :].transpose(0, 2, 1, 3)
    lse = lse[:, :, :Sq, 0]
    # In-kernel "nothing attended" rows carry the finite NEG_INF stand-in;
    # the ring merge keys off true -inf.
    lse = jnp.where(lse <= NEG_INF / 2, -jnp.inf, lse)
    return o, lse


PAD_POS_KV = 2 ** 30  # kv-position pad: larger than any real position, so
#                       padded columns never pass the >= causal test and
#                       fully-padded blocks are skippable by min().


def _prep(
    q, k, v, q_segment_ids, kv_segment_ids,
    causal, logit_softcap, q_offset, block_q, block_kv, interpret,
    q_positions=None, kv_positions=None, window=None, seg_pad_zero=False,
):
    """Shared wrapper prep: statics + [B,N,S,H] transpose + block padding.

    block_q/block_kv default to large (1024) tiles: on v5e the online-softmax
    bookkeeping (max/sum/rescale on the VPU) is amortized over tile area, and
    1024x1024 measured ~2.3x xla attention fwd+bwd at the bench shapes while
    the conservative 128x128 was ~2x *slower* than xla.
    """
    assert (q_segment_ids is None) == (kv_segment_ids is None)
    assert (q_positions is None) == (kv_positions is None)
    if window is not None and (not causal or window < 1):
        raise ValueError(
            f"window={window} requires causal attention and window >= 1"
        )
    B, Sq, N, H = q.shape
    Skv, K = k.shape[1], k.shape[2]
    assert N % K == 0, (N, K)

    bq = min(block_q or 1024, round_up(Sq, 8))
    bk = min(block_kv or 1024, round_up(Skv, 8))
    if q_segment_ids is not None or q_positions is not None:
        # Segment/position refs are full-length (B, 1, S) int32 arrays that
        # the kernel slices at dynamic lane offsets (i * block). Mosaic
        # requires dynamic lane slices to be provably 128-aligned, so the
        # blocks (and hence every offset, a multiple of the block) must be
        # multiples of the 128-lane tile — the round-5 compiled run died
        # on a 64-wide i32 load here. Padded q rows slice off at the end;
        # padded kv columns stay masked (seg 0 / PAD_POS_KV conventions).
        bq = round_up(bq, 128)
        bk = round_up(bk, 128)
    Sq_p, Skv_p = round_up(Sq, bq), round_up(Skv, bk)

    st = _Statics(
        causal=causal,
        logit_softcap=logit_softcap,
        q_offset=q_offset,
        seq_kv=Skv,
        block_q=bq,
        block_kv=bk,
        interpret=resolve_interpret(interpret),
        has_pos=q_positions is not None,
        window=window,
        seg_pad_zero=seg_pad_zero and q_segment_ids is not None,
    )

    qt = pad_axis(q.transpose(0, 2, 1, 3), 2, Sq_p)
    kt = pad_axis(k.transpose(0, 2, 1, 3), 2, Skv_p)
    vt = pad_axis(v.transpose(0, 2, 1, 3), 2, Skv_p)
    qseg = kseg = None
    if q_segment_ids is not None:
        # (B, 1, S) so the full-seq segment blocks are TPU tiling-legal.
        qseg = pad_axis(q_segment_ids.astype(jnp.int32), 1, Sq_p)[:, None, :]
        kseg = pad_axis(kv_segment_ids.astype(jnp.int32), 1, Skv_p)[:, None, :]
    qpos = kpos = None
    if q_positions is not None:
        if q_positions.ndim == 1:
            q_positions = jnp.broadcast_to(q_positions[None], (B, Sq))
        if kv_positions.ndim == 1:
            kv_positions = jnp.broadcast_to(kv_positions[None], (B, Skv))
        # q pad -1 (rows sliced off; never attends under >=), kv pad huge
        # (never attended; keeps fully-padded blocks skippable).
        qpos = pad_axis(
            q_positions.astype(jnp.int32) + 1, 1, Sq_p
        )[:, None, :] - 1
        kpos = jnp.pad(
            kv_positions.astype(jnp.int32), ((0, 0), (0, Skv_p - Skv)),
            constant_values=PAD_POS_KV,
        )[:, None, :]
    return st, qt, kt, vt, qseg, kseg, qpos, kpos, Sq


def flash_attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    q_segment_ids: Optional[jax.Array] = None,
    kv_segment_ids: Optional[jax.Array] = None,
    logit_softcap: Optional[float] = None,
    q_offset: int = 0,
    block_q: Optional[int] = None,
    block_kv: Optional[int] = None,
    interpret: Optional[bool] = None,
    q_positions: Optional[jax.Array] = None,
    kv_positions: Optional[jax.Array] = None,
    window: Optional[int] = None,
    seg_pad_zero: bool = False,
) -> jax.Array:
    """Flash attention; shapes/semantics match ``attention_xla``.

    q: [B, Sq, N, H]; k, v: [B, Skv, K, H] with N % K == 0 -> [B, Sq, N, H].
    With ``q_positions``/``kv_positions`` ([B, S] or [S] int32), causal
    masking compares those explicit positions (permuted/striped sequence
    layouts); otherwise token index + ``q_offset``. ``window`` restricts
    attention to the last ``window`` positions (sliding-window / Mistral;
    blocks fully behind the window skip their compute). ``seg_pad_zero``
    declares segment id 0 as padding, letting all-padding blocks SKIP
    (ragged prefill / packed tails) — only set it when the caller
    guarantees the pack_rows convention.
    See ``_prep`` for the tile-size default rationale.
    """
    st, qt, kt, vt, qseg, kseg, qpos, kpos, Sq = _prep(
        q, k, v, q_segment_ids, kv_segment_ids,
        causal, logit_softcap, q_offset, block_q, block_kv, interpret,
        q_positions, kv_positions, window, seg_pad_zero,
    )
    o = _flash(st, qt, kt, vt, qseg, kseg, qpos, kpos)
    return o[:, :, :Sq, :].transpose(0, 2, 1, 3)
