"""Blockwise paged-flash prefill: chunk queries attend paged KV history.

Chunked prefill (``runner._prefill_layer`` with P_pre > 0) is a
mid-sequence tail prefill: S_pad new tokens per slot attend the slot's
ENTIRE paged KV history. The XLA reference body gathers the P_pre prefix
pages into a dense [Nb, P_pre*psz, K, H] copy per chunk per layer —
O(padded_context) HBM traffic that grows with the cursor, exactly the
copy tax that makes long-context prefill copy-bound instead of
FLOPs-bound (PERF.md §"Long context"). This kernel is the ragged
paged-attention kernel (W <= 31 verify queries) generalized to full
prefill-chunk query blocks, sharing its design decisions:

  - (slot, q_block, walk_page) grid over a COMBINED page walk: the
    scalar-prefetched walk table is ``concat([prefix_pages, chunk_pages],
    axis=1)`` — walk steps below P_pre read history pages from the pool,
    steps at/above P_pre own the chunk's pages. Per-dispatch VMEM is
    bounded by one page block, never by the context length.
  - Same clamped-index DMA elision: prefix pages past a row's own
    prefix clamp DOWN to its last real prefix page, behind-window
    prefix pages clamp UP to the q block's window start — Mosaic elides
    the revisit DMA either way. Chunk pages past the q block's causal
    horizon clamp DOWN to the q block's own page (causal block-skip
    among the new positions).
  - The chunk's OWN K/V is read from a dense per-page operand, never
    from the pool — so write timing can never affect reads, and the new
    tokens are attended RAW (unquantized), exactly like the XLA
    reference's ``concat([k_pre, k])``.
  - Chunk pages are written INSIDE the kernel via input/output aliasing.
    Because chunks are page-aligned (the engine page-aligns mid-prompt
    chunk sizes), every chunk page is overwritten WHOLE — no one-hot
    merge needed, the write is ``quantize_kv(page)`` (or the raw page)
    and is idempotent, so clamped revisits re-applying it are harmless.
    Written pool bytes match the XLA scatter bit-for-bit: same
    ``common.quantize_kv``, same whole-page layout, padding columns
    included (the XLA path writes padding garbage too; decode masks it).
  - Int8 history pages dequantize in-kernel via the lanes-padded scale
    pools; new scale pages land in the first psz scale columns with the
    remaining lanes passed through, matching ``.at[rows, :, :psz].set``.

Like the ragged kernel, padding queries (rows past ``lens``) compute
garbage the caller discards — the XLA reference's discard semantics are
the contract. Prefill is inference-only; no VJP is defined.
"""

from __future__ import annotations

import functools
from typing import Optional, Union

import jax
import jax.numpy as jnp
from jax import lax
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from orion_tpu.ops.pallas.common import (
    NEG_INF,
    quantize_kv,
    resolve_interpret,
    round_up,
)

LANES = 128
VMEM_BUDGET_BYTES = 12 * 2 ** 20


def prefill_vmem_bytes(
    *,
    n_heads: int,
    n_kv_heads: int,
    head_dim: int,
    page_size: int,
    kv_itemsize: int,
    quant: bool,
) -> int:
    """Estimated VMEM footprint of one paged-flash-prefill grid step: the
    q/out blocks (one page of queries x GQA group), double-buffered
    in+out pool page blocks, the dense chunk K/V page blocks, the f32
    online-softmax scratch, and the scale blocks under quant. Page-block
    bounded — S never appears."""
    K = n_kv_heads
    G = n_heads // K
    QG8 = max(round_up(page_size * G, 8), 8)
    q_io = 2 * K * QG8 * head_dim * 4
    kv_io = 2 * 2 * 2 * K * page_size * head_dim * kv_itemsize
    new = 2 * 2 * K * page_size * head_dim * 4
    scratch = K * QG8 * (2 * LANES + head_dim) * 4
    scales = (2 * 2 * 2 * K * LANES * 4) if quant else 0
    return q_io + kv_io + new + scratch + scales


def check_prefill_fit(
    *,
    n_heads: int,
    n_kv_heads: int,
    head_dim: int,
    page_size: int,
    kv_quant: Optional[str],
    dtype_itemsize: int = 2,
) -> None:
    """Reject a page size the prefill kernel cannot hold in VMEM — called
    by the engine at init when chunked prefill rides the pallas kernel
    path, so the failure is a config error naming the knob, not a Mosaic
    allocation failure mid-serving."""
    quant = kv_quant == "int8"
    need = prefill_vmem_bytes(
        n_heads=n_heads, n_kv_heads=n_kv_heads, head_dim=head_dim,
        page_size=page_size, kv_itemsize=1 if quant else dtype_itemsize,
        quant=quant,
    )
    if need > VMEM_BUDGET_BYTES:
        raise ValueError(
            f"paged-flash prefill needs ~{need / 2**20:.1f} MiB of VMEM "
            f"per kernel step at page_size={page_size}, over the "
            f"~{VMEM_BUDGET_BYTES / 2**20:.0f} MiB budget; lower "
            f"inference.page_size, set inference.paged_prefill=false, or "
            f"serve with model.kernels='xla'"
        )


def _kernel(
    softcap: Optional[float],
    psz: int,
    K: int,
    G: int,
    P_pre: int,
    NC: int,
    QG8: int,
    window: Optional[int],
    quant: bool,
    wt_ref,        # [B, P_pre+NC] scalar-prefetched combined page walk
    base_ref,      # [1] scalar-prefetched flat-pool row base (layer * NP)
    st_ref,        # [B] scalar-prefetched cursor (page-aligned prefix len)
    ln_ref,        # [B] scalar-prefetched real chunk tokens per row
    *refs,
):
    refs = list(refs)
    q_ref, k_ref, v_ref = refs[:3]
    i = 3
    ks_ref = vs_ref = None
    if quant:
        ks_ref, vs_ref = refs[i], refs[i + 1]
        i += 2
    kn_ref, vn_ref = refs[i], refs[i + 1]
    i += 2
    o_ref, ko_ref, vo_ref = refs[i], refs[i + 1], refs[i + 2]
    i += 3
    kso_ref = vso_ref = None
    if quant:
        kso_ref, vso_ref = refs[i], refs[i + 1]
        i += 2
    m_s, l_s, acc_s = refs[i:]

    b, qb, ip = pl.program_id(0), pl.program_id(1), pl.program_id(2)
    start = st_ref[b]      # page-aligned: tokens already in the pool
    qlen = ln_ref[b]       # real new tokens this row (1..NC*psz)
    H = q_ref.shape[-1]
    scale = H ** -0.5
    is_chunk = ip >= P_pre
    cb = ip - P_pre        # raw chunk-block index (valid when run_ch)

    @pl.when(ip == 0)
    def _init():
        m_s[:] = jnp.full_like(m_s, NEG_INF)
        l_s[:] = jnp.zeros_like(l_s)
        acc_s[:] = jnp.zeros_like(acc_s)

    # Packed-row decomposition shared by both phases: row r of a K band
    # holds query qw = r // G at chunk-local position qb*psz + qw
    # (padding rows past psz*G clamp to the block's last query; their
    # outputs are sliced away by the caller).
    rowq = lax.broadcasted_iota(jnp.int32, (K * QG8, psz), 0) % QG8
    qw = jnp.minimum(rowq // G, psz - 1)
    q_loc = qb * psz + qw                       # chunk-local query pos

    def update(z, mask):
        """One online-softmax step over a masked [K*QG8, psz] logit
        block: folds the block into m/l scratch, returns (p, alpha) for
        the caller's acc update."""
        z = jnp.where(mask, z, NEG_INF)
        m_prev = m_s[:, :1]
        m_new = jnp.maximum(m_prev, z.max(axis=-1, keepdims=True))
        p = jnp.exp(z - m_new) * mask.astype(jnp.float32)
        alpha = jnp.exp(m_prev - m_new)
        l_s[:] = jnp.broadcast_to(
            l_s[:, :1] * alpha + p.sum(axis=-1, keepdims=True), l_s.shape
        )
        m_s[:] = jnp.broadcast_to(m_new, m_s.shape)
        return p, alpha

    # History phase: this q block's queries against one prefix page.
    # Skip pages wholly past the row's prefix, wholly behind the earliest
    # query's window, or belonging to an all-padding q block.
    run_pre = (~is_chunk) & (ip * psz < start) & (qb * psz < qlen)
    if window is not None:
        run_pre &= ip * psz + psz - 1 >= start + qb * psz - window + 1

    @pl.when(run_pre)
    def _pre():
        q = q_ref[0, 0].reshape(K, QG8, H).astype(jnp.float32)
        k = k_ref[0].astype(jnp.float32)             # [K, psz, H]
        v = v_ref[0].astype(jnp.float32)
        z = lax.dot_general(
            q * scale, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )                                            # [K, QG8, psz]
        if quant:
            z = z * ks_ref[0][:, :psz][:, None, :]
        z = z.reshape(K * QG8, psz)
        if softcap is not None:
            z = softcap * jnp.tanh(z / softcap)
        kv_pos = ip * psz + lax.broadcasted_iota(
            jnp.int32, (K * QG8, psz), 1
        )
        # Prefix columns are causal for every new query; the segment is
        # the row's own prefix length (clamped revisits mask entirely).
        mask = kv_pos < start
        if window is not None:
            mask &= kv_pos >= start + q_loc - window + 1
        p, alpha = update(z, mask)
        pw = p.reshape(K, QG8, psz)
        if quant:
            pw = pw * vs_ref[0][:, :psz][:, None, :]
        pv = lax.dot_general(
            pw, v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        acc_s[:] = acc_s[:] * alpha + pv.reshape(K * QG8, H)

    # Chunk phase: the q block against one of the chunk's own pages, read
    # RAW from the dense operand (never the pool). Causal block-skip:
    # pages past the q block do nothing; pages wholly past the row's real
    # tokens hold only padding every real query masks.
    run_ch = is_chunk & (cb <= qb) & (cb * psz < qlen) & (qb * psz < qlen)
    if window is not None:
        run_ch &= cb * psz + psz - 1 >= qb * psz - window + 1

    @pl.when(run_ch)
    def _ch():
        q = q_ref[0, 0].reshape(K, QG8, H).astype(jnp.float32)
        k = kn_ref[0, 0].astype(jnp.float32)         # [K, psz, H] raw
        v = vn_ref[0, 0].astype(jnp.float32)
        z = lax.dot_general(
            q * scale, k, (((2,), (2,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        ).reshape(K * QG8, psz)
        if softcap is not None:
            z = softcap * jnp.tanh(z / softcap)
        kv_loc = cb * psz + lax.broadcasted_iota(
            jnp.int32, (K * QG8, psz), 1
        )
        mask = kv_loc <= q_loc
        if window is not None:
            mask &= kv_loc >= q_loc - window + 1
        p, alpha = update(z, mask)
        pv = lax.dot_general(
            p.reshape(K, QG8, psz), v, (((2,), (1,)), ((0,), (0,))),
            preferred_element_type=jnp.float32,
        )
        acc_s[:] = acc_s[:] * alpha + pv.reshape(K * QG8, H)

    # Fused page write: chunk pages are whole-page overwrites (chunks are
    # page-aligned), recomputed identically on every visit — clamped
    # revisits are harmless. Prefix-phase steps pass the fetched block
    # through so a revisit's write-back never clobbers history.
    @pl.when(is_chunk)
    def _write():
        if not quant:
            ko_ref[0] = kn_ref[0, 0].astype(ko_ref.dtype)
            vo_ref[0] = vn_ref[0, 0].astype(vo_ref.dtype)
        else:
            SW = kso_ref.shape[-1]
            colc = lax.broadcasted_iota(jnp.int32, (SW, psz), 0)
            tokc = lax.broadcasted_iota(jnp.int32, (SW, psz), 1)
            selc = (colc == tokc).astype(jnp.float32)    # [SW, psz]
            col_has = selc.sum(axis=1) > 0.5             # [SW]
            sel_c = jnp.broadcast_to(selc[None], (K, SW, psz))
            for new_ref, out_ref, sin_ref, sout_ref in (
                (kn_ref, ko_ref, ks_ref, kso_ref),
                (vn_ref, vo_ref, vs_ref, vso_ref),
            ):
                qv, s = quantize_kv(new_ref[0, 0])   # [K,psz,H], [K,psz]
                out_ref[0] = qv.astype(out_ref.dtype)
                s_m = lax.dot_general(
                    sel_c, s, (((2,), (1,)), ((0,), (0,))),
                    preferred_element_type=jnp.float32,
                )                                        # [K, SW]
                sout_ref[0] = jnp.where(col_has[None, :], s_m, sin_ref[0])

    @pl.when(~is_chunk)
    def _passthru():
        ko_ref[0] = k_ref[0]
        vo_ref[0] = v_ref[0]
        if quant:
            kso_ref[0] = ks_ref[0]
            vso_ref[0] = vs_ref[0]

    @pl.when(ip == P_pre + NC - 1)
    def _finish():
        l = l_s[:, :1]
        l_safe = jnp.where(l == 0.0, 1.0, l)
        o_ref[0, 0] = (acc_s[:] / l_safe).astype(o_ref.dtype)


def _call(q, k_pool, v_pool, walk, start, lens, base, k_new, v_new,
          P_pre, softcap, window, interpret, k_scale=None, v_scale=None):
    B, S, N, H = q.shape
    _, K, psz, _ = k_pool.shape
    assert S % psz == 0, (S, psz)
    NC = S // psz
    G = N // K
    QG = psz * G
    QG8 = max(round_up(QG, 8), 8)
    quant = k_scale is not None

    # Pack each page-sized q block's GQA bands per kv head, padded to a
    # sublane multiple: [B, NC, K*QG8, H], row = qw * G + g.
    qg = q.reshape(B, NC, psz, K, G, H).transpose(0, 1, 3, 2, 4, 5)
    qg = qg.reshape(B, NC, K, QG, H)
    if QG8 != QG:
        qg = jnp.pad(qg, ((0, 0), (0, 0), (0, 0), (0, QG8 - QG), (0, 0)))
    qg = qg.reshape(B, NC, K * QG8, H)
    # Chunk K/V pre-arranged in page layout: [B, NC, K, psz, H] so walk
    # step P_pre + cb's dense block IS the page to write.
    kn = k_new.reshape(B, NC, psz, K, H).transpose(0, 1, 3, 2, 4)
    vn = v_new.reshape(B, NC, psz, K, H).transpose(0, 1, 3, 2, 4)

    def chunk_cb(qb, ip):
        # Causal clamp: chunk pages past the q block elide their DMA by
        # re-requesting the q block's own page (idempotent rewrite).
        cb = jnp.clip(ip - P_pre, 0, NC - 1)
        return jnp.minimum(cb, qb)

    def kv_index(b, qb, ip, wt, bs, st, ln):
        # Prefix half: clamp DOWN past the row's own prefix, UP behind
        # the q block's earliest window — both elide the revisit DMA.
        last_pre = jnp.maximum(st[b] // psz - 1, 0)
        pre_ip = jnp.minimum(ip, last_pre)
        if window is not None:
            first = jnp.maximum(st[b] + qb * psz - window + 1, 0) // psz
            pre_ip = jnp.maximum(pre_ip, jnp.minimum(first, last_pre))
        idx = jnp.where(ip < P_pre, pre_ip, P_pre + chunk_cb(qb, ip))
        return (bs[0] + wt[b, idx], 0, 0, 0)

    q_spec = pl.BlockSpec(
        (1, 1, K * QG8, H), lambda b, qb, ip, *_: (b, qb, 0, 0)
    )
    kv_spec = pl.BlockSpec((1, K, psz, H), kv_index)
    new_spec = pl.BlockSpec(
        (1, 1, K, psz, H),
        lambda b, qb, ip, *_: (b, chunk_cb(qb, ip), 0, 0, 0),
    )
    in_specs = [q_spec, kv_spec, kv_spec]
    args = [qg, k_pool, v_pool]
    if quant:
        sw = k_scale.shape[-1]
        sc_spec = pl.BlockSpec(
            (1, K, sw),
            lambda b, qb, ip, wt, bs, st, ln: kv_index(
                b, qb, ip, wt, bs, st, ln)[:3],
        )
        in_specs += [sc_spec, sc_spec]
        args += [k_scale, v_scale]
    in_specs += [new_spec, new_spec]
    args += [kn, vn]
    out_specs = [q_spec, kv_spec, kv_spec]
    out_shape = [
        jax.ShapeDtypeStruct((B, NC, K * QG8, H), q.dtype),
        jax.ShapeDtypeStruct(k_pool.shape, k_pool.dtype),
        jax.ShapeDtypeStruct(v_pool.shape, v_pool.dtype),
    ]
    # Operand order: 4 scalar-prefetch args, then q, pools, [scales,]
    # kn, vn. The pools (and scale pools) alias outputs 1.. so the fused
    # write is in place.
    if quant:
        out_specs += [sc_spec, sc_spec]
        out_shape += [
            jax.ShapeDtypeStruct(k_scale.shape, k_scale.dtype),
            jax.ShapeDtypeStruct(v_scale.shape, v_scale.dtype),
        ]
        aliases = {5 + i: 1 + i for i in range(4)}
    else:
        aliases = {5: 1, 6: 2}

    grid_spec = pltpu.PrefetchScalarGridSpec(
        num_scalar_prefetch=4,
        grid=(B, NC, P_pre + NC),
        in_specs=in_specs,
        out_specs=out_specs,
        scratch_shapes=[
            pltpu.VMEM((K * QG8, LANES), jnp.float32),
            pltpu.VMEM((K * QG8, LANES), jnp.float32),
            pltpu.VMEM((K * QG8, H), jnp.float32),
        ],
    )
    out = pl.pallas_call(
        functools.partial(
            _kernel, softcap, psz, K, G, P_pre, NC, QG8, window, quant,
        ),
        grid_spec=grid_spec,
        out_shape=out_shape,
        input_output_aliases=aliases,
        interpret=resolve_interpret(interpret),
    )(
        walk.astype(jnp.int32), base, start.astype(jnp.int32),
        lens.astype(jnp.int32), *args,
    )
    attn = out[0].reshape(B, NC, K, QG8, H)[:, :, :, :QG, :]
    attn = attn.reshape(B, NC, K, psz, G, H).transpose(0, 1, 3, 2, 4, 5)
    attn = attn.reshape(B, S, N, H)
    return (attn, *out[1:])


def paged_flash_prefill(
    q: jax.Array,            # [B, S_pad, N, H] the chunk's queries
    k_pool: jax.Array,       # [L*num_pages, K, psz, H] flat pool
    v_pool: jax.Array,       # [L*num_pages, K, psz, H]
    walk: jax.Array,         # [B, P_pre + S_pad//psz] int32 page walk:
    #                          prefix pages ++ the chunk's own pages
    start: jax.Array,        # [B] int32 page-aligned cursor (prefix len)
    lens: jax.Array,         # [B] int32 real new tokens per row
    k_new: jax.Array,        # [B, S_pad, K, H] chunk K/V (raw dtype)
    v_new: jax.Array,
    *,
    n_prefix_pages: int,
    layer_base: Union[jax.Array, int] = 0,
    logit_softcap: Optional[float] = None,
    window: Optional[int] = None,
    interpret: Optional[bool] = None,
    k_scale: Optional[jax.Array] = None,
    v_scale: Optional[jax.Array] = None,
    mesh: Optional[jax.sharding.Mesh] = None,
    tp_axis: str = "tp",
):
    """Chunk-of-S_pad-queries prefill attention over the paged pool, the
    chunk's own pages written in place (aliased).

    Row b resumes at page-aligned ``start[b]``: query j (absolute
    position ``start[b] + j``) attends the row's whole paged history
    (walk steps < n_prefix_pages) plus the chunk's earlier positions,
    under the optional sliding window and logit softcap. Returns
    ``(out [B, S_pad, N, H], k_pool', v_pool'[, k_scale', v_scale'])``.
    Semantics match ``runner._prefill_layer``'s XLA reference: the dense
    prefix gather + flash attention + page scatter collapse into one
    kernel whose HBM traffic is O(real context), not O(padded gather
    copy), and whose VMEM is bounded by the page block, not S.
    """
    assert (k_scale is None) == (v_scale is None)
    if window is not None and window < 1:
        raise ValueError(f"window={window} must be >= 1")
    K = k_pool.shape[1]
    assert q.shape[2] % K == 0, (q.shape, K)
    base = jnp.asarray(layer_base, jnp.int32).reshape(1)

    tp = mesh.shape.get(tp_axis, 1) if mesh is not None else 1
    if tp > 1:
        # Head-sharded serving, the ragged kernel's scheme verbatim: the
        # page walk is head-independent, each device owns K/tp of every
        # page and G = N/K is preserved per shard.
        N = q.shape[2]
        if N % tp or K % tp:
            raise ValueError(
                f"tp-sharded paged-flash prefill needs n_heads ({N}) and "
                f"n_kv_heads ({K}) divisible by {tp_axis}={tp}; lower tp "
                f"or serve with kernels='xla'"
            )
        from jax.sharding import PartitionSpec as P

        qspec = P(None, None, tp_axis, None)
        poolspec = P(None, tp_axis, None, None)
        rep2, rep1 = P(None, None), P(None)
        args = [q, k_pool, v_pool, walk, start, lens, base, k_new, v_new]
        in_specs = [
            qspec, poolspec, poolspec, rep2, rep1, rep1, rep1, qspec,
            qspec,
        ]
        out_specs = [qspec, poolspec, poolspec]
        have_scale = k_scale is not None
        if have_scale:
            scspec = P(None, tp_axis, None)
            args += [k_scale, v_scale]
            in_specs += [scspec, scspec]
            out_specs += [scspec, scspec]

        def body(q_, kp_, vp_, wt_, st_, ln_, base_, kn_, vn_, *rest):
            ks = vs = None
            if have_scale:
                ks, vs = rest[0], rest[1]
            return _call(
                q_, kp_, vp_, wt_, st_, ln_, base_, kn_, vn_,
                n_prefix_pages, logit_softcap, window, interpret, ks, vs,
            )

        mapped = jax.shard_map(
            body, mesh=mesh, in_specs=tuple(in_specs),
            out_specs=tuple(out_specs), check_vma=False,
        )
        return tuple(mapped(*args))

    return _call(
        q, k_pool, v_pool, walk, start, lens, base, k_new, v_new,
        n_prefix_pages, logit_softcap, window, interpret, k_scale, v_scale,
    )
