"""Fused rotary embedding as a Pallas TPU kernel (reference fused RoPE).

One VMEM pass per (batch, seq-block): computes the f32 angle tables from the
integer positions in-kernel (no host-side cos/sin materialization in HBM) and
applies the Llama rotate-half convention to all heads of the block.

The rotation is linear and orthogonal in x, so the VJP is the same kernel
with the angle sign flipped: dx = rope(g, -theta-angles).
"""

from __future__ import annotations

import functools
from typing import Optional

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl

from orion_tpu.ops.pallas.common import pad_axis, resolve_interpret, round_up


def _rope_kernel(theta, flip, x_ref, pos_ref, o_ref):
    # x_ref: [1, bs, N, H]; pos_ref: [1, 1, bs] (3D for TPU tiling)
    H = x_ref.shape[-1]
    half = H // 2
    x = x_ref[0].astype(jnp.float32)                      # [bs, N, H]
    pos = pos_ref[0, 0, :].astype(jnp.float32)            # [bs]
    expo = (
        jax.lax.broadcasted_iota(jnp.int32, (1, half), 1).astype(jnp.float32)
        / half
    )
    freq = jnp.exp(-jnp.log(theta) * expo)                # [1, half]
    angles = pos[:, None] * freq                          # [bs, half]
    cos = jnp.cos(angles)[:, None, :]                     # [bs, 1, half]
    sin = jnp.sin(angles)[:, None, :]
    if flip:
        sin = -sin
    x1 = x[..., :half]
    x2 = x[..., half:]
    o_ref[0] = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    ).astype(o_ref.dtype)


def _rope_call(theta, flip, block_seq, interpret, x, positions):
    B, S, N, H = x.shape
    bs = min(block_seq, round_up(S, 8))
    Sp = round_up(S, bs)
    xp = pad_axis(x, 1, Sp)
    pp = pad_axis(positions, 1, Sp)[:, None, :]  # (B, 1, Sp): TPU tiling
    out = pl.pallas_call(
        functools.partial(_rope_kernel, theta, flip),
        grid=(B, Sp // bs),
        in_specs=[
            pl.BlockSpec((1, bs, N, H), lambda b, i: (b, i, 0, 0)),
            pl.BlockSpec((1, 1, bs), lambda b, i: (b, 0, i)),
        ],
        out_specs=pl.BlockSpec((1, bs, N, H), lambda b, i: (b, i, 0, 0)),
        out_shape=jax.ShapeDtypeStruct(xp.shape, x.dtype),
        interpret=interpret,
    )(xp, pp)
    return out[:, :S]


@functools.partial(jax.custom_vjp, nondiff_argnums=(0, 1, 2))
def _rope(theta, block_seq, interpret, x, positions):
    return _rope_call(theta, False, block_seq, interpret, x, positions)


def _rope_fwd(theta, block_seq, interpret, x, positions):
    return _rope(theta, block_seq, interpret, x, positions), positions


def _rope_bwd(theta, block_seq, interpret, positions, g):
    return _rope_call(theta, True, block_seq, interpret, g, positions), None


_rope.defvjp(_rope_fwd, _rope_bwd)


def rope_pallas(
    x: jax.Array,
    positions: jax.Array,
    *,
    theta: float = 500_000.0,
    block_seq: int = 256,
    interpret: Optional[bool] = None,
) -> jax.Array:
    """Apply rotary embedding; x [B, S, N, H], positions [B, S] or [S]."""
    if positions.ndim == 1:
        positions = jnp.broadcast_to(positions[None, :], x.shape[:2])
    return _rope(
        float(theta), block_seq, resolve_interpret(interpret), x, positions.astype(jnp.int32)
    )
