"""Pallas TPU kernels — the L0 native-op layer (reference ``orion.ops``).

The reference's fused CUDA kernels (attention / RoPE / RMSNorm,
BASELINE.json:5) map to these Mosaic-lowered Pallas kernels. Each has an
interpret mode so the identical kernel code runs on the fake-CPU-device test
mesh (SURVEY.md §5) and is parity-tested against the jnp/XLA reference ops.
"""

from orion_tpu.ops.pallas.flash_attention import flash_attention
from orion_tpu.ops.pallas.norms import rmsnorm_pallas
from orion_tpu.ops.pallas.rope import rope_pallas

__all__ = ["flash_attention", "rmsnorm_pallas", "rope_pallas"]
