"""RMSNorm / LayerNorm (reference ``orion.ops`` fused-norm equivalents).

The xla implementations compute in float32 regardless of input dtype (the
bf16-safe convention) and cast back. Pallas fused variants are registered by
``orion_tpu.ops.pallas.norms`` under impl="pallas".
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp


def _rmsnorm_xla(x: jax.Array, scale: jax.Array, eps: float) -> jax.Array:
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    var = jnp.mean(jnp.square(xf), axis=-1, keepdims=True)
    y = xf * jax.lax.rsqrt(var + eps)
    # Llama convention: scale applied after the cast-critical normalization,
    # with (1 + 0) style plain multiplicative weight.
    return (y * scale.astype(jnp.float32)).astype(dtype)


def _layernorm_xla(
    x: jax.Array, scale: jax.Array, bias: Optional[jax.Array], eps: float
) -> jax.Array:
    dtype = x.dtype
    xf = x.astype(jnp.float32)
    mean = jnp.mean(xf, axis=-1, keepdims=True)
    var = jnp.mean(jnp.square(xf - mean), axis=-1, keepdims=True)
    y = (xf - mean) * jax.lax.rsqrt(var + eps)
    y = y * scale.astype(jnp.float32)
    if bias is not None:
        y = y + bias.astype(jnp.float32)
    return y.astype(dtype)


def rmsnorm(
    x: jax.Array,
    scale: jax.Array,
    *,
    eps: float = 1e-5,
    impl: str = "xla",
) -> jax.Array:
    """Root-mean-square normalization over the last axis."""
    from orion_tpu.ops._dispatch import resolve_impl

    use_pallas, interpret = resolve_impl(impl)
    if use_pallas:
        from orion_tpu.ops.pallas.norms import rmsnorm_pallas

        return rmsnorm_pallas(x, scale, eps=eps, interpret=interpret)
    return _rmsnorm_xla(x, scale, eps)


def layernorm(
    x: jax.Array,
    scale: jax.Array,
    bias: Optional[jax.Array] = None,
    *,
    eps: float = 1e-5,
    impl: str = "xla",
) -> jax.Array:
    """LayerNorm over the last axis (GPT-2 family)."""
    # LayerNorm is not a hot op in the judged configs; xla only.
    return _layernorm_xla(x, scale, bias, eps)
