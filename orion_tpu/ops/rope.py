"""Rotary position embeddings (reference ``orion.ops`` fused-RoPE equivalent).

Llama rotate-half convention: the head dim is split in two halves, rotated by
position-dependent angles with base ``theta``. Frequencies are computed once
in float32; application casts back to the activation dtype.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp


def rope_frequencies(
    head_dim: int, positions: jax.Array, theta: float
) -> tuple[jax.Array, jax.Array]:
    """cos/sin tables for given integer positions.

    positions: [...,] int array (any shape, typically [B, S] or [S]).
    Returns (cos, sin), each [..., head_dim // 2], float32.
    """
    half = head_dim // 2
    freq = 1.0 / (
        theta ** (jnp.arange(0, half, dtype=jnp.float32) / half)
    )
    angles = positions.astype(jnp.float32)[..., None] * freq  # [..., half]
    return jnp.cos(angles), jnp.sin(angles)


def apply_rope(
    x: jax.Array,
    positions: jax.Array,
    *,
    theta: float = 500_000.0,
    impl: str = "xla",
) -> jax.Array:
    """Apply rotary embedding to q or k.

    x: [B, S, N, H]; positions: [B, S] (or [S], broadcast over batch).
    """
    from orion_tpu.ops._dispatch import resolve_impl

    use_pallas, interpret = resolve_impl(impl)
    if use_pallas:
        from orion_tpu.ops.pallas.rope import rope_pallas

        return rope_pallas(x, positions, theta=theta, interpret=interpret)
    return _rope_xla(x, positions, theta)


def _rope_xla(x: jax.Array, positions: jax.Array, theta: float) -> jax.Array:
    dtype = x.dtype
    head_dim = x.shape[-1]
    if positions.ndim == 1:
        positions = positions[None, :]
    cos, sin = rope_frequencies(head_dim, positions, theta)  # [B, S, half]
    cos = cos[:, :, None, :]  # broadcast over heads
    sin = sin[:, :, None, :]
    xf = x.astype(jnp.float32)
    x1, x2 = jnp.split(xf, 2, axis=-1)
    out = jnp.concatenate(
        [x1 * cos - x2 * sin, x2 * cos + x1 * sin], axis=-1
    )
    return out.astype(dtype)
