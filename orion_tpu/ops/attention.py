"""Multi-head attention (reference ``orion.ops`` fused-attention equivalent).

The xla implementation is the semantic reference: grouped-query causal
attention with a numerically stable float32 softmax, optional segment masking
(packed sequences) and logit soft-capping. The Pallas flash kernel
(orion_tpu.ops.pallas.flash_attention) implements the same contract with
blockwise online softmax; both are exercised against each other in tests.
"""

from __future__ import annotations

from typing import Optional

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def _gqa_expand(k: jax.Array, n_heads: int) -> jax.Array:
    """[B, S, K, H] -> [B, S, N, H] by repeating each kv head N/K times."""
    n_kv = k.shape[2]
    if n_kv == n_heads:
        return k
    assert n_heads % n_kv == 0, (n_heads, n_kv)
    return jnp.repeat(k, n_heads // n_kv, axis=2)


def attention_mask(
    q_len: int,
    kv_len: int,
    *,
    causal: bool = True,
    q_offset: int = 0,
    q_segment_ids: Optional[jax.Array] = None,
    kv_segment_ids: Optional[jax.Array] = None,
    q_positions: Optional[jax.Array] = None,
    kv_positions: Optional[jax.Array] = None,
    window: Optional[int] = None,
) -> Optional[jax.Array]:
    """Boolean [.., q_len, kv_len] mask; True = attend.

    ``window`` (sliding-window / Mistral-family) keeps only the last
    ``window`` positions: 0 <= q_pos - kv_pos < window. Positions default
    to token index (+ q_offset for q); explicit per-token positions
    ([.., q_len] / [.., kv_len]) serve packed/permuted layouts.
    """
    if window is not None and (not causal or window < 1):
        raise ValueError(
            f"window={window} requires causal attention and window >= 1"
        )
    mask = None
    if causal:
        q_pos = (
            q_positions
            if q_positions is not None
            else jnp.arange(q_len) + q_offset
        )
        kv_pos = (
            kv_positions if kv_positions is not None else jnp.arange(kv_len)
        )
        dist = q_pos[..., :, None] - kv_pos[..., None, :]
        mask = dist >= 0
        if window is not None:
            mask &= dist < window
    if q_segment_ids is not None:
        seg = q_segment_ids[..., :, None] == kv_segment_ids[..., None, :]
        mask = seg if mask is None else (mask & seg)
    return mask


def attention_xla(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    mask: Optional[jax.Array] = None,
    q_segment_ids: Optional[jax.Array] = None,
    kv_segment_ids: Optional[jax.Array] = None,
    logit_softcap: Optional[float] = None,
    q_offset: int = 0,
    q_positions: Optional[jax.Array] = None,
    kv_positions: Optional[jax.Array] = None,
    window: Optional[int] = None,
) -> jax.Array:
    """q: [B, Sq, N, H]; k, v: [B, Skv, K, H] with N % K == 0 -> [B, Sq, N, H]."""
    dtype = q.dtype
    n_heads, head_dim = q.shape[2], q.shape[3]
    k = _gqa_expand(k, n_heads)
    v = _gqa_expand(v, n_heads)

    if mask is not None and window is not None:
        raise ValueError(
            "window cannot combine with an explicit mask (it would be "
            "silently ignored); fold the window into the mask or drop it"
        )
    scale = head_dim ** -0.5
    logits = jnp.einsum(
        "bqnh,bknh->bnqk", q, k, preferred_element_type=jnp.float32
    ) * scale
    if logit_softcap is not None:
        logits = logit_softcap * jnp.tanh(logits / logit_softcap)

    if mask is None:
        mask = attention_mask(
            q.shape[1],
            k.shape[1],
            causal=causal,
            q_offset=q_offset,
            q_segment_ids=q_segment_ids,
            kv_segment_ids=kv_segment_ids,
            q_positions=q_positions,
            kv_positions=kv_positions,
            window=window,
        )
    if mask is not None:
        if mask.ndim == 2:
            mask = mask[None, None, :, :]
        elif mask.ndim == 3:  # [B, q, kv]
            mask = mask[:, None, :, :]
        logits = jnp.where(mask, logits, NEG_INF)

    probs = jax.nn.softmax(logits, axis=-1).astype(dtype)
    return jnp.einsum("bnqk,bknh->bqnh", probs, v)


def attention(
    q: jax.Array,
    k: jax.Array,
    v: jax.Array,
    *,
    causal: bool = True,
    mask: Optional[jax.Array] = None,
    q_segment_ids: Optional[jax.Array] = None,
    kv_segment_ids: Optional[jax.Array] = None,
    logit_softcap: Optional[float] = None,
    q_offset: int = 0,
    q_positions: Optional[jax.Array] = None,
    kv_positions: Optional[jax.Array] = None,
    window: Optional[int] = None,
    block_q: Optional[int] = None,
    block_kv: Optional[int] = None,
    impl: str = "xla",
    seg_pad_zero: bool = False,
    mesh: Optional[jax.sharding.Mesh] = None,
    tp_axis: str = "tp",
) -> jax.Array:
    """Grouped-query scaled-dot-product attention. Shapes as attention_xla.

    ``seg_pad_zero`` declares segment id 0 = padding so the flash kernel
    may SKIP all-padding blocks (ragged prefill / packed tails); results
    are unchanged for callers honoring the pack_rows convention, and the
    xla path ignores it (no block structure to skip).

    ``mesh`` (with a ``tp_axis`` of size > 1) runs the flash kernel under a
    ``shard_map`` that splits the HEAD axes over tensor parallelism: a bare
    ``pallas_call`` is opaque to XLA's SPMD partitioner, so jitting it over
    tp-sharded q/k/v would otherwise gather full-size operands onto every
    device (serving an 8B+ model sharded, SURVEY.md §4 stack B, needs the
    kernel to stay sharded). The xla path ignores ``mesh`` — einsums
    partition natively from the operands' shardings.
    """
    from orion_tpu.ops._dispatch import resolve_impl

    use_pallas, interpret = resolve_impl(impl)
    if use_pallas:
        if mask is not None:
            raise ValueError(
                "explicit `mask` is only supported by impl='xla'; express the "
                "mask via causal/q_segment_ids for the flash kernel"
            )
        from orion_tpu.ops.pallas.flash_attention import flash_attention

        kernel_kw = dict(
            causal=causal,
            logit_softcap=logit_softcap,
            q_offset=q_offset,
            window=window,
            block_q=block_q,
            block_kv=block_kv,
            interpret=interpret,
            seg_pad_zero=seg_pad_zero,
        )
        tp = mesh.shape.get(tp_axis, 1) if mesh is not None else 1
        if tp > 1:
            from jax.sharding import PartitionSpec as P

            n_heads, n_kv = q.shape[2], k.shape[2]
            if n_heads % tp or n_kv % tp:
                raise ValueError(
                    f"tp-sharded flash attention needs n_heads ({n_heads}) "
                    f"and n_kv_heads ({n_kv}) divisible by {tp_axis}={tp}; "
                    f"lower tp or use impl='xla'"
                )
            # Heads shard; batch/seq operands (segments, positions)
            # replicate. Optional operands join the arg list only when
            # present so the shard_map signature stays positional.
            hspec = P(None, None, tp_axis, None)
            sspec = P(None, None)  # segments are [B, S] (kernel contract)
            opt = [
                ("q_segment_ids", q_segment_ids, sspec),
                ("kv_segment_ids", kv_segment_ids, sspec),
                ("q_positions", q_positions,
                 P(*([None] * (q_positions.ndim if q_positions is not None
                               else 1)))),
                ("kv_positions", kv_positions,
                 P(*([None] * (kv_positions.ndim if kv_positions is not None
                               else 1)))),
            ]
            names = [n for n, a, _ in opt if a is not None]
            extras = [a for _, a, _ in opt if a is not None]
            especs = [s for _, a, s in opt if a is not None]

            def body(q_, k_, v_, *rest):
                kw = dict(zip(names, rest))
                return flash_attention(q_, k_, v_, **kernel_kw, **kw)

            mapped = jax.shard_map(
                body,
                mesh=mesh,
                in_specs=(hspec, hspec, hspec, *especs),
                out_specs=hspec,
                check_vma=False,
            )
            return mapped(q, k, v, *extras)

        return flash_attention(
            q,
            k,
            v,
            q_segment_ids=q_segment_ids,
            kv_segment_ids=kv_segment_ids,
            q_positions=q_positions,
            kv_positions=kv_positions,
            **kernel_kw,
        )
    return attention_xla(
        q,
        k,
        v,
        causal=causal,
        mask=mask,
        q_segment_ids=q_segment_ids,
        kv_segment_ids=kv_segment_ids,
        logit_softcap=logit_softcap,
        q_offset=q_offset,
        q_positions=q_positions,
        kv_positions=kv_positions,
        window=window,
    )
