"""Fused-op surface: attention, RoPE, RMSNorm/LayerNorm.

TPU-native replacement for the reference's ``orion.ops`` CUDA kernels
(BASELINE.json:5 — "fused attention/RoPE/RMSNorm CUDA kernels ... become
Pallas"). Every op has two implementations behind one interface:

  - ``xla``    — pure jnp; XLA fuses the elementwise work. The reference
                 semantics, the CPU/test path, and the fallback.
  - ``pallas`` — hand-written TPU kernels (orion_tpu.ops.pallas.*) for the
                 hot ops where manual fusion/blocking beats XLA.

Selection is by ``ModelConfig.kernels`` or per-call ``impl=``.
"""

from orion_tpu.ops.norms import layernorm, rmsnorm
from orion_tpu.ops.rope import apply_rope, rope_frequencies
from orion_tpu.ops.attention import attention

__all__ = [
    "attention",
    "apply_rope",
    "layernorm",
    "rmsnorm",
    "rope_frequencies",
]
