"""Kernel-implementation dispatch shared by the op wrappers.

``ModelConfig.kernels`` selects the op backend:
  - "xla"              — pure-jnp reference path (CPU/test default)
  - "pallas"           — compiled Pallas TPU kernels
  - "pallas_interpret" — same kernels through the Pallas interpreter (for
                         the fake-CPU-device test mesh, SURVEY.md §5)
"""

from __future__ import annotations

from typing import Optional

_VALID = ("xla", "pallas", "pallas_interpret")


def resolve_impl(impl: str) -> tuple[bool, Optional[bool]]:
    """-> (use_pallas, interpret); interpret=None means autodetect."""
    if impl not in _VALID:
        raise ValueError(f"unknown kernel impl {impl!r}; expected one of {_VALID}")
    if impl == "xla":
        return False, None
    return True, (True if impl == "pallas_interpret" else None)
