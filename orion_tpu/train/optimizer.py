"""AdamW with explicit, shardable state.

The optimizer state is a plain pytree ``{"mu": <like params>, "nu": <like
params>, "count": scalar}`` rather than an opaque optax chain state, so the
ZeRO-3 story is one line: moments inherit the parameters' NamedShardings
(SURVEY.md §3 FSDP row — params+grads+opt state all sharded). Schedules come
from optax (pure functions, no state).
"""

from __future__ import annotations

from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax

from orion_tpu.config import OptimizerConfig

OptState = dict[str, Any]

# Parameter leaves exempt from weight decay: norm scales and all biases.
_NO_DECAY_KEYS = frozenset(
    {"scale", "bias", "bq", "bk", "bv", "bo", "b_in", "b_out"}
)


def make_schedule(
    cfg: OptimizerConfig, num_steps: int
) -> Callable[[jax.Array], jax.Array]:
    decay_steps = cfg.decay_steps if cfg.decay_steps is not None else num_steps
    # Keep schedules well-formed when num_steps < warmup (smoke tests).
    decay_steps = max(decay_steps, cfg.warmup_steps + 1)
    peak, floor = cfg.learning_rate, cfg.learning_rate * cfg.min_lr_ratio
    if cfg.schedule == "constant":
        warm = optax.linear_schedule(0.0, peak, cfg.warmup_steps)
        return optax.join_schedules(
            [warm, optax.constant_schedule(peak)], [cfg.warmup_steps]
        )
    if cfg.schedule == "linear":
        warm = optax.linear_schedule(0.0, peak, cfg.warmup_steps)
        decay = optax.linear_schedule(
            peak, floor, max(decay_steps - cfg.warmup_steps, 1)
        )
        return optax.join_schedules([warm, decay], [cfg.warmup_steps])
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=peak,
        warmup_steps=cfg.warmup_steps,
        decay_steps=decay_steps,
        end_value=floor,
    )


def init_opt_state(params: Any, cfg: OptimizerConfig) -> OptState:
    mdt = jnp.dtype(cfg.moment_dtype)

    def zeros(p):
        return jnp.zeros(p.shape, mdt)

    return {
        "mu": jax.tree.map(zeros, params),
        "nu": jax.tree.map(zeros, params),
        "count": jnp.zeros((), jnp.int32),
    }


def _decay_mask(path) -> bool:
    last = path[-1]
    key = getattr(last, "key", None)
    return key not in _NO_DECAY_KEYS


def global_norm(tree: Any) -> jax.Array:
    leaves = jax.tree.leaves(tree)
    return jnp.sqrt(
        sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)
    )


def tree_all_finite(tree: Any) -> jax.Array:
    """Scalar bool: every element of every floating leaf is finite.

    The gradient anomaly guard's device-side check (train.anomaly_guard):
    ONE fused reduction over the grad tree, cheap next to the backward
    pass it follows. Non-floating leaves (step counters) are skipped —
    integers are always finite and isfinite rejects them.
    """
    ok = jnp.bool_(True)
    for leaf in jax.tree.leaves(tree):
        if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating):
            ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(leaf)))
    return ok


def apply_updates(
    params: Any,
    grads: Any,
    opt_state: OptState,
    cfg: OptimizerConfig,
    learning_rate: jax.Array,
    gnorm: Optional[jax.Array] = None,
) -> tuple[Any, OptState, dict[str, jax.Array]]:
    """One optimizer update. Returns (params, opt_state, metrics).

    ``gnorm`` lets a caller that already computed the global grad norm
    (the anomaly guard) share it instead of paying the reduction twice.
    """
    if cfg.name not in ("adamw", "sgd"):
        raise ValueError(f"unknown optimizer {cfg.name!r}")
    if gnorm is None:
        gnorm = global_norm(grads)
    if cfg.grad_clip_norm > 0:
        scale = jnp.minimum(1.0, cfg.grad_clip_norm / (gnorm + 1e-9))
    else:
        scale = jnp.ones((), jnp.float32)

    count = opt_state["count"] + 1
    cf = count.astype(jnp.float32)
    bc1 = 1.0 - cfg.b1 ** cf
    bc2 = 1.0 - cfg.b2 ** cf
    mdt = jnp.dtype(cfg.moment_dtype)

    def upd(path, p, g, mu, nu):
        g = g.astype(jnp.float32) * scale
        if cfg.name == "sgd":
            # Momentum SGD: mu is the velocity; nu rides along unused so
            # the state tree (and its shardings / checkpoints) is the same
            # shape for every optimizer family.
            mu_f = cfg.b1 * mu.astype(jnp.float32) + g
            step = mu_f
            nu_f = nu.astype(jnp.float32)
        else:
            mu_f = cfg.b1 * mu.astype(jnp.float32) + (1 - cfg.b1) * g
            nu_f = (
                cfg.b2 * nu.astype(jnp.float32)
                + (1 - cfg.b2) * jnp.square(g)
            )
            step = (mu_f / bc1) / (jnp.sqrt(nu_f / bc2) + cfg.eps)
        if cfg.weight_decay > 0 and _decay_mask(path):
            step = step + cfg.weight_decay * p.astype(jnp.float32)
        new_p = p.astype(jnp.float32) - learning_rate * step
        return new_p.astype(p.dtype), mu_f.astype(mdt), nu_f.astype(mdt)

    flat = jax.tree_util.tree_map_with_path(
        lambda path, p, g, mu, nu: upd(path, p, g, mu, nu),
        params, grads, opt_state["mu"], opt_state["nu"],
    )
    # Unzip the 3-tuples back into three trees.
    is_triple = lambda x: isinstance(x, tuple) and len(x) == 3 and not isinstance(x[0], tuple)
    new_params = jax.tree.map(lambda t: t[0], flat, is_leaf=is_triple)
    new_mu = jax.tree.map(lambda t: t[1], flat, is_leaf=is_triple)
    new_nu = jax.tree.map(lambda t: t[2], flat, is_leaf=is_triple)

    new_state = {"mu": new_mu, "nu": new_nu, "count": count}
    return new_params, new_state, {"grad_norm": gnorm}
