"""AdamW with explicit, shardable state.

The optimizer state is a plain pytree ``{"mu": <like params>, "nu": <like
params>, "count": scalar}`` rather than an opaque optax chain state, so the
ZeRO-3 story is one line: moments inherit the parameters' NamedShardings
(SURVEY.md §3 FSDP row — params+grads+opt state all sharded). Schedules come
from optax (pure functions, no state).

ZeRO-1 (``train.zero1``; PAPERS.md 2004.13336) rides the same tree: a
:class:`Zero1Plan` tells :func:`apply_updates` to run the weight update on
each replica's 1/dp shard of the state. Two formulations share the math:

  - the **auto** path (``plan.quantize is None``) expresses the sharding as
    ``with_sharding_constraint`` inside the jit train step — XLA's SPMD
    partitioner emits the gradient reduce-scatter and the updated-param
    all-gather itself, and the result is bitwise-equal to the unsharded
    baseline (the clip norm is pinned to the baseline's replicated layout);
  - the **manual** path (any int8 leg) must be called inside ``shard_map``
    over ``plan.axis`` with per-replica PARTIAL gradients: the two wire
    legs run explicitly through ``comm.quantized_reduce_scatter`` /
    ``quantized_all_gather`` so the DCN exchange is blockwise int8.

With ``model.param_dtype != model.dtype`` the optimizer state additionally
carries a dp-sharded f32 ``master`` copy (``init_opt_state(master=True)``)
and ``state["params"]`` holds only the cast-down working copy the forward
reads — the all-gather leg then moves the narrow dtype.
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import optax
from jax import lax

from orion_tpu.config import OptimizerConfig

OptState = dict[str, Any]


@dataclasses.dataclass(frozen=True)
class Zero1Plan:
    """How the weight update shards across the data-parallel axis.

    ``dims`` is a pytree (mirroring params) of the per-leaf update-shard
    dim (-1 = replicated), ``state_shardings``/``param_shardings`` the
    dp-sharded master/moment layouts and the baseline layouts params
    return to (``parallel.sharding.zero1_shardings``). ``quantize`` picks
    the wire format per collective leg: None (both fp32, the bitwise
    constraint path), "int8" (both legs), "rs_int8"/"ag_int8" (one leg).
    """

    axis: str
    dims: Any
    state_shardings: Any
    param_shardings: Any
    quantize: Optional[str] = None
    block: int = 256

    @property
    def manual(self) -> bool:
        return self.quantize is not None

    @property
    def rs_int8(self) -> bool:
        return self.quantize in ("int8", "rs_int8")

    @property
    def ag_int8(self) -> bool:
        return self.quantize in ("int8", "ag_int8")

# Parameter leaves exempt from weight decay: norm scales and all biases.
_NO_DECAY_KEYS = frozenset(
    {"scale", "bias", "bq", "bk", "bv", "bo", "b_in", "b_out"}
)


def make_schedule(
    cfg: OptimizerConfig, num_steps: int
) -> Callable[[jax.Array], jax.Array]:
    decay_steps = cfg.decay_steps if cfg.decay_steps is not None else num_steps
    # Keep schedules well-formed when num_steps < warmup (smoke tests).
    decay_steps = max(decay_steps, cfg.warmup_steps + 1)
    peak, floor = cfg.learning_rate, cfg.learning_rate * cfg.min_lr_ratio
    if cfg.schedule == "constant":
        warm = optax.linear_schedule(0.0, peak, cfg.warmup_steps)
        return optax.join_schedules(
            [warm, optax.constant_schedule(peak)], [cfg.warmup_steps]
        )
    if cfg.schedule == "linear":
        warm = optax.linear_schedule(0.0, peak, cfg.warmup_steps)
        decay = optax.linear_schedule(
            peak, floor, max(decay_steps - cfg.warmup_steps, 1)
        )
        return optax.join_schedules([warm, decay], [cfg.warmup_steps])
    return optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=peak,
        warmup_steps=cfg.warmup_steps,
        decay_steps=decay_steps,
        end_value=floor,
    )


def init_opt_state(
    params: Any, cfg: OptimizerConfig, *, master: bool = False
) -> OptState:
    """Fresh optimizer state for ``params``. With ``master`` (the ZeRO-1
    mixed-precision split, ``train.zero1`` when param_dtype != dtype) the
    state additionally carries the full-precision master copy — ``params``
    must still be in param_dtype here; the trainer casts the working copy
    down afterwards."""
    mdt = jnp.dtype(cfg.moment_dtype)

    def zeros(p):
        return jnp.zeros(p.shape, mdt)

    state = {
        "mu": jax.tree.map(zeros, params),
        "nu": jax.tree.map(zeros, params),
        "count": jnp.zeros((), jnp.int32),
    }
    if master:
        state["master"] = jax.tree.map(lambda p: p, params)
    return state


def _decay_mask(path) -> bool:
    last = path[-1]
    key = getattr(last, "key", None)
    return key not in _NO_DECAY_KEYS


def global_norm(tree: Any) -> jax.Array:
    leaves = jax.tree.leaves(tree)
    return jnp.sqrt(
        sum(jnp.sum(jnp.square(l.astype(jnp.float32))) for l in leaves)
    )


def tree_all_finite(tree: Any) -> jax.Array:
    """Scalar bool: every element of every floating leaf is finite.

    The gradient anomaly guard's device-side check (train.anomaly_guard):
    ONE fused reduction over the grad tree, cheap next to the backward
    pass it follows. Non-floating leaves (step counters) are skipped —
    integers are always finite and isfinite rejects them.
    """
    ok = jnp.bool_(True)
    for leaf in jax.tree.leaves(tree):
        if jnp.issubdtype(jnp.asarray(leaf).dtype, jnp.floating):
            ok = jnp.logical_and(ok, jnp.all(jnp.isfinite(leaf)))
    return ok


def _make_leaf_update(
    cfg: OptimizerConfig,
    learning_rate: jax.Array,
    scale: jax.Array,
    count: jax.Array,
):
    """The per-leaf AdamW/SGD math, shared by every apply_updates branch
    (replicated, ZeRO-1 auto-sharded, ZeRO-1 manual). ``p`` must be the
    update SOURCE (the master leaf under a mixed-precision split); the
    returned new value keeps p's dtype."""
    cf = count.astype(jnp.float32)
    bc1 = 1.0 - cfg.b1 ** cf
    bc2 = 1.0 - cfg.b2 ** cf
    mdt = jnp.dtype(cfg.moment_dtype)

    def upd(path, p, g, mu, nu):
        g = g.astype(jnp.float32) * scale
        if cfg.name == "sgd":
            # Momentum SGD: mu is the velocity; nu rides along unused so
            # the state tree (and its shardings / checkpoints) is the same
            # shape for every optimizer family.
            mu_f = cfg.b1 * mu.astype(jnp.float32) + g
            step = mu_f
            nu_f = nu.astype(jnp.float32)
        else:
            mu_f = cfg.b1 * mu.astype(jnp.float32) + (1 - cfg.b1) * g
            nu_f = (
                cfg.b2 * nu.astype(jnp.float32)
                + (1 - cfg.b2) * jnp.square(g)
            )
            step = (mu_f / bc1) / (jnp.sqrt(nu_f / bc2) + cfg.eps)
        if cfg.weight_decay > 0 and _decay_mask(path):
            step = step + cfg.weight_decay * p.astype(jnp.float32)
        new_p = p.astype(jnp.float32) - learning_rate * step
        return new_p.astype(p.dtype), mu_f.astype(mdt), nu_f.astype(mdt)

    return upd


def _clip_scale(cfg: OptimizerConfig, gnorm: jax.Array) -> jax.Array:
    if cfg.grad_clip_norm > 0:
        return jnp.minimum(1.0, cfg.grad_clip_norm / (gnorm + 1e-9))
    return jnp.ones((), jnp.float32)


_IS_TRIPLE = lambda x: (
    isinstance(x, tuple) and len(x) == 3 and not isinstance(x[0], tuple)
)


def _unzip3(flat: Any) -> tuple[Any, Any, Any]:
    """Unzip a tree of 3-tuples back into three trees."""
    return (
        jax.tree.map(lambda t: t[0], flat, is_leaf=_IS_TRIPLE),
        jax.tree.map(lambda t: t[1], flat, is_leaf=_IS_TRIPLE),
        jax.tree.map(lambda t: t[2], flat, is_leaf=_IS_TRIPLE),
    )


def apply_updates(
    params: Any,
    grads: Any,
    opt_state: OptState,
    cfg: OptimizerConfig,
    learning_rate: jax.Array,
    gnorm: Optional[jax.Array] = None,
    zero1: Optional[Zero1Plan] = None,
) -> tuple[Any, OptState, dict[str, jax.Array]]:
    """One optimizer update. Returns (params, opt_state, metrics).

    ``gnorm`` lets a caller that already computed the global grad norm
    (the anomaly guard) share it instead of paying the reduction twice.
    With a :class:`Zero1Plan` the update runs on each replica's 1/dp
    shard of the master state (see the module docstring); the manual
    (quantized) branch must be called inside ``shard_map`` over
    ``zero1.axis`` with per-replica PARTIAL gradients and ignores any
    passed ``gnorm`` (the norm must come from the reduced shards).
    """
    if cfg.name not in ("adamw", "sgd"):
        raise ValueError(f"unknown optimizer {cfg.name!r}")
    if zero1 is not None and zero1.manual:
        return _apply_updates_manual(
            params, grads, opt_state, cfg, learning_rate, zero1
        )

    wsc = jax.lax.with_sharding_constraint
    if zero1 is not None:
        # Pin the clip norm to the baseline's replicated grad layout:
        # a norm taken over the dp shards would regroup the reduction and
        # break bitwise parity with the unsharded run.
        grads = wsc(grads, zero1.param_shardings)
    if gnorm is None:
        gnorm = global_norm(grads)
    scale = _clip_scale(cfg, gnorm)
    count = opt_state["count"] + 1
    upd = _make_leaf_update(cfg, learning_rate, scale, count)

    master = opt_state.get("master")
    src = master if master is not None else params
    mu, nu = opt_state["mu"], opt_state["nu"]
    if zero1 is not None:
        # The reduce-scatter leg: grads, masters and moments constrained
        # onto the 1/dp update layout — XLA slices the (replicated) grads
        # per shard and every op below runs shard-local.
        src = wsc(src, zero1.state_shardings)
        grads = wsc(grads, zero1.state_shardings)
        mu = wsc(mu, zero1.state_shardings)
        nu = wsc(nu, zero1.state_shardings)

    flat = jax.tree_util.tree_map_with_path(upd, src, grads, mu, nu)
    new_src, new_mu, new_nu = _unzip3(flat)

    new_state = {"mu": new_mu, "nu": new_nu, "count": count}
    if zero1 is not None:
        new_src = wsc(new_src, zero1.state_shardings)
        new_state["mu"] = wsc(new_mu, zero1.state_shardings)
        new_state["nu"] = wsc(new_nu, zero1.state_shardings)
    if master is not None:
        new_state["master"] = new_src
        # The all-gather leg, in the cast-down working dtype: the wire
        # moves model.dtype bytes, not the f32 masters.
        new_params = jax.tree.map(
            lambda m, p: m.astype(p.dtype), new_src, params
        )
    else:
        new_params = new_src
    if zero1 is not None:
        new_params = wsc(new_params, zero1.param_shardings)
    return new_params, new_state, {"grad_norm": gnorm}


def _apply_updates_manual(
    params: Any,
    grads: Any,
    opt_state: OptState,
    cfg: OptimizerConfig,
    learning_rate: jax.Array,
    plan: Zero1Plan,
) -> tuple[Any, OptState, dict[str, jax.Array]]:
    """ZeRO-1 update inside a ``shard_map`` manual region over
    ``plan.axis`` (the quantized-wire path, ``train.zero1_quantize``).

    ``grads`` are this replica's PARTIAL per-shard means; masters and
    moments arrive as local 1/dp shards (full for dims == -1 leaves);
    ``params`` is the full working copy. Per leaf: reduce-scatter the
    gradient onto its update dim (int8 wire when ``rs_int8``), update the
    local shard, all-gather the updated cast-down params (int8 when
    ``ag_int8``). The clip norm comes from the reduced shards — one
    scalar psum, the standard ZeRO formulation (not bitwise vs the
    replicated baseline, whose reduction groups differently).
    """
    from orion_tpu.comm.quantized import (
        quantized_all_gather,
        quantized_reduce_scatter,
    )

    axis, block = plan.axis, plan.block
    n = lax.axis_size(axis)
    idx = lax.axis_index(axis)

    def rs(g, d):
        if d < 0:
            return lax.pmean(g, axis)
        if plan.rs_int8:
            return quantized_reduce_scatter(
                g, axis, scatter_dim=d, block=block, mean=True
            )
        return lax.psum_scatter(
            g, axis, scatter_dimension=d, tiled=True
        ) / n

    g_red = jax.tree.map(rs, grads, plan.dims)

    # Global grad norm from the reduced shards: sharded leaves contribute
    # local partial squares (summed once across the axis); dims == -1
    # leaves are fully replicated and counted once, NOT psum'd.
    sq_shard = jnp.zeros((), jnp.float32)
    sq_repl = jnp.zeros((), jnp.float32)
    for g, d in zip(jax.tree.leaves(g_red), jax.tree.leaves(plan.dims)):
        s = jnp.sum(jnp.square(g.astype(jnp.float32)))
        if d < 0:
            sq_repl = sq_repl + s
        else:
            sq_shard = sq_shard + s
    gnorm = jnp.sqrt(lax.psum(sq_shard, axis) + sq_repl)
    scale = _clip_scale(cfg, gnorm)
    count = opt_state["count"] + 1
    upd = _make_leaf_update(cfg, learning_rate, scale, count)

    master = opt_state.get("master")

    def src_shard(p, d):
        """This replica's slice of the (replicated) working params — the
        update source when there is no separate master copy."""
        if d < 0:
            return p
        c = p.shape[d] // n
        return lax.dynamic_slice_in_dim(p, idx * c, c, axis=d)

    # Update source: the master shards when split (they already arrive as
    # local 1/dp shards through the shard_map in_specs), else a local
    # slice of the replicated working params.
    src = (
        master if master is not None
        else jax.tree.map(src_shard, params, plan.dims)
    )

    flat = jax.tree_util.tree_map_with_path(upd, src, g_red,
                                            opt_state["mu"],
                                            opt_state["nu"])
    new_src, new_mu, new_nu = _unzip3(flat)

    def ag(m, p, d):
        """The all-gather leg: updated shard -> full working copy, cast
        down to the working dtype (the narrow-wire trick; int8 narrower
        still under ag_int8)."""
        if d < 0:
            return m.astype(p.dtype)
        if plan.ag_int8:
            return quantized_all_gather(
                m.astype(jnp.float32), axis, gather_dim=d, block=block
            ).astype(p.dtype)
        return lax.all_gather(
            m.astype(p.dtype), axis, axis=d, tiled=True
        )

    new_params = jax.tree.map(ag, new_src, params, plan.dims)
    new_state = {"mu": new_mu, "nu": new_nu, "count": count}
    if master is not None:
        new_state["master"] = new_src
    return new_params, new_state, {"grad_norm": gnorm}
