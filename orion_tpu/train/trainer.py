"""The training step loop (reference ``orion.trainer`` equivalent).

Design (SURVEY.md §4 stack A): control crosses host->device once per step —
batch feed in, metric scalars out. Everything else (forward, backward, grad
accumulation, clipping, AdamW update, the DDP psum / ZeRO-3 gathers / TP and
EP collectives implied by the sharding rules) is one jit-compiled XLA program
with donated buffers. Fault injection and preemption-safe resume hook in at
the step boundary (SURVEY.md §6 "Failure detection").
"""

from __future__ import annotations

import logging
import os
import time
from typing import Any, Callable, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import NamedSharding, PartitionSpec as P

from orion_tpu import metrics as metrics_lib
from orion_tpu.ckpt import CheckpointManager
from orion_tpu.config import Config
from orion_tpu.data import make_loader
from orion_tpu.models import init_params, loss_fn, param_logical_axes
from orion_tpu.parallel import (
    batch_sharding,
    param_shardings,
    zero1_shardings,
)
from orion_tpu.runtime import build_mesh, initialize
from orion_tpu.train.optimizer import (
    Zero1Plan,
    apply_updates,
    global_norm,
    init_opt_state,
    make_schedule,
    tree_all_finite,
)

log = logging.getLogger("orion_tpu.train")

TrainState = dict[str, Any]


class FaultInjected(RuntimeError):
    """Raised by the --inject_fault_at_step test hook (SURVEY.md §6)."""


class RollbackFailed(RuntimeError):
    """Auto-rollback (train.anomaly_limit consecutive anomalies) found no
    intact checkpoint to restore. Retryable by run_with_restarts only in
    the sense that a supervisor restart re-inits from scratch."""


# Each injected fault fires once per (checkpoint dir, step) per process, so
# a supervisor restart that resumes from *before* the fault step does not
# crash again on the same hook — mimicking a transient failure.
_FIRED_FAULTS: set = set()


def zero1_master_split(cfg: Config) -> bool:
    """Whether ZeRO-1 carries a separate dp-sharded master copy.

    Two reasons to split:

    - mixed precision (``param_dtype != dtype``): ``state['params']``
      holds the cast-down working copy the forward reads and
      ``opt['master']`` the sharded full-precision source of truth;
    - a quantized all-gather leg (``zero1_quantize=int8|ag_int8``): the
      gathered params are an int8 round-trip, and WITHOUT a master the
      owner's own shard would re-enter the next update quantized — a
      per-step error random walk that compounds over a long run. With
      the master split the update always reads the exact master shards
      and params are a bounded ONE-step quantization of them (and stay
      bit-identical across replicas, since every device — owner
      included — takes the same gathered bytes).

    Otherwise the params ARE the masters and stay replicated — a separate
    copy would cost memory, not save it."""
    if not cfg.train.zero1:
        return False
    if jnp.dtype(cfg.model.param_dtype) != jnp.dtype(cfg.model.dtype):
        return True
    return cfg.train.zero1_quantize in ("int8", "ag_int8")


def make_zero1_plan(cfg: Config, mesh) -> Optional[Zero1Plan]:
    """The per-leaf ZeRO-1 update-sharding plan (train.zero1), or None."""
    if not cfg.train.zero1:
        return None
    logical = param_logical_axes(cfg.model)
    shapes = jax.eval_shape(
        lambda: init_params(cfg.model, jax.random.key(0))
    )
    zshard, dims = zero1_shardings(mesh, logical, shapes)
    return Zero1Plan(
        axis="dp",
        dims=dims,
        state_shardings=zshard,
        param_shardings=param_shardings(mesh, logical),
        quantize=cfg.train.zero1_quantize,
    )


def init_train_state(cfg: Config, key: jax.Array) -> TrainState:
    params = init_params(cfg.model, key)
    opt = init_opt_state(
        params, cfg.optimizer, master=zero1_master_split(cfg)
    )
    if zero1_master_split(cfg):
        wdt = jnp.dtype(cfg.model.dtype)
        params = jax.tree.map(lambda p: p.astype(wdt), params)
    return {
        "params": params,
        "opt": opt,
        "step": jnp.zeros((), jnp.int32),
    }


def state_shardings(
    cfg: Config, mesh, zero1_plan: Optional[Zero1Plan] = None
) -> TrainState:
    """NamedShardings for the full train state: ZeRO-3 by construction —
    moments share the params' shardings, scalars are replicated. With
    train.zero1 the moments (and the master copy, when split) instead take
    the dp-sharded weight-update layout (parallel.sharding.zero1_shardings)
    so each replica physically holds 1/dp of the optimizer state.
    ``zero1_plan`` lets a caller that already built the plan (the Trainer)
    reuse its layout trees instead of re-tracing the abstract init."""
    if zero1_plan is None:
        zero1_plan = make_zero1_plan(cfg, mesh)
    if zero1_plan is not None:
        pshard = zero1_plan.param_shardings
        mshard = zero1_plan.state_shardings
    else:
        pshard = param_shardings(mesh, param_logical_axes(cfg.model))
        mshard = pshard
    repl = NamedSharding(mesh, P())
    opt = {"mu": mshard, "nu": mshard, "count": repl}
    if zero1_master_split(cfg):
        opt["master"] = mshard
    return {
        "params": pshard,
        "opt": opt,
        "step": repl,
    }


def abstract_train_state(cfg: Config, shardings=None) -> TrainState:
    """ShapeDtypeStructs (with NamedShardings) of the full train state.

    The sharding-aware restore template: Orbax reads each leaf directly into
    its mesh layout instead of materializing host-side (a 70B state would
    host-OOM otherwise). Free function so non-training consumers (e.g. the
    serving CLI restoring params from a trainer checkpoint) don't need a
    Trainer; ``shardings`` defaults to the production rules on a fresh mesh.
    """
    if shardings is None:
        mesh = build_mesh(cfg.parallel, platform=cfg.runtime.platform)
        shardings = state_shardings(cfg, mesh)
    key = jax.random.key(cfg.train.seed)
    shapes = jax.eval_shape(lambda: init_train_state(cfg, key))
    return jax.tree.map(
        lambda s, sh: jax.ShapeDtypeStruct(s.shape, s.dtype, sharding=sh),
        shapes,
        shardings,
    )


def _require_unmasked_dp_batch(batch, knob: str) -> None:
    """Shared guard for the manual-over-dp paths (grad_quant_bits and the
    quantized zero1 wire legs): the combined ce+moe gradient cannot be
    re-weighted by per-shard valid-token counts after the fact, so a
    uniform pmean would bias shards with few valid tokens. Masked /
    packed batches need the exact (XLA-inserted) reduction."""
    if "loss_mask" in batch:
        raise ValueError(
            f"{knob} does not support loss_mask batches: dp shards with "
            f"unequal valid-token counts need token-weighted reduction; "
            f"use the full-precision automatic path"
        )


def _dp_mean_metrics(loss, aux):
    """Reduce per-shard loss/aux across dp inside a manual region: means
    everywhere except token counts, which accumulate."""
    from jax import lax as _lax

    loss = _lax.pmean(loss, "dp")
    aux = {
        k: _lax.psum(v, "dp") if k == "tokens" else _lax.pmean(v, "dp")
        for k, v in aux.items()
    }
    return loss, aux


def make_train_step(
    cfg: Config,
    schedule: Callable[[jax.Array], jax.Array],
    mesh: Any = None,
    poison: bool = False,
    zero1: Optional[Zero1Plan] = None,
) -> Callable[..., tuple[TrainState, dict[str, jax.Array]]]:
    """Build the compiled per-step function.

    With ``train.anomaly_guard`` the returned callable takes a third
    ``norm_limit`` scalar (the host-maintained spike threshold) and folds a
    donation-safe all-finite + global-norm-spike check into the program:
    an anomalous step selects the PRE-step params/optimizer back out
    bit-identically and reports ``anomaly``/``nonfinite``/``spike`` flags
    in the step metrics. Guard off returns exactly the pre-guard two-arg
    program (trace bit-identical — no finiteness ops are ever staged).

    ``poison=True`` builds the fault-injection variant: the loss is
    multiplied by NaN INSIDE the differentiated function, so real NaNs
    flow through the real backward into every grad leaf (the trainer
    dispatches one step through this program when a FaultInjector "nan"
    spec fires).
    """
    mcfg = cfg.model
    accum = cfg.train.grad_accum
    gdt = (
        jnp.dtype(cfg.train.grad_dtype)
        if cfg.train.grad_dtype is not None else None
    )
    if poison:
        def _loss_fn(p, mb, m, mesh_):
            loss, aux = loss_fn(p, mb, m, mesh_)
            return loss * jnp.float32(jnp.nan), aux
    else:
        _loss_fn = loss_fn

    def _value_and_grad(params, mb):
        """value_and_grad of the loss; under train.grad_dtype the grads are
        taken wrt a downcast param tree, so every stacked per-layer grad
        buffer (the scan-stash traffic, PERF.md) carries that dtype. The
        optimizer upcasts per leaf; with grad_accum the accumulator tree
        stays f32 (zeros_like(params) + bf16 promotes), so only the
        per-microbatch gradient signal is rounded."""
        if gdt is not None:
            params = jax.tree.map(
                lambda p: p.astype(gdt)
                if jnp.issubdtype(p.dtype, jnp.floating) else p,
                params,
            )
        return jax.value_and_grad(_loss_fn, has_aux=True)(
            params, mb, mcfg, mesh
        )

    def loss_and_grads(params, batch):
        if accum == 1:
            (loss, aux), grads = _value_and_grad(params, batch)
            return loss, aux, grads

        # batch leaves are [A, b, S]; scan over microbatches, summing grads.
        def micro(carry, mb):
            acc_grads, acc_loss, acc_aux = carry
            (loss, aux), grads = _value_and_grad(params, mb)
            acc_grads = jax.tree.map(jnp.add, acc_grads, grads)
            acc_loss = acc_loss + loss
            acc_aux = jax.tree.map(jnp.add, acc_aux, aux)
            return (acc_grads, acc_loss, acc_aux), None

        zero_grads = jax.tree.map(jnp.zeros_like, params)
        micro0 = jax.tree.map(lambda v: v[0], batch)
        aux_shapes = jax.eval_shape(
            lambda p, b: loss_fn(p, b, mcfg, mesh)[1], params, micro0
        )
        zero_aux = jax.tree.map(
            lambda s: jnp.zeros(s.shape, s.dtype), aux_shapes
        )
        (grads, loss, aux), _ = jax.lax.scan(
            micro, (zero_grads, jnp.zeros(()), zero_aux), batch
        )
        inv = 1.0 / accum
        grads = jax.tree.map(lambda g: g * inv, grads)
        # Means over microbatches, except token counts which accumulate.
        aux = {
            k: v if k == "tokens" else v * inv for k, v in aux.items()
        }
        return loss * inv, aux, grads

    quant_bits = cfg.train.grad_quant_bits
    if quant_bits:
        # Int8-wire DP gradient reduction (EQuARX-class; comm/quantized.py).
        # Grads are computed per-dp-shard inside a shard_map manual over dp
        # only, reduced with quantized collectives, and returned replicated.
        # Pure DP is required: with the other axes at 1 the model forward
        # contains no cross-device collectives of its own, so the manual dp
        # region is self-contained.
        from orion_tpu.comm.quantized import quantized_all_reduce

        if quant_bits != 8:
            raise ValueError(f"grad_quant_bits={quant_bits}; only 8 works")
        others = {
            k: v
            for k, v in (mesh.shape.items() if mesh is not None else [])
            if k != "dp" and v > 1
        }
        if others:
            raise ValueError(
                f"grad_quant_bits needs pure DP; mesh has {others}"
            )

        def reduced_loss_and_grads(params, batch):
            _require_unmasked_dp_batch(batch, "train.grad_quant_bits")

            def body(params, batch):
                loss, aux, grads = loss_and_grads(params, batch)
                grads = jax.tree.map(
                    lambda g: quantized_all_reduce(g, "dp", mean=True), grads
                )
                loss, aux = _dp_mean_metrics(loss, aux)
                return loss, aux, grads

            bspec = P(None, "dp") if accum > 1 else P("dp")
            return jax.shard_map(
                body,
                mesh=mesh,
                in_specs=(
                    jax.tree.map(lambda _: P(), params),
                    jax.tree.map(lambda _: bspec, batch),
                ),
                out_specs=(P(), P(), P()),
                check_vma=False,
            )(params, batch)

        grads_fn = reduced_loss_and_grads
    else:
        grads_fn = loss_and_grads

    manual_zero1 = zero1 is not None and zero1.manual
    if manual_zero1:
        # The quantized-wire ZeRO-1 path (train.zero1_quantize): the whole
        # fwd/bwd + sharded update runs manual over dp, so the gradient
        # exchange is the PARTIAL per-replica grads (the reduce-scatter
        # leg quantizes real wire traffic, not an already-psum'd copy) and
        # the updated params return through the explicit all-gather leg.
        # Pure DP is required (Trainer validates): with the other axes at
        # 1 the model forward contains no collectives of its own.
        from jax import lax as _lax

        zspec = jax.tree.map(lambda s: s.spec, zero1.state_shardings)
        opt_spec: dict = {"mu": zspec, "nu": zspec, "count": P()}
        if zero1_master_split(cfg):
            opt_spec["master"] = zspec
        bspec = P(None, "dp") if accum > 1 else P("dp")

        def _manual_body(params, opt, batch, lr, want_finite):
            loss, aux, grads = loss_and_grads(params, batch)
            if want_finite:
                # Checked on the LOCAL partial grads: the int8 wire leg
                # would round a NaN away before a post-reduce check saw
                # it. psum-of-bools == n <=> every replica finite.
                fin = jnp.logical_and(
                    jnp.isfinite(loss), tree_all_finite(grads)
                )
                fin = _lax.psum(
                    fin.astype(jnp.int32), "dp"
                ) >= _lax.axis_size("dp")
            else:
                fin = jnp.bool_(True)
            new_params, new_opt, m = apply_updates(
                params, grads, opt, cfg.optimizer, lr, zero1=zero1
            )
            loss, aux = _dp_mean_metrics(loss, aux)
            return loss, aux, new_params, new_opt, m["grad_norm"], fin

        def manual_update(state, batch, lr, want_finite):
            _require_unmasked_dp_batch(batch, "train.zero1_quantize")
            return jax.shard_map(
                lambda p, o, b, lr_: _manual_body(
                    p, o, b, lr_, want_finite
                ),
                mesh=mesh,
                in_specs=(P(), opt_spec, bspec, P()),
                out_specs=(P(), P(), P(), opt_spec, P(), P()),
                check_vma=False,
            )(state["params"], state["opt"], batch, lr)

    def train_step(state: TrainState, batch):
        params = state["params"]
        lr = schedule(state["opt"]["count"]).astype(jnp.float32)
        if manual_zero1:
            with jax.named_scope("fwd_bwd_zero1"):
                loss, aux, new_params, new_opt, gnorm, _ = manual_update(
                    state, batch, lr, False
                )
        else:
            with jax.named_scope("fwd_bwd"):
                loss, aux, grads = grads_fn(params, batch)
            with jax.named_scope("optimizer"):
                new_params, new_opt, opt_metrics = apply_updates(
                    params, grads, state["opt"], cfg.optimizer, lr,
                    zero1=zero1,
                )
            gnorm = opt_metrics["grad_norm"]
        new_state = {
            "params": new_params,
            "opt": new_opt,
            "step": state["step"] + 1,
        }
        step_metrics = {
            "loss": loss,
            "ce_loss": aux["ce_loss"],
            "moe_aux": aux["moe_aux"],
            "grad_norm": gnorm,
            "lr": lr,
        }
        return new_state, step_metrics

    if not cfg.train.anomaly_guard:
        return train_step

    def guarded_step(state: TrainState, batch, norm_limit):
        """train_step + the gradient anomaly guard (ISSUE 8).

        Donation-safe skip: the old params/moments are read BEFORE the
        update and selected back per leaf when the step is anomalous, so
        a skipped step's outputs are byte-identical to the pre-step state
        even with the inputs donated (XLA still aliases the buffers —
        shapes/dtypes match — and `where` reads happen before writes).
        The schedule count only advances on applied steps, mirroring
        standard skip-nonfinite optimizers: a skipped batch neither moves
        the params nor burns an LR-schedule position.
        """
        params = state["params"]
        lr = schedule(state["opt"]["count"]).astype(jnp.float32)
        if manual_zero1:
            with jax.named_scope("fwd_bwd_zero1"):
                (loss, aux, new_params, new_opt, gnorm,
                 finite) = manual_update(state, batch, lr, True)
            with jax.named_scope("anomaly_guard"):
                spike = jnp.logical_and(finite, gnorm > norm_limit)
                ok = jnp.logical_and(finite, jnp.logical_not(spike))
            keep = lambda new, old: jnp.where(ok, new, old)
            new_state = {
                "params": jax.tree.map(keep, new_params, params),
                "opt": jax.tree.map(keep, new_opt, state["opt"]),
                "step": state["step"] + 1,
            }
            f32 = jnp.float32
            return new_state, {
                "loss": loss,
                "ce_loss": aux["ce_loss"],
                "moe_aux": aux["moe_aux"],
                "grad_norm": gnorm,
                "lr": lr,
                "anomaly": jnp.logical_not(ok).astype(f32),
                "nonfinite": jnp.logical_not(finite).astype(f32),
                "spike": spike.astype(f32),
            }
        with jax.named_scope("fwd_bwd"):
            loss, aux, grads = grads_fn(params, batch)
        if zero1 is not None:
            # Pin the guard's norm (and the clip below, via gnorm=) to the
            # baseline's replicated grad layout — the bitwise-parity rule
            # apply_updates applies when it computes the norm itself.
            grads = jax.lax.with_sharding_constraint(
                grads, zero1.param_shardings
            )
        with jax.named_scope("anomaly_guard"):
            gnorm = global_norm(grads)
            finite = jnp.logical_and(
                jnp.isfinite(loss), tree_all_finite(grads)
            )
            spike = jnp.logical_and(finite, gnorm > norm_limit)
            ok = jnp.logical_and(finite, jnp.logical_not(spike))
        with jax.named_scope("optimizer"):
            new_params, new_opt, opt_metrics = apply_updates(
                params, grads, state["opt"], cfg.optimizer, lr,
                gnorm=gnorm, zero1=zero1,
            )
        keep = lambda new, old: jnp.where(ok, new, old)
        new_state = {
            "params": jax.tree.map(keep, new_params, params),
            "opt": jax.tree.map(keep, new_opt, state["opt"]),
            "step": state["step"] + 1,
        }
        f32 = jnp.float32
        step_metrics = {
            "loss": loss,
            "ce_loss": aux["ce_loss"],
            "moe_aux": aux["moe_aux"],
            "grad_norm": gnorm,
            "lr": lr,
            "anomaly": jnp.logical_not(ok).astype(f32),
            "nonfinite": jnp.logical_not(finite).astype(f32),
            "spike": spike.astype(f32),
        }
        return new_state, step_metrics

    return guarded_step


class Trainer:
    """Builds the distributed runtime and runs the fit loop.

    Call stack mirror of the reference train path (SURVEY.md §4 stack A):
    runtime.init -> mesh -> loader -> sharded model init or checkpoint
    restore -> jit train_step -> loop.
    """

    def __init__(self, cfg: Config, fault_injector: Optional[Any] = None):
        import dataclasses as _dc

        self.fault_injector = fault_injector
        if cfg.runtime.checkify and cfg.train.anomaly_guard:
            raise ValueError(
                "train.anomaly_guard handles non-finite steps by skipping "
                "them in-program; runtime.checkify raises host-side on the "
                "same condition — pick one"
            )
        if cfg.model.weight_quant is not None:
            raise ValueError(
                "model.weight_quant is a serving-only knob (the engine "
                "quantizes at init); training runs full-precision masters"
            )
        if cfg.train.zero1:
            if cfg.parallel.dp < 2:
                raise ValueError(
                    "train.zero1 needs parallel.dp > 1: the optimizer "
                    "state shards 1/dp across the dp axis"
                )
            if cfg.train.grad_quant_bits:
                raise ValueError(
                    "train.zero1 replaces the dp gradient all-reduce with "
                    "a reduce-scatter, so train.grad_quant_bits has no "
                    "collective left to quantize; use train.zero1_quantize"
                )
            if cfg.train.zero1_quantize:
                if cfg.parallel.pp > 1:
                    # Named separately from the generic pure-DP check:
                    # the full-precision zero1 path DOES compose with pp
                    # (stage-local dp via sharding constraints), so this
                    # is the one zero1 combo that stays rejected — the
                    # int8 wire legs run shard_map manual over dp, and
                    # nesting that inside the pipeline's pp-manual
                    # region is unproven.
                    raise ValueError(
                        "train.zero1_quantize is rejected under "
                        "parallel.pp: the int8 wire legs run manual "
                        "over dp and cannot nest inside the pipeline's "
                        "pp shard_map; use full-precision train.zero1 "
                        "(composes with pp) or drop pp"
                    )
                others = {
                    k: v for k, v in cfg.parallel.axis_sizes.items()
                    if k != "dp" and v > 1
                }
                if others:
                    raise ValueError(
                        f"train.zero1_quantize needs pure DP (the wire "
                        f"legs run manual over dp); mesh has {others}"
                    )
        elif cfg.train.zero1_quantize:
            raise ValueError(
                "train.zero1_quantize without train.zero1 has no "
                "ZeRO-1 collective legs to quantize"
            )
        if cfg.train.remat != "inherit" or cfg.train.remat_offload:
            # train.remat / train.remat_offload are the training-side
            # spelling of the remat policy: fold them into the model config
            # (the source of truth the forward pass reads), so checkpoints
            # and serving configs keep their own model.remat. An explicit
            # train.remat=none arrives as None (the override parser's
            # spelling) and ModelConfig.__post_init__ normalizes it — it
            # must DISABLE remat, not fall back to model.remat. An explicit
            # train.remat to a NON-names policy takes the offload decision
            # wholesale (model.remat_offload is dropped, not OR'd in), so
            # overriding an offload-configured checkpoint to dots/full does
            # not dead-end on the offload-requires-names check — but an
            # explicit train.remat=names keeps a configured
            # model.remat_offload (OR), so restating the canonical spelling
            # cannot silently move the stash back into HBM.
            explicit = cfg.train.remat != "inherit"
            drop_model_offload = explicit and cfg.train.remat != "names"
            cfg = _dc.replace(
                cfg,
                model=_dc.replace(
                    cfg.model,
                    remat=(cfg.train.remat if explicit
                           else cfg.model.remat),
                    remat_offload=(
                        cfg.train.remat_offload if drop_model_offload
                        else (cfg.train.remat_offload
                              or cfg.model.remat_offload)
                    ),
                ),
            )
        # Validate the remat-policy coupling (offload requires "names") and
        # the scan-unit split NOW, with config vocabulary — not as a trace-
        # time error out of the middle of the forward pass.
        from orion_tpu.models.transformer import remat_policy

        remat_policy(cfg.model)
        if cfg.model.scan_layers and cfg.model.n_layers % cfg.model.scan_unit:
            raise ValueError(
                f"model.n_layers={cfg.model.n_layers} must be divisible by "
                f"the layer-scan unit {cfg.model.scan_unit} "
                f"(model.scan_group={cfg.model.scan_group}"
                + (f" x pattern={cfg.model.window_pattern}"
                   if cfg.model.window_pattern else "") + ")"
            )
        if cfg.model.scan_group > 1 and not cfg.model.scan_layers:
            raise ValueError(
                "model.scan_group > 1 requires model.scan_layers=true "
                "(grouping is a property of the layer scan)"
            )
        if (
            cfg.parallel.pp_virtual_stages != 1
            and cfg.parallel.pp_schedule != "interleaved"
        ):
            # Checked regardless of pp: at pp=1 the setting would otherwise
            # be silently ignored — the exact no-op it exists to reject.
            raise ValueError(
                "pp_virtual_stages > 1 requires pp_schedule=interleaved"
            )
        if cfg.parallel.pp > 1:
            # Route the layer stack through the pipeline over pp
            # (parallel.pipeline); params/opt shard "layers" -> pp by rule.
            pp, M = cfg.parallel.pp, cfg.parallel.pp_microbatches
            micro = cfg.data.batch_size // max(cfg.train.grad_accum, 1)
            # The pipeline unit is the layer-scan unit: scan_group
            # homogeneous layers times the window pattern (Gemma-family
            # models group local/global layers). Same source of truth as
            # the forward pass (ModelConfig.scan_unit), so scan_group
            # composes with pp instead of being rejected.
            unit = cfg.model.scan_unit
            n_units, rem = divmod(cfg.model.n_layers, unit)
            if rem or n_units % pp:
                raise ValueError(
                    f"model.n_layers={cfg.model.n_layers} must split into "
                    f"scan units of {unit} (scan_group="
                    f"{cfg.model.scan_group} x pattern="
                    f"{cfg.model.window_pattern or 1}) divisible by "
                    f"parallel.pp={pp}"
                )
            if M < 1 or micro % M:
                raise ValueError(
                    f"per-step batch {micro} must be divisible by "
                    f"pp_microbatches={M}"
                )
            if not cfg.model.scan_layers:
                raise ValueError("parallel.pp > 1 requires model.scan_layers")
            sched = cfg.parallel.pp_schedule
            V = cfg.parallel.pp_virtual_stages
            if sched == "interleaved":
                if n_units % (pp * V):
                    raise ValueError(
                        f"model.n_layers={cfg.model.n_layers} gives "
                        f"{n_units} pipeline units (scan unit {unit}); "
                        f"must be divisible by pp*pp_virtual_stages "
                        f"({pp}*{V})"
                    )
                if M > pp:
                    raise ValueError(
                        f"pp_schedule=interleaved needs pp_microbatches "
                        f"({M}) <= pp ({pp}); raise pp_virtual_stages to "
                        f"amortize the bubble instead"
                    )
            cfg = _dc.replace(
                cfg,
                model=_dc.replace(
                    cfg.model, pipeline_axis="pp", pp_microbatches=M,
                    pp_schedule=sched, pp_virtual_stages=V,
                ),
            )
        if cfg.parallel.sp > 1:
            # Route attention through ring/Ulysses over the sp axis
            # (parallel.sequence); all other layers are pointwise over the
            # sequence and stay sequence-sharded via the "seq" rule.
            if cfg.data.seq_len % cfg.parallel.sp:
                raise ValueError(
                    f"data.seq_len={cfg.data.seq_len} must be divisible by "
                    f"parallel.sp={cfg.parallel.sp}"
                )
            if cfg.parallel.sequence_method == "ulysses":
                sp_tp = cfg.parallel.sp * cfg.parallel.tp
                if cfg.model.n_heads % sp_tp:
                    raise ValueError(
                        f"ulysses needs model.n_heads={cfg.model.n_heads} "
                        f"divisible by sp*tp={sp_tp}"
                    )
                kv = cfg.model.n_kv_heads
                if kv < sp_tp and sp_tp % kv == 0:
                    # GQA KV replication (the only sub-divisible shape
                    # sequence.py accepts): the head<->seq all_to_all moves
                    # whole heads, so kv_heads replicate up to sp*tp — that
                    # inflates KV comm volume by sp*tp/kv_heads vs ring's
                    # exact O(S/sp) KV rotation. Warn and quantify so the
                    # config author can switch (parallel.sequence_method).
                    log.warning(
                        "ulysses with GQA (kv_heads=%d < sp*tp=%d) "
                        "replicates KV heads: %dx KV all_to_all volume. "
                        "parallel.sequence_method='ring' (or "
                        "'ring_striped') avoids the inflation for this "
                        "config.",
                        kv, sp_tp, sp_tp // kv,
                    )
            cfg = _dc.replace(
                cfg,
                model=_dc.replace(
                    cfg.model,
                    sequence_axis="sp",
                    sequence_method=cfg.parallel.sequence_method,
                ),
            )
        self.cfg = cfg
        if cfg.data.batch_size % max(cfg.train.grad_accum, 1):
            raise ValueError(
                f"grad_accum={cfg.train.grad_accum} must divide global batch "
                f"{cfg.data.batch_size}"
            )
        micro = cfg.data.batch_size // max(cfg.train.grad_accum, 1)
        dpf = cfg.parallel.dp * cfg.parallel.fsdp
        if micro % dpf:
            raise ValueError(
                f"per-step batch {micro} (data.batch_size="
                f"{cfg.data.batch_size} / grad_accum="
                f"{max(cfg.train.grad_accum, 1)}) must be divisible by "
                f"dp*fsdp={dpf}"
            )
        initialize(cfg.runtime)
        self.mesh = build_mesh(cfg.parallel, platform=cfg.runtime.platform)
        # Plan first, shardings from it: both need the same abstract init
        # trace; building the plan once avoids paying it twice.
        self._zero1 = make_zero1_plan(self.cfg, self.mesh)
        self.shardings = state_shardings(
            cfg, self.mesh, zero1_plan=self._zero1
        )
        self.batch_shard = self._batch_sharding()
        self.loader = make_loader(cfg.data, cfg.model.vocab_size)
        schedule = make_schedule(cfg.optimizer, cfg.train.num_steps)
        self._schedule = schedule
        base_step = make_train_step(
            self.cfg, schedule, self.mesh, zero1=self._zero1
        )
        if cfg.runtime.checkify:
            # Sanitizer mode (SURVEY.md §6, SANITIZERS.md): functionalized
            # device-side nan/inf + index-OOB checks; the error pytree is
            # fetched and thrown host-side after every step.
            from jax.experimental import checkify as _checkify

            # checkify's error plumbing does not compose with manual
            # shard_map regions in this jax version (the error pytree's
            # shapes diverge across the manual boundary) — fail loudly
            # with the reason instead of a cryptic trace-time TypeError.
            manual = []
            if cfg.parallel.sp > 1:
                manual.append("parallel.sp>1 (ring/Ulysses shard_map)")
            if cfg.parallel.pp > 1:
                manual.append("parallel.pp>1 (pipeline shard_map)")
            if (cfg.model.is_moe and cfg.parallel.ep > 1
                    and cfg.model.moe_dispatch == "sorted_a2a"):
                manual.append("moe_dispatch=sorted_a2a (explicit ep a2a)")
            if cfg.train.grad_quant_bits:
                manual.append("train.grad_quant_bits (dp shard_map)")
            if cfg.train.zero1_quantize:
                manual.append(
                    "train.zero1_quantize (dp shard_map wire legs)"
                )
            if manual:
                raise ValueError(
                    "runtime.checkify does not compose with manual "
                    f"shard_map regions ({', '.join(manual)}); use "
                    "runtime.debug_nans, or check the step on an "
                    "SPMD-automatic layout (dp/fsdp/tp/ep-sorted)"
                )
            # Full check set: float (nan/inf) AND index (out-of-bounds)
            # checks. Two rewrites make this possible on this jax version:
            # the loss's target gather routes through a custom VJP whose
            # backward is a one-hot product, not a scatter
            # (models/transformer._gather_target), and the MoE router's
            # top-k is argsort + one-hot product (models/moe._router_topk)
            # — checkify's index rewrite crashes on gather's scatter
            # transpose and on lax.top_k, which previously forced
            # float_checks-only here.
            checked = jax.jit(
                _checkify.checkify(base_step, errors=_checkify.all_checks),
                donate_argnums=(0,),
            )

            def _checked_step(state, batch):
                err, out = checked(state, batch)
                _checkify.check_error(err)
                return out

            self._jit_step = checked
            self.train_step = _checked_step
        else:
            self._jit_step = jax.jit(base_step, donate_argnums=(0,))
            self.train_step = self._jit_step
        if cfg.model.debug_asserts:
            # Manual-region sanitizer (runtime/asserts.py): device_assert
            # callbacks RECORD failures (raising inside an async callback
            # aborts the runtime); surface them loudly at this per-step
            # host sync point. The block_until_ready forces the step's
            # callbacks to have run before we check.
            from orion_tpu.runtime import asserts as _asserts

            inner_step = self.train_step

            def _asserted_step(*args):
                out = inner_step(*args)
                jax.block_until_ready(out[1])
                # Output readiness does not order the async callback
                # thread; the barrier does — without it a failure could
                # surface a step late (or never, on the final step).
                jax.effects_barrier()
                _asserts.raise_if_failed()
                return out

            self.train_step = _asserted_step
        self.eval_loader = None
        self._eval_batches = None
        if cfg.train.eval_interval:
            eval_data = _dc.replace(
                cfg.data,
                path=cfg.data.eval_path or cfg.data.path,
                shuffle_seed=cfg.data.eval_seed,
            )
            self.eval_loader = make_loader(eval_data, cfg.model.vocab_size)
            mcfg, mesh = self.cfg.model, self.mesh
            self.eval_step = jax.jit(
                lambda params, batch: loss_fn(params, batch, mcfg, mesh)[1][
                    "ce_loss"
                ]
            )
        self.ckpt: Optional[CheckpointManager] = None
        if cfg.checkpoint.directory:
            self.ckpt = CheckpointManager(
                cfg.checkpoint.directory, cfg.checkpoint,
                fault_injector=fault_injector,
            )
        # Anomaly-guard host state (persisted in the checkpoint manifest so
        # resume reproduces the exact skip decisions) + robustness counters.
        self._gnorm_ema: Optional[float] = None
        self._anomaly_run = 0
        self._poison_jit = None
        self.robustness = metrics_lib.TrainRobustnessStats()
        # The PRNG key the run was seeded with, recorded in every manifest
        # (pillar 2: a resumed run must be able to prove it continues the
        # same key lineage).
        self._prng_key_data = [
            int(x) for x in np.ravel(
                jax.random.key_data(jax.random.key(cfg.train.seed))
            )
        ]
        # data.batch_size is the global batch per optimizer step; grad_accum
        # only splits it into microbatches and must not inflate throughput.
        tokens_per_step = cfg.data.batch_size * cfg.data.seq_len
        self.metrics = metrics_lib.MetricsLogger(
            flops_per_token=cfg.model.flops_per_token(cfg.data.seq_len),
            num_devices=self.mesh.size,
            peak_flops=cfg.train.peak_flops_per_device,
            jsonl_path=cfg.train.metrics_jsonl,
            log_interval=cfg.train.log_interval,
        )
        self.tokens_per_step = tokens_per_step
        # -- Observability (orion_tpu/obs; README "Observability") ---------
        # Registry always exists (lazy provider reads — no hot-path cost);
        # tracer/flight only when train.trace / train.flight_dir ask, so
        # the untraced fit loop is byte-identical to the pre-obs one.
        from orion_tpu.obs import MetricsRegistry, init_obs, live_hbm_metrics

        self.registry = MetricsRegistry()
        self.registry.register(
            "robust", lambda: self.robustness.as_timing()
        )
        self.registry.register("train", self._last_step_metrics)
        self.registry.register("hbm", live_hbm_metrics)
        self._tracer, self._flight = init_obs(
            trace=cfg.train.trace,
            trace_ring=cfg.train.trace_ring,
            flight_dir=cfg.train.flight_dir,
            trace_path=cfg.train.trace_path,
            snapshot=self.registry.snapshot,
            injector=fault_injector,
        )

    def _last_step_metrics(self) -> dict:
        """Registry provider: the newest StepMetrics row (the same dict
        the JSONL sink writes), or {} before the first step."""
        h = self.metrics.history
        return h[-1].to_dict() if h else {}

    def _flight_dump(self, reason: str, **context) -> None:
        """Write a flight-recorder postmortem (no-op without
        train.flight_dir); best-effort like the engine's
        (FlightRecorder.try_dump)."""
        if self._flight is not None:
            self._flight.try_dump(reason, **context)

    def _batch_sharding(self) -> NamedSharding:
        shard = batch_sharding(self.mesh)
        if self.cfg.train.grad_accum > 1:
            # Microbatch axis leads and is unsharded: [A, b, S].
            return NamedSharding(self.mesh, P(None, *shard.spec))
        return shard

    # -- state ------------------------------------------------------------

    def init_state(self) -> TrainState:
        key = jax.random.key(self.cfg.train.seed)
        init = lambda: init_train_state(self.cfg, key)
        return jax.jit(init, out_shardings=self.shardings)()

    def abstract_state(self) -> TrainState:
        return abstract_train_state(self.cfg, shardings=self.shardings)

    def memory_report(self, assert_donation: bool = True) -> dict:
        """AOT-compile the jitted train step and report XLA's compiled
        memory analysis — the ground truth for "does this remat policy fit"
        (temp bytes = activations + workspace) and for whether the donated
        master-param/optimizer-state buffers were actually reused.

        All state accounting is PER CHIP (``sharding.shard_shape``), so a
        dp-sharded layout (train.zero1) shows its 1/dp master+moment
        shrink directly; ``by_category`` breaks the per-chip bytes into
        params / grads / master / moments / activations (grads and
        activations are estimates: the effective grad dtype over the param
        layout, and XLA's temp bytes — activations + workspace + transient
        grads — respectively).

        With ``assert_donation`` (default), raise if any donated state
        bytes failed to alias into the outputs: an un-aliased master/
        moment buffer silently DOUBLES its footprint for the step, which
        is exactly the headroom that decides whether remat=names fits at
        bench batch 8 (PERF.md). The check compares per-chip donated bytes
        against the per-executable alias size, so it covers sharded
        layouts too; multi-PROCESS runs still skip it (this process only
        sees its own executable). (Not called from the hot path: the AOT
        executable is separate from jit's own cache, so this costs one
        extra compile.)
        """
        import math

        state = self.abstract_state()
        # Specs from the REAL assembled global batch (one materialization,
        # trivial next to the AOT compile): on multi-process runs the
        # host-local batch is only this process's shard, and lowering with
        # its shape would analyze a program the hot path never runs.
        batch = jax.tree.map(
            lambda a: jax.ShapeDtypeStruct(a.shape, a.dtype,
                                           sharding=a.sharding),
            self.global_batch(0),
        )
        args = (state, batch)
        if self.cfg.train.anomaly_guard:
            # The guarded program takes the host-fed spike threshold too.
            args = (*args, jax.ShapeDtypeStruct((), jnp.float32))
        compiled = self._jit_step.lower(*args).compile()
        ma = compiled.memory_analysis()

        def _nbytes(leaf):
            return math.prod(leaf.shape) * jnp.dtype(leaf.dtype).itemsize

        def _chip_nbytes(leaf, dtype=None):
            """Per-device bytes: the leaf's local shard (replicated dims
            count in full on every chip). ``dtype`` overrides the leaf's
            (the grads estimate prices the param layout at grad dtype)."""
            sharding = getattr(leaf, "sharding", None)
            shape = (
                sharding.shard_shape(leaf.shape)
                if sharding is not None else leaf.shape
            )
            dt = jnp.dtype(dtype if dtype is not None else leaf.dtype)
            return math.prod(shape) * dt.itemsize

        def _chip_tree(tree):
            return sum(_chip_nbytes(x) for x in jax.tree.leaves(tree))

        donated = sum(_nbytes(leaf) for leaf in jax.tree.leaves(state))
        donated_chip = _chip_tree(state)
        opt = state["opt"]
        gdt = jnp.dtype(
            self.cfg.train.grad_dtype
            if self.cfg.train.grad_dtype is not None
            else jax.tree.leaves(state["params"])[0].dtype
        )
        by_category = {
            "params": _chip_tree(state["params"]),
            "grads": sum(
                _chip_nbytes(p, gdt)
                for p in jax.tree.leaves(state["params"])
            ),
            "master": _chip_tree(opt["master"]) if "master" in opt else 0,
            "moments": _chip_tree(opt["mu"]) + _chip_tree(opt["nu"]),
        }
        report = {
            "donated_state_bytes": donated,
            "donated_bytes_per_chip": donated_chip,
            "by_category": by_category,
            "available": ma is not None,
        }
        if jax.process_count() > 1:
            assert_donation = False
            report["note"] = (
                "multi-process run: this process's executable only covers "
                "its own devices; donation assertion skipped"
            )
        if ma is not None:
            report.update(
                argument_bytes=int(ma.argument_size_in_bytes),
                output_bytes=int(ma.output_size_in_bytes),
                temp_bytes=int(ma.temp_size_in_bytes),
                alias_bytes=int(ma.alias_size_in_bytes),
                unaliased_donated_bytes=max(
                    0, donated_chip - int(ma.alias_size_in_bytes)
                ),
            )
            by_category["activations"] = int(ma.temp_size_in_bytes)
            if assert_donation and report["unaliased_donated_bytes"] > 0:
                raise RuntimeError(
                    f"train-step donation leaked a copy: "
                    f"{report['unaliased_donated_bytes']} of "
                    f"{donated_chip} donated per-chip state bytes were "
                    f"not aliased into the outputs "
                    f"(alias_size={report['alias_bytes']}); check for "
                    f"dtype/sharding mismatches between old and new "
                    f"state leaves"
                )
        return report

    def restore_or_init(self) -> tuple[TrainState, int]:
        if self.ckpt is not None and self.cfg.checkpoint.restore:
            restored = self.ckpt.restore_latest(self.abstract_state())
            self.robustness.corrupt_checkpoints += len(self.ckpt.quarantined)
            if restored is not None:
                state, step = restored
                self._apply_restore_extra(self.ckpt.last_restore_extra)
                return state, step
        return self.init_state(), 0

    def _apply_restore_extra(self, extra: Optional[dict]) -> None:
        """Rehydrate the host-side resume state the manifest carried:
        data-loader cursor, anomaly-guard EMA/run, PRNG-lineage check."""
        if not extra:
            return
        if extra.get("loader"):
            loader_state = dict(extra["loader"])
            # The manifest-level stream-format check already warned on a
            # mismatch; don't let load_state_dict repeat it.
            loader_state.pop("stream_format", None)
            self.loader.load_state_dict(loader_state)
        if "gnorm_ema" in extra:
            self._gnorm_ema = extra["gnorm_ema"]
        self._anomaly_run = int(extra.get("anomaly_run") or 0)
        key = extra.get("prng_key")
        if key is not None and list(key) != self._prng_key_data:
            log.warning(
                "checkpoint was written under a different train.seed PRNG "
                "key (%s vs %s): any key-derived randomness diverges from "
                "the original run", key, self._prng_key_data,
            )

    def _ckpt_extra(self) -> dict:
        extra = {
            "loader": self.loader.state_dict(),
            "train_seed": self.cfg.train.seed,
            "prng_key": self._prng_key_data,
        }
        if self.cfg.train.anomaly_guard:
            extra["gnorm_ema"] = self._gnorm_ema
            extra["anomaly_run"] = self._anomaly_run
        return extra

    def _spike_limit(self) -> np.float32:
        """The norm threshold fed to the guarded step: factor x the
        running EMA, or +inf while no reference exists (first steps, or
        spike checking disabled — finiteness is still checked)."""
        factor = self.cfg.train.anomaly_spike_factor
        if factor is None or not self._gnorm_ema:
            return np.float32(np.inf)
        return np.float32(factor * self._gnorm_ema)

    def _poison_variant(self):
        """The FaultInjector "nan" step program, compiled on first use
        (same config/schedule family; the loss is NaN-poisoned inside the
        differentiated function, so every grad leaf comes out NaN through
        the real backward)."""
        if self._poison_jit is None:
            self._poison_jit = jax.jit(
                make_train_step(
                    self.cfg, self._schedule, self.mesh, poison=True,
                    zero1=self._zero1,
                ),
                donate_argnums=(0,),
            )
        return self._poison_jit

    def _rollback(self, failed_step: int) -> tuple[TrainState, int]:
        """Auto-rollback after train.anomaly_limit consecutive anomalies:
        restore the newest intact checkpoint and fast-forward the data
        cursor past the poisoned batch window, so the replayed optimizer
        steps draw fresh batches instead of the poison. Idempotent under
        repetition — every episode skips further."""
        stats = self.robustness
        stats.rollbacks += 1
        stats.last_fault_reason = (
            f"anomaly_rollback: {self._anomaly_run} consecutive anomalous "
            f"steps ending at step {failed_step}"
        )
        # Postmortem BEFORE the restore mutates loader/EMA state: the dump
        # captures the poisoned window as the rollback saw it.
        self._flight_dump(
            "anomaly_rollback", failed_step=failed_step,
            anomaly_run=self._anomaly_run,
        )
        if self.ckpt is None:
            raise RollbackFailed(
                f"{self._anomaly_run} consecutive anomalous steps at step "
                f"{failed_step} and no checkpoint.directory to roll back to"
            )
        restored = self.ckpt.restore_latest(self.abstract_state())
        stats.corrupt_checkpoints += len(self.ckpt.quarantined)
        if restored is None:
            raise RollbackFailed(
                f"{self._anomaly_run} consecutive anomalous steps at step "
                f"{failed_step} and no intact checkpoint to roll back to"
            )
        state, good_step = restored
        extra = self.ckpt.last_restore_extra or {}
        loader_state = dict(extra.get("loader") or {})
        # Defensive clamp: if the newest intact checkpoint is somehow AHEAD
        # of the failed step (a later-step checkpoint resurfacing after a
        # transient validation failure), replay starts past the poison
        # already — never ask the cursor to rewind.
        skip = max((failed_step + 1) - good_step, 0)
        self.loader.load_state_dict(loader_state)
        self.loader.skip_batches(skip)
        stats.skipped_batches += skip
        self._gnorm_ema = extra.get("gnorm_ema")
        self._anomaly_run = 0
        # Persist the advanced cursor AT the restored step immediately: a
        # crash before the next periodic save would otherwise resume with
        # the old cursor, replay the poison, and have to roll back again.
        self.ckpt.save(
            good_step, state, force=True, overwrite=True,
            extra=self._ckpt_extra(),
        )
        log.warning(
            "auto-rollback: restored step %d, skipping the %d-batch poison "
            "window (data cursor offset now %d)",
            good_step, skip, self.loader.offset,
        )
        return state, good_step

    # -- data -------------------------------------------------------------

    def _host_batch(self, step: int) -> dict:
        """The host-side batch exactly as the train step receives it
        (grad_accum microbatch axis applied). Shared by the hot path and
        memory_report, so the AOT-analyzed shapes cannot drift from the
        shapes the real step runs."""
        host = dict(self.loader.batch_at(step))
        accum = self.cfg.train.grad_accum
        if accum > 1:
            host = {
                k: v.reshape(accum, v.shape[0] // accum, *v.shape[1:])
                for k, v in host.items()
            }
        return host

    def global_batch(self, step: int) -> Any:
        return jax.tree.map(
            lambda v: jax.make_array_from_process_local_data(
                self.batch_shard, v
            ),
            self._host_batch(step),
        )

    def evaluate(self, params: Any) -> float:
        """Mean held-out CE loss over the fixed eval batch set (the same
        (seed, step) batches every call, so curves are comparable).

        The batch set never changes, so the device arrays are built once
        and reused across eval points (they are tiny next to model state).
        """
        assert self.eval_loader is not None, "set train.eval_interval"
        if self._eval_batches is None:
            shard = batch_sharding(self.mesh)
            self._eval_batches = [
                jax.tree.map(
                    lambda v: jax.make_array_from_process_local_data(
                        shard, v
                    ),
                    dict(self.eval_loader.batch_at(i)),
                )
                for i in range(self.cfg.train.eval_batches)
            ]
        total = 0.0
        for batch in self._eval_batches:
            total += float(jax.device_get(self.eval_step(params, batch)))
        return total / max(len(self._eval_batches), 1)

    # -- loop -------------------------------------------------------------

    def fit(
        self,
        state: Optional[TrainState] = None,
        preemption_handler: Optional[Any] = None,
        restart_info: Optional[tuple] = None,
    ) -> list:
        """Run the step loop from the restored (or given) state.

        ``restart_info=(attempt, reason)`` threads the supervisor context
        (run_with_restarts) into the step log: the restart count rides the
        metrics extras, the previous attempt's fault reason the log line.
        """
        from orion_tpu.runtime.fault import (
            InjectedFault, Preempted, PreemptionHandler, Watchdog,
        )
        import contextlib

        cfg = self.cfg
        stats = self.robustness
        if restart_info is not None:
            attempt, reason = restart_info
            stats.restarts = int(attempt)
            if reason:
                stats.last_fault_reason = str(reason)
            if attempt:
                log.warning(
                    "supervisor restart %d: resuming after %s",
                    attempt, reason or "unknown fault",
                )
        if state is None:
            state, start = self.restore_or_init()
        else:
            start = int(jax.device_get(state["step"]))
        guard = cfg.train.anomaly_guard
        injector = self.fault_injector
        profile = cfg.train.profile_steps
        watch = metrics_lib.Stopwatch()
        tracing = False
        # After an auto-rollback the replayed trajectory differs from the
        # one the existing checkpoints captured; overwrite them up to the
        # rollback point so a crash mid-replay resumes the NEW trajectory.
        overwrite_until = -1
        try:
          with contextlib.ExitStack() as stack:
            # An externally-managed handler (tests, schedulers) is used
            # as-is; otherwise install our own for the duration of the loop.
            preempt = (
                preemption_handler
                if preemption_handler is not None
                else stack.enter_context(PreemptionHandler())
            )
            # Disabled no-op when watchdog_timeout_s is None.
            watchdog = stack.enter_context(
                Watchdog(cfg.train.watchdog_timeout_s,
                         action=cfg.train.watchdog_action)
            )
            step = start
            while step < cfg.train.num_steps:
                if cfg.train.inject_fault_at_step == step:
                    key = (cfg.checkpoint.directory, step)
                    if key not in _FIRED_FAULTS:
                        _FIRED_FAULTS.add(key)
                        raise FaultInjected(f"injected fault at step {step}")
                if injector is not None \
                        and injector.take("dispatch", step, "train"):
                    raise InjectedFault(
                        f"injected train dispatch fault at step {step}"
                    )
                if profile and step == profile[0]:
                    jax.profiler.start_trace(cfg.train.profile_dir)
                    tracing = True
                s0 = time.monotonic() if self._tracer.enabled else 0.0
                with self._tracer.span("data", step=step):
                    batch = self.global_batch(step)
                step_fn = self.train_step
                if injector is not None \
                        and injector.take("nan", step, "train") is not None:
                    log.warning(
                        "fault injection: NaN-poisoned train step %d", step
                    )
                    step_fn = self._poison_variant()
                # StepTraceAnnotation marks the step boundary in a device
                # profile captured over the same window (profile_steps),
                # so xprof's step view lines up with the host spans; the
                # dispatch span covers compiled-step call + metric fetch.
                with self._tracer.step_annotation("train", step), \
                        self._tracer.span("dispatch", step=step):
                    if guard:
                        state, m = step_fn(state, batch, self._spike_limit())
                    else:
                        state, m = step_fn(state, batch)
                    m = jax.device_get(m)
                dt = watch.lap(sync_on=m["loss"])
                watchdog.heartbeat()
                extras = {
                    "ce_loss": float(m["ce_loss"]),
                    "moe_aux": float(m["moe_aux"]),
                }
                anomalous = bool(guard and m["anomaly"] > 0)
                if guard:
                    with self._tracer.span("guard", step=step):
                        extras["anomaly"] = float(m["anomaly"])
                        if anomalous:
                            stats.anomalous_steps += 1
                            stats.nonfinite_steps += int(m["nonfinite"] > 0)
                            stats.spike_steps += int(m["spike"] > 0)
                            self._anomaly_run += 1
                            log.warning(
                                "anomalous step %d skipped (%s; grad_norm "
                                "%.3g; run %d/%d)", step,
                                "non-finite" if m["nonfinite"] > 0
                                else "norm spike",
                                float(m["grad_norm"]), self._anomaly_run,
                                cfg.train.anomaly_limit,
                            )
                        else:
                            self._anomaly_run = 0
                            beta = cfg.train.anomaly_ema_beta
                            g = float(m["grad_norm"])
                            self._gnorm_ema = (
                                g if self._gnorm_ema is None
                                else beta * self._gnorm_ema + (1 - beta) * g
                            )
                if stats.restarts or stats.rollbacks or stats.anomalous_steps:
                    extras.update(stats.as_extras())
                eval_iv = cfg.train.eval_interval
                if eval_iv and (step + 1) % eval_iv == 0:
                    extras["eval_loss"] = self.evaluate(state["params"])
                    log.info(
                        "eval at step %d: loss %.4f",
                        step + 1,
                        extras["eval_loss"],
                    )
                    watch.lap()  # keep eval time out of the next step's MFU
                self.metrics.record(
                    step=step + 1,
                    loss=m["loss"],
                    tokens=self.tokens_per_step,
                    step_time_s=dt,
                    grad_norm=m["grad_norm"],
                    learning_rate=m["lr"],
                    **extras,
                )
                if tracing and step + 1 >= profile[1]:
                    jax.profiler.stop_trace()
                    tracing = False
                if cfg.train.metrics_prom and \
                        (step + 1) % max(cfg.train.log_interval, 1) == 0:
                    try:
                        self.registry.export_prometheus(
                            cfg.train.metrics_prom
                        )
                    except OSError as e:
                        log.error("metrics_prom export failed: %s", e)
                if anomalous \
                        and self._anomaly_run >= cfg.train.anomaly_limit:
                    if self._tracer.enabled:
                        # Close the step span BEFORE the rollback's
                        # `continue` — the anomalous step a postmortem
                        # inspects must not be a hole in the timeline.
                        self._tracer.record_span(
                            "train_step", s0, time.monotonic(), step=step,
                            anomalous=True,
                        )
                    state, step = self._rollback(step)
                    overwrite_until = self._overwrite_from(step)
                    watch.lap()   # rollback time out of the next step's MFU
                    continue
                if self.ckpt is not None:
                    # ckpt span: async saves enqueue here (the host-side
                    # snapshot copy), sync saves block — either cost lands
                    # in this phase of the timeline.
                    with self._tracer.span("ckpt", step=step):
                        self.ckpt.save(
                            step + 1, state, extra=self._ckpt_extra(),
                            overwrite=step + 1 <= overwrite_until,
                        )
                if self._tracer.enabled:
                    self._tracer.record_span(
                        "train_step", s0, time.monotonic(), step=step,
                        anomalous=anomalous,
                    )
                if preempt.preempted:
                    # Step boundary: state is consistent. Persist and stop
                    # cleanly; the supervisor restart resumes losslessly.
                    # The emergency save queues BEHIND any in-flight async
                    # save (single writer queue) and wait() drains both
                    # inside the grace window.
                    if self.ckpt is not None and cfg.train.emergency_ckpt:
                        if self.ckpt.save(
                            step + 1, state, force=True,
                            extra=self._ckpt_extra(),
                            overwrite=step + 1 <= overwrite_until,
                        ):
                            stats.emergency_saves += 1
                        self.ckpt.wait()
                    raise Preempted(f"preempted after step {step + 1}")
                step += 1
            if self.ckpt is not None:
                self.ckpt.save(
                    cfg.train.num_steps, state, force=True,
                    extra=self._ckpt_extra(),
                )
            return self.metrics.history
        except (KeyboardInterrupt, FaultInjected, InjectedFault):
            # Preemption-safe path: persist the newest complete state, then
            # re-raise so a supervisor can restart and restore_or_init.
            # If the interrupt landed inside train_step, `state` is the
            # donated (deleted) input — in that case the last periodic
            # checkpoint stands and at most one step is lost.
            if self.ckpt is not None and cfg.train.emergency_ckpt:
                try:
                    at_step = int(jax.device_get(state["step"]))
                    if self.ckpt.save(
                        at_step, state, force=True, extra=self._ckpt_extra(),
                        # Inside a rollback-replay window the committed
                        # checkpoint at this step captured the ABANDONED
                        # trajectory; the emergency save must replace it or
                        # the restart resumes the wrong stream.
                        overwrite=at_step <= overwrite_until,
                    ):
                        stats.emergency_saves += 1
                except RuntimeError:
                    log.warning(
                        "state was donated mid-step; relying on last "
                        "periodic checkpoint"
                    )
                self.ckpt.wait()
            raise
        finally:
            if tracing:
                jax.profiler.stop_trace()
            if self.ckpt is not None:
                self.ckpt.wait()
            self.metrics.close()
            from orion_tpu.obs import export_chrome_safe

            export_chrome_safe(self._tracer, cfg.train.trace_path)
            if cfg.train.metrics_prom:
                try:
                    self.registry.export_prometheus(cfg.train.metrics_prom)
                except OSError as e:
                    log.error("metrics_prom export failed: %s", e)

    def _overwrite_from(self, good_step: int) -> int:
        """Newest committed step at rollback time: checkpoints in
        (good_step, newest] captured the abandoned trajectory and are
        overwritten as the replay passes them."""
        if self.ckpt is None:
            return -1
        latest = self.ckpt.latest_step()
        return latest if latest is not None else -1
