"""Training runtime (reference ``orion.trainer`` equivalent, BASELINE.json:5).

The step loop, optimizer, LR schedule, grad accumulation/clipping — compiled
into a single XLA program per step (SURVEY.md §4 stack A): no Python in the
hot loop, donated buffers, collectives inserted by XLA from the sharding
rules in orion_tpu.parallel.
"""

from orion_tpu.train.optimizer import (
    init_opt_state,
    make_schedule,
    apply_updates,
)
from orion_tpu.train.trainer import Trainer, make_train_step, init_train_state

__all__ = [
    "Trainer",
    "apply_updates",
    "init_opt_state",
    "init_train_state",
    "make_schedule",
    "make_train_step",
]
