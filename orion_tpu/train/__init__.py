"""Training runtime (reference ``orion.trainer`` equivalent, BASELINE.json:5).

The step loop, optimizer, LR schedule, grad accumulation/clipping — compiled
into a single XLA program per step (SURVEY.md §4 stack A): no Python in the
hot loop, donated buffers, collectives inserted by XLA from the sharding
rules in orion_tpu.parallel.
"""

from orion_tpu.train.optimizer import (
    Zero1Plan,
    init_opt_state,
    make_schedule,
    apply_updates,
)
from orion_tpu.train.trainer import (
    Trainer,
    init_train_state,
    make_train_step,
    make_zero1_plan,
    zero1_master_split,
)

__all__ = [
    "Trainer",
    "Zero1Plan",
    "apply_updates",
    "init_opt_state",
    "init_train_state",
    "make_schedule",
    "make_train_step",
    "make_zero1_plan",
    "zero1_master_split",
]
