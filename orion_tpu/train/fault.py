"""Compatibility shim: the fault-tolerance machinery moved to
``orion_tpu.runtime.fault`` so the serving stack can share it (preemption
drains, the stall watchdog, fault injection). Import from there."""

from orion_tpu.runtime.fault import (  # noqa: F401
    Preempted,
    PreemptionHandler,
    Watchdog,
    run_with_restarts,
)

__all__ = ["Preempted", "PreemptionHandler", "Watchdog", "run_with_restarts"]
