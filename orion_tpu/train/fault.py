"""Failure detection and elastic recovery (SURVEY.md §6 "Failure detection /
elastic recovery / fault injection").

TPU-native mapping of the reference's torchelastic-class machinery:

  - ``PreemptionHandler`` — TPU pods are preempted with SIGTERM; the handler
    flips a flag that the trainer checks at the step boundary, saves a final
    checkpoint and exits cleanly so the supervisor restart resumes losslessly.
  - ``run_with_restarts`` — the in-process supervisor loop: rebuild the
    trainer and resume from the latest checkpoint after a recoverable
    failure (the cross-process equivalent is just re-running train.py, since
    restore_or_init is the first thing the trainer does).
  - ``Watchdog`` — step-progress heartbeat; a hung collective (the
    multi-host failure mode NCCL surfaces as a timeout) trips the callback
    after ``timeout_s`` without a heartbeat.

Fault *injection* lives in the trainer (train.inject_fault_at_step), closing
the loop: tests crash a real run and assert recovery.
"""

from __future__ import annotations

import logging
import signal
import threading
import time
from typing import Callable, Optional, Sequence, Type

log = logging.getLogger("orion_tpu.fault")


class Preempted(RuntimeError):
    """Raised by the trainer after a preemption-triggered final save."""


class PreemptionHandler:
    """Installs SIGTERM/SIGINT-compatible preemption flagging.

    Usage: ``with PreemptionHandler() as h: ... if h.preempted: save+exit``.
    Signal delivery only sets a flag — all real work (checkpoint save)
    happens synchronously at the trainer's step boundary, where the train
    state is consistent.
    """

    def __init__(self, signals: Sequence[int] = (signal.SIGTERM,)):
        self.signals = tuple(signals)
        self._flag = threading.Event()
        self._prev: dict[int, object] = {}

    @property
    def preempted(self) -> bool:
        return self._flag.is_set()

    def _on_signal(self, signum, frame):
        log.warning("received signal %d: preemption flagged", signum)
        self._flag.set()

    def __enter__(self) -> "PreemptionHandler":
        for s in self.signals:
            try:
                self._prev[s] = signal.signal(s, self._on_signal)
            except ValueError:
                # Not the main thread (e.g. under some test runners): fall
                # back to manual .trigger() only.
                log.debug("cannot install handler for signal %d", s)
        return self

    def trigger(self) -> None:
        """Manually flag preemption (tests / external schedulers)."""
        self._flag.set()

    def __exit__(self, *exc) -> None:
        for s, prev in self._prev.items():
            signal.signal(s, prev)
        self._prev.clear()


def run_with_restarts(
    make_and_fit: Callable[[int], object],
    *,
    max_restarts: int = 3,
    retry_on: tuple[Type[BaseException], ...] = (Exception,),
    non_retryable: tuple[Type[BaseException], ...] = (ValueError, TypeError),
    backoff_s: float = 0.0,
) -> object:
    """Supervisor loop: call ``make_and_fit(attempt)``, restarting on failure.

    ``make_and_fit`` must rebuild its world from scratch (config -> Trainer
    -> restore_or_init -> fit) so every attempt resumes from the newest
    checkpoint. KeyboardInterrupt and Preempted always propagate — those are
    orderly shutdowns, not failures — as do ``non_retryable`` types
    (config/typo errors are deterministic; retrying them wastes compute).
    """
    attempt = 0
    while True:
        try:
            return make_and_fit(attempt)
        except (KeyboardInterrupt, Preempted):
            raise
        except non_retryable:
            raise
        except retry_on as e:
            attempt += 1
            if attempt > max_restarts:
                log.error("giving up after %d restarts", max_restarts)
                raise
            log.warning(
                "attempt %d failed (%s: %s); restarting (%d/%d)",
                attempt - 1, type(e).__name__, e, attempt, max_restarts,
            )
            if backoff_s:
                time.sleep(backoff_s)


class Watchdog:
    """Detects stalled training (hung collective / dead host).

    The trainer calls ``heartbeat()`` once per completed step; once armed,
    if no heartbeat arrives within ``timeout_s``, ``on_stall`` fires
    (default: log loudly). The watchdog ARMS AT THE FIRST HEARTBEAT — the
    first step's jit compile is unbounded and must not trip a false "hung
    collective" alarm. The monitor is a daemon thread and never blocks
    training. ``timeout_s=None`` constructs a disabled no-op watchdog.
    """

    def __init__(
        self,
        timeout_s: Optional[float],
        on_stall: Optional[Callable[[float], None]] = None,
        poll_s: Optional[float] = None,
        action: str = "log",
    ):
        if action not in ("log", "abort"):
            raise ValueError(f"unknown watchdog action {action!r}")
        self.timeout_s = timeout_s
        if on_stall is not None:
            self.on_stall = on_stall
        elif action == "abort":
            self.on_stall = self._abort_on_stall
        else:
            self.on_stall = self._default_on_stall
        self._poll_s = (
            poll_s if poll_s is not None
            else min((timeout_s or 40.0) / 4, 10.0)
        )
        self._last: Optional[float] = None   # None until armed
        self._stop = threading.Event()
        self._fired = False
        self._thread: Optional[threading.Thread] = None

    @staticmethod
    def _default_on_stall(elapsed: float) -> None:
        log.error(
            "watchdog: no step completed for %.1fs — suspect hung "
            "collective or dead peer host", elapsed,
        )

    @staticmethod
    def _abort_on_stall(elapsed: float) -> None:
        """Kill the process so the (cross-process) supervisor restarts it.

        A hung collective cannot be recovered in-process — the device queue
        is wedged — so detection must feed the restart loop: SIGABRT takes
        the whole process down and the supervisor (re-run of train.py, or
        an external scheduler) resumes from the latest checkpoint.
        """
        import os

        log.error(
            "watchdog: no step completed for %.1fs — aborting for "
            "supervisor restart (hung collective / dead peer host)", elapsed,
        )
        os.kill(os.getpid(), signal.SIGABRT)

    def heartbeat(self) -> None:
        self._last = time.monotonic()
        self._fired = False

    @property
    def stalled(self) -> bool:
        return self._fired

    def _run(self) -> None:
        while not self._stop.wait(self._poll_s):
            if self._last is None:
                continue  # not armed: first step still compiling
            elapsed = time.monotonic() - self._last
            if elapsed > self.timeout_s and not self._fired:
                self._fired = True
                try:
                    self.on_stall(elapsed)
                except Exception:
                    log.exception("watchdog on_stall callback failed")

    def __enter__(self) -> "Watchdog":
        if self.timeout_s is None:
            return self
        self._thread = threading.Thread(
            target=self._run, name="orion-watchdog", daemon=True
        )
        self._thread.start()
        return self

    def __exit__(self, *exc) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
