"""Deprecated compatibility shim: the fault-tolerance machinery moved to
``orion_tpu.runtime.fault`` so the serving stack can share it (preemption
drains, the stall watchdog, fault injection, run_with_restarts). Import
from there; this shim lasts one release and warns on import.
"""

import warnings

from orion_tpu.runtime.fault import (  # noqa: F401
    Preempted,
    PreemptionHandler,
    Watchdog,
    run_with_restarts,
)

warnings.warn(
    "orion_tpu.train.fault moved to orion_tpu.runtime.fault; this shim "
    "will be removed next release",
    DeprecationWarning,
    stacklevel=2,
)

__all__ = ["Preempted", "PreemptionHandler", "Watchdog", "run_with_restarts"]
