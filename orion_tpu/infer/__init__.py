"""Inference runtime: paged KV cache, prefill/decode, continuous batching.

TPU-native equivalent of the reference's ``inference/generate.py`` with
continuous batching (BASELINE.json:11; SURVEY.md §4 stack B): a fixed-size
paged KV-cache pool keeps every device shape static for XLA, prefill and
decode are separate jit programs, and a host-side admission/scheduler loop
streams requests in and tokens out.
"""

from orion_tpu.infer.engine import InferenceEngine, Request
from orion_tpu.infer.kv_cache import PageAllocator, init_cache
from orion_tpu.infer.prefix_cache import PrefixCache
from orion_tpu.infer.sampling import sample

__all__ = [
    "InferenceEngine",
    "Request",
    "PageAllocator",
    "PrefixCache",
    "init_cache",
    "sample",
]
