"""Inference runtime: paged KV cache, prefill/decode, continuous batching.

TPU-native equivalent of the reference's ``inference/generate.py`` with
continuous batching (BASELINE.json:11; SURVEY.md §4 stack B): a fixed-size
paged KV-cache pool keeps every device shape static for XLA, prefill and
decode are separate jit programs, and a host-side admission/scheduler loop
streams requests in and tokens out. With ``inference.chunked_prefill`` the
two programs fuse into a third: ``runner.mixed_step`` runs one decode
token per live slot plus a bounded prompt chunk per dispatch, so a prompt
burst can never stall in-flight decodes by more than the chunk budget.
With ``inference.speculative`` a host-side prompt-lookup proposer
(``spec_decode``) drafts continuation tokens and ``runner.verify_step``
scores every slot's drafts in one pass over the weights — up to
speculate_tokens+1 emitted tokens per dispatch on self-repetitive text,
greedy output byte-identical, sampled output distribution-preserving.
The engine itself is split into a scheduler face (``scheduler``:
Request lifecycle + admission policy) and a dispatch executor
(``executor``); ``router.Router`` fans requests across N engine replicas
with prefix-affinity placement, health circuit breakers and typed-outcome
failover (``router.replicas``).
"""

from orion_tpu.infer.engine import InferenceEngine, Request
from orion_tpu.infer.kv_cache import PageAllocator, init_cache
from orion_tpu.infer.prefix_cache import PrefixCache
from orion_tpu.infer.router import Router, RouterRequest
from orion_tpu.infer.runner import (
    decode_window,
    mixed_step,
    mixed_verify_step,
    prefill_step,
    verify_step,
)
from orion_tpu.infer.sampling import sample
from orion_tpu.infer.spec_decode import NgramProposer, propose_ngram

__all__ = [
    "InferenceEngine",
    "Request",
    "Router",
    "RouterRequest",
    "NgramProposer",
    "PageAllocator",
    "PrefixCache",
    "decode_window",
    "init_cache",
    "mixed_step",
    "mixed_verify_step",
    "prefill_step",
    "propose_ngram",
    "sample",
    "verify_step",
]
