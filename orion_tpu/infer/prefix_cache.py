"""Automatic prefix caching: a radix tree over token-ID sequences whose
nodes own immutable, refcounted KV-cache pages.

The serving stack's dominant production pattern — thousands of requests
sharing a system prompt / few-shot prefix — re-pays the full quadratic
prefill cost per request without this module. The paged KV pool
(infer/kv_cache.py) already gives page-granular ownership; this is the
vLLM/SGLang-style cache on top of it (PAPERS.md "Ragged Paged Attention"
stack B): when a request finishes (or is preempted), the FULL pages of its
context are inserted into a host-side radix tree keyed by token ids; a new
request matches the longest cached prefix at page granularity, maps those
pages into its page table (refcount++ — shared, never written), and
prefills only the uncached tail (runner.prefill_step's prefix plumbing).

Design notes:

- Page granularity everywhere: edges hold page-multiple token runs, so
  node SPLITS land on page boundaries and a matched node maps 1:1 onto
  pool pages. Partial tail pages are never cached (a request's own fresh
  page takes the tail), which is what keeps shared pages immutable — the
  one exception, a fully-cached context whose final-token KV slot must be
  rewritten by the first decode step, is handled by the engine with
  copy-on-write (kv_cache.copy_page) into a private page.
- Locks vs refcounts: ``node.lock`` counts live requests currently mapping
  the node's pages and PROPAGATES TO THE ROOT (locking a node locks its
  whole path), so ``lock == 0`` means "no locker at or below" and such
  nodes are safely evictable. Page refcounts (PageAllocator) are the
  ownership ground truth: the tree holds one ref per cached page, each
  mapping request one more.
- Eviction is LRU at PAGE granularity: trailing pages of the
  least-recently-used unlocked leaf go first (trimming the leaf's edge),
  so a hot prefix's head survives while its cold tail is reclaimed. The
  engine treats every unlocked cached page as reclaimable pool headroom —
  cache and live requests share one pool under the allocator's single
  accounting invariant.
- Host tier (optional): with a ``HostPagePool`` attached, eviction first
  DEMOTES pages — the engine's ``spill`` callback copies their bytes to
  host RAM in one batched d2h, and the tree entry becomes a ``HostPage``
  marker carrying the host slot id. The node's tokens stay matchable; a
  later hit on a host-resident path restores the bytes into fresh pool
  pages (one batched h2d, engine-side) and the markers flip back to
  device ids via ``promote_path``. Within a node, device entries always
  form a PREFIX and host entries a SUFFIX: demote takes trailing device
  entries first, ``_split`` preserves the property per half, and insert
  only creates all-device leaves. Discard (``_discard``) remains the
  fallback when the host tier is absent, full, or the spill fails —
  with no host pool attached every path below is byte-identical to the
  untiered tree.
"""

from __future__ import annotations

import itertools
from typing import Callable, Optional

from orion_tpu.infer.kv_cache import HostPagePool, PageAllocator


class HostPage:
    """A tree page entry whose KV bytes live in the host tier: ``hid`` is
    the ``HostPagePool`` slot (the tree holds one ref on it). Appears in
    ``_Node.pages`` wherever a demoted page used to sit."""

    __slots__ = ("hid",)

    def __init__(self, hid: int):
        self.hid = hid

    def __repr__(self):  # debugging aid only
        return f"HostPage({self.hid})"


def _n_device(node: "_Node") -> int:
    """Device-resident entries in ``node.pages`` (they form a prefix)."""
    return sum(1 for p in node.pages if not isinstance(p, HostPage))


class _Node:
    """One radix-tree edge+node: ``key`` is the page-multiple token run on
    the edge INTO this node, ``pages`` the pool pages holding its KV."""

    __slots__ = ("key", "pages", "children", "parent", "lock", "stamp")

    def __init__(self, key: tuple, pages: list, parent: Optional["_Node"]):
        self.key = key                  # tuple[int, ...], len == len(pages)*psz
        self.pages = pages              # list[int] pool page ids
        # Children keyed by their edge's FIRST PAGE of tokens: siblings may
        # share a first token yet diverge inside the page, so a first-token
        # key would collide; a full first page is unique among siblings
        # (two edges sharing a whole page get merged by the split walk).
        self.children: dict[tuple, _Node] = {}
        self.parent = parent
        self.lock = 0                   # live requests at/below this node
        self.stamp = 0                  # LRU clock

    def __repr__(self):  # debugging aid only
        return (
            f"_Node(pages={self.pages}, lock={self.lock}, "
            f"children={len(self.children)})"
        )


class PrefixCache:
    """Host-side radix tree of cached KV pages (see module docstring)."""

    def __init__(
        self,
        page_size: int,
        alloc: PageAllocator,
        host_pool: Optional[HostPagePool] = None,
        spill: Optional[Callable[[list], Optional[list]]] = None,
    ):
        self.psz = page_size
        self.alloc = alloc
        # Host tier seam: ``spill(pages) -> hids | None`` is the engine's
        # batched d2h (alloc host slots, gather, device_get, store); None
        # means the copy could not happen and eviction falls back to
        # discarding. Both None => the tree behaves exactly as before.
        self.host_pool = host_pool
        self.spill = spill
        self.root = _Node((), [], None)
        self._clock = itertools.count(1)
        self.total_pages = 0            # DEVICE pages owned by the tree
        self.host_pages = 0             # HostPage entries (host slots held)
        # O(1) evictable accounting for the scheduler hot path: DEVICE
        # pages in nodes with lock > 0 (lock propagates to the root, so a
        # 0->1 / 1->0 transition during the lock/unlock walk pins/unpins
        # exactly that node's device pages). Kept in sync by lock/unlock/
        # insert/evict/clear/promote_path; splits move pages between
        # equal-lock nodes (no change). Host entries are never pool
        # headroom, so they are excluded throughout.
        self.locked_pages = 0
        # token_paths() memo: the path SET only changes on insert/evict/
        # clear (splits preserve it), so the speculative proposer's
        # per-step read is amortized to a dict lookup between mutations.
        self._paths_version = 0
        self._paths_cache: Optional[tuple[int, list]] = None

    # -- internals ---------------------------------------------------------

    def _match_edge(self, node: _Node, tokens, i: int, max_pages: int) -> int:
        """Whole pages of ``node.key`` matching ``tokens[i:]`` (<= max_pages)."""
        psz = self.psz
        m = 0
        while (
            m < len(node.pages)
            and m < max_pages
            and i + (m + 1) * psz <= len(tokens)
            and node.key[m * psz:(m + 1) * psz]
            == tuple(tokens[i + m * psz:i + (m + 1) * psz])
        ):
            m += 1
        return m

    def _split(self, node: _Node, m: int) -> _Node:
        """Split ``node``'s edge after ``m`` pages; returns the new UPPER
        node. ``node`` keeps the lower part (so existing handles held by
        lockers stay valid) and the upper inherits the lock count — every
        locker of the lower part pins the whole edge."""
        psz = self.psz
        assert 0 < m < len(node.pages), (m, len(node.pages))
        upper = _Node(node.key[: m * psz], node.pages[:m], node.parent)
        upper.lock = node.lock
        upper.stamp = node.stamp
        node.parent.children[upper.key[:psz]] = upper
        node.key = node.key[m * psz:]
        node.pages = node.pages[m:]
        node.parent = upper
        upper.children[node.key[:psz]] = node
        return upper

    def _touch(self, node: _Node) -> None:
        stamp = next(self._clock)
        while node is not None:
            node.stamp = stamp
            node = node.parent

    def _walk(self):
        stack = [self.root]
        while stack:
            node = stack.pop()
            yield node
            stack.extend(node.children.values())

    # -- public API --------------------------------------------------------

    def held_pages(self):
        """Every DEVICE pool page a cache node currently holds, one yield
        per (node, page) reference — the public accounting surface the
        engine's pool invariant (assert_page_accounting) sums against,
        so refcount checks never couple to the tree's internals."""
        for node in self._walk():
            for p in node.pages:
                if not isinstance(p, HostPage):
                    yield p

    def held_host_pages(self):
        """Every host-tier slot a cache node currently holds, one yield
        per (node, HostPage) reference — the host half of the accounting
        surface (each yield is one tree ref on that ``HostPagePool``
        slot)."""
        for node in self._walk():
            for p in node.pages:
                if isinstance(p, HostPage):
                    yield p.hid

    def match(self, tokens, max_pages: int):
        """Longest cached page-granular prefix of ``tokens`` (capped at
        ``max_pages`` pages). Returns ``(pages, node)``: the shared page
        ids in order and a handle pinning them — the matched path is
        LOCKED against eviction until ``unlock(node)``. ``(([], None))``
        on a miss. The caller must ``alloc.retain`` any page it maps.

        With a host tier attached, entries may be ``HostPage`` markers:
        the tokens matched but the bytes live in host RAM. The engine
        either restores them (``promote_path`` flips the markers to fresh
        device ids under this match's lock) or unlocks and re-matches
        capped at the first host entry — it never maps a marker."""
        pages: list[int] = []
        node = self.root
        i = 0
        while max_pages > 0 and i + self.psz <= len(tokens):
            child = node.children.get(tuple(tokens[i:i + self.psz]))
            if child is None:
                break
            m = self._match_edge(child, tokens, i, max_pages)
            if m == 0:
                break
            if m < len(child.pages):
                child = self._split(child, m)
            pages.extend(child.pages)
            node = child
            i += m * self.psz
            max_pages -= m
        if node is self.root:
            return [], None
        self._touch(node)
        self.lock(node)
        return pages, node

    def peek(self, tokens, max_pages: int) -> int:
        """Pages of the longest cached page-granular prefix of ``tokens``
        (capped at ``max_pages``) — the READ-ONLY twin of ``match()``:
        no locks taken, no LRU stamps touched, no edge splits. The
        multi-replica router probes every replica's tree per placement
        (infer/router.py prefix affinity), and a probe must never mutate
        a tree it then routes AWAY from."""
        return self.peek_tiered(tokens, max_pages)[0]

    def peek_tiered(self, tokens, max_pages: int) -> tuple[int, int, int]:
        """Read-only tiered probe: ``(matched, host, first_host)`` where
        ``matched`` is ``peek()``'s page count, ``host`` how many of those
        entries are host-resident, and ``first_host`` the flat index of
        the first host entry (== ``matched`` when the whole match is
        device-resident). The router's affinity probe uses this so a
        replica holding the prefix only in host RAM still advertises the
        match — host-warm beats cold — while the engine's own probe can
        apply the break-even threshold to the host span."""
        node = self.root
        i = 0
        matched = 0
        host = 0
        first_host = -1
        while max_pages > 0 and i + self.psz <= len(tokens):
            child = node.children.get(tuple(tokens[i:i + self.psz]))
            if child is None:
                break
            m = self._match_edge(child, tokens, i, max_pages)
            if m == 0:
                break
            for k in range(m):
                if isinstance(child.pages[k], HostPage):
                    host += 1
                    if first_host < 0:
                        first_host = matched + k
            matched += m
            i += m * self.psz
            max_pages -= m
            if m < len(child.pages):
                break   # match ends inside this edge: nothing deeper
            node = child
        if first_host < 0:
            first_host = matched
        return matched, host, first_host

    def lock(self, node: _Node) -> None:
        while node is not None:
            if node.lock == 0:
                self.locked_pages += _n_device(node)
            node.lock += 1
            node = node.parent

    def unlock(self, node: _Node) -> None:
        while node is not None:
            assert node.lock > 0
            node.lock -= 1
            if node.lock == 0:
                self.locked_pages -= _n_device(node)
            node = node.parent

    def insert(self, tokens, pages: list) -> int:
        """Cache ``pages`` (full pages backing ``tokens``, contiguous from
        position 0; ``len(tokens) == len(pages) * page_size``). Ranges the
        tree already holds are deduplicated in favor of the existing
        pages; novel pages are RETAINED (the tree takes its own ref), so
        the caller releases its refs on ALL of ``pages`` afterwards either
        way. Returns the number of pages newly added."""
        psz = self.psz
        assert len(tokens) == len(pages) * psz, (len(tokens), len(pages))
        node = self.root
        i = 0
        while i + psz <= len(tokens):
            child = node.children.get(tuple(tokens[i:i + psz]))
            if child is None:
                break
            m = self._match_edge(child, tokens, i, len(pages) - i // psz)
            if m == 0:
                break
            if m < len(child.pages):
                child = self._split(child, m)
            node = child
            i += m * psz
        added = len(pages) - i // psz
        if added:
            key = tuple(tokens[i:])
            kept = pages[i // psz:]
            for p in kept:
                self.alloc.retain(p)
            leaf = _Node(key, list(kept), node)
            node.children[key[:psz]] = leaf
            node = leaf
            self.total_pages += added
            self._paths_version += 1
        self._touch(node)
        return added

    def token_paths(self, max_paths: int = 64):
        """Root-to-leaf token sequences currently cached, most recently
        used first (capped at ``max_paths``). Draft source for
        speculative decoding (infer/spec_decode.py): a cached
        system-prompt + answer path predicts continuations for requests
        sharing the prefix, so the n-gram proposer can draft across
        requests, not just from a request's own history. Read-only — no
        locks taken, no stamps touched. Memoized between structural
        mutations (insert/evict/clear), so the per-decode-step call costs
        a version check, not a tree walk; recency ORDER within the memo
        window is the mutation-time order, which is draft-priority
        fidelity enough for a fallback source."""
        if (
            self._paths_cache is not None
            and self._paths_cache[0] == self._paths_version
        ):
            return self._paths_cache[1]
        paths: list[tuple[int, tuple]] = []

        def walk(node: _Node, prefix: tuple) -> None:
            run = prefix + node.key
            if not node.children and run:
                paths.append((node.stamp, run))
                return
            for child in node.children.values():
                walk(child, run)

        walk(self.root, ())
        paths.sort(key=lambda sp: -sp[0])
        out = [p for _, p in paths[:max_paths]]
        self._paths_cache = (self._paths_version, out)
        return out

    def evictable_pages(self) -> int:
        """Device pages reclaimable right now: every device page in a
        subtree no live request has locked. O(1) — the scheduler consults
        this once per admission candidate per step (locks propagate to
        the root, so the locked/unlocked page split is maintained
        incrementally)."""
        return self.total_pages - self.locked_pages

    def evict(self, n: int) -> int:
        """Free up to ``n`` device pages back to the allocator, LRU-first
        at page granularity. With a host tier attached the pages are
        DEMOTED (bytes spilled to host RAM, tokens stay matchable) before
        any are discarded outright; without one — or when the spill
        fails — eviction discards exactly as before. Returns the number
        of device pages actually freed either way."""
        freed = self.demote(n) if (
            self.host_pool is not None and self.spill is not None
        ) else 0
        if freed < n:
            freed += self._discard(n - freed)
        return freed

    def demote(self, n: int) -> int:
        """Move up to ``n`` of the coldest unlocked device pages to the
        host tier: ONE ``spill`` callback copies their bytes (the batched
        d2h lives engine-side), then each tree entry flips to a
        ``HostPage`` marker and the device page returns to the allocator.
        Trailing device entries of the coldest nodes go first — stamps
        propagate to the root, so descendants demote before ancestors and
        every node keeps its device-prefix/host-suffix shape. Token paths
        are unchanged (no ``_paths_version`` bump: the same sequences
        still match). Returns device pages freed; 0 when the tier is
        absent, out of room after its own LRU eviction, or the spill
        declines."""
        hp = self.host_pool
        if hp is None or self.spill is None or n <= 0:
            return 0
        if hp.free_slots < n:
            self.evict_host(n - hp.free_slots)
        want = min(n, hp.free_slots)
        if want <= 0:
            return 0
        victims: list[tuple[_Node, int, int]] = []
        nodes = sorted(
            (
                nd for nd in self._walk()
                if nd is not self.root and nd.lock == 0
            ),
            key=lambda nd: nd.stamp,
        )
        for nd in nodes:
            for idx in range(_n_device(nd) - 1, -1, -1):
                victims.append((nd, idx, nd.pages[idx]))
                if len(victims) == want:
                    break
            if len(victims) == want:
                break
        if not victims:
            return 0
        hids = self.spill([p for _, _, p in victims])
        if hids is None:
            return 0
        assert len(hids) == len(victims), (len(hids), len(victims))
        for (nd, idx, page), hid in zip(victims, hids):
            nd.pages[idx] = HostPage(hid)
            self.alloc.release(page)
            self.total_pages -= 1
            self.host_pages += 1
        return len(victims)

    def evict_host(self, n: int) -> int:
        """Free up to ``n`` host-tier slots, LRU-first: trailing
        ``HostPage`` entries of the least-recently-used unlocked leaves
        are dropped (their tokens stop matching — this is the tier's own
        capacity eviction, the end of the line for those bytes). Stops at
        a device entry: host entries are always the suffix, so the trim
        never strands a device page behind a hole. Returns slots freed."""
        psz = self.psz
        freed = 0
        while freed < n:
            leaves = [
                nd for nd in self._walk()
                if nd.lock == 0 and nd.pages and not nd.children
                and isinstance(nd.pages[-1], HostPage)
            ]
            if not leaves:
                break
            leaf = min(leaves, key=lambda nd: nd.stamp)
            first = leaf.key[:psz]
            while (
                leaf.pages and freed < n
                and isinstance(leaf.pages[-1], HostPage)
            ):
                entry = leaf.pages.pop()
                leaf.key = leaf.key[: len(leaf.pages) * psz]
                self.host_pool.release(entry.hid)
                self.host_pages -= 1
                freed += 1
            if not leaf.pages:
                del leaf.parent.children[first]
        if freed:
            self._paths_version += 1
        return freed

    def _discard(self, n: int) -> int:
        """Free up to ``n`` device pages by dropping LRU leaf tails
        outright — the untiered eviction path, byte-identical to the
        pre-tier ``evict``. Host entries encountered on the way out (the
        leaf's suffix pops first) release their slots without counting
        toward ``n``: a discarded token range must not leave orphaned
        host bytes behind."""
        psz = self.psz
        freed = 0
        while freed < n:
            leaves = [
                nd for nd in self._walk()
                if nd.lock == 0 and nd.pages and not nd.children
            ]
            if not leaves:
                break
            leaf = min(leaves, key=lambda nd: nd.stamp)
            first = leaf.key[:psz]
            while leaf.pages and freed < n:
                page = leaf.pages.pop()
                leaf.key = leaf.key[: len(leaf.pages) * psz]
                if isinstance(page, HostPage):
                    self.host_pool.release(page.hid)
                    self.host_pages -= 1
                else:
                    self.alloc.release(page)
                    self.total_pages -= 1
                    freed += 1
            if not leaf.pages:
                del leaf.parent.children[first]
        if freed:
            self._paths_version += 1
        return freed

    def promote_path(self, node: _Node, new_pages: dict) -> None:
        """Flip restored entries on the root->``node`` path from
        ``HostPage`` markers back to device page ids. ``new_pages`` maps
        flat match indices (positions in the page list ``match()``
        returned) to freshly-allocated device pages whose bytes the
        engine has already scattered in. Must be called under the match's
        lock: every mutation path (demote / evict_host / _discard /
        clear-then-orphan) skips locked nodes, so the path cannot have
        shifted since the match. The tree's host-slot refs are released
        here; the engine releases its own in-flight refs separately."""
        path: list[_Node] = []
        nd = node
        while nd is not None and nd is not self.root:
            path.append(nd)
            nd = nd.parent
        path.reverse()
        done = 0
        i = 0
        for nd in path:
            for j in range(len(nd.pages)):
                if i in new_pages:
                    entry = nd.pages[j]
                    assert isinstance(entry, HostPage), (i, entry)
                    nd.pages[j] = new_pages[i]
                    self.host_pool.release(entry.hid)
                    self.host_pages -= 1
                    self.total_pages += 1
                    if nd.lock > 0:
                        self.locked_pages += 1
                    done += 1
                i += 1
        assert done == len(new_pages), (done, sorted(new_pages))

    def clear(self) -> int:
        """Drop the whole cache (releases every tree-owned page ref, both
        tiers); returns the number of DEVICE pages released. Locked pages
        survive via their requests' refs but their nodes are forgotten."""
        released = 0
        for node in self._walk():
            if node is self.root:
                continue
            for p in node.pages:
                if isinstance(p, HostPage):
                    self.host_pool.release(p.hid)
                else:
                    self.alloc.release(p)
                    released += 1
            # Orphaned nodes may still be unlocked later by live request
            # handles; empty page lists keep those walks (and the
            # locked_pages accounting) no-ops.
            node.pages = []
        self.root = _Node((), [], None)
        self.total_pages = 0
        self.locked_pages = 0
        self.host_pages = 0
        self._paths_version += 1
        self._paths_cache = None
        return released
