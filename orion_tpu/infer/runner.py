"""Cache-aware forward passes: prefill and decode over the paged KV pool.

The reference serves generation through prefill/decode phases over a KV
cache (BASELINE.json:11; SURVEY.md §4 stack B). TPU-native shape discipline:

  - ``prefill_step`` processes one prompt padded to a static bucket length
    (one jit specialization per bucket), runs ordinary causal (flash)
    attention, and scatters the computed K/V pages into the pool.
  - ``decode_step`` advances ALL batch slots one token in a single program of
    fully static shape: scatter the new token's K/V into each sequence's
    current page, gather each sequence's pages, and attend under a
    length mask. Inactive slots point at the reserved scratch page 0 and are
    masked by seq_len only — no dynamic batch shapes anywhere.

Model math is shared with training via models.transformer.qkv_proj /
out_proj / mlp_or_moe — the cache runner only changes what attention reads.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from orion_tpu.config import ModelConfig
from orion_tpu.models.transformer import (
    Params,
    _norm,
    embed,
    mlp_or_moe,
    out_proj,
    qkv_proj,
    unembed,
)
from orion_tpu.ops import attention
from orion_tpu.ops.attention import attention_xla

Cache = dict[str, jax.Array]


def _layer_iter(params: Params, cache: Cache, cfg: ModelConfig, body):
    """Run ``body(x, bp, k_pool_l, v_pool_l) -> (x, k_pool_l, v_pool_l)``
    over all layers, scanning when the params are stacked."""

    def scan_body(x, xs):
        bp, kl, vl = xs
        x, kl, vl = body(x, bp, kl, vl)
        return x, (kl, vl)

    def run(x):
        if cfg.scan_layers:
            x, (new_k, new_v) = jax.lax.scan(
                scan_body, x, (params["blocks"], cache["k"], cache["v"])
            )
        else:
            ks, vs = [], []
            for i, bp in enumerate(params["blocks"]):
                x, kl, vl = body(x, bp, cache["k"][i], cache["v"][i])
                ks.append(kl)
                vs.append(vl)
            new_k, new_v = jnp.stack(ks), jnp.stack(vs)
        return x, {"k": new_k, "v": new_v}

    return run


def prefill_step(
    params: Params,
    cache: Cache,
    tokens: jax.Array,        # [1, S_pad]  (padded prompt)
    length: jax.Array,        # scalar int32: true prompt length
    pages: jax.Array,         # [S_pad // page_size] int32 page ids
    cfg: ModelConfig,
) -> tuple[jax.Array, Cache]:
    """Prefill one prompt; returns (next-token logits [V], updated cache)."""
    S_pad = tokens.shape[1]
    psz = cache["k"].shape[2]
    n_pages = S_pad // psz
    positions = jnp.broadcast_to(
        jnp.arange(S_pad, dtype=jnp.int32), (1, S_pad)
    )

    def body(x, bp, kl, vl):
        h = _norm(x, bp["attn_norm"], cfg)
        q, k, v = qkv_proj(h, bp["attn"], cfg, positions)
        out = attention(q, k, v, causal=True, impl=cfg.kernels)
        x = x + out_proj(out, bp["attn"], cfg)
        h2 = _norm(x, bp["mlp_norm"], cfg)
        y, _ = mlp_or_moe(h2, bp, cfg)
        x = x + y
        # Scatter this layer's K/V pages into the pool. Positions beyond
        # `length` hold garbage from the padding — decode masks them out
        # via seq_lens, and the next real token overwrites its slot.
        K, H = k.shape[2], k.shape[3]
        kl = kl.at[pages].set(k[0].reshape(n_pages, psz, K, H))
        vl = vl.at[pages].set(v[0].reshape(n_pages, psz, K, H))
        return x, kl, vl

    x = embed(params, tokens, positions, cfg)
    x, new_cache = _layer_iter(params, cache, cfg, body)(x)
    # Only the last real position's logits are needed; slice before the LM
    # head so the vocab matmul is [1, 1, V], not [1, S_pad, V].
    x_last = jax.lax.dynamic_slice_in_dim(x, length - 1, 1, axis=1)
    logits = unembed(params, x_last, cfg)     # [1, 1, V]
    return logits[0, 0], new_cache


def decode_step(
    params: Params,
    cache: Cache,
    tokens: jax.Array,        # [B, 1]  newest token per slot
    seq_lens: jax.Array,      # [B] int32: tokens already in cache per slot
    page_table: jax.Array,    # [B, pages_per_seq] int32
    cfg: ModelConfig,
) -> tuple[jax.Array, Cache]:
    """One decode step for every slot; returns (logits [B, V], cache)."""
    B = tokens.shape[0]
    psz = cache["k"].shape[2]
    P = page_table.shape[1]
    positions = seq_lens[:, None]              # new token's position [B, 1]
    batch_idx = jnp.arange(B)

    page_idx = page_table[batch_idx, seq_lens // psz]   # [B]
    offset = seq_lens % psz                              # [B]
    # KV positions valid after the write: arange <= seq_len.
    kv_mask = (
        jnp.arange(P * psz, dtype=jnp.int32)[None, None, :]
        <= seq_lens[:, None, None]
    )                                                    # [B, 1, P*psz]

    from orion_tpu.ops._dispatch import resolve_impl

    use_pallas, interpret = resolve_impl(cfg.kernels)

    def body(x, bp, kl, vl):
        h = _norm(x, bp["attn_norm"], cfg)
        q, k, v = qkv_proj(h, bp["attn"], cfg, positions)
        K, H = k.shape[2], k.shape[3]
        kl = kl.at[page_idx, offset].set(k[:, 0])
        vl = vl.at[page_idx, offset].set(v[:, 0])
        if use_pallas:
            # Ragged paged-attention kernel: walks the page table directly,
            # compute proportional to actual context lengths.
            from orion_tpu.ops.pallas.paged_attention import paged_attention

            out = paged_attention(
                q[:, 0], kl, vl, page_table, seq_lens,
                logit_softcap=cfg.attn_logit_softcap,
                interpret=interpret,
            )[:, None]
        else:
            k_ctx = kl[page_table].reshape(B, P * psz, K, H)
            v_ctx = vl[page_table].reshape(B, P * psz, K, H)
            out = attention_xla(q, k_ctx, v_ctx, causal=False, mask=kv_mask)
        x = x + out_proj(out, bp["attn"], cfg)
        h2 = _norm(x, bp["mlp_norm"], cfg)
        y, _ = mlp_or_moe(h2, bp, cfg)
        return x + y, kl, vl

    x = embed(params, tokens, positions, cfg)
    x, new_cache = _layer_iter(params, cache, cfg, body)(x)
    logits = unembed(params, x, cfg)          # [B, 1, V]
    return logits[:, 0], new_cache
