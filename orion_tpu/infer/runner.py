"""Cache-aware forward passes: prefill and decode over the paged KV pool.

The reference serves generation through prefill/decode phases over a KV
cache (BASELINE.json:11; SURVEY.md §4 stack B). TPU-native shape discipline:

  - ``prefill_step`` processes a batch of same-bucket prompts padded to a
    static bucket length (one jit specialization per bucket/batch pair),
    runs ordinary causal (flash) attention, and scatters the computed K/V
    pages into the pool.
  - ``decode_window`` advances ALL batch slots ``n_steps`` tokens in a
    single program of fully static shape, sampling fused in: scatter each
    new token's K/V into each sequence's current page, attend via the
    ragged paged kernel (or a masked gather under xla), sample, feed the
    token back — one dispatch and ONE host fetch per window, which matters
    because a device->host fetch costs tens of ms through a remote-chip
    tunnel while a dispatch costs ~1 ms.

Memory discipline (the part that makes decode bandwidth-bound instead of
copy-bound): the KV pool is a single flat [L*num_pages, K, psz, H] array
(heads-major pages — see kv_cache.py) carried through the layer scan, and
every update is a sparse in-place write at rows ``l*num_pages + page`` —
performed INSIDE the paged-attention kernel on the pallas path, because an
external scatter feeding a custom call makes XLA materialize a fresh pool
copy per layer. Carrying per-layer pool slices as scan xs/ys instead makes
XLA rewrite the whole pool every step (measured 5.4 GB/step on the 1B
bench model — 20x the useful traffic).

Model math is shared with training via models.transformer.qkv_proj /
out_proj / mlp_or_moe — the cache runner only changes what attention reads.
Inactive batch slots point at the reserved scratch page 0 and are masked by
seq_lens alone — no dynamic batch shapes anywhere.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from orion_tpu.config import ModelConfig
from orion_tpu.models.transformer import (
    Params,
    _norm,
    embed,
    mlp_or_moe,
    out_proj,
    qkv_proj,
    unembed,
)
from orion_tpu.ops import attention
from orion_tpu.ops.attention import attention_xla

Cache = dict[str, jax.Array]


def _scan_layers(params: Params, cfg: ModelConfig, body, init_carry):
    """Run ``body(carry, bp, l, j) -> carry`` over all layers.

    ``l`` is the layer index (traced under scan, static ints otherwise);
    ``j`` is the STATIC pattern position (l % sliding_window_pattern, or 0
    without a pattern) — the sliding window is static in every kernel, so
    interleaved local/global models (Gemma-family) scan over GROUPS of
    ``pattern`` layers with one body call per static position.
    """
    L = cfg.n_layers
    pattern = cfg.window_pattern
    if cfg.scan_layers:
        if pattern is None:
            def scan_body(carry, xs):
                bp, l = xs
                return body(carry, bp, l, 0), None

            carry, _ = jax.lax.scan(
                scan_body, init_carry, (params["blocks"], jnp.arange(L))
            )
            return carry
        if L % pattern:
            raise ValueError(
                f"n_layers={L} must be divisible by "
                f"sliding_window_pattern={pattern}"
            )
        grouped = jax.tree.map(
            lambda a: a.reshape(L // pattern, pattern, *a.shape[1:]),
            params["blocks"],
        )

        def group_body(carry, xs):
            gbp, g = xs
            for j in range(pattern):
                carry = body(
                    carry, jax.tree.map(lambda a: a[j], gbp),
                    g * pattern + j, j,
                )
            return carry, None

        carry, _ = jax.lax.scan(
            group_body, init_carry, (grouped, jnp.arange(L // pattern))
        )
        return carry
    carry = init_carry
    for l, bp in enumerate(params["blocks"]):
        carry = body(carry, bp, l, l % pattern if pattern else 0)
    return carry


def _prefill_ctx(
    cache: Cache,
    tokens: jax.Array,
    lengths: jax.Array,
    pages: jax.Array,
    prefix_lens: Optional[jax.Array],
    prefix_pages: Optional[jax.Array],
    cfg: ModelConfig,
    paged_prefill: bool = False,
) -> dict:
    """Batch-level tensors the per-layer prefill body consumes (positions,
    segment ids, page arithmetic). Shared by whole-prompt prefill, the
    prefix-cache tail prefill, and the chunked-prefill rows of a mixed
    step — a prefill CHUNK is exactly a mid-sequence tail prefill that
    resumes at a page-aligned ``prefix_lens`` over already-written pages.

    ``paged_prefill`` (inference.paged_prefill, pallas path only) routes
    the P_pre > 0 layers through the blockwise paged-flash prefill kernel
    instead of the dense prefix gather + flash attention + scatter: the
    chunk's queries walk the paged history directly and the chunk's own
    pages are written in-kernel (aliased), so per-chunk HBM traffic is
    O(real context) instead of O(padded gather copy)."""
    from orion_tpu.ops._dispatch import resolve_impl

    Nb, S_pad = tokens.shape
    psz = cache["k"].shape[2]
    NP = cache["k"].shape[0] // cfg.n_layers
    quant = "k_scale" in cache
    P_pre = 0 if prefix_pages is None else prefix_pages.shape[1]
    use_pallas, interpret = resolve_impl(cfg.kernels)
    paged = bool(paged_prefill and P_pre and use_pallas and S_pad % psz == 0)
    kv_pos = kv_seg = None
    if P_pre:
        positions = prefix_lens[:, None] + jnp.arange(S_pad, dtype=jnp.int32)
        pre_idx = jnp.arange(P_pre * psz, dtype=jnp.int32)
        # Prefix kv positions are absolute [0, P_pre*psz); columns past a
        # row's own prefix are garbage -> segment id 0 (and, under SWA,
        # behind the window anyway for pages the engine mapped to scratch).
        kv_pos = jnp.concatenate(
            [jnp.broadcast_to(pre_idx[None], (Nb, P_pre * psz)), positions],
            axis=1,
        )
        seg = (
            jnp.arange(S_pad, dtype=jnp.int32)[None] < lengths[:, None]
        ).astype(jnp.int32)
        kv_seg = jnp.concatenate(
            [(pre_idx[None] < prefix_lens[:, None]).astype(jnp.int32), seg],
            axis=1,
        )
    else:
        positions = jnp.broadcast_to(
            jnp.arange(S_pad, dtype=jnp.int32), (Nb, S_pad)
        )
        # Ragged burst: rows shorter than the bucket mark their padding tail
        # with segment id 0 — the flash kernel SKIPS all-padding blocks, so a
        # mixed-length admission burst pays per-row actual-length compute in
        # one dispatch instead of bucket-padded compute per bucket.
        seg = (positions < lengths[:, None]).astype(jnp.int32)
    walk = None
    if paged:
        # Combined page walk for the paged-flash kernel: the row's prefix
        # pages, then the chunk's own pages (walk step P_pre + cb OWNS
        # chunk page cb — the kernel's fused write targets it).
        walk = jnp.concatenate([prefix_pages, pages], axis=1)
    return dict(
        Nb=Nb, S_pad=S_pad, psz=psz, NP=NP, n_pages=S_pad // psz,
        quant=quant, P_pre=P_pre, positions=positions, seg=seg,
        kv_pos=kv_pos, kv_seg=kv_seg, pages=pages,
        prefix_pages=prefix_pages, prefix_lens=prefix_lens,
        lengths=lengths, paged=paged, interpret=interpret, walk=walk,
    )


def _prefill_layer(
    x: jax.Array,
    cc: Cache,
    bp: Any,
    l,
    j: int,
    ctx: dict,
    cfg: ModelConfig,
    mesh: Optional[jax.sharding.Mesh],
) -> tuple[jax.Array, Cache]:
    """One transformer layer of (possibly mid-sequence) prefill: flash/xla
    attention over [gathered prefix pages + own K/V], then scatter the new
    K/V pages into the carried pool."""
    Nb, psz, NP = ctx["Nb"], ctx["psz"], ctx["NP"]
    n_pages, quant, P_pre = ctx["n_pages"], ctx["quant"], ctx["P_pre"]
    positions, seg = ctx["positions"], ctx["seg"]
    h = _norm(x, bp["attn_norm"], cfg)
    q, k, v = qkv_proj(h, bp["attn"], cfg, positions)
    if P_pre and ctx["paged"]:
        # Paged-flash prefill: the chunk's queries walk the paged history
        # in-kernel (no dense prefix gather) and the chunk's own pages
        # are written fused (no external scatter) — one kernel replaces
        # the whole gather/attend/scatter body below, O(real context)
        # HBM traffic per chunk.
        from orion_tpu.ops.pallas.paged_flash_prefill import (
            paged_flash_prefill,
        )

        res = paged_flash_prefill(
            q, cc["k"], cc["v"], ctx["walk"], ctx["prefix_lens"],
            ctx["lengths"], k, v,
            n_prefix_pages=P_pre, layer_base=l * NP,
            logit_softcap=cfg.attn_logit_softcap,
            window=cfg.layer_window(j), interpret=ctx["interpret"],
            k_scale=cc.get("k_scale"), v_scale=cc.get("v_scale"),
            mesh=mesh,
        )
        cc = dict(cc)
        if quant:
            out, cc["k"], cc["v"], cc["k_scale"], cc["v_scale"] = res
        else:
            out, cc["k"], cc["v"] = res
        a = out_proj(out, bp["attn"], cfg)
        if cfg.post_norms:
            a = _norm(a, bp["post_attn_norm"], cfg)
        x = x + a
        h2 = _norm(x, bp["mlp_norm"], cfg)
        y, _ = mlp_or_moe(h2, bp, cfg)
        if cfg.post_norms:
            y = _norm(y, bp["post_mlp_norm"], cfg)
        return x + y, cc
    if P_pre:
        # Gather this layer's cached prefix K/V pages from the pool
        # and attend tail queries over prefix + tail. [Nb, P_pre] page
        # rows -> [Nb, P_pre*psz, K, H] (heads-major pages).
        Kh, Hd = k.shape[2], k.shape[3]
        rows_pre = l * NP + ctx["prefix_pages"]
        k_pre = cc["k"][rows_pre].transpose(0, 1, 3, 2, 4)
        v_pre = cc["v"][rows_pre].transpose(0, 1, 3, 2, 4)
        if quant:
            ksc = cc["k_scale"][rows_pre][..., :psz]   # [Nb,P,K,psz]
            vsc = cc["v_scale"][rows_pre][..., :psz]
            k_pre = k_pre.astype(jnp.float32) * ksc.transpose(
                0, 1, 3, 2)[..., None]
            v_pre = v_pre.astype(jnp.float32) * vsc.transpose(
                0, 1, 3, 2)[..., None]
        k_pre = k_pre.reshape(Nb, P_pre * psz, Kh, Hd).astype(k.dtype)
        v_pre = v_pre.reshape(Nb, P_pre * psz, Kh, Hd).astype(v.dtype)
        out = attention(
            q,
            jnp.concatenate([k_pre, k], axis=1),
            jnp.concatenate([v_pre, v], axis=1),
            causal=True,
            q_segment_ids=seg, kv_segment_ids=ctx["kv_seg"],
            seg_pad_zero=True,
            q_positions=positions, kv_positions=ctx["kv_pos"],
            logit_softcap=cfg.attn_logit_softcap,
            window=cfg.layer_window(j),
            block_q=cfg.attn_block_q, block_kv=cfg.attn_block_kv,
            impl=cfg.kernels, mesh=mesh,
        )
    else:
        out = attention(
            q, k, v, causal=True,
            q_segment_ids=seg, kv_segment_ids=seg, seg_pad_zero=True,
            logit_softcap=cfg.attn_logit_softcap,
            window=cfg.layer_window(j),
            block_q=cfg.attn_block_q, block_kv=cfg.attn_block_kv,
            impl=cfg.kernels, mesh=mesh,
        )
    a = out_proj(out, bp["attn"], cfg)
    if cfg.post_norms:
        a = _norm(a, bp["post_attn_norm"], cfg)
    x = x + a
    h2 = _norm(x, bp["mlp_norm"], cfg)
    y, _ = mlp_or_moe(h2, bp, cfg)
    if cfg.post_norms:
        y = _norm(y, bp["post_mlp_norm"], cfg)
    x = x + y
    # Scatter this layer's K/V pages into the pool (in-place on the
    # carried flat pool). Positions beyond each row's `length` hold
    # garbage from the padding — decode masks them out via seq_lens,
    # and the next real token overwrites its slot.
    K, H = k.shape[2], k.shape[3]
    rows = l * NP + ctx["pages"]                 # [Nb, n_pages]
    cc = dict(cc)
    if quant:
        from orion_tpu.infer.kv_cache import quantize_kv

        # Per (token, head) int8 + f32 scale; scale pages land in the
        # first psz columns of the lanes-padded scale pool rows.
        k, ks = quantize_kv(k)               # [Nb,S,K,H] i8, [Nb,S,K]
        v, vs = quantize_kv(v)
        kspg = ks.reshape(Nb, n_pages, psz, K).transpose(0, 1, 3, 2)
        vspg = vs.reshape(Nb, n_pages, psz, K).transpose(0, 1, 3, 2)
        cc["k_scale"] = cc["k_scale"].at[rows, :, :psz].set(kspg)
        cc["v_scale"] = cc["v_scale"].at[rows, :, :psz].set(vspg)
    # Pool pages are [K, psz, H] (heads major, see kv_cache.py).
    kpages = k.reshape(Nb, n_pages, psz, K, H).transpose(0, 1, 3, 2, 4)
    vpages = v.reshape(Nb, n_pages, psz, K, H).transpose(0, 1, 3, 2, 4)
    cc["k"] = cc["k"].at[rows].set(kpages)
    cc["v"] = cc["v"].at[rows].set(vpages)
    return x, cc


def _prefill_logits(
    params: Params, x: jax.Array, lengths: jax.Array, cfg: ModelConfig
) -> jax.Array:
    """Next-token logits [Nb, V] off each row's last real position.

    Gathers before the LM head so the vocab matmul is [Nb, 1, V], not
    [Nb, S_pad, V]."""
    idx = (lengths - 1).astype(jnp.int32)[:, None, None]
    x_last = jnp.take_along_axis(
        x, jnp.broadcast_to(idx, (x.shape[0], 1, x.shape[-1])), axis=1
    )
    return unembed(params, x_last, cfg)[:, 0]


def prefill_step(
    params: Params,
    cache: Cache,
    tokens: jax.Array,        # [Nb, S_pad]  (padded prompts, one bucket)
    lengths: jax.Array,       # [Nb] int32: true prompt lengths
    pages: jax.Array,         # [Nb, S_pad // page_size] int32 page ids
    prefix_lens: Optional[jax.Array] = None,   # [Nb] int32 cached tokens
    prefix_pages: Optional[jax.Array] = None,  # [Nb, P_pre] int32 page ids
    *,
    cfg: ModelConfig,
    mesh: Optional[jax.sharding.Mesh] = None,
    paged_prefill: bool = False,
) -> tuple[jax.Array, Cache]:
    """Prefill a batch of same-bucket prompts in ONE dispatch.

    ``mesh`` (tensor-parallel serving) makes the flash kernel run under a
    head-sharded shard_map instead of gathering tp-sharded q/k/v; the
    dense matmuls partition from the params' shardings as usual.

    Prefix caching (``prefix_pages`` with static width P_pre > 0): rows
    start MID-SEQUENCE — ``tokens`` holds only the uncached tail,
    positions (RoPE / learned PE) begin at each row's ``prefix_lens``, and
    attention runs tail queries against the CACHED prefix K/V (gathered
    from the pool pages per layer) concatenated with the tail's own K/V.
    Explicit q/kv positions + segment ids carry the mid-sequence causal
    structure through both kernel paths (the flash kernel's segment
    masking skips all-padding prefix blocks for rows with shorter
    matches). With P_pre == 0 the program is byte-identical to the
    pre-prefix-cache prefill. The tail's page scatter is unchanged: cached
    prefixes are page-aligned, so tail token t keeps in-page offset
    ``t % page_size``. Chunked prefill (mixed_step) reuses this row type
    unchanged: a chunk is a tail prefill resuming at its chunk cursor.

    Returns (next-token logits [Nb, V], updated cache). Rows are independent
    sequences (separate page sets); a burst of admissions is served by a
    single program instead of Nb serialized dispatches (VERDICT r2 item 4).
    Padding rows (engine rounds the batch up to a bucket size) carry
    all-zero page lists: their K/V lands on the reserved scratch page 0 and
    is never read.
    """
    ctx = _prefill_ctx(
        cache, tokens, lengths, pages, prefix_lens, prefix_pages, cfg,
        paged_prefill=paged_prefill,
    )

    def body(carry, bp, l, j):
        x, cc = carry
        return _prefill_layer(x, cc, bp, l, j, ctx, cfg, mesh)

    x = embed(params, tokens, ctx["positions"], cfg)
    x, cache = _scan_layers(params, cfg, body, (x, dict(cache)))
    return _prefill_logits(params, x, lengths, cfg), cache


def _decode_ctx(
    cache: Cache,
    write_pos: jax.Array,
    page_table: jax.Array,
    cfg: ModelConfig,
) -> dict:
    """Batch-level tensors the per-layer decode body consumes."""
    B = write_pos.shape[0]
    kp = cache["k"]
    psz = kp.shape[2]
    NP = kp.shape[0] // cfg.n_layers
    P = page_table.shape[1]
    batch_idx = jnp.arange(B)
    page_idx = page_table[batch_idx, write_pos // psz]   # [B]
    offset = write_pos % psz                             # [B]
    # KV positions valid after the write: arange <= write_pos; the
    # (per-layer) sliding window narrows it inside the body.
    kv_arange = jnp.arange(P * psz, dtype=jnp.int32)[None, None, :]
    kv_base_mask = kv_arange <= write_pos[:, None, None]  # [B, 1, P*psz]

    from orion_tpu.ops._dispatch import resolve_impl

    use_pallas, interpret = resolve_impl(cfg.kernels)
    return dict(
        B=B, psz=psz, NP=NP, P=P, quant="k_scale" in cache,
        write_pos=write_pos, page_table=page_table,
        positions=write_pos[:, None], page_idx=page_idx, offset=offset,
        kv_arange=kv_arange, kv_base_mask=kv_base_mask,
        use_pallas=use_pallas, interpret=interpret,
    )


def _decode_layer(
    x: jax.Array,
    cc: Cache,
    bp: Any,
    l,
    j: int,
    ctx: dict,
    cfg: ModelConfig,
    mesh: Optional[jax.sharding.Mesh],
) -> tuple[jax.Array, Cache]:
    """One transformer layer of single-token decode: fused-write ragged
    paged attention (pallas) or scatter + masked pool gather (xla).

    LOCKSTEP: _verify_layer is this body generalized from 1 to W queries
    per slot, branch for branch (its pallas branch is the multi-query
    ragged paged-attention kernel, its xla branch this scatter+gather
    with a W dim), and speculative byte-identity (greedy spec-on ==
    spec-off, enforced by tests/test_spec_decode.py across kv_quant /
    SWA / prefix-cache compositions) holds only while the two agree
    op-for-op on the write/gather/dequant/mask math — fix both together.
    """
    B, psz, NP, P = ctx["B"], ctx["psz"], ctx["NP"], ctx["P"]
    quant = ctx["quant"]
    write_pos, page_table = ctx["write_pos"], ctx["page_table"]
    page_idx, offset = ctx["page_idx"], ctx["offset"]
    cc = dict(cc)
    win = cfg.layer_window(j)
    h = _norm(x, bp["attn_norm"], cfg)
    q, k, v = qkv_proj(h, bp["attn"], cfg, ctx["positions"])
    K, H = k.shape[2], k.shape[3]
    if ctx["use_pallas"]:
        # Ragged paged-attention kernel: walks the page table directly
        # (compute proportional to actual context lengths) and writes
        # the new token's K/V itself — the pool stays in place through
        # the kernel's input/output aliasing, where an external scatter
        # feeding the kernel would cost a pool copy per layer. Under
        # kv_quant the kernel also dequantizes in place and quantizes
        # the written token (scales aliased alongside).
        from orion_tpu.ops.pallas.paged_attention import paged_attention

        res = paged_attention(
            q[:, 0], cc["k"], cc["v"], page_table, write_pos,
            layer_base=l * NP,
            k_new=k[:, 0], v_new=v[:, 0],
            logit_softcap=cfg.attn_logit_softcap,
            window=win,
            interpret=ctx["interpret"],
            k_scale=cc.get("k_scale"),
            v_scale=cc.get("v_scale"),
            mesh=mesh,
        )
        if quant:
            out, cc["k"], cc["v"], cc["k_scale"], cc["v_scale"] = res
        else:
            out, cc["k"], cc["v"] = res
        out = out[:, None]
    else:
        rows = l * NP + page_idx
        if quant:
            from orion_tpu.infer.kv_cache import quantize_kv

            kq, ks = quantize_kv(k[:, 0])    # [B,K,H] i8, [B,K]
            vq, vs = quantize_kv(v[:, 0])
            cc["k"] = cc["k"].at[rows, :, offset].set(kq)
            cc["v"] = cc["v"].at[rows, :, offset].set(vq)
            cc["k_scale"] = cc["k_scale"].at[rows, :, offset].set(ks)
            cc["v_scale"] = cc["v_scale"].at[rows, :, offset].set(vs)
        else:
            cc["k"] = cc["k"].at[rows, :, offset].set(k[:, 0])
            cc["v"] = cc["v"].at[rows, :, offset].set(v[:, 0])
        # [B, P, K, psz, H] -> [B, P*psz, K, H] padded-context gather.
        k_ctx = cc["k"][l * NP + page_table].transpose(0, 1, 3, 2, 4)
        v_ctx = cc["v"][l * NP + page_table].transpose(0, 1, 3, 2, 4)
        if quant:
            # Dequantize the gathered context: [B, P, psz, K] scales.
            ksc = cc["k_scale"][l * NP + page_table][..., :psz]
            vsc = cc["v_scale"][l * NP + page_table][..., :psz]
            k_ctx = k_ctx.astype(jnp.float32) * ksc.transpose(
                0, 1, 3, 2)[..., None]
            v_ctx = v_ctx.astype(jnp.float32) * vsc.transpose(
                0, 1, 3, 2)[..., None]
            k_ctx = k_ctx.astype(q.dtype)
            v_ctx = v_ctx.astype(q.dtype)
        k_ctx = k_ctx.reshape(B, P * psz, K, H)
        v_ctx = v_ctx.reshape(B, P * psz, K, H)
        kv_mask = ctx["kv_base_mask"]
        if win is not None:
            kv_mask = kv_mask & (
                ctx["kv_arange"] >= (write_pos - win + 1)[:, None, None]
            )
        out = attention_xla(
            q, k_ctx, v_ctx, causal=False, mask=kv_mask,
            logit_softcap=cfg.attn_logit_softcap,
        )
    a = out_proj(out, bp["attn"], cfg)
    if cfg.post_norms:
        a = _norm(a, bp["post_attn_norm"], cfg)
    x = x + a
    h2 = _norm(x, bp["mlp_norm"], cfg)
    y, _ = mlp_or_moe(h2, bp, cfg)
    if cfg.post_norms:
        y = _norm(y, bp["post_mlp_norm"], cfg)
    return x + y, cc


def _decode_core(
    params: Params,
    cache: Cache,
    tokens: jax.Array,        # [B] newest token per slot
    write_pos: jax.Array,     # [B] int32 position being written/attended
    page_table: jax.Array,    # [B, pages_per_seq] int32 (per-layer-relative)
    cfg: ModelConfig,
    mesh: Optional[jax.sharding.Mesh] = None,
) -> tuple[jax.Array, Cache]:
    """One decode forward for every slot -> (logits [B, V], cache')."""
    ctx = _decode_ctx(cache, write_pos, page_table, cfg)

    def body(carry, bp, l, j):
        x, cc = carry
        return _decode_layer(x, cc, bp, l, j, ctx, cfg, mesh)

    x = embed(params, tokens[:, None], ctx["positions"], cfg)
    x, cache = _scan_layers(params, cfg, body, (x, dict(cache)))
    logits = unembed(params, x, cfg)          # [B, 1, V]
    return logits[:, 0], cache


def decode_window(
    params: Params,
    cache: Cache,
    tokens: jax.Array,        # [B] newest token per slot
    seq_lens: jax.Array,      # [B] int32
    page_table: jax.Array,    # [B, pages_per_seq] int32
    active: jax.Array,        # [B] bool: slot holds a live request
    keys: jax.Array,          # [W] PRNG keys, one per inner step
    temperature: jax.Array,   # [B] f32 per-request (vLLM-style params)
    top_k: jax.Array,         # [B] i32
    top_p: jax.Array,         # [B] f32
    cfg: ModelConfig,
    max_seq_len: int,
    mesh: Optional[jax.sharding.Mesh] = None,
    nan_guard: bool = False,
) -> tuple[jax.Array, ...]:
    """W fused decode+sample steps; returns (tokens [W, B] int32, cache).

    The engine fetches the whole [W, B] token block once per window and does
    its bookkeeping (EOS, max_new, admission) on the host afterwards; slots
    that finish mid-window keep decoding garbage the host discards — wasted
    FLOPs traded for W-fold fewer host round-trips. Slots advance only while
    ``active`` and within the context window; frozen slots clamp their
    write position to max_seq_len - 1 (their own last slot — garbage there
    is unreachable because the host has already finished them).

    With ``nan_guard`` the return is ``(tokens, ok, cache)``: ``ok`` [B]
    bool is per-slot "every live inner step's logits were finite" — the
    engine quarantines slots that trip it. Guard off keeps the carry and
    trace exactly the pre-guard program.
    """
    from orion_tpu.infer.sampling import sample

    def stepf(carry, sub):
        if nan_guard:
            tok, sl, ok, cc = carry
        else:
            tok, sl, cc = carry
        act = active & (sl < max_seq_len)
        wp = jnp.minimum(sl, max_seq_len - 1)
        logits, cc = _decode_core(params, cc, tok, wp, page_table, cfg, mesh)
        toks = sample(
            logits, sub, temperature=temperature, top_k=top_k, top_p=top_p
        )
        tok = jnp.where(act, toks, tok)
        sl = sl + act.astype(sl.dtype)
        if nan_guard:
            ok = ok & (jnp.isfinite(logits).all(-1) | ~act)
            return (tok, sl, ok, cc), toks
        return (tok, sl, cc), toks

    if nan_guard:
        init = (
            tokens, seq_lens, jnp.ones_like(active, dtype=bool), dict(cache)
        )
        (_, _, ok, cache), toks = jax.lax.scan(stepf, init, keys)
        return toks, ok, cache
    (_, _, cache), toks = jax.lax.scan(
        stepf, (tokens, seq_lens, dict(cache)), keys
    )
    return toks, cache


def _verify_ctx(
    cache: Cache,
    seq_lens: jax.Array,      # [B] accepted-token cursor per slot
    lens: jax.Array,          # [B] real verify tokens this row (1..W)
    page_table: jax.Array,    # [B, pages_per_seq]
    active: jax.Array,        # [B] bool
    W: int,
    max_seq_len: int,
    cfg: ModelConfig,
    depths: Optional[jax.Array] = None,     # [B, W] tree depth per column
    tree_mask: Optional[jax.Array] = None,  # [B, W] packed ancestor words
) -> dict:
    """Batch-level tensors for the verify body (speculative decoding).

    Row b holds ``lens[b]`` real tokens — the pending last token plus its
    drafts — writing KV at positions ``seq_lens[b] + j``. Unlike prefill
    chunks these start MID-PAGE (the cursor is arbitrary), so per-token
    (page, offset) pairs come from the page table exactly as decode's do;
    unlike decode there are W of them per row. Padding positions (j >=
    lens, inactive rows, past max_seq_len) scatter to scratch page 0 on
    the xla branch — never clamped onto a real page, so a row near the
    context limit cannot clobber its own final KV slot the way a clamp
    would; the pallas kernel excludes them from its in-kernel merge
    instead. Both leave every real page untouched.

    Token trees (``depths``/``tree_mask`` given, inference.spec_tree_width
    > 1): column j still WRITES its KV at pool position ``seq_lens + j``
    (slot-sequential — page provisioning and the fused write are
    layout-identical to the chain), but its LOGICAL position (RoPE,
    causal/window structure) is ``seq_lens + depths[b, j]`` and it
    attends, among the W new columns, exactly the columns whose bits are
    set in ``tree_mask[b, j]`` (its ancestors, the root, itself) instead
    of every earlier column. Chain-shaped inputs (depths == steps, words
    == the causal prefix bits) produce bit-identical masks to the
    position-order formulation, so the degenerate tree IS today's
    verify; with both None this function is untouched (same trace).
    """
    B = seq_lens.shape[0]
    kp = cache["k"]
    psz = kp.shape[2]
    NP = kp.shape[0] // cfg.n_layers
    P = page_table.shape[1]
    batch_idx = jnp.arange(B)[:, None]
    steps = jnp.arange(W, dtype=jnp.int32)[None, :]
    tree = tree_mask is not None
    assert (depths is None) == (tree_mask is None)
    # WRITE positions are always slot-sequential (cursor + column).
    write_pos = seq_lens[:, None] + steps                   # [B, W] true
    wp = jnp.minimum(write_pos, max_seq_len - 1)            # in-bounds
    valid = (
        active[:, None] & (steps < lens[:, None])
        & (write_pos < max_seq_len)
    )
    page_idx = jnp.where(
        valid, page_table[batch_idx, wp // psz], 0
    )                                                       # [B, W]
    offset = wp % psz
    kv_arange = jnp.arange(P * psz, dtype=jnp.int32)[None, None, :]
    if not tree:
        # Chain: logical position == write position; each query attends
        # everything at or before its own position (earlier drafts of
        # the same dispatch included — they sit at seq_lens..q_pos).
        q_pos = write_pos
        rope_pos = wp
        kv_base_mask = kv_arange <= q_pos[:, :, None]
        in_slots = slot_depth = None
    else:
        q_pos = seq_lens[:, None] + depths.astype(jnp.int32)
        rope_pos = jnp.minimum(q_pos, max_seq_len - 1)
        # Committed context (below the cursor) is visible to every
        # query; the W new columns are visible by ancestor bit.
        slot_idx = kv_arange - seq_lens[:, None, None]      # [B, 1, P*psz]
        in_slots = (slot_idx >= 0) & (slot_idx < W)
        anc = (
            jnp.right_shift(
                tree_mask.astype(jnp.int32)[:, :, None],
                steps[None, :, :],
            )
            & 1
        ).astype(bool)                                      # [B, W(q), W(kv)]
        anc = anc | jnp.eye(W, dtype=bool)[None]            # self-visibility
        slot_c = jnp.clip(slot_idx, 0, W - 1)
        vis_new = jnp.take_along_axis(
            anc, jnp.broadcast_to(slot_c, (B, W, P * psz)), axis=2
        )
        # Per-kv-position slot depth (for the sliding-window test among
        # new columns, which windows DEPTH, not pool offset).
        slot_depth = jnp.take_along_axis(
            jnp.broadcast_to(
                depths.astype(jnp.int32)[:, None, :], (B, 1, W)
            ),
            slot_c, axis=2,
        )                                                   # [B, 1, P*psz]
        kv_base_mask = jnp.where(
            in_slots, vis_new, kv_arange < seq_lens[:, None, None]
        )

    from orion_tpu.ops._dispatch import resolve_impl

    use_pallas, interpret = resolve_impl(cfg.kernels)
    # Ragged-kernel view of the same layout: the cursor and a real-token
    # count clamped so start + lens - 1 stays inside the context — for
    # live rows the engine already guarantees it (drafts are capped at
    # max_seq_len - 1 - cursor), so the clamp is an identity there;
    # inactive/mid-prefill rows carry all-zero page-table rows and land
    # on the scratch page, the same sink the XLA body's `valid` redirect
    # uses.
    start = jnp.minimum(seq_lens, max_seq_len - 1).astype(jnp.int32)
    k_lens = jnp.clip(jnp.minimum(lens, max_seq_len - start), 1, W)
    return dict(
        B=B, W=W, psz=psz, NP=NP, P=P, quant="k_scale" in cache,
        page_table=page_table, positions=rope_pos, q_pos=q_pos,
        page_idx=page_idx, offset=offset,
        kv_arange=kv_arange, kv_base_mask=kv_base_mask,
        start=start, k_lens=k_lens,
        depths=depths, tree_mask=tree_mask,
        in_slots=in_slots, slot_depth=slot_depth,
        use_pallas=use_pallas, interpret=interpret,
    )


def _verify_layer(
    x: jax.Array,
    cc: Cache,
    bp: Any,
    l,
    j: int,
    ctx: dict,
    cfg: ModelConfig,
    mesh: Optional[jax.sharding.Mesh],
) -> tuple[jax.Array, Cache]:
    """One transformer layer of batched draft verification: the decode
    body generalized from one query to W per slot — every draft
    position's K/V lands in the pool first (quantized under kv_quant,
    exactly as a sequential decode would have written it), then each
    query attends the context up to its own position. One pass over this
    layer's weights serves all W positions of all slots; position i's
    logits therefore match the i-th sequential decode step's bit-for-bit,
    which is what makes greedy acceptance exact.

    Pallas branch: the multi-query ragged paged-attention kernel
    (ops/pallas/ragged_paged_attention.py) — the fused-write W=1 decode
    kernel generalized to W ragged queries, writing all lens[b] drafts'
    K/V in-kernel (aliased pools, quantized in-kernel under kv_quant with
    the shared common.quantize_kv, so its written bytes match this body's
    xla scatter bit-for-bit). XLA branch: scatter + masked padded-context
    gather, the reference.

    LOCKSTEP: this is _decode_layer with a W dimension, branch for
    branch — any change to either body's write/gather/dequant/mask math
    must land in both, or the greedy spec-on == spec-off equivalence
    suite (tests/test_spec_decode.py) fails."""
    B, W, psz, NP, P = ctx["B"], ctx["W"], ctx["psz"], ctx["NP"], ctx["P"]
    quant = ctx["quant"]
    page_table = ctx["page_table"]
    page_idx, offset = ctx["page_idx"], ctx["offset"]
    cc = dict(cc)
    win = cfg.layer_window(j)
    h = _norm(x, bp["attn_norm"], cfg)
    q, k, v = qkv_proj(h, bp["attn"], cfg, ctx["positions"])
    K, H = k.shape[2], k.shape[3]
    if ctx["use_pallas"]:
        # Multi-query ragged paged attention: one kernel walks each
        # slot's pages once for all W queries (page DMAs amortized W×),
        # writes every real draft's K/V in place through the aliased
        # pools, and masks queries causally among the W new positions —
        # the verify step stops being the one step type that abandons
        # the fused kernels. Rows with all-zero page-table entries
        # (inactive / mid-prefill slots) read and write only the
        # reserved scratch page, like the xla branch's `valid` redirect.
        from orion_tpu.ops.pallas.ragged_paged_attention import (
            ragged_paged_attention,
        )

        res = ragged_paged_attention(
            q, cc["k"], cc["v"], page_table, ctx["start"], ctx["k_lens"],
            layer_base=l * NP,
            k_new=k, v_new=v,
            logit_softcap=cfg.attn_logit_softcap,
            window=win,
            interpret=ctx["interpret"],
            k_scale=cc.get("k_scale"),
            v_scale=cc.get("v_scale"),
            tree_mask=ctx["tree_mask"],
            depths=ctx["depths"],
            mesh=mesh,
        )
        if quant:
            out, cc["k"], cc["v"], cc["k_scale"], cc["v_scale"] = res
        else:
            out, cc["k"], cc["v"] = res
    else:
        rows = l * NP + page_idx                   # [B, W]
        if quant:
            from orion_tpu.infer.kv_cache import quantize_kv

            kq, ks = quantize_kv(k)                # [B,W,K,H] i8, [B,W,K]
            vq, vs = quantize_kv(v)
            cc["k"] = cc["k"].at[rows, :, offset].set(kq)
            cc["v"] = cc["v"].at[rows, :, offset].set(vq)
            cc["k_scale"] = cc["k_scale"].at[rows, :, offset].set(ks)
            cc["v_scale"] = cc["v_scale"].at[rows, :, offset].set(vs)
        else:
            cc["k"] = cc["k"].at[rows, :, offset].set(k)
            cc["v"] = cc["v"].at[rows, :, offset].set(v)
        # [B, P, K, psz, H] -> [B, P*psz, K, H] padded-context gather (the
        # just-written draft K/V reads back out of the pool, so under
        # kv_quant each query attends its drafts DEQUANTIZED — the decode
        # path's exact numerics).
        k_ctx = cc["k"][l * NP + page_table].transpose(0, 1, 3, 2, 4)
        v_ctx = cc["v"][l * NP + page_table].transpose(0, 1, 3, 2, 4)
        if quant:
            ksc = cc["k_scale"][l * NP + page_table][..., :psz]
            vsc = cc["v_scale"][l * NP + page_table][..., :psz]
            k_ctx = k_ctx.astype(jnp.float32) * ksc.transpose(
                0, 1, 3, 2)[..., None]
            v_ctx = v_ctx.astype(jnp.float32) * vsc.transpose(
                0, 1, 3, 2)[..., None]
            k_ctx = k_ctx.astype(q.dtype)
            v_ctx = v_ctx.astype(q.dtype)
        k_ctx = k_ctx.reshape(B, P * psz, K, H)
        v_ctx = v_ctx.reshape(B, P * psz, K, H)
        kv_mask = ctx["kv_base_mask"]
        if win is not None:
            wmask = (
                ctx["kv_arange"] >= (ctx["q_pos"] - win + 1)[:, :, None]
            )
            if ctx["tree_mask"] is not None:
                # Among the W new columns the window measures DEPTH
                # distance (logical positions), not pool-slot distance —
                # chain-degenerate trees make the two identical.
                dmask = ctx["slot_depth"] >= (
                    ctx["depths"].astype(jnp.int32) - win + 1
                )[:, :, None]
                wmask = jnp.where(ctx["in_slots"], dmask, wmask)
            kv_mask = kv_mask & wmask
        out = attention_xla(
            q, k_ctx, v_ctx, causal=False, mask=kv_mask,
            logit_softcap=cfg.attn_logit_softcap,
        )
    a = out_proj(out, bp["attn"], cfg)
    if cfg.post_norms:
        a = _norm(a, bp["post_attn_norm"], cfg)
    x = x + a
    h2 = _norm(x, bp["mlp_norm"], cfg)
    y, _ = mlp_or_moe(h2, bp, cfg)
    if cfg.post_norms:
        y = _norm(y, bp["post_mlp_norm"], cfg)
    return x + y, cc


def _draft_next(tokens: jax.Array, lens: jax.Array) -> jax.Array:
    """[B, W] draft-under-check per logits position: position j's logits
    predict the token at j+1, so they check ``tokens[:, j+1]`` — or
    nothing (-1: the row's bonus/correction position, and all padding)."""
    B, W = tokens.shape
    shifted = jnp.concatenate(
        [tokens[:, 1:], jnp.full((B, 1), -1, jnp.int32)], axis=1
    )
    steps = jnp.arange(W, dtype=jnp.int32)[None, :]
    return jnp.where(steps + 1 < lens[:, None], shifted, -1)


def verify_step(
    params: Params,
    cache: Cache,
    tokens: jax.Array,        # [B, W]: pending last token + its drafts
    seq_lens: jax.Array,      # [B] int32 accepted-token cursor
    lens: jax.Array,          # [B] int32 real verify tokens (1..W)
    page_table: jax.Array,    # [B, pages_per_seq] int32
    active: jax.Array,        # [B] bool: slot holds a live decode request
    key: jax.Array,           # PRNG key (sampled acceptance draws)
    temperature: jax.Array,   # [B] f32 per-request sampling params
    top_k: jax.Array,         # [B] i32   (python scalars for the all-
    top_p: jax.Array,         # [B] f32    defaults greedy specialization)
    cfg: ModelConfig,
    max_seq_len: int,
    mesh: Optional[jax.sharding.Mesh] = None,
    nan_guard: bool = False,
    depths: Optional[jax.Array] = None,     # [B, W] tree depth per column
    parents: Optional[jax.Array] = None,    # [B, W] parent column per col
    tree_mask: Optional[jax.Array] = None,  # [B, W] packed ancestor words
    legal_mask: Optional[jax.Array] = None,  # [B, W, V] constraint masks
) -> tuple[jax.Array, ...]:
    """Score K drafts for EVERY live slot in ONE dispatch (speculative
    decoding's verification half; drafting is infer/spec_decode.py).

    Structurally the [W, B] decode-window shape turned sideways: W = max
    drafts + 1 positions per slot in a single forward pass instead of W
    sequential passes — ONE pass over the weights emits up to W tokens per
    slot, which is the whole speculative bargain. Per-slot real lengths
    ride in ``lens`` (the dispatch width is static at speculate_tokens+1;
    shorter rows pad, and padding positions write to scratch page 0).
    Draft KV is written INTO the paged pool as it goes — accepted
    positions' KV is already in place, so acceptance costs nothing; the
    engine rewinds rejected positions afterwards (cursor retreat + page
    release, kv_cache.rollback_pages) and the garbage beyond the rewound
    cursor is masked by seq_lens exactly like decode-window overshoot.

    Returns ``(accept [B, W] bool, alt [B, W] int32, cache)`` — the
    per-position acceptance verdicts and fallback tokens of
    sampling.spec_verify_sample; the engine walks each row to its first
    rejection and emits ``accepted drafts + one bonus/correction token``.

    The body follows the decode step's resolve_impl switch: under
    kernels='pallas' each layer runs the multi-query ragged
    paged-attention kernel (page walk + in-kernel fused write for all W
    positions — the pool gather never materializes, and the page DMAs
    amortize over the W queries); under 'xla' it is the decode body's
    scatter + masked gather with a W dimension, kept as the reference.
    Either way the per-position logits match sequential decode on the
    same kernel setting bit-for-bit.

    Token trees (``depths``/``parents``/``tree_mask`` given): columns
    1..lens-1 hold a flattened DraftTree instead of a chain — writes
    stay slot-sequential, attention follows the ancestor mask, and
    acceptance becomes the CHILD-indexed tree walk of
    ``sampling.spec_verify_sample_tree``. With all three None this is
    bit-for-bit the chain program.

    ``legal_mask`` (constrained decoding, [B, W, V] bool): the host
    precomputes position j's legal-token bitmask by walking the FSM
    along the row's draft prefix (chain) or ancestor path (tree) — the
    states are known before dispatch because the drafts are — and the
    mask composes into the SAME filtered target the acceptance math
    already uses. ``None`` keeps this the unconstrained trace (its own
    jit specialization), which is what the byte-identity pin tests.
    """
    from orion_tpu.infer.sampling import (
        spec_verify_sample,
        spec_verify_sample_tree,
    )

    W = tokens.shape[1]
    ctx = _verify_ctx(
        cache, seq_lens, lens, page_table, active, W, max_seq_len, cfg,
        depths=depths, tree_mask=tree_mask,
    )

    def body(carry, bp, l, j):
        x, cc = carry
        return _verify_layer(x, cc, bp, l, j, ctx, cfg, mesh)

    x = embed(params, tokens, ctx["positions"], cfg)
    x, cache = _scan_layers(params, cfg, body, (x, dict(cache)))
    logits = unembed(params, x, cfg)                       # [B, W, V]
    if parents is None:
        accept, alt = spec_verify_sample(
            logits, _draft_next(tokens, lens), key,
            temperature=temperature, top_k=top_k, top_p=top_p,
            legal_mask=legal_mask,
        )
    else:
        accept, alt = spec_verify_sample_tree(
            logits, tokens, parents, lens, key,
            temperature=temperature, top_k=top_k, top_p=top_p,
            legal_mask=legal_mask,
        )
    if nan_guard:
        # Per-slot finite check over the row's REAL positions only (padding
        # positions compute on scratch-page garbage by design).
        steps = jnp.arange(W, dtype=jnp.int32)[None, :]
        valid = active[:, None] & (steps < lens[:, None])
        ok = jnp.where(valid, jnp.isfinite(logits).all(-1), True).all(-1)
        return accept, alt, ok, cache
    return accept, alt, cache


def mixed_step(
    params: Params,
    cache: Cache,
    tokens: jax.Array,        # [B] newest token per decode slot
    seq_lens: jax.Array,      # [B] int32
    page_table: jax.Array,    # [B, pages_per_seq] int32; mid-prefill slots
    #                           carry all-zero rows (their write -> scratch)
    active: jax.Array,        # [B] bool: slot holds a DECODING request
    key: jax.Array,           # PRNG key for the decode sample
    p_tokens: jax.Array,      # [Nc, S_chunk] prompt-chunk tail tokens
    p_lengths: jax.Array,     # [Nc] int32: true chunk lengths
    p_pages: jax.Array,       # [Nc, S_chunk // psz] pages the chunk writes
    p_prefix_lens: jax.Array, # [Nc] int32: context tokens already in cache
    p_prefix_pages: jax.Array,  # [Nc, P_pre] pages holding that context
    temperature: jax.Array,   # [B] f32 per-request decode sampling params
    top_k: jax.Array,         # [B] i32   (python scalars for the all-
    top_p: jax.Array,         # [B] f32    defaults greedy specialization)
    *,
    cfg: ModelConfig,
    max_seq_len: int,
    mesh: Optional[jax.sharding.Mesh] = None,
    nan_guard: bool = False,
    paged_prefill: bool = False,
) -> tuple[jax.Array, ...]:
    """One UNIFIED mixed prefill+decode step (inference.chunked_prefill):
    a single-token decode for every live slot fused with up to the chunk
    budget of prompt-tail tokens, in ONE dispatch.

    Returns ``(decode_tokens [B], chunk_logits [Nc, V], cache)``.

    Each layer runs the decode body (fused-write ragged paged attention —
    the same math as ``decode_window`` with W=1, so the greedy decode
    stream is bit-identical to unchunked serving; sampled decode matches
    a decode_window=1 engine at equal PRNG state, while W>1 windows group
    key splits differently) and the prefill body (a
    prefill chunk is exactly the prefix-cache mid-sequence tail prefill:
    resume at a page-aligned ``p_prefix_lens`` over the pages earlier
    chunks already wrote, flash attention with per-row segment ids
    skipping padding blocks) over the SAME carried pool and the SAME
    block params — one pass over the weights serves both, which is the
    MBU point of mixing: bandwidth-bound decode and compute-bound prefill
    share the chip instead of alternating. Chunk rows and decode rows
    touch disjoint pages (a slot is either decoding or prefilling, and
    mid-prefill slots' decode rows are masked onto scratch page 0 by the
    engine), so the two in-place pool updates commute.

    ``chunk_logits`` holds every chunk row's last-position logits; the
    host samples only the rows whose prompt just completed (fetching the
    array lazily, so non-finishing steps never pay the [Nc, V] transfer).
    """
    from orion_tpu.infer.sampling import sample

    if not nan_guard:
        del active  # host-side bookkeeping filters; kept for decode parity
    wp = jnp.minimum(seq_lens, max_seq_len - 1)
    pctx = _prefill_ctx(
        cache, p_tokens, p_lengths, p_pages, p_prefix_lens, p_prefix_pages,
        cfg, paged_prefill=paged_prefill,
    )
    dctx = _decode_ctx(cache, wp, page_table, cfg)

    def body(carry, bp, l, j):
        xp, xd, cc = carry
        xp, cc = _prefill_layer(xp, cc, bp, l, j, pctx, cfg, mesh)
        xd, cc = _decode_layer(xd, cc, bp, l, j, dctx, cfg, mesh)
        return xp, xd, cc

    xp = embed(params, p_tokens, pctx["positions"], cfg)
    xd = embed(params, tokens[:, None], dctx["positions"], cfg)
    xp, xd, cache = _scan_layers(params, cfg, body, (xp, xd, dict(cache)))
    # Two unembed calls, not one over a concat: the decode half must stay
    # op-for-op identical to decode_window's so its tokens are bitwise
    # unchanged by the rider chunk rows.
    d_logits = unembed(params, xd, cfg)[:, 0]            # [B, V]
    toks = sample(
        d_logits, key, temperature=temperature, top_k=top_k, top_p=top_p
    )
    p_logits = _prefill_logits(params, xp, p_lengths, cfg)
    if nan_guard:
        ok = jnp.isfinite(d_logits).all(-1) | ~active
        return toks, ok, p_logits, cache
    return toks, p_logits, cache


def mixed_verify_step(
    params: Params,
    cache: Cache,
    tokens: jax.Array,        # [B, W]: pending last token + drafts per slot
    seq_lens: jax.Array,      # [B] int32 accepted-token cursor
    lens: jax.Array,          # [B] int32 real verify tokens (1..W)
    page_table: jax.Array,    # [B, pages_per_seq] int32; mid-prefill slots
    #                           carry all-zero rows (their writes -> scratch)
    active: jax.Array,        # [B] bool: slot holds a DECODING request
    key: jax.Array,           # PRNG key (sampled acceptance draws)
    p_tokens: jax.Array,      # [Nc, S_chunk] prompt-chunk tail tokens
    p_lengths: jax.Array,     # [Nc] int32: true chunk lengths
    p_pages: jax.Array,       # [Nc, S_chunk // psz] pages the chunk writes
    p_prefix_lens: jax.Array, # [Nc] int32: context tokens already in cache
    p_prefix_pages: jax.Array,  # [Nc, P_pre] pages holding that context
    temperature: jax.Array,   # [B] f32 per-request decode sampling params
    top_k: jax.Array,         # [B] i32
    top_p: jax.Array,         # [B] f32
    *,
    cfg: ModelConfig,
    max_seq_len: int,
    mesh: Optional[jax.sharding.Mesh] = None,
    nan_guard: bool = False,
    paged_prefill: bool = False,
    depths: Optional[jax.Array] = None,     # [B, W] tree depth per column
    parents: Optional[jax.Array] = None,    # [B, W] parent column per col
    tree_mask: Optional[jax.Array] = None,  # [B, W] packed ancestor words
    legal_mask: Optional[jax.Array] = None,  # [B, W, V] constraint masks
) -> tuple[jax.Array, ...]:
    """``mixed_step`` with the decode half replaced by the verify body:
    speculative decoding composed with chunked prefill. One dispatch runs
    up to the chunk budget of prompt tail (prompt-phase slots — they skip
    drafting by construction, their prompts ARE the chunk rows) AND a
    W-position draft verification for every decoding slot, over the same
    carried pool and the same pass over the weights.

    Returns ``(accept [B, W], alt [B, W], chunk_logits [Nc, V], cache)``.
    Chunk rows and verify rows touch disjoint pages for the same reason
    mixed_step's halves do: a slot is either prefilling (its verify row is
    masked onto scratch by the engine's zeroed page-table copy) or
    decoding (its pages are not in any chunk row), so the in-place pool
    updates commute.
    """
    from orion_tpu.infer.sampling import (
        spec_verify_sample,
        spec_verify_sample_tree,
    )

    W = tokens.shape[1]
    pctx = _prefill_ctx(
        cache, p_tokens, p_lengths, p_pages, p_prefix_lens, p_prefix_pages,
        cfg, paged_prefill=paged_prefill,
    )
    vctx = _verify_ctx(
        cache, seq_lens, lens, page_table, active, W, max_seq_len, cfg,
        depths=depths, tree_mask=tree_mask,
    )

    def body(carry, bp, l, j):
        xp, xv, cc = carry
        xp, cc = _prefill_layer(xp, cc, bp, l, j, pctx, cfg, mesh)
        xv, cc = _verify_layer(xv, cc, bp, l, j, vctx, cfg, mesh)
        return xp, xv, cc

    xp = embed(params, p_tokens, pctx["positions"], cfg)
    xv = embed(params, tokens, vctx["positions"], cfg)
    xp, xv, cache = _scan_layers(params, cfg, body, (xp, xv, dict(cache)))
    logits = unembed(params, xv, cfg)                      # [B, W, V]
    if parents is None:
        accept, alt = spec_verify_sample(
            logits, _draft_next(tokens, lens), key,
            temperature=temperature, top_k=top_k, top_p=top_p,
            legal_mask=legal_mask,
        )
    else:
        accept, alt = spec_verify_sample_tree(
            logits, tokens, parents, lens, key,
            temperature=temperature, top_k=top_k, top_p=top_p,
            legal_mask=legal_mask,
        )
    p_logits = _prefill_logits(params, xp, p_lengths, cfg)
    if nan_guard:
        steps = jnp.arange(W, dtype=jnp.int32)[None, :]
        valid = active[:, None] & (steps < lens[:, None])
        ok = jnp.where(valid, jnp.isfinite(logits).all(-1), True).all(-1)
        return accept, alt, ok, p_logits, cache
    return accept, alt, p_logits, cache
