"""Token sampling: greedy / temperature / top-k / nucleus (top-p).

Static-shape TPU formulation: top-k and top-p are masks over the full vocab
(sort + cumulative sum), never a dynamic-length candidate list.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def sample(
    logits: jax.Array,
    key: jax.Array,
    *,
    temperature=0.0,
    top_k=0,
    top_p=1.0,
) -> jax.Array:
    """logits: [B, V] -> sampled token ids [B] int32.

    Each parameter is a python scalar (whole batch) or a [B] array
    (per-request sampling params, vLLM-style). temperature <= 0 means
    greedy argmax for that row (the deterministic mode the
    batching-equivalence tests rely on). top_k=0 / top_p=1.0 disable the
    respective filters.

    The all-scalar greedy case short-circuits to a bare argmax — the bench
    path compiles no sampling machinery.
    """
    # Trace-time constants (python scalars, e.g. bound via functools.partial
    # before jit) let disabled filters compile to nothing: the greedy bench
    # decode is a bare argmax, plain-temperature sampling skips the [B, V]
    # sort/softmax/cumsum entirely.
    no_topk = isinstance(top_k, int) and top_k == 0
    no_topp = isinstance(top_p, (int, float)) and top_p >= 1.0
    if isinstance(temperature, (int, float)):
        if temperature <= 0.0:
            return jnp.argmax(logits, axis=-1).astype(jnp.int32)
        if no_topk and no_topp:
            scaled = logits.astype(jnp.float32) / temperature
            return jax.random.categorical(key, scaled, axis=-1).astype(
                jnp.int32
            )

    B, V = logits.shape
    logits = logits.astype(jnp.float32)
    temp = jnp.broadcast_to(jnp.asarray(temperature, jnp.float32), (B,))
    top_k = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), (B,))
    top_p = jnp.broadcast_to(jnp.asarray(top_p, jnp.float32), (B,))

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = logits / jnp.where(temp > 0, temp, 1.0)[:, None]

    if not (no_topk and no_topp):
        sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]

    if not no_topk:
        # top-k: threshold at the k-th largest logit per row (0 disables).
        kth_idx = jnp.clip(top_k - 1, 0, V - 1)[:, None]
        kth = jnp.take_along_axis(sorted_desc, kth_idx, axis=-1)
        scaled = jnp.where(
            (top_k[:, None] > 0) & (scaled < kth), NEG_INF, scaled
        )

    if not no_topp:
        # top-p: keep the smallest prefix with cumulative mass >= top_p
        # (always keep the row argmax). 1.0 disables. Mass is measured on
        # the top-k-filtered distribution (descending positions >= k are
        # the filtered-out tail), matching filters applied in sequence.
        idx = jnp.arange(V)[None, :]
        sorted_masked = jnp.where(
            (top_k[:, None] > 0) & (idx >= top_k[:, None]),
            NEG_INF,
            sorted_desc,
        )
        probs = jax.nn.softmax(sorted_masked, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep_sorted = jnp.concatenate(
            [jnp.ones((B, 1), bool), cum[:, :-1] < top_p[:, None]], axis=-1
        )
        thresh = jnp.min(
            jnp.where(keep_sorted, sorted_masked, jnp.inf), axis=-1,
            keepdims=True,
        )
        scaled = jnp.where(
            (top_p[:, None] < 1.0) & (scaled < thresh), NEG_INF, scaled
        )

    sampled = jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
    return jnp.where(temp > 0, sampled, greedy)
