"""Token sampling: greedy / temperature / top-k / nucleus (top-p).

Static-shape TPU formulation: top-k and top-p are masks over the full vocab
(sort + cumulative sum), never a dynamic-length candidate list.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

NEG_INF = -1e30


def sample(
    logits: jax.Array,
    key: jax.Array,
    *,
    temperature: float = 0.0,
    top_k: int = 0,
    top_p: float = 1.0,
) -> jax.Array:
    """logits: [B, V] -> sampled token ids [B] int32.

    temperature <= 0 means greedy argmax (the deterministic mode the
    batching-equivalence tests rely on). top_k=0 / top_p=1.0 disable the
    respective filters.
    """
    if temperature <= 0.0:
        return jnp.argmax(logits, axis=-1).astype(jnp.int32)

    logits = logits.astype(jnp.float32) / temperature

    if top_k > 0:
        kth = jnp.sort(logits, axis=-1)[:, -top_k][:, None]
        logits = jnp.where(logits < kth, NEG_INF, logits)

    if top_p < 1.0:
        sorted_logits = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jax.nn.softmax(sorted_logits, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        # Keep the smallest prefix with cumulative mass >= top_p (always
        # keep the argmax itself).
        keep_sorted = jnp.concatenate(
            [jnp.ones_like(cum[:, :1], bool), cum[:, :-1] < top_p], axis=-1
        )
        # Threshold = smallest kept logit per row.
        thresh = jnp.min(
            jnp.where(keep_sorted, sorted_logits, jnp.inf), axis=-1,
            keepdims=True,
        )
        logits = jnp.where(logits < thresh, NEG_INF, logits)

    return jax.random.categorical(key, logits, axis=-1).astype(jnp.int32)
