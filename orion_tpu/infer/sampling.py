"""Token sampling: greedy / temperature / top-k / nucleus (top-p).

Static-shape TPU formulation: top-k and top-p are masks over the full vocab
(sort + cumulative sum), never a dynamic-length candidate list.

Constrained decoding (orion_tpu.constrain) composes a per-row legal-token
bitmask into the SAME filtered distribution every consumer shares: greedy,
sampled, and both speculative verify paths mask before any filtering, so a
constrained draft is accepted by exactly the rejection-sampling math the
unconstrained path runs — no new acceptance rule. ``legal_mask=None``
keeps every trace byte-identical to the unconstrained build (the jit
specializes on the None pytree).
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
import numpy as np

NEG_INF = -1e30


class AllMaskedRows(ValueError):
    """Typed per-slot error: legal-mask rows that admit NO token. The
    filtered distribution for such a row is undefined (softmax of all
    NEG_INF is uniform garbage), so the engine must fail the offending
    slots — and only those slots — before dispatch. ``slots`` lists the
    guilty row indices; neighbors are unaffected."""

    def __init__(self, slots):
        self.slots = list(slots)
        super().__init__(
            f"legal_mask rows {self.slots} admit no token (constraint "
            f"dead end); quarantine those slots"
        )


def check_legal_mask(legal_mask) -> None:
    """Host-side pre-dispatch validation: raise :class:`AllMaskedRows`
    naming every all-masked row. Rows are the leading axis (flatten
    [B, W, V] masks to row-major [B*W, V] semantics upstream if per-slot
    attribution over positions is needed; the engine checks per-slot
    rows before building verify masks)."""
    m = np.asarray(legal_mask, bool)
    rows = m.reshape(-1, m.shape[-1])
    bad = np.flatnonzero(~rows.any(axis=-1))
    if bad.size:
        raise AllMaskedRows(bad.tolist())


def _apply_mask(logits: jax.Array, legal_mask) -> jax.Array:
    """Illegal tokens drop to NEG_INF BEFORE temperature/top-k/top-p so
    every downstream filter sees the constrained distribution."""
    if legal_mask is None:
        return logits
    return jnp.where(legal_mask, logits.astype(jnp.float32), NEG_INF)


def sample(
    logits: jax.Array,
    key: jax.Array,
    *,
    temperature=0.0,
    top_k=0,
    top_p=1.0,
    legal_mask=None,
) -> jax.Array:
    """logits: [B, V] -> sampled token ids [B] int32.

    Each parameter is a python scalar (whole batch) or a [B] array
    (per-request sampling params, vLLM-style). temperature <= 0 means
    greedy argmax for that row (the deterministic mode the
    batching-equivalence tests rely on). top_k=0 / top_p=1.0 disable the
    respective filters.

    ``legal_mask`` ([B, V] bool or None) constrains rows to their legal
    tokens: illegal logits drop to NEG_INF before any filter, and a row
    whose mask admits exactly ONE token short-circuits to that token —
    deterministically, on BOTH the greedy and sampled paths (a forced
    continuation must not depend on the sampling mode). All-masked rows
    are a caller bug; validate with ``check_legal_mask`` pre-dispatch.

    The all-scalar greedy case short-circuits to a bare argmax — the bench
    path compiles no sampling machinery.
    """
    logits = _apply_mask(logits, legal_mask)
    if legal_mask is not None:
        forced = jnp.argmax(legal_mask, axis=-1).astype(jnp.int32)
        single = jnp.sum(legal_mask, axis=-1) == 1

        def finish(toks):
            return jnp.where(single, forced, toks)
    else:
        def finish(toks):
            return toks

    # Trace-time constants (python scalars, e.g. bound via functools.partial
    # before jit) let disabled filters compile to nothing: the greedy bench
    # decode is a bare argmax, plain-temperature sampling skips the [B, V]
    # sort/softmax/cumsum entirely.
    no_topk = isinstance(top_k, int) and top_k == 0
    no_topp = isinstance(top_p, (int, float)) and top_p >= 1.0
    if isinstance(temperature, (int, float)):
        if temperature <= 0.0:
            return finish(jnp.argmax(logits, axis=-1).astype(jnp.int32))
        if no_topk and no_topp:
            scaled = logits.astype(jnp.float32) / temperature
            return finish(
                jax.random.categorical(key, scaled, axis=-1).astype(
                    jnp.int32
                )
            )

    B, V = logits.shape
    logits = logits.astype(jnp.float32)
    temp = jnp.broadcast_to(jnp.asarray(temperature, jnp.float32), (B,))
    top_k = jnp.broadcast_to(jnp.asarray(top_k, jnp.int32), (B,))
    top_p = jnp.broadcast_to(jnp.asarray(top_p, jnp.float32), (B,))

    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)
    scaled = filter_logits(logits, temp, top_k, top_p,
                           no_topk=no_topk, no_topp=no_topp)
    sampled = jax.random.categorical(key, scaled, axis=-1).astype(jnp.int32)
    return finish(jnp.where(temp > 0, sampled, greedy))


def filter_logits(
    logits: jax.Array,     # [B, V] float32
    temp: jax.Array,       # [B] f32 (rows <= 0 pass through at scale 1)
    top_k: jax.Array,      # [B] i32
    top_p: jax.Array,      # [B] f32
    *,
    no_topk: bool = False,
    no_topp: bool = False,
    legal_mask=None,
) -> jax.Array:
    """Temperature-scaled, top-k/top-p-masked logits [B, V].

    The single definition of the target distribution: ``sample`` draws a
    categorical from it, and speculative verification (spec_verify_sample)
    measures draft-acceptance probabilities against softmax of the SAME
    array — rejection sampling preserves the output distribution only if
    both sides agree on it exactly. ``legal_mask`` applies FIRST, so
    top-k/top-p renormalize over the constrained support (top-k acts as
    min(k, legal count): the k-th largest of a masked row is NEG_INF
    once k exceeds the legal count, which keeps every legal token).
    """
    B, V = logits.shape
    logits = _apply_mask(logits, legal_mask)
    scaled = logits / jnp.where(temp > 0, temp, 1.0)[:, None]

    if not (no_topk and no_topp):
        sorted_desc = jnp.sort(scaled, axis=-1)[:, ::-1]

    if not no_topk:
        # top-k: threshold at the k-th largest logit per row (0 disables).
        kth_idx = jnp.clip(top_k - 1, 0, V - 1)[:, None]
        kth = jnp.take_along_axis(sorted_desc, kth_idx, axis=-1)
        scaled = jnp.where(
            (top_k[:, None] > 0) & (scaled < kth), NEG_INF, scaled
        )

    if not no_topp:
        # top-p: keep the smallest prefix with cumulative mass >= top_p
        # (always keep the row argmax). 1.0 disables. Mass is measured on
        # the top-k-filtered distribution (descending positions >= k are
        # the filtered-out tail), matching filters applied in sequence.
        idx = jnp.arange(V)[None, :]
        sorted_masked = jnp.where(
            (top_k[:, None] > 0) & (idx >= top_k[:, None]),
            NEG_INF,
            sorted_desc,
        )
        probs = jax.nn.softmax(sorted_masked, axis=-1)
        cum = jnp.cumsum(probs, axis=-1)
        keep_sorted = jnp.concatenate(
            [jnp.ones((B, 1), bool), cum[:, :-1] < top_p[:, None]], axis=-1
        )
        thresh = jnp.min(
            jnp.where(keep_sorted, sorted_masked, jnp.inf), axis=-1,
            keepdims=True,
        )
        scaled = jnp.where(
            (top_p[:, None] < 1.0) & (scaled < thresh), NEG_INF, scaled
        )
    return scaled


def spec_verify_sample(
    logits: jax.Array,       # [B, W, V] verify logits, position-major
    draft_next: jax.Array,   # [B, W] i32: the draft token each position is
    #                          checking (tokens[:, j+1]); -1 at bonus /
    #                          padding positions (no draft to check)
    key: jax.Array,
    *,
    temperature=0.0,
    top_k=0,
    top_p=1.0,
    legal_mask=None,
) -> tuple[jax.Array, jax.Array]:
    """Per-position draft acceptance for speculative decoding.

    Returns ``(accept [B, W] bool, alt [B, W] int32)``. The host walks each
    row's positions left to right: while ``accept[j]`` holds, draft j+1 is
    emitted; at the first rejection (or at the row's bonus position)
    ``alt[j]`` is emitted instead, and the rest of the row is discarded.

    Greedy rows (temperature <= 0): accept is exact argmax match and alt
    is the argmax — the emitted stream is byte-identical to non-speculative
    greedy decoding. Sampled rows use standard rejection sampling against
    the deterministic n-gram proposal q = delta(draft): accept with
    probability p(draft) under the filtered target distribution p
    (filter_logits — the same array ``sample`` draws from); on rejection,
    alt is drawn from the residual max(0, p - q) normalized, i.e. p
    conditioned on != draft; at the bonus position (draft_next < 0) alt is
    a plain sample from p. The marginal law of every emitted token is
    exactly p, so the served distribution is provably unchanged.

    The all-scalar greedy case (python temperature <= 0) compiles to a bare
    argmax + compare — no sort, no categorical (mirrors ``sample``'s
    specialization contract).

    ``legal_mask`` ([B, W, V] bool or None): position j's mask is the
    constraint state AFTER consuming the row's draft prefix up to j —
    masking before filtering makes p the constrained target, so a forced
    draft (single legal token) has p(draft) exactly 1.0 in f32 (every
    competitor underflows through exp(NEG_INF)) and u ~ U[0,1) < 1.0
    accepts it ALWAYS, greedy or sampled: forced runs are free drafts
    under the unmodified acceptance rule.
    """
    B, W, V = logits.shape
    logits = _apply_mask(logits, legal_mask)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)    # [B, W]
    if isinstance(temperature, (int, float)) and temperature <= 0.0:
        return greedy == draft_next, greedy

    flat = logits.reshape(B * W, V).astype(jnp.float32)
    # Per-request params broadcast over the row's W positions.
    rep = lambda a, dt: jnp.broadcast_to(  # noqa: E731
        jnp.asarray(a, dt).reshape(-1, 1) if jnp.ndim(a) else
        jnp.asarray(a, dt), (B, W)
    ).reshape(B * W)
    temp = rep(temperature, jnp.float32)
    no_topk = isinstance(top_k, int) and top_k == 0
    no_topp = isinstance(top_p, (int, float)) and top_p >= 1.0
    filtered = filter_logits(
        flat, temp, rep(top_k, jnp.int32), rep(top_p, jnp.float32),
        no_topk=no_topk, no_topp=no_topp,
    )
    dn = draft_next.reshape(B * W)
    probs = jax.nn.softmax(filtered, axis=-1)
    p_draft = jnp.take_along_axis(
        probs, jnp.clip(dn, 0, V - 1)[:, None], axis=-1
    )[:, 0]
    k_u, k_alt = jax.random.split(key)
    u = jax.random.uniform(k_u, (B * W,))
    # Residual on rejection: p excluding the rejected draft; the bonus
    # position (dn < 0) excludes nothing (plain sample from p).
    excl = (jnp.arange(V)[None, :] == dn[:, None]) & (dn >= 0)[:, None]
    alt_s = jax.random.categorical(
        k_alt, jnp.where(excl, NEG_INF, filtered), axis=-1
    ).astype(jnp.int32)
    g = greedy.reshape(B * W)
    accept = jnp.where(temp > 0, u < p_draft, g == dn) & (dn >= 0)
    alt = jnp.where(temp > 0, alt_s, g)
    return accept.reshape(B, W), alt.reshape(B, W)


def spec_verify_sample_tree(
    logits: jax.Array,       # [B, W, V] verify logits, column-major
    tokens: jax.Array,       # [B, W] i32: col 0 the pending token, cols
    #                          1..lens-1 the tree nodes' tokens
    parents: jax.Array,      # [B, W] i32: parent COLUMN per column (col 0
    #                          ignored); chain rows carry j - 1
    lens: jax.Array,         # [B] i32: real columns (1..W)
    key: jax.Array,
    *,
    temperature=0.0,
    top_k=0,
    top_p=1.0,
    legal_mask=None,
) -> tuple[jax.Array, jax.Array]:
    """Token-tree draft acceptance (``spec_verify_sample`` generalized
    from a chain to an ancestor tree; SpecInfer-style multi-branch
    rejection sampling).

    Returns ``(accept [B, W] bool, alt [B, W] int32)``, CHILD-indexed:
    ``accept[c]`` says whether node column c is accepted by its PARENT's
    logits, and ``alt[j]`` is column j's fallback token — drawn from j's
    filtered target distribution with j's own children's tokens excluded
    (the residual after every child was rejected; a leaf excludes
    nothing, which is the chain bonus sample). The host walks the tree
    root-down: at each node it descends into the first accepted child in
    sibling (insertion-priority) order, else emits ``alt`` and stops.

    Greedy (temperature <= 0): ``accept[c]`` is an exact argmax match
    against the parent — at most one sibling can match (sibling tokens
    are distinct by tree construction), so the walk reproduces
    sequential greedy decoding byte-for-byte, and a chain-shaped tree
    reproduces ``spec_verify_sample``'s emissions exactly.

    Sampled rows: sibling c's acceptance probability is
    ``p(x_c) / (1 - sum of ELDER siblings' p)`` — the sequential
    rejection-sampling scheme against the shared filtered target
    (filter_logits): try the first sibling against p, on rejection
    renormalize p without it and try the next, finally sample the
    residual excluding all siblings. The marginal law of every emitted
    token is exactly p, so the served distribution is unchanged; with a
    single child per node this is rejection sampling against the same
    target as ``spec_verify_sample`` (the draws ride child-indexed keys,
    so the chain STREAM differs while the law does not).

    ``legal_mask`` ([B, W, V] bool or None): column j's mask is the
    constraint state after consuming j's ANCESTOR path (the distribution
    j's logits feed) — siblings at an FSM branch point are each legal
    under their shared parent's mask, so multi-branch rejection sampling
    covers the branch with the standard elder-sibling renormalization.
    """
    B, W, V = logits.shape
    logits = _apply_mask(logits, legal_mask)
    steps = jnp.arange(W, dtype=jnp.int32)[None, :]
    valid = (steps >= 1) & (steps < lens[:, None])             # [B, W]
    par = jnp.clip(parents.astype(jnp.int32), 0, W - 1)
    greedy = jnp.argmax(logits, axis=-1).astype(jnp.int32)     # [B, W]
    g_par = jnp.take_along_axis(greedy, par, axis=1)           # [B, W]
    g_accept = valid & (g_par == tokens)
    if isinstance(temperature, (int, float)) and temperature <= 0.0:
        return g_accept, greedy

    flat = logits.reshape(B * W, V).astype(jnp.float32)
    rep = lambda a, dt: jnp.broadcast_to(  # noqa: E731
        jnp.asarray(a, dt).reshape(-1, 1) if jnp.ndim(a) else
        jnp.asarray(a, dt), (B, W)
    ).reshape(B * W)
    temp = rep(temperature, jnp.float32)
    no_topk = isinstance(top_k, int) and top_k == 0
    no_topp = isinstance(top_p, (int, float)) and top_p >= 1.0
    filtered = filter_logits(
        flat, temp, rep(top_k, jnp.int32), rep(top_p, jnp.float32),
        no_topk=no_topk, no_topp=no_topp,
    ).reshape(B, W, V)
    probs = jax.nn.softmax(filtered, axis=-1)                  # [B, W, V]
    # p(x_c) under the PARENT's target distribution, per child column.
    parent_probs = probs[jnp.arange(B)[:, None], par]          # [B, W, V]
    p_vals = jnp.take_along_axis(
        parent_probs, jnp.clip(tokens, 0, V - 1)[:, :, None], axis=2
    )[:, :, 0]
    p_vals = jnp.where(valid, p_vals, 0.0)                     # [B, W]
    # Elder-sibling mass: same parent, earlier column — the probability
    # already consumed by the siblings tried (and rejected) before c.
    same_par = par[:, :, None] == par[:, None, :]              # [B, W, W]
    elder = (
        same_par & (steps[:, None, :] < steps[:, :, None])
        & valid[:, :, None] & valid[:, None, :]
    )
    mass = jnp.einsum("bcs,bs->bc", elder.astype(jnp.float32), p_vals)
    k_u, k_alt = jax.random.split(key)
    u = jax.random.uniform(k_u, (B, W))
    s_accept = valid & (
        u * jnp.maximum(1.0 - mass, 1e-9) < p_vals
    )
    # Residual fallback per NODE: its target with its children's tokens
    # excluded (scatter child tokens onto their parents' rows; invalid
    # columns drop out of range).
    bidx = jnp.broadcast_to(jnp.arange(B)[:, None], (B, W))
    excl = jnp.zeros((B, W, V), bool).at[
        bidx,
        jnp.where(valid, par, W),
        jnp.clip(tokens, 0, V - 1),
    ].set(True, mode="drop")
    alt_s = jax.random.categorical(
        k_alt, jnp.where(excl, NEG_INF, filtered).reshape(B * W, V),
        axis=-1,
    ).astype(jnp.int32).reshape(B, W)
    tmat = temp.reshape(B, W)
    accept = jnp.where(tmat > 0, s_accept, g_accept)
    alt = jnp.where(tmat > 0, alt_s, greedy)
    return accept, alt
