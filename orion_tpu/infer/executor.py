"""Dispatch executor of the serving engine (ISSUE 12 tentpole split).

The other half of the scheduler/executor split (see infer/scheduler.py):
this module owns the *device-facing* machinery the engine delegates to —
the jitted dispatch-program factory (primary and XLA-fallback builds
share one code path so they can never drift), and the per-dispatch
fault-tolerance envelope: injection points, the degradation-ladder
fallback retry loop (``inference.dispatch_retries`` attempts with
jittered backoff between them — ISSUE 12 satellite), and the
DispatchFault contract the engine's failed-step containment consumes.

The executor holds a back-reference to its engine rather than copies of
the engine's mutable state (robust stats, injector, tracer): those
objects are swapped by ``reset_timing``/lifecycle paths and the envelope
must always read the live ones.
"""

from __future__ import annotations

import dataclasses
import logging
import random
import time
from functools import partial
from typing import Any

import jax

from orion_tpu.infer.runner import (
    decode_window,
    mixed_step,
    mixed_verify_step,
    prefill_step,
    verify_step,
)
from orion_tpu.runtime.fault import DispatchFault, InjectedFault

log = logging.getLogger("orion_tpu.infer")


class DispatchExecutor:
    """Owns the engine's dispatch programs and the fault envelope around
    every device call (previously ``InferenceEngine._jit_program`` /
    ``_fallback_program`` / ``_run_dispatch``, relocated verbatim plus
    the configurable-retry satellite)."""

    PROGRAM_FNS = {
        "prefill": prefill_step,
        "decode": decode_window,
        "mixed": mixed_step,
        "verify": verify_step,
        "mixed_verify": mixed_verify_step,
    }

    def __init__(self, engine):
        self.eng = engine
        # XLA reference programs, built lazily per dispatch name the first
        # time a Pallas dispatch fails (inference.dispatch_fallback).
        self._xla_fallbacks: dict[str, Any] = {}
        # Backoff jitter source. Fixed seed so a replayed fault episode
        # sleeps the same schedule; sleep durations never touch tokens,
        # so this is log-determinism, not output-determinism.
        self._rng = random.Random(0)

    def jit_program(self, name: str, mcfg, mesh):
        """Build one jitted dispatch program. ``name`` is a coarse path
        stem optionally suffixed "_defaults" (python-scalar sampling params
        bound as trace-time constants — the sort-free greedy
        specialization). The SAME factory builds the XLA fallback programs
        (kernels="xla", mesh=None), so the two paths share every static
        binding and can never drift."""
        icfg = self.eng.icfg
        is_default = name.endswith("_defaults")
        stem = name[: -len("_defaults")] if is_default else name
        fn = self.PROGRAM_FNS[stem]
        if stem == "prefill":
            kw: dict[str, Any] = dict(cfg=mcfg, mesh=mesh)
        else:
            kw = dict(
                cfg=mcfg, max_seq_len=icfg.max_seq_len, mesh=mesh,
                nan_guard=self.eng._guard,
            )
        if stem in ("prefill", "mixed", "mixed_verify"):
            # Blockwise paged-flash prefill (inference.paged_prefill):
            # resolved against THIS build's kernels — the XLA fallback
            # build (kernels="xla") ignores it inside _prefill_ctx, so
            # the reference body stays the degradation-ladder rung.
            kw["paged_prefill"] = icfg.paged_prefill
        if is_default:
            kw.update(
                temperature=icfg.temperature,
                top_k=icfg.top_k,
                top_p=icfg.top_p,
            )
        return jax.jit(partial(fn, **kw), donate_argnums=(1,))

    def fallback_program(self, name: str):
        """The XLA reference program for ``name`` (degradation ladder rung
        1), or None when no fallback applies — the primary already runs
        XLA, or inference.dispatch_fallback is off / retry count 0. Built
        lazily on the first fault and cached; mesh=None because the XLA
        ops partition from the params' shardings alone."""
        from orion_tpu.ops._dispatch import resolve_impl

        eng = self.eng
        if not eng.icfg.dispatch_fallback or eng.icfg.dispatch_retries < 1:
            return None
        if not resolve_impl(eng.mcfg.kernels)[0]:
            return None
        fb = self._xla_fallbacks.get(name)
        if fb is None:
            mcfg_xla = dataclasses.replace(eng.mcfg, kernels="xla")
            fb = self.jit_program(name, mcfg_xla, None)
            self._xla_fallbacks[name] = fb
        return fb

    def _backoff(self, attempt: int) -> None:
        """Jittered exponential backoff between fallback attempts
        (inference.dispatch_retry_backoff_s; 0.0 = today's immediate
        retry). Full jitter on the upper half keeps a fleet of replicas
        retrying a shared transient from re-colliding in lockstep."""
        base = self.eng.icfg.dispatch_retry_backoff_s
        if base <= 0.0:
            return
        time.sleep(base * (2 ** attempt) * (0.5 + 0.5 * self._rng.random()))

    def run(self, path: str, name: str, *args, **kwargs):
        """Run one device dispatch with the fault-tolerance envelope: the
        injection points (stall sleeps; dispatch exceptions raised BEFORE
        the primary call, so engine/cache state is untouched and retry is
        sound), then on ANY failure up to ``inference.dispatch_retries``
        retries on the XLA reference path, jittered backoff between
        attempts. Raises DispatchFault(path) when every path is exhausted
        — the engine fails the step, not the process.

        The primary result is blocked on HERE so that execute-time device
        errors (async dispatch defers them to the first fetch) surface
        inside this envelope instead of crashing the caller's device_get;
        the engine fetches the step's tokens immediately afterwards
        anyway, so no overlap is lost. Fallback scope: trace/compile/
        lowering failures (the dominant Pallas fault class) and injected
        faults retry cleanly; an EXECUTE-time failure may already have
        consumed the donated cache buffer, in which case the fallback
        double-faults and the episode is contained as a failed step."""
        eng = self.eng
        inj = eng._injector
        if inj is not None:
            st = inj.take("stall", eng.step_no, path)
            if st is not None:
                log.warning(
                    "injected %.2fs stall in %s dispatch (step %d)",
                    st.stall_s, path, eng.step_no,
                )
                time.sleep(st.stall_s)
        try:
            if inj is not None and (
                inj.take("dispatch", eng.step_no, path) is not None
            ):
                raise InjectedFault(
                    f"injected {path} dispatch fault (step {eng.step_no})"
                )
            # TraceAnnotation (not a host-ring span — _device_span owns
            # that window): names this dispatch in a concurrently-captured
            # device profile so xprof rows align with the Chrome export.
            with eng._tracer.annotation("orion/" + path):
                out = getattr(eng, "_" + name)(*args, **kwargs)
                # orion: allow[host-sync] THE envelope sync point: execute-time faults must surface here, not at the caller's fetch
                jax.block_until_ready(out)
            return out
        # orion: allow[fault-except] the fault envelope exists to contain ANY dispatch failure (DispatchFault re-raise below)
        except Exception as e:
            eng.robust.dispatch_faults += 1
            eng._flight_note(
                "dispatch_fault", path=path,
                error=f"{type(e).__name__}: {e}",
            )
            if path in ("verify", "mixed_verify"):
                # Degradation ladder rung 2 counts PRIMARY verify faults
                # here — before the fallback — so a persistently broken
                # verify kernel disables speculation even when every
                # episode is absorbed by a successful XLA retry (otherwise
                # the engine would pay a doomed primary attempt + fallback
                # on every verify step forever).
                eng._note_spec_fault(e)
            fb = self.fallback_program(name)
            if fb is None:
                raise DispatchFault(
                    path, f"{type(e).__name__}: {e}"
                ) from e
            last: Exception = e
            for attempt in range(eng.icfg.dispatch_retries):
                self._backoff(attempt)
                eng.robust.dispatch_retries += 1
                log.warning(
                    "%s dispatch failed (%s: %s); retry %d/%d on the XLA "
                    "reference path", path, type(last).__name__, last,
                    attempt + 1, eng.icfg.dispatch_retries,
                )
                try:
                    with eng._tracer.annotation(
                        "orion/" + path + "/fallback"
                    ):
                        out = fb(*args, **kwargs)
                        # orion: allow[host-sync] fallback attempts must surface their own execute-time faults inside the retry loop
                        jax.block_until_ready(out)
                # orion: allow[fault-except] retry-ladder rung: a failed fallback attempt feeds the next retry, then DispatchFault
                except Exception as e2:
                    eng.robust.dispatch_faults += 1
                    last = e2
                    continue
                eng.robust.dispatch_fallbacks += 1
                eng._flight_note("dispatch_fallback", path=path)
                return out
            raise DispatchFault(
                path, f"xla fallback failed too: {last}"
            ) from last
