"""Draft-model-free speculative decoding: prompt-lookup n-gram proposer.

Decode is memory-bandwidth-bound — one full pass over the weights per
emitted token (see PERF.md "Serving"). Speculative decoding amortizes that
pass: propose K likely continuation tokens per request on the HOST, verify
all of them (plus the pending last token) in ONE device dispatch
(runner.verify_step — structurally the multi-token machinery decode_window
and mixed_step already proved out), and accept the matched prefix. On
self-repetitive text (code, structured output, a model whose greedy
continuation loops) one weight pass emits up to K+1 tokens.

This module is the drafting half, deliberately model-free (prompt lookup,
a.k.a. n-gram speculation): the last ``n`` tokens of a request's context
are matched against earlier positions of the request's own prompt+output —
and, when the prefix cache is on, against the radix tree's cached token
paths (cross-request reuse: a cached system-prompt + answer path predicts
the next request's continuation) — and the continuation of the most recent
match is the draft. No draft model, no extra weights, no device work:
drafting costs O(n_slots * ngram * context) python per step, which is
noise next to a dispatch.

Acceptance is computed by the engine from the verify logits: greedy
acceptance is exact argmax match (spec-on output byte-identical to
spec-off); sampled acceptance is rejection sampling against the filtered
target distribution (sampling.spec_verify_sample), so the served
distribution is provably unchanged.

The per-request draft length adapts (``SpecState``): halve on a
low-acceptance verify (wasted KV writes + rollback churn), double back on
full acceptance, always within [1, speculate_tokens]. A request whose
context has no n-gram match simply drafts nothing that step — and if NO
slot drafts, the engine falls back to the plain decode window (speculation
never costs a non-repetitive workload more than the proposal scan).

Token TREES (``inference.spec_tree_width`` > 1): the same lookup collects
up to ``tree_width`` DISTINCT continuations (``propose_ngram_candidates``)
and merges them into a trie (``build_tree`` -> ``DraftTree``) flattened
parent-before-child onto the static verify width. One dispatch verifies
every branch under a packed ancestor mask; the engine accepts the longest
verified root-path and compacts its KV if it was not the primary chain.
Depth rides the SAME adaptive controller — on traffic where the single
path keeps missing, the halved depth frees verify-width for siblings,
which is the regime where breadth beats depth.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Iterable, Optional, Sequence


def propose_ngram(
    context: Sequence[int],
    k: int,
    *,
    max_n: int = 3,
    min_n: int = 1,
    extra_sources: Iterable[Sequence[int]] = (),
) -> list[int]:
    """Up to ``k`` draft tokens continuing ``context`` by prompt lookup.

    For ``n`` from ``max_n`` down to ``min_n``: find the MOST RECENT
    earlier occurrence of the context's last ``n`` tokens — first inside
    ``context`` itself, then in each of ``extra_sources`` (e.g. the prefix
    cache's token paths) — and return the tokens that followed it. Longer
    n-grams are tried first (a longer match is a stronger continuation
    signal); the in-context match wins over external sources at equal n
    (the request's own history is the better predictor of its own loop).
    """
    if k <= 0:
        return []
    L = len(context)
    max_n = min(max_n, L - 1)
    for n in range(max_n, min_n - 1, -1):
        suffix = list(context[L - n:])
        first = suffix[0]
        # Most recent occurrence strictly before the suffix itself, so a
        # continuation exists: context[i : i+n] == suffix with i+n < L.
        # The hot loop is a first-token compare per position (no slice
        # allocation); the full n-gram compare runs only on candidates —
        # this scan sits on the ITL-critical host path every decode step.
        for i in range(L - n - 1, -1, -1):
            if context[i] == first and list(context[i:i + n]) == suffix:
                return list(context[i + n:i + n + k])
        for src in extra_sources:
            S = len(src)
            for i in range(S - n - 1, -1, -1):
                if src[i] == first and list(src[i:i + n]) == suffix:
                    return list(src[i + n:i + n + k])
    return []


def propose_ngram_candidates(
    context: Sequence[int],
    k: int,
    *,
    max_n: int = 3,
    min_n: int = 1,
    extra_sources: Iterable[Sequence[int]] = (),
    max_candidates: int = 4,
) -> list[list[int]]:
    """Up to ``max_candidates`` DISTINCT continuations of ``context`` by
    prompt lookup, best-first.

    Same search order as ``propose_ngram`` — longer n-grams before
    shorter, in-context matches before external sources, most recent
    occurrence first — but instead of stopping at the first hit it keeps
    collecting distinct continuations, so the FIRST candidate is exactly
    the chain proposal and later candidates are the alternatives a
    single-path draft had to bet against. A continuation that is a
    prefix of an already-collected one adds nothing (its nodes are
    already in the tree) and is skipped.
    """
    if k <= 0:
        return []
    L = len(context)
    max_n = min(max_n, L - 1)
    cands: list[list[int]] = []

    def add(cont: list[int]) -> bool:
        """True once the candidate budget is exhausted."""
        if cont and not any(
            cand[: len(cont)] == cont for cand in cands
        ):
            cands.append(cont)
        return len(cands) >= max_candidates

    for n in range(max_n, min_n - 1, -1):
        suffix = list(context[L - n:])
        first = suffix[0]
        for i in range(L - n - 1, -1, -1):
            if context[i] == first and list(context[i:i + n]) == suffix:
                if add(list(context[i + n:i + n + k])):
                    return cands
        for src in extra_sources:
            S = len(src)
            for i in range(S - n - 1, -1, -1):
                if src[i] == first and list(src[i:i + n]) == suffix:
                    if add(list(src[i + n:i + n + k])):
                        return cands
    return cands


@dataclass
class DraftTree:
    """A token tree flattened to the static verify layout.

    Column 0 of the verify row is the slot's pending last token (the
    tree's root — it is NOT in ``tokens``); node i of the tree occupies
    column i + 1 and ``parents[i]`` is the COLUMN of its parent (0 for
    the root's children). Nodes are stored parent-before-child, and the
    FIRST inserted candidate chain occupies contiguous columns 1..d —
    so when the primary chain is the accepted path, acceptance needs no
    KV compaction (columns already equal depths), exactly the
    single-path layout.
    """

    tokens: list[int] = field(default_factory=list)
    parents: list[int] = field(default_factory=list)

    def __len__(self) -> int:
        return len(self.tokens)

    def depths(self) -> list[int]:
        """Depth per COLUMN (0..len), root column included (depth 0)."""
        d = [0]
        for p in self.parents:
            d.append(d[p] + 1)
        return d

    @property
    def max_depth(self) -> int:
        return max(self.depths())

    def mask_words(self) -> list[int]:
        """Packed ancestor mask per COLUMN: bit i of word j is set iff
        column j may attend the KV written at column i — its ancestors,
        the root, and itself. Chain-degenerate trees produce the causal
        words ``(1 << (j+1)) - 1``, the mask today's W-query verify
        applies implicitly. Columns must fit an int32 word
        (``len(tokens) + 1 <= 31``; the engine validates at init)."""
        words = [1]
        for j, p in enumerate(self.parents):
            words.append(words[p] | (1 << (j + 1)))
        return words

    def children(self) -> list[list[int]]:
        """Child COLUMNS per column, in insertion (priority) order —
        the order the engine's acceptance walk tries siblings in."""
        ch: list[list[int]] = [[] for _ in range(len(self.tokens) + 1)]
        for i, p in enumerate(self.parents):
            ch[p].append(i + 1)
        return ch

    @staticmethod
    def chain(tokens: Sequence[int]) -> "DraftTree":
        return DraftTree(list(tokens), list(range(len(tokens))))


def build_tree(candidates: list[list[int]], budget: int) -> DraftTree:
    """Merge candidate chains into a token trie of at most ``budget``
    nodes. Chains insert in priority order, sharing common prefixes and
    branching where they diverge; a chain that hits the node budget is
    truncated (its prefix may still have merged). Duplicate sibling
    tokens are merged by construction, so the acceptance walk never has
    two children matching the same verified token."""
    tree = DraftTree()
    child_of: dict[tuple[int, int], int] = {}
    for chain in candidates:
        col = 0
        for t in chain:
            nxt = child_of.get((col, t))
            if nxt is None:
                if len(tree.tokens) >= budget:
                    break
                tree.tokens.append(t)
                tree.parents.append(col)
                nxt = len(tree.tokens)
                child_of[(col, t)] = nxt
            col = nxt
    return tree


@dataclass
class SpecState:
    """Per-request adaptive draft length + lifetime acceptance counters.

    ``miss_streak``/``cooldown`` back the proposal-scan throttle: the
    n-gram scan is O(context) host work, and the workload that never
    matches is exactly the one that gains nothing from paying it every
    step. The first three misses rescan every step — right after prefill
    is when a repetition first establishes, so early throttling would
    delay real draft onset — then consecutive misses back off linearly
    (skip ``min(miss_streak - 3, 8)`` steps before rescanning), bounding
    steady-state non-repetitive traffic at ~1/8th of the scan cost; a
    hit resets the streak. The throttle never changes emitted tokens
    (speculation is output-invariant by construction)."""

    draft_len: int
    drafted: int = 0
    accepted: int = 0
    miss_streak: int = 0
    cooldown: int = 0

    def update(self, drafted: int, accepted: int, cap: int) -> None:
        """Adapt after one verify: halve on low acceptance (< half the
        drafts landed — the rejected tail is pure rollback churn), double
        back on full acceptance, clamp to [1, cap]. No-draft steps leave
        the length untouched (nothing was learned)."""
        if drafted <= 0:
            return
        self.drafted += drafted
        self.accepted += accepted
        if accepted >= drafted:
            self.draft_len = min(self.draft_len * 2, cap)
        elif accepted * 2 < drafted:
            self.draft_len = max(self.draft_len // 2, 1)


class NgramProposer:
    """Engine-facing proposer: owns the n-gram parameters and the
    per-request SpecState table (keyed by rid; dropped when the request
    leaves — a preempted request that re-enters restarts its adaptation
    from the configured cap, matching its re-prefilled cold start)."""

    def __init__(
        self,
        *,
        speculate_tokens: int,
        max_n: int,
        min_n: int,
        tree_width: int = 1,
    ):
        if speculate_tokens < 1:
            raise ValueError(
                f"speculate_tokens must be >= 1, got {speculate_tokens}"
            )
        if not 1 <= min_n <= max_n:
            raise ValueError(
                f"need 1 <= spec_ngram_min <= spec_ngram_max, got "
                f"[{min_n}, {max_n}]"
            )
        if tree_width < 1:
            raise ValueError(
                f"spec_tree_width must be >= 1, got {tree_width}"
            )
        self.cap = speculate_tokens
        self.max_n = max_n
        self.min_n = min_n
        self.tree_width = tree_width
        self._states: dict[int, SpecState] = {}

    def state(self, rid: int) -> SpecState:
        st = self._states.get(rid)
        if st is None:
            st = self._states[rid] = SpecState(draft_len=self.cap)
        return st

    def drop(self, rid: int) -> None:
        self._states.pop(rid, None)

    def propose(
        self,
        rid: int,
        context: Sequence[int],
        limit: int,
        extra_sources: Iterable[Sequence[int]] = (),
    ) -> list[int]:
        """Draft for one request: n-gram lookup capped by the adaptive
        per-request length AND the caller's ``limit`` (context-window /
        budget headroom), throttled after consecutive misses (see
        SpecState)."""
        st = self.state(rid)
        if st.cooldown > 0:
            st.cooldown -= 1
            return []
        k = min(st.draft_len, limit)
        d = propose_ngram(
            context, k, max_n=self.max_n, min_n=self.min_n,
            extra_sources=extra_sources,
        )
        if d:
            st.miss_streak = 0
        else:
            st.miss_streak += 1
            st.cooldown = max(0, min(st.miss_streak - 3, 8))
        return d

    def propose_tree(
        self,
        rid: int,
        context: Sequence[int],
        limit: int,
        extra_sources: Iterable[Sequence[int]] = (),
    ) -> Optional[DraftTree]:
        """Tree drafting (inference.spec_tree_width > 1): up to
        ``tree_width`` distinct n-gram continuations merged into a token
        trie of at most ``min(speculate_tokens, limit)`` nodes.

        The per-candidate DEPTH rides the same acceptance-driven
        adaptive length as the chain proposer (``SpecState.draft_len``):
        on traffic where the single path keeps being rejected, the
        controller halves the depth — and the freed verify-width budget
        turns into sibling branches, which is exactly the regime where
        breadth beats depth. On fully-accepting (looping) traffic the
        depth grows back to the cap and the tree degenerates to the
        chain. The miss-streak scan throttle is shared with ``propose``.
        Returns None on a no-draft step."""
        st = self.state(rid)
        if st.cooldown > 0:
            st.cooldown -= 1
            return None
        k = min(st.draft_len, limit)
        cands = propose_ngram_candidates(
            context, k, max_n=self.max_n, min_n=self.min_n,
            extra_sources=extra_sources, max_candidates=self.tree_width,
        )
        if not cands:
            st.miss_streak += 1
            st.cooldown = max(0, min(st.miss_streak - 3, 8))
            return None
        st.miss_streak = 0
        budget = min(self.cap, limit)
        if len(cands) > 1:
            # Real ambiguity must materialize as branches even when the
            # adaptive depth fills the node budget: reserve one node per
            # alternative candidate by trimming the primary chain's tail
            # — the bet breadth makes is exactly that the trimmed tail's
            # expected yield is lower than a sibling's when the n-gram
            # evidence is split. Alternatives that share their prefix
            # with the primary merge in the trie and give the room back.
            head = max(1, budget - (len(cands) - 1))
            cands = [cands[0][:head]] + cands[1:]
        tree = build_tree(cands, budget)
        return tree if len(tree) else None
