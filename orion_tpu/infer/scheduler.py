"""Scheduler face of the serving engine (ISSUE 12 tentpole split).

The engine used to be one 2.7k-line class mixing two concerns: the
*scheduler face* — what a multi-replica front-end talks to: the request
lifecycle (typed outcomes), the admission queue with its shed/deadline
policy, and the radix prefix index as a placement signal — and the
*executor* — the jitted dispatch programs, the KV pool and the
degradation ladder (infer/executor.py). This module owns the scheduler
half: the ``Request`` dataclass and the ``AdmissionQueue`` policy object
the engine delegates its queue decisions to. ``infer/router.py`` builds
on exactly this face: a replica is "somewhere requests can be admitted,
with typed outcomes and registry gauges", nothing more.

Behavior contract: everything here is a verbatim relocation of engine
policy — single-replica serving compiles byte-identical programs and
produces byte-identical greedy streams (pinned by tests/test_router.py's
pass-through equivalence case).
"""

from __future__ import annotations

import time
from collections import deque
from dataclasses import dataclass, field
from typing import Any, Optional


@dataclass
class Request:
    rid: int
    prompt: list[int]
    max_new_tokens: int
    generated: list[int] = field(default_factory=list)
    # Per-request sampling overrides; None = inference.* config defaults.
    temperature: Optional[float] = None
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    # SLO class (higher = more important): admission and page-pressure
    # preemption prefer high-priority requests; overload shedding evicts
    # the lowest class first.
    priority: int = 0
    # Absolute time.monotonic() deadline (None = none). Expired requests
    # are reaped at step boundaries with a typed "expired" outcome.
    deadline: Optional[float] = None
    # Typed terminal outcome: "" while live, then exactly one of
    # "completed" | "expired" | "cancelled" | "shed" | "shed:<kind>" |
    # "error:<kind>". Every submitted request surfaces from step() with
    # an outcome — no silent drops. "shed:<kind>" carries a policy
    # reason (today: "shed:context_too_long" — the long-context
    # feasibility check; plain "shed" stays overload/drain).
    outcome: str = ""
    # Trace context (ISSUE 14): the fleet-level correlation id stamped by
    # the router at submit and carried through every engine attempt —
    # engine-side rids are per-replica and change across failover, so
    # lifecycle instants tag ``tid`` (trace_id, falling back to rid on a
    # bare engine) to make one request's journey a single correlated
    # track in the merged timeline. ``attempt`` is the failover attempt
    # number (0 = first placement); retried attempts tag their instants
    # ``retried=attempt``.
    trace_id: Optional[int] = None
    attempt: int = 0
    # scheduler state
    slot: Optional[int] = None
    pages: list[int] = field(default_factory=list)
    done: bool = False
    admit_seq: int = -1   # admission order; preemption evicts the youngest
    freed_until: int = 0  # logical pages below this are freed (SWA rolling)
    # Prefix-cache state: the first n_prefix entries of ``pages`` are
    # SHARED (refcounted, immutable) cache pages; prefix_node pins their
    # radix-tree path against eviction until release.
    n_prefix: int = 0
    prefix_node: Optional[Any] = None
    # Chunked-prefill cursor (inference.chunked_prefill): context tokens
    # whose KV is already in the pool (cached prefix + completed chunks,
    # always page-aligned until the final chunk). While prefill_pending,
    # the slot rides mixed steps as a prompt-chunk row, never a decode row.
    prefill_done: int = 0
    prefill_pending: bool = False
    # Long-context host paging (inference.long_context): logical page
    # index -> HostPagePool slot holding that page's KV bytes, one
    # ENGINE-owned ref per slot (the prefix tree never sees these).
    # Populated by residency demotion (inference.request_resident_pages)
    # and preempt-to-host; drained by the engine's page-in pass before
    # the chunk/decode dispatch that reads them, or dropped when the SWA
    # window rolls past a host-resident page / the request terminates.
    host_pages: dict[int, int] = field(default_factory=dict)
    # Spill-time snapshot for preempt-to-host: KV is valid in
    # [0, host_cursor) across device+host pages, and host_last_token is
    # the in-flight token — re-admission restores and resumes instead of
    # re-prefilling the whole context.
    host_cursor: int = 0
    host_last_token: int = 0
    # Grammar constraint (orion_tpu.constrain.ConstraintState): the
    # request's walk through its token DFA. Pure host state — survives
    # preemption (re-prefill replays prompt + generated; the state
    # re-syncs off ``generated`` if a failover replayed the request).
    constraint: Optional[Any] = None

    @property
    def context(self) -> list[int]:
        """Tokens whose KV must be in cache: prompt + everything generated.
        This is what (re-)prefill runs on, so a preempted request resumes
        exactly where it left off."""
        return self.prompt + self.generated

    @property
    def active(self) -> bool:
        return self.slot is not None and not self.done


def in_flight(req: Request) -> bool:
    """A queued request that has RUN: admitted at least once and not
    since un-claimed (admit_seq >= 0 — preemption and fault unwinds
    keep it), or carrying generated tokens from a previous residency
    (survives even an admission pool-fault deferral, which resets
    admit_seq). In-flight requests are exempt from overload shedding
    and are finished — not shed — by drain()."""
    return req.admit_seq >= 0 or bool(req.generated)


class AdmissionQueue(deque):
    """The engine's wait queue plus its admission-side policy.

    A plain deque (every existing queue operation — appendleft, index
    deletion, iteration — keeps working) carrying the two policy
    decisions the scheduler face owns:

      - ``shed_victim``: which request an over-limit submit sheds;
      - ``sweep_expired``: the step-boundary deadline sweep over
        still-waiting requests.

    Both are verbatim relocations of the engine's inline logic.
    """

    def shed_victim(self, incoming: Request) -> Request:
        """The least defensible overload-shed candidate among the queued
        never-run requests plus ``incoming``: lowest priority first, then
        the nearest (most infeasible) deadline, then the newest arrival —
        which may be the incoming request itself. In-flight requests
        (see ``in_flight``) are never victims: "shed" means never
        admitted (RobustnessStats contract)."""
        return min(
            [r for r in self if not in_flight(r)] + [incoming],
            key=lambda r: (
                r.priority,
                r.deadline if r.deadline is not None else float("inf"),
                -r.rid,
            ),
        )

    def sweep_expired(self, now: Optional[float] = None) -> list[Request]:
        """Remove and return every queued request whose deadline has
        passed (callers mark them "expired" — the typed outcome stays
        with the engine, which owns the stats and the finished list)."""
        if now is None:
            now = time.monotonic()
        if not any(
            r.deadline is not None and now >= r.deadline for r in self
        ):
            return []
        expired: list[Request] = []
        keep: list[Request] = []
        for r in self:
            if r.deadline is not None and now >= r.deadline:
                expired.append(r)
            else:
                keep.append(r)
        self.clear()
        self.extend(keep)
        return expired
