"""Paged KV-cache pool + host-side page allocator.

The reference's continuous-batching server manages a paged KV cache
(BASELINE.json:11; PAPERS.md:9 "ragged paged attention for TPU"). TPU-native
design: one global pool of fixed-size pages per layer, so every jit program
sees static shapes; sequences own pages through an integer page table, and
the *allocator* — the only dynamic piece — lives on the host, where it is a
free list, not a device computation.

Layout:
    k_pool, v_pool: [n_layers * num_pages, n_kv_heads, page_size, head_dim]
    page_table:     [max_batch, pages_per_seq] int32 (host, shipped per step)
    seq_lens:       [max_batch] int32            (host, shipped per step)

Heads sit OUTSIDE the (page_size, head_dim) minor dims so one page's whole
(1, K, psz, H) block is TPU-tiling-legal for the ragged paged-attention
kernel, with the head dim as a batched-matmul dim (see
ops/pallas/paged_attention.py).

The layer dim is FLATTENED into the page dim (layer l's pages are rows
[l*num_pages, (l+1)*num_pages)): the pool can then be a single scan carry
whose per-layer updates are in-place scatters at dynamic row offsets —
carrying it as per-layer scan xs/ys instead would make XLA rewrite the
entire multi-GB pool every step (measured 5.4 GB/step on the 1B bench
model). Page ids in page tables are per-layer-relative; device code adds
``l * num_pages``.

Page 0 (of each layer region) is reserved as a scratch page: every inactive
batch slot points at it, so device-side gathers/scatters are always
in-bounds and slot masking is done with seq_lens alone.
"""

from __future__ import annotations

from typing import Any, Optional

import jax
import jax.numpy as jnp

from orion_tpu.config import InferenceConfig, ModelConfig

Cache = dict[str, jax.Array]


def pages_per_seq(icfg: InferenceConfig) -> int:
    assert icfg.max_seq_len % icfg.page_size == 0, (
        icfg.max_seq_len, icfg.page_size)
    return icfg.max_seq_len // icfg.page_size


def init_cache(
    mcfg: ModelConfig,
    icfg: InferenceConfig,
    device: Optional[jax.Device] = None,
) -> Cache:
    """Allocate the paged KV pool (zeros)."""
    shape = (
        mcfg.n_layers * icfg.num_pages,
        mcfg.n_kv_heads,
        icfg.page_size,
        mcfg.resolved_head_dim,
    )
    dtype = jnp.dtype(mcfg.dtype)

    def alloc():
        return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}

    if device is not None:
        with jax.default_device(device):
            return alloc()
    return alloc()


class PageAllocator:
    """Host-side free list over the page pool (page 0 reserved as scratch)."""

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is scratch)")
        self.num_pages = num_pages
        self._free: list[int] = list(range(num_pages - 1, 0, -1))

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def alloc(self, n: int) -> list[int]:
        if n > len(self._free):
            raise MemoryError(
                f"KV cache pool exhausted: want {n} pages, have "
                f"{len(self._free)}"
            )
        pages = [self._free.pop() for _ in range(n)]
        return pages

    def free(self, pages: list[int]) -> None:
        for p in pages:
            assert 0 < p < self.num_pages, p
            self._free.append(p)
