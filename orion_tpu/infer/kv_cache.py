"""Paged KV-cache pool + host-side page allocator.

The reference's continuous-batching server manages a paged KV cache
(BASELINE.json:11; PAPERS.md:9 "ragged paged attention for TPU"). TPU-native
design: one global pool of fixed-size pages per layer, so every jit program
sees static shapes; sequences own pages through an integer page table, and
the *allocator* — the only dynamic piece — lives on the host, where it is a
free list, not a device computation.

Layout:
    k_pool, v_pool: [n_layers * num_pages, n_kv_heads, page_size, head_dim]
    page_table:     [max_batch, pages_per_seq] int32 (host, shipped per step)
    seq_lens:       [max_batch] int32            (host, shipped per step)

Heads sit OUTSIDE the (page_size, head_dim) minor dims so one page's whole
(1, K, psz, H) block is TPU-tiling-legal for the ragged paged-attention
kernel, with the head dim as a batched-matmul dim (see
ops/pallas/paged_attention.py).

The layer dim is FLATTENED into the page dim (layer l's pages are rows
[l*num_pages, (l+1)*num_pages)): the pool can then be a single scan carry
whose per-layer updates are in-place scatters at dynamic row offsets —
carrying it as per-layer scan xs/ys instead would make XLA rewrite the
entire multi-GB pool every step (measured 5.4 GB/step on the 1B bench
model). Page ids in page tables are per-layer-relative; device code adds
``l * num_pages``.

Page 0 (of each layer region) is reserved as a scratch page: every inactive
batch slot points at it, so device-side gathers/scatters are always
in-bounds and slot masking is done with seq_lens alone.
"""

from __future__ import annotations

import itertools
import math
from typing import Any, Optional

import jax
import jax.numpy as jnp
import numpy as np

from orion_tpu.config import InferenceConfig, ModelConfig

Cache = dict[str, jax.Array]


def pages_per_seq(icfg: InferenceConfig) -> int:
    assert icfg.max_seq_len % icfg.page_size == 0, (
        icfg.max_seq_len, icfg.page_size)
    return icfg.max_seq_len // icfg.page_size


SCALE_LANES = 128  # scale pools pad the token dim to a full lane tile so
#                    their (1, K, SCALE_LANES) kernel blocks are (8, 128)-
#                    tiling-legal f32; columns >= page_size are dead.


def scale_width(psz: int) -> int:
    if psz > SCALE_LANES:
        raise ValueError(
            f"kv_quant='int8' requires page_size <= {SCALE_LANES}, "
            f"got {psz} (one lane tile holds one page's scales)"
        )
    return SCALE_LANES


# Single definition shared with the paged kernel's fused in-kernel write
# (decode and prefill quantization must agree bit-for-bit).
from orion_tpu.ops.pallas.common import quantize_kv  # noqa: F401,E402


def init_cache(
    mcfg: ModelConfig,
    icfg: InferenceConfig,
    device: Optional[jax.Device] = None,
) -> Cache:
    """Allocate the paged KV pool (zeros).

    With ``inference.kv_quant='int8'`` the pools are int8 and carry f32
    scale pools ``k_scale``/``v_scale`` of shape [rows, K, SCALE_LANES]
    (column t = token t's scale on that page; lanes-padded past
    page_size). Presence of the scale keys is what runner/kernel code
    keys off — the cache dict is the single source of truth.
    """
    rows = mcfg.n_layers * icfg.num_pages
    K, psz, H = mcfg.n_kv_heads, icfg.page_size, mcfg.resolved_head_dim
    shape = (rows, K, psz, H)

    def alloc():
        if icfg.kv_quant == "int8":
            sw = scale_width(psz)
            return {
                "k": jnp.zeros(shape, jnp.int8),
                "v": jnp.zeros(shape, jnp.int8),
                "k_scale": jnp.zeros((rows, K, sw), jnp.float32),
                "v_scale": jnp.zeros((rows, K, sw), jnp.float32),
            }
        if icfg.kv_quant is not None:
            raise ValueError(f"unknown inference.kv_quant={icfg.kv_quant!r}")
        dtype = jnp.dtype(mcfg.dtype)
        return {"k": jnp.zeros(shape, dtype), "v": jnp.zeros(shape, dtype)}

    if device is not None:
        with jax.default_device(device):
            return alloc()
    return alloc()


class PageAllocator:
    """Host-side refcounted free list over the page pool (page 0 = scratch).

    Pages are refcounted so the prefix cache (infer/prefix_cache.py) and
    live requests can SHARE immutable pages: ``alloc`` hands out pages at
    refcount 1, ``retain`` adds an owner, and ``release`` drops one — the
    page returns to the free list only when its last owner lets go. The
    single accounting invariant every owner relies on:

        free_pages + sum(refcounted live pages) == num_pages - 1

    where a page is live iff its refcount > 0 (owners: one per mapping in a
    live request's page table, plus one for the radix-tree node that caches
    it). ``free`` remains as a bulk release for owners holding exactly one
    ref per page.
    """

    def __init__(self, num_pages: int):
        if num_pages < 2:
            raise ValueError("need at least 2 pages (page 0 is scratch)")
        self.num_pages = num_pages
        self._free: list[int] = list(range(num_pages - 1, 0, -1))
        self._refs: list[int] = [0] * num_pages

    @property
    def free_pages(self) -> int:
        return len(self._free)

    def refcount(self, page: int) -> int:
        return self._refs[page]

    def alloc(self, n: int) -> list[int]:
        if n > len(self._free):
            raise MemoryError(
                f"KV cache pool exhausted: want {n} pages, have "
                f"{len(self._free)}"
            )
        pages = [self._free.pop() for _ in range(n)]
        for p in pages:
            self._refs[p] = 1
        return pages

    def retain(self, page: int) -> None:
        """Add an owner to a live (shared) page."""
        assert 0 < page < self.num_pages, page
        assert self._refs[page] > 0, f"retain of free page {page}"
        self._refs[page] += 1

    def release(self, page: int) -> bool:
        """Drop one ownership ref; returns True iff the page was freed."""
        assert 0 < page < self.num_pages, page
        assert self._refs[page] > 0, f"release of free page {page}"
        self._refs[page] -= 1
        if self._refs[page] == 0:
            self._free.append(page)
            return True
        return False

    def free(self, pages: list[int]) -> None:
        """Bulk release for owners holding one ref per page."""
        for p in pages:
            self.release(p)


def rollback_pages(
    alloc: PageAllocator,
    pages: list,
    n_keep: int,
) -> list[int]:
    """Speculative-decode rollback: truncate a request's page list to its
    first ``n_keep`` entries, releasing the tail back to the pool.

    The verify step pre-provisions pages for the whole draft window
    (write positions may run speculate_tokens past the accepted cursor);
    after acceptance, pages covering ONLY rejected tokens are dead — no
    position below the rewound cursor lives in them, and the next window's
    provisioning re-allocates from the free list (LIFO, so the same pages
    come straight back if speculation continues). Releasing them here
    restores exactly the page footprint a non-speculative (window=1)
    engine holds after its step, which is what keeps pool-pressure
    preemption and the admission math speculation-agnostic.

    Tail entries are always privately-owned (refcount 1): shared prefix
    pages and SWA-rolled ``None`` placeholders live strictly below any
    live cursor, hence below ``n_keep``. Returns the released page ids
    (the caller zeroes their page-table columns).
    """
    assert n_keep >= 0, n_keep
    dead = [p for p in pages[n_keep:] if p is not None]
    del pages[n_keep:]
    alloc.free(dead)
    return dead


def compact_draft_kv(
    cache: Cache,
    page_table: jax.Array,    # [B, P] int32 per-layer-relative page ids
    seq_lens: jax.Array,      # [B] int32: the verify-time cursor (start)
    src: jax.Array,           # [B, W] int32: column whose KV moves to
    #                           position start + i (identity = no move)
    *,
    n_layers: int,
    num_pages: int,
) -> Cache:
    """Tree-speculation KV compaction: move accepted off-path draft KV
    into cursor-contiguous positions.

    A verify step writes tree column j's K/V at pool position
    ``start + j``; an accepted root-path of depth d consists of columns
    ``path[1..d]``, which are slot-contiguous ONLY when the accepted path
    is the tree's first inserted chain. For any other branch, position
    ``start + i`` must end up holding column ``path[i]``'s KV before the
    next decode step reads it. This gathers every (b, i) source entry
    (position ``start + src[b, i]``) across ALL layers and cache arrays
    (int8 pools move with their scale columns) and scatters it to
    position ``start + i`` — gather-before-scatter, so overlapping moves
    (dst slots are always <= src slots: depth <= column index) read
    pre-move bytes. Identity entries copy onto themselves; rows past a
    slot's real width point at whatever the clamp hits, which is either
    a self-copy or the scratch page — both unobservable. One jitted
    program serves every step (the engine pads ``src`` with identity).

    Accepted KV bytes are MOVED verbatim (quantized bytes + scales under
    kv_quant), so the compacted pool is bitwise the pool a sequential
    decode of the accepted tokens would have produced — the greedy
    byte-identity argument runs through this function.
    """
    B, W = src.shape
    psz = cache["k"].shape[2]
    P = page_table.shape[1]
    max_pos = P * psz - 1
    bidx = jnp.arange(B, dtype=jnp.int32)[:, None]
    steps = jnp.arange(W, dtype=jnp.int32)[None, :]
    src_pos = jnp.minimum(seq_lens[:, None] + src.astype(jnp.int32),
                          max_pos)
    dst_pos = jnp.minimum(seq_lens[:, None] + steps, max_pos)
    layer = jnp.arange(n_layers, dtype=jnp.int32)[:, None, None] * num_pages
    rows_src = layer + page_table[bidx, src_pos // psz][None]   # [L, B, W]
    rows_dst = layer + page_table[bidx, dst_pos // psz][None]
    off_src = jnp.broadcast_to(src_pos % psz, (n_layers, B, W))
    off_dst = jnp.broadcast_to(dst_pos % psz, (n_layers, B, W))
    out = dict(cache)
    for name, arr in cache.items():
        # Pools are [rows, K, psz, H]; scale pools [rows, K, SCALE_LANES].
        # Either way the per-token column is axis 2 of the row block.
        vals = arr[rows_src, :, off_src]
        out[name] = arr.at[rows_dst, :, off_dst].set(vals)
    return out


def poison_page(cache: Cache, page, *, n_layers: int, num_pages: int) -> Cache:
    """Overwrite one pool page's K rows (all layers) with NaN — the fault
    INJECTION primitive behind the NaN-quarantine tests (runtime/fault.py
    FaultSpec kind="nan"): real NaNs flow through the real attention into
    exactly one slot's logits, because no other slot ever reads this
    request's pages. Under kv_quant the int8 pool cannot hold a NaN, so the
    f32 ``k_scale`` rows are poisoned instead (dequantized K goes NaN, same
    blast radius). ``page`` may be a traced scalar."""
    layer_rows = jnp.arange(n_layers, dtype=jnp.int32) * num_pages + page
    target = "k_scale" if "k_scale" in cache else "k"
    out = dict(cache)
    arr = out[target]
    out[target] = arr.at[layer_rows].set(jnp.asarray(jnp.nan, arr.dtype))
    return out


def scrub_pages(
    cache: Cache, pages: jax.Array, *, n_layers: int, num_pages: int
) -> Cache:
    """Zero the given pool pages' rows across every cache array (all
    layers): the quarantine path scrubs a poisoned request's private pages
    before returning them to the free list, so stale NaNs can never leak
    into a later tenant of the same page. ``pages`` may contain repeats
    and scratch page 0 (padding) — zeroing scratch is harmless, it is
    never read."""
    layer_rows = (
        jnp.arange(n_layers, dtype=jnp.int32)[:, None] * num_pages
        + pages[None, :].astype(jnp.int32)
    ).reshape(-1)
    return {
        name: arr.at[layer_rows].set(jnp.zeros((), arr.dtype))
        for name, arr in cache.items()
    }


class HostPagePool:
    """Host-RAM page store: the second tier behind the radix tree.

    ``PageAllocator``'s counterpart for host memory — same refcounted
    free-list discipline (slots at refcount 1 from ``alloc``, ``retain``
    adds an owner, ``release`` drops one) plus the two things a HOST tier
    needs that the device pool does not:

    * byte storage: ``store``/``load`` move page blocks (the per-array
      ``[n, n_layers, ...]`` stacks that ``gather_pages`` produces) into
      and out of preallocated numpy buffers, one slot per page. The
      buffers are allocated lazily on the first ``store`` so the pool
      never needs the cache dict's dtypes up front, and they are plain
      pinned-by-the-OS host arrays — no device allocation ever.
    * its own LRU clock: ``touch`` stamps a slot on every store/load,
      ``evict_lru`` frees the coldest UNREFERENCED slots. A slot with
      refcount > 1 is skipped, never reclaimed out from under an extra
      owner (e.g. an in-flight restore's ref) — the evict-while-
      referenced refusal.

    One object-store shape serves KV pages today and adapter pages later
    (ROADMAP LoRA item): nothing here knows what the bytes mean.
    """

    def __init__(self, capacity: int, page_bytes: int = 0):
        if capacity < 1:
            raise ValueError(f"HostPagePool needs capacity >= 1, got {capacity}")
        self.capacity = capacity
        self.page_bytes = page_bytes
        self._free: list[int] = list(range(capacity - 1, -1, -1))
        self._refs: list[int] = [0] * capacity
        self._stamps: list[int] = [0] * capacity
        self._clock = itertools.count(1)
        self._store: dict[str, np.ndarray] = {}

    @property
    def free_slots(self) -> int:
        return len(self._free)

    def refcount(self, hid: int) -> int:
        return self._refs[hid]

    def alloc(self, n: int) -> list[int]:
        if n > len(self._free):
            raise MemoryError(
                f"host page pool exhausted: want {n} slots, have "
                f"{len(self._free)}"
            )
        hids = [self._free.pop() for _ in range(n)]
        now = next(self._clock)
        for h in hids:
            self._refs[h] = 1
            self._stamps[h] = now
        return hids

    def retain(self, hid: int) -> None:
        assert 0 <= hid < self.capacity, hid
        assert self._refs[hid] > 0, f"retain of free host slot {hid}"
        self._refs[hid] += 1

    def release(self, hid: int) -> bool:
        """Drop one ownership ref; returns True iff the slot was freed."""
        assert 0 <= hid < self.capacity, hid
        assert self._refs[hid] > 0, f"release of free host slot {hid}"
        self._refs[hid] -= 1
        if self._refs[hid] == 0:
            self._free.append(hid)
            return True
        return False

    def free(self, hids: list[int]) -> None:
        """Bulk release for owners holding one ref per slot."""
        for h in hids:
            self.release(h)

    def touch(self, hid: int) -> None:
        self._stamps[hid] = next(self._clock)

    def evict_lru(self, n: int) -> list[int]:
        """Free up to ``n`` of the coldest single-owner slots.

        Only slots at refcount exactly 1 are reclaimable: a second ref
        means someone (an in-flight restore, a future adapter mapping)
        is actively relying on the bytes, and evicting those would tear
        them — such slots are skipped, not stolen. Returns the freed
        slot ids; the CALLER owns dropping its tree/table entries for
        them (this pool knows nothing about the radix tree).
        """
        if n <= 0:
            return []
        victims = sorted(
            (h for h in range(self.capacity) if self._refs[h] == 1),
            key=lambda h: self._stamps[h],
        )[:n]
        for h in victims:
            self.release(h)
        return victims

    def store(self, hids: list[int], blocks: dict[str, np.ndarray],
              n: Optional[int] = None) -> None:
        """Copy the first ``n`` rows of each per-array page block into the
        given slots (``blocks`` row i -> ``hids[i]``). Rows past ``n`` are
        dispatch padding (scratch-page gathers) and are dropped here —
        padding never occupies host RAM."""
        n = len(hids) if n is None else n
        assert n <= len(hids), (n, len(hids))
        rows = list(hids[:n])
        now = next(self._clock)
        for name, blk in blocks.items():
            blk = np.asarray(blk)
            buf = self._store.get(name)
            if buf is None:
                buf = np.empty((self.capacity,) + blk.shape[1:], blk.dtype)
                self._store[name] = buf
            buf[rows] = blk[:n]
        for h in rows:
            self._stamps[h] = now

    def load(self, hids: list[int]) -> dict[str, np.ndarray]:
        """Stack the given slots' bytes into per-array page blocks
        (row i = ``hids[i]``), shaped for ``scatter_pages``."""
        rows = list(hids)
        now = next(self._clock)
        for h in rows:
            self._stamps[h] = now
        return {name: buf[rows] for name, buf in self._store.items()}


def gather_pages(
    cache: Cache, pages: jax.Array, *, n_layers: int, num_pages: int
) -> Cache:
    """Gather whole pool pages (all layers, all cache arrays) into dense
    per-array blocks ``[n, n_layers, ...]`` — the device half of the ONE
    batched d2h an eviction sweep performs. ``pages`` may contain scratch
    page 0 as padding (one jit program per pow2 batch size); padding rows
    gather scratch bytes, which the caller drops before storing. Scale
    pools under kv_quant ride along because the gather walks the whole
    cache dict. No donation: the pool is read, not consumed."""
    rows = (
        pages[:, None].astype(jnp.int32)
        + jnp.arange(n_layers, dtype=jnp.int32)[None, :] * num_pages
    )
    return {name: arr[rows] for name, arr in cache.items()}


def scatter_pages(
    cache: Cache, pages: jax.Array, blocks: Cache,
    *, n_layers: int, num_pages: int,
) -> Cache:
    """Scatter dense page blocks (``gather_pages``' shape) back into the
    pool pages — the device half of the ONE batched h2d a restore
    performs. Padding entries target scratch page 0 (never read; repeated
    scatter indices land arbitrarily but harmlessly there). The engine
    jits this with the pool donated: restore rewrites rows in place."""
    rows = (
        pages[:, None].astype(jnp.int32)
        + jnp.arange(n_layers, dtype=jnp.int32)[None, :] * num_pages
    )
    return {
        name: arr.at[rows].set(blocks[name].astype(arr.dtype))
        for name, arr in cache.items()
    }


def host_page_bytes(cache: Cache, n_layers: int) -> int:
    """Host bytes one pool page occupies across every cache array (all
    layers; scale pools included under kv_quant) — the unit the
    ``inference.host_tier_bytes`` budget is divided by."""
    total = 0
    for arr in cache.values():
        per_row = math.prod(arr.shape[1:]) * arr.dtype.itemsize
        total += n_layers * per_row
    return total


def host_tier_break_even_tokens(
    page_bytes: int,
    page_size: int,
    h2d_gbps: float,
    restore_overhead_s: float,
    prefill_tok_s: float,
) -> Optional[int]:
    """Break-even match length: the token count above which restoring a
    host-resident prefix beats recomputing it (PERF.md "Host-tier
    break-even").

        restore(t)   = overhead + t * bytes_per_token / (bw * 1e9)
        recompute(t) = t / prefill_tok_s

    Both are linear in t; restore pays a fixed dispatch/sync overhead but
    a (typically much) cheaper per-token slope, so the lines cross at

        t* = overhead / (1/prefill_tok_s - bytes_per_token/bw)

    Returns ``None`` when the restore slope is >= the recompute slope
    (restore NEVER wins — e.g. a slow interconnect against a tiny model);
    otherwise the crossing, floored at one page so a sub-page match never
    qualifies. The constants are config knobs with measured defaults
    (``tools/prefix_cache_bench.py --capacity-sweep`` reports real ones).
    """
    per_tok_restore = (page_bytes / page_size) / (h2d_gbps * 1e9)
    per_tok_compute = 1.0 / prefill_tok_s
    if per_tok_restore >= per_tok_compute:
        return None
    gain = per_tok_compute - per_tok_restore
    return max(page_size, math.ceil(restore_overhead_s / gain))


def copy_page(cache: Cache, src, dst, *, n_layers: int, num_pages: int) -> Cache:
    """Copy one pool page's rows (all layers, all cache arrays) src -> dst.

    The copy-on-write primitive behind prefix caching: when a request's
    whole context is cached, its first decode step must (re)write the KV
    slot of the final token — which lives in a SHARED page. The engine
    copies that page into a private one first, so shared pages stay
    immutable. ``src``/``dst`` may be traced scalars (one jit program
    serves every copy); scale pools under kv_quant ride along because the
    copy walks the whole cache dict.
    """
    layer_rows = jnp.arange(n_layers, dtype=jnp.int32) * num_pages
    rows_src = layer_rows + src
    rows_dst = layer_rows + dst
    return {
        name: arr.at[rows_dst].set(arr[rows_src])
        for name, arr in cache.items()
    }
