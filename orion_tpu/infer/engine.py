"""Continuous-batching inference engine (host-side scheduler).

Mirrors the reference's ``inference/generate.py`` serving loop
(BASELINE.json:11; SURVEY.md §4 stack B): an admission/scheduler loop on the
host drives two jit programs — per-prompt prefill (bucketed static lengths)
and whole-batch decode (fully static shapes). Requests join mid-flight as
slots and KV pages free up; batching never changes any request's tokens
(checked by the equivalence tests in tests/test_infer.py).

ISSUE 12 split the single class into a scheduler face and an executor:
the request lifecycle + admission-queue policy live in
``infer/scheduler.py`` (Request, AdmissionQueue), the dispatch programs +
fault envelope in ``infer/executor.py`` (DispatchExecutor), and this
class composes them — byte-identical programs and streams to the
pre-split engine. ``infer/router.py`` fans requests across N of these
engines as replicas, reading the scheduler face (typed outcomes,
registry gauges, ``prefix_match_tokens``) and nothing deeper.
"""

from __future__ import annotations

import contextlib
import itertools
import logging
import time
from functools import lru_cache, partial
from typing import Any, Optional, Sequence

import jax
import jax.numpy as jnp
import numpy as np

from orion_tpu.config import Config
from orion_tpu.infer.executor import DispatchExecutor
from orion_tpu.infer.kv_cache import (
    HostPagePool,
    PageAllocator,
    copy_page,
    gather_pages,
    host_page_bytes,
    host_tier_break_even_tokens,
    init_cache,
    pages_per_seq,
    poison_page,
    rollback_pages,
    scatter_pages,
    scrub_pages,
)
from orion_tpu.infer.scheduler import AdmissionQueue, Request, in_flight
from orion_tpu.infer.sampling import sample
from orion_tpu.metrics import (
    ConstraintStats,
    PrefixCacheStats,
    RobustnessStats,
    SpecDecodeStats,
)
from orion_tpu.obs import (
    MetricsRegistry,
    export_chrome_safe,
    init_obs,
    live_hbm_metrics,
)
from orion_tpu.runtime.fault import (
    DispatchFault,
    FaultInjector,
    InjectedFault,
    Watchdog,
)

log = logging.getLogger("orion_tpu.infer")


@lru_cache(maxsize=None)
def _gather_pages_jit(n_layers: int, num_pages: int):
    """Process-wide jitted batched page gather, keyed by pool geometry:
    fleet replicas in one process (infer.Router) share the compiled
    executables instead of each engine re-compiling its own — a
    migration's scatter compile on a decode replica would otherwise land
    in that replica's serving clock."""
    return jax.jit(
        partial(gather_pages, n_layers=n_layers, num_pages=num_pages),
    )


@lru_cache(maxsize=None)
def _scatter_pages_jit(n_layers: int, num_pages: int):
    return jax.jit(
        partial(scatter_pages, n_layers=n_layers, num_pages=num_pages),
        donate_argnums=(0,),
    )


def _detect_tp_mesh(params: Any, axis: str = "tp"):
    """The params' mesh, iff they are sharded over a ``tp`` axis of size > 1.

    The engine is mesh-agnostic for the dense math (XLA partitions the
    einsums from the params' shardings alone), but the Pallas kernels are
    opaque to the SPMD partitioner and need an explicit head-sharded
    shard_map — which needs the mesh. Detecting it from the params keeps
    the public engine API unchanged: shard the params, get sharded serving.
    """
    for leaf in jax.tree.leaves(params):
        s = getattr(leaf, "sharding", None)
        if (
            isinstance(s, jax.sharding.NamedSharding)
            and s.mesh.shape.get(axis, 1) > 1
        ):
            return s.mesh
    return None


class InferenceEngine:
    """Paged-KV continuous-batching engine over a single model replica.

    Multi-chip serving shards the same programs over a mesh (the params'
    shardings decide); the scheduler below is mesh-agnostic.
    """

    def __init__(
        self,
        cfg: Config,
        params: Any,
        *,
        eos_id: Optional[int] = None,
        seed: int = 0,
        fault_injector: Optional[FaultInjector] = None,
    ):
        self.cfg = cfg
        self.mcfg = cfg.model
        self.icfg = cfg.inference
        if self.mcfg.weight_quant == "int8":
            from orion_tpu.models.quantize import quantize_params

            params = quantize_params(params, self.mcfg)
        elif self.mcfg.weight_quant is not None:
            raise ValueError(
                f"unknown model.weight_quant={self.mcfg.weight_quant!r}"
            )
        self.params = params
        self.eos_id = eos_id
        self.psz = self.icfg.page_size
        self.pages_per_seq = pages_per_seq(self.icfg)
        self.max_batch = self.icfg.max_batch_size
        if self.icfg.prefill_chunk % self.psz:
            raise ValueError(
                f"prefill_chunk={self.icfg.prefill_chunk} must be a "
                f"multiple of page_size={self.psz}"
            )
        self.chunked = self.icfg.chunked_prefill
        if self.chunked and (
            self.icfg.prefill_chunk_tokens < self.psz
            or self.icfg.prefill_chunk_tokens % self.psz
        ):
            raise ValueError(
                f"prefill_chunk_tokens={self.icfg.prefill_chunk_tokens} "
                f"must be a positive multiple of page_size={self.psz} "
                f"(chunks split at page granularity)"
            )
        # Long-context serving (inference.long_context; README "Long
        # context"): per-request KV paging to the host tier + lazy page
        # provisioning under chunked prefill. Cross-field checks live
        # here per the config lint rule (dotted overrides apply one
        # field at a time).
        self._long = self.icfg.long_context
        if self._long:
            if not self.chunked:
                raise ValueError(
                    "inference.long_context=true requires "
                    "inference.chunked_prefill=true (over-pool contexts "
                    "prefill through page-aligned chunks)"
                )
            if self.icfg.host_tier_bytes <= 0:
                raise ValueError(
                    "inference.long_context=true requires "
                    "inference.host_tier_bytes > 0 (per-request paging "
                    "needs somewhere to page to)"
                )

        self.cache = init_cache(self.mcfg, self.icfg)
        # Tensor-parallel serving on the Pallas path: the kernels run under
        # head-sharded shard_maps (see runner/ops), and the KV pool lives
        # sharded over kv heads — each device holds K/tp of every page, so
        # pool memory scales down with tp like the params do.
        from orion_tpu.ops._dispatch import resolve_impl

        self.mesh = (
            _detect_tp_mesh(self.params)
            if resolve_impl(self.mcfg.kernels)[0] else None
        )
        if self.mesh is not None:
            from jax.sharding import NamedSharding, PartitionSpec as P

            tp = self.mesh.shape["tp"]
            if self.mcfg.n_heads % tp or self.mcfg.n_kv_heads % tp:
                raise ValueError(
                    f"Pallas serving with tp={tp} needs n_heads "
                    f"({self.mcfg.n_heads}) and n_kv_heads "
                    f"({self.mcfg.n_kv_heads}) divisible by it; lower tp "
                    f"or set model.kernels='xla'"
                )
            spec = {
                "k": P(None, "tp", None, None),
                "v": P(None, "tp", None, None),
                "k_scale": P(None, "tp", None),
                "v_scale": P(None, "tp", None),
            }
            self.cache = {
                name: jax.device_put(
                    arr, NamedSharding(self.mesh, spec[name])
                )
                for name, arr in self.cache.items()
            }
        self.alloc = PageAllocator(self.icfg.num_pages)
        # Automatic prefix caching (inference.prefix_cache): radix tree of
        # immutable refcounted KV pages over the SAME allocator — cached
        # pages are reclaimable headroom, evicted LRU under pressure.
        self._pcache = None
        self.prefix_stats = PrefixCacheStats()
        # Host-RAM second tier (inference.host_tier_bytes; README "Tiered
        # prefix cache"): LRU eviction demotes cached pages into host
        # buffers (one batched d2h per sweep) instead of discarding, and
        # a later match on a host-resident path restores them (one
        # batched h2d) — tail prefill then resumes exactly as a warm HBM
        # hit. Off (0): everything below stays None and the engine is
        # byte-identical to the untiered one.
        self._host_pool: Optional[HostPagePool] = None
        self._host_min_tokens: float = 0.0
        # Batched page-copy programs, shared by the host tier's spill/
        # restore envelopes AND cross-replica KV-page migration (ISSUE
        # 20) — built unconditionally so a tier-off prefill replica can
        # still export pages. gather is a pure pool read (no donation);
        # scatter donates the pool like every other cache-updating
        # program.
        self._gather_pages = _gather_pages_jit(
            self.mcfg.n_layers, self.icfg.num_pages
        )
        self._scatter_pages = _scatter_pages_jit(
            self.mcfg.n_layers, self.icfg.num_pages
        )
        if self.icfg.host_tier_bytes > 0:
            if not (self.icfg.prefix_cache or self._long):
                raise ValueError(
                    "inference.host_tier_bytes > 0 requires "
                    "inference.prefix_cache=true (the tier lives behind "
                    "the radix tree) or inference.long_context=true "
                    "(per-request paging owns its slots directly)"
                )
            pb = host_page_bytes(self.cache, self.mcfg.n_layers)
            cap = self.icfg.host_tier_bytes // pb
            if cap < 1:
                raise ValueError(
                    f"inference.host_tier_bytes={self.icfg.host_tier_bytes}"
                    f" is smaller than one page's KV footprint ({pb} "
                    f"bytes); raise it or disable the tier with 0"
                )
            self._host_pool = HostPagePool(cap, page_bytes=pb)
            # Break-even gate: explicit knob wins; otherwise derive from
            # the measured constants (PERF.md "Host-tier break-even").
            # None from the arithmetic means restore NEVER wins — the
            # tier still absorbs evictions (a fleet-warm replica beats a
            # cold one at placement) but every local hit recomputes.
            if self.icfg.host_tier_min_tokens is not None:
                self._host_min_tokens = float(
                    self.icfg.host_tier_min_tokens
                )
            else:
                auto = host_tier_break_even_tokens(
                    pb, self.psz,
                    self.icfg.host_tier_h2d_gbps,
                    self.icfg.host_tier_restore_overhead_s,
                    self.icfg.host_tier_prefill_tok_s,
                )
                self._host_min_tokens = (
                    float(auto) if auto is not None else float("inf")
                )
        if self.icfg.prefix_cache:
            from orion_tpu.infer.prefix_cache import PrefixCache

            self._pcache = PrefixCache(
                self.psz, self.alloc,
                host_pool=self._host_pool,
                spill=(
                    self._spill_pages if self._host_pool is not None
                    else None
                ),
            )
        self._cow = jax.jit(
            partial(
                copy_page,
                n_layers=self.mcfg.n_layers,
                num_pages=self.icfg.num_pages,
            ),
            donate_argnums=(0,),
        )
        self.page_table = np.zeros(
            (self.max_batch, self.pages_per_seq), np.int32
        )
        self.seq_lens = np.zeros(self.max_batch, np.int32)
        self.last_token = np.zeros(self.max_batch, np.int32)
        self.slots: list[Optional[Request]] = [None] * self.max_batch
        # Scheduler face (infer/scheduler.py): the wait queue carries the
        # admission-side policy (shed victim selection, deadline sweep).
        self.waiting: AdmissionQueue = AdmissionQueue()
        self._just_finished: list[Request] = []
        self._rid = itertools.count()
        self._admit_seq = itertools.count()
        self._key = jax.random.key(seed)
        self.preemptions = 0
        # Page-management window: with interleaved local/global layers
        # (sliding_window_pattern) the GLOBAL layers read the whole
        # history, so pages never die and rolling/dead-on-arrival page
        # logic must treat the model as unwindowed; only the attention
        # masks are per-layer windowed (runner/cfg.layer_window).
        self.page_window = (
            self.mcfg.sliding_window
            if self.mcfg.sliding_window_pattern is None else None
        )
        # Decode window: mutable engine state (inference.decode_window is
        # only the starting point when auto-tune is on). Page provisioning
        # and admission always budget for _provision_window, so growth can
        # never strand an already-admitted request.
        self.decode_window = self.icfg.decode_window
        if self.icfg.decode_window_autotune and (
            self.icfg.decode_window_max < self.icfg.decode_window
        ):
            raise ValueError(
                f"decode_window_max={self.icfg.decode_window_max} < "
                f"decode_window={self.icfg.decode_window}"
            )
        # Lazy chunk provisioning (the over-pool admission path): only
        # meaningful with a sliding window — a full-attention chunk reads
        # its WHOLE history from the pool, so its device working set is
        # O(context) no matter how pages move (the typed
        # "shed:context_too_long" outcome covers that case instead).
        self._lazy = self._long and self.page_window is not None
        self._dev_span = 0.0
        self._mixed_span = 0.0
        self._prefill_span = 0.0
        self._spill_span = 0.0
        self._restore_span = 0.0
        self._pagein_span = 0.0
        self._migrate_span = 0.0
        self.timing = self._zero_timing()
        # Cross-replica migration staging (ISSUE 20): requests whose KV
        # pages are arriving from a prefill replica but have not claimed
        # a slot yet. Page owners for assert_page_accounting.
        self._importing: dict[int, Request] = {}

        # -- Fault tolerance (runtime/fault.py; README "Robustness") -------
        self._injector = fault_injector
        self.robust = RobustnessStats()
        self.step_no = 0            # completed step() calls; FaultSpec.step
        self._consec_failed = 0     # consecutive failed steps (bounded)
        self._spec_faults = 0       # verify-path dispatch faults (lifetime)
        self._spec_disabled = False
        self._guard = self.icfg.nan_guard
        self.draining = False       # drain(): admission stopped
        # Executor face (infer/executor.py): the dispatch-program factory,
        # the lazily-built XLA fallbacks and the per-dispatch fault
        # envelope all live there; _jit_program/_run_dispatch delegate.
        self._executor = DispatchExecutor(self)
        # Quarantine primitives: poison is the NaN fault injection
        # (FaultSpec kind="nan"), scrub zeroes a quarantined request's
        # private pages before they return to the free list.
        self._poison = jax.jit(
            partial(
                poison_page,
                n_layers=self.mcfg.n_layers,
                num_pages=self.icfg.num_pages,
            ),
            donate_argnums=(0,),
        )
        self._scrub = jax.jit(
            partial(
                scrub_pages,
                n_layers=self.mcfg.n_layers,
                num_pages=self.icfg.num_pages,
            ),
            donate_argnums=(0,),
        )
        # Serving step watchdog: flags stalls (counted in reset_timing's
        # stalled_steps); never aborts the process — a stalled step fails
        # the step, not the engine (unlike train.watchdog_action="abort").
        self._watchdog: Optional[Watchdog] = None
        if self.icfg.watchdog_timeout_s is not None:
            self._watchdog = Watchdog(
                self.icfg.watchdog_timeout_s,
                on_stall=lambda elapsed: log.error(
                    "serving watchdog: step stalled for %.1fs", elapsed
                ),
            ).start()

        # -- Observability (orion_tpu/obs; README "Observability") ---------
        # Registry: always constructed (providers are lazy reads of live
        # state — zero hot-path cost); tracer/flight only when asked for,
        # so the untraced host path is byte-identical to the pre-obs
        # engine.
        self.registry = MetricsRegistry()
        self._register_metrics()
        self._tracer, self._flight = init_obs(
            trace=self.icfg.trace,
            trace_ring=self.icfg.trace_ring,
            flight_dir=self.icfg.flight_dir,
            trace_path=self.icfg.trace_path,
            snapshot=self.registry.snapshot,
            injector=self._injector,
        )
        self._register_trace_metrics()
        self._ttft_seen: set[int] = set()   # rids with a first_token event
        self._closed = False

        # Per-slot sampling params (inference.* defaults; submit() can
        # override per request, vLLM-style).
        self.slot_temp = np.full(self.max_batch, self.icfg.temperature,
                                 np.float32)
        self.slot_top_k = np.full(self.max_batch, self.icfg.top_k, np.int32)
        self.slot_top_p = np.full(self.max_batch, self.icfg.top_p,
                                  np.float32)
        # Dispatch programs, built by the shared _jit_program factory (the
        # XLA-fallback degradation ladder rebuilds the same programs with
        # kernels="xla" on demand, so primary and fallback can never drift):
        #   decode           — the fused decode window; the "_defaults"
        #                      variant binds python-scalar sampling params
        #                      so sample()'s greedy short-circuit compiles
        #                      no sampling machinery (no [B, V] sort).
        #   prefill          — one specialization per (padded bucket length,
        #                      padded batch size) pair, keyed by jit.
        #   mixed            — unified mixed prefill+decode
        #                      (inference.chunked_prefill): ONE dispatch per
        #                      engine step while prompt chunks are in
        #                      flight.
        self._decode = self._jit_program("decode", self.mcfg, self.mesh)
        self._decode_defaults = self._jit_program(
            "decode_defaults", self.mcfg, self.mesh
        )
        self._prefill = self._jit_program("prefill", self.mcfg, self.mesh)
        self._mixed = self._jit_program("mixed", self.mcfg, self.mesh)
        self._mixed_defaults = self._jit_program(
            "mixed_defaults", self.mcfg, self.mesh
        )
        # Fixed key for mixed steps with no live decode slot: those steps
        # must not advance the engine PRNG stream (sampled chunked-vs-
        # unchunked equivalence relies on one split per SAMPLING event,
        # not per dispatch).
        self._null_key = jax.random.key(0)

        # Speculative decoding (inference.speculative): host-side n-gram
        # proposer (infer/spec_decode.py) + single-dispatch batched
        # verification (runner.verify_step / mixed_verify_step). The
        # verify width is STATIC at speculate_tokens+1 — per-request
        # adaptive draft lengths ride the `lens` argument, so there is
        # one jit specialization, not one per draft-length mix.
        self._spec = None
        self._tree = False          # token-tree drafting (spec_tree_width>1)
        self.spec_stats = SpecDecodeStats()
        self._spec_step = False     # this step ran verify, not decode
        self._autotune_skip = False  # first step after a window resize
        # Grammar-constrained decoding (inference.constrained; ISSUE 16):
        # constrained slots decode through the VERIFY path — FSM forced
        # runs are free drafts and per-position legal masks are
        # host-precomputable along a known draft, while the fused
        # multi-token decode window cannot carry them (the next mask
        # depends on the device-side sample). So the verify programs are
        # built for `speculative OR constrained`; the draft budget is
        # speculate_tokens either way (one static verify width).
        self.constrained = self.icfg.constrained
        self.constraint_stats = ConstraintStats()
        # Forced-run bookkeeping for the CURRENT verify step: slot ->
        # number of leading draft tokens that were FSM-forced (the
        # guaranteed-accept prefix); consumed by the acceptance walks.
        self._constraint_forced: dict[int, int] = {}
        need_verify = self.icfg.speculative or self.constrained
        if need_verify and resolve_impl(self.mcfg.kernels)[0]:
            # Pallas verify path: reject a verify width the ragged
            # paged-attention kernel cannot hold in VMEM at engine
            # init — a config error naming the knob, instead of a
            # Mosaic allocation failure mid-serving.
            from orion_tpu.ops.pallas.ragged_paged_attention import (
                check_verify_fit,
            )

            # Per-SHARD head counts: under tp the kernel runs inside
            # a head-sharded shard_map with K/tp kv heads per device
            # (divisibility already validated above), so the fit is
            # per shard — whole-model counts would reject configs
            # that actually fit.
            tp = self.mesh.shape["tp"] if self.mesh is not None else 1
            check_verify_fit(
                self.icfg.speculate_tokens + 1,
                n_heads=self.mcfg.n_heads // tp,
                n_kv_heads=self.mcfg.n_kv_heads // tp,
                head_dim=self.mcfg.resolved_head_dim,
                page_size=self.psz,
                kv_quant=self.icfg.kv_quant,
                dtype_itemsize=jnp.dtype(self.mcfg.dtype).itemsize,
            )
        if self.icfg.paged_prefill and resolve_impl(self.mcfg.kernels)[0]:
            # Same init-time VMEM gate for the paged-flash prefill
            # kernel: its blocks are page-sized (one page of queries x
            # the GQA group), so the failure mode is a too-large
            # page_size, named here instead of a Mosaic OOM mid-chunk.
            from orion_tpu.ops.pallas.paged_flash_prefill import (
                check_prefill_fit,
            )

            tp = self.mesh.shape["tp"] if self.mesh is not None else 1
            check_prefill_fit(
                n_heads=self.mcfg.n_heads // tp,
                n_kv_heads=self.mcfg.n_kv_heads // tp,
                head_dim=self.mcfg.resolved_head_dim,
                page_size=self.psz,
                kv_quant=self.icfg.kv_quant,
                dtype_itemsize=jnp.dtype(self.mcfg.dtype).itemsize,
            )
        if self.icfg.speculative:
            from orion_tpu.infer.spec_decode import NgramProposer

            if self.icfg.spec_min_draft_slots < 1:
                raise ValueError(
                    f"inference.spec_min_draft_slots="
                    f"{self.icfg.spec_min_draft_slots} must be >= 1"
                )
            if self.icfg.spec_tree_width > self.icfg.speculate_tokens:
                raise ValueError(
                    f"inference.spec_tree_width="
                    f"{self.icfg.spec_tree_width} exceeds "
                    f"speculate_tokens={self.icfg.speculate_tokens}: a "
                    f"tree of w branches needs at least w nodes"
                )
            if (
                self.icfg.spec_tree_width > 1
                and self.icfg.speculate_tokens + 1 > 31
            ):
                raise ValueError(
                    f"tree speculation packs the per-column ancestor mask "
                    f"into int32 words: speculate_tokens="
                    f"{self.icfg.speculate_tokens} needs "
                    f"{self.icfg.speculate_tokens + 1} columns > the "
                    f"31-bit budget; lower inference.speculate_tokens or "
                    f"set spec_tree_width=1"
                )
            self._spec = NgramProposer(
                speculate_tokens=self.icfg.speculate_tokens,
                max_n=self.icfg.spec_ngram_max,
                min_n=self.icfg.spec_ngram_min,
                tree_width=self.icfg.spec_tree_width,
            )
            # Token trees (inference.spec_tree_width > 1): the accepted
            # root-path may live at non-contiguous verify columns; this
            # program moves its KV into cursor-contiguous slots before
            # the losing branches roll back (kv_cache.compact_draft_kv).
            self._tree = self.icfg.spec_tree_width > 1
            if self._tree:
                from orion_tpu.infer.kv_cache import compact_draft_kv

                self._compact = jax.jit(
                    partial(
                        compact_draft_kv,
                        n_layers=self.mcfg.n_layers,
                        num_pages=self.icfg.num_pages,
                    ),
                    donate_argnums=(0,),
                )
        if need_verify:
            self._verify = self._jit_program("verify", self.mcfg, self.mesh)
            self._verify_defaults = self._jit_program(
                "verify_defaults", self.mcfg, self.mesh
            )
            if self.chunked:
                self._mixed_verify = self._jit_program(
                    "mixed_verify", self.mcfg, self.mesh
                )
                self._mixed_verify_defaults = self._jit_program(
                    "mixed_verify_defaults", self.mcfg, self.mesh
                )

    # -- observability (orion_tpu/obs) ------------------------------------

    def _register_metrics(self) -> None:
        """Wire the engine's live state into the metrics registry: the
        per-window counters (timing/prefix/spec/robust — the same objects
        reset_timing drains, read lazily so the registry always reports
        the CURRENT window) plus the gauges the old reset_timing surface
        never had: pool/prefix-tree occupancy and live HBM."""
        reg = self.registry
        reg.register("engine", lambda: {
            **self.timing,
            "decode_window": self.decode_window,
            "step_no": self.step_no,
            "waiting": len(self.waiting),
            "active": sum(
                1 for r in self.slots if r is not None and not r.done
            ),
            "preemptions": self.preemptions,
        })
        reg.register("robust", lambda: self.robust.as_timing())
        if self.icfg.prefix_cache:
            reg.register("prefix", lambda: self.prefix_stats.as_timing())
        if self.icfg.speculative:
            reg.register("spec", lambda: self.spec_stats.as_timing())
        if self.icfg.constrained:
            reg.register(
                "constrain", lambda: self.constraint_stats.as_timing()
            )
        reg.register("pool", self._pool_metrics)
        reg.register("hbm", live_hbm_metrics)

    def _register_trace_metrics(self) -> None:
        """Ring-occupancy gauges ("trace" section: events/capacity/
        dropped), registered only when tracing is actually on — the
        obs-off snapshot (and thus the Prometheus/JSONL row set) stays
        byte-identical to the pre-obs engine. A nonzero ``dropped`` means
        any export from this ring is a truncated timeline (ISSUE 14
        satellite; obs_report flags it)."""
        if self._tracer.enabled:
            self.registry.register("trace", self._tracer.metrics)

    @staticmethod
    def _trace_ctx(req: Request) -> dict:
        """Correlation tags for a lifecycle instant: ``tid`` (the fleet
        trace id — the router's request id when routed, the engine rid on
        a bare engine) plus ``retried=attempt`` on failover re-placements
        (attempt > 0), so a failed-over request's instants on BOTH
        replicas' tracks carry the same tid and the retry is visible in
        the merged timeline."""
        tid = req.trace_id if req.trace_id is not None else req.rid
        if req.attempt:
            return {"tid": tid, "retried": req.attempt}
        return {"tid": tid}

    def _pool_metrics(self) -> dict:
        """Page-pool and radix-tree occupancy gauges. ``occupancy`` counts
        the usable pool (page 0 is the reserved scratch page); cached
        pages are reclaimable headroom but still occupied."""
        n = self.icfg.num_pages
        usable = max(n - 1, 1)
        free = self.alloc.free_pages
        out = {
            "num_pages": n,
            "free_pages": free,
            "occupancy": (usable - free) / usable,
        }
        if self._pcache is not None:
            # total_pages is the incrementally-maintained count of what
            # held_pages() would walk-and-yield: O(1), which matters now
            # that the router reads this gauge per placement candidate
            # (the walk equivalence is covered by assert_page_accounting,
            # which sums the real held_pages against the allocator).
            out["cached_pages"] = self._pcache.total_pages
            out["evictable_pages"] = self._pcache.evictable_pages()
        if self._host_pool is not None:
            # Host-tier occupancy (inference.host_tier_bytes): slots held
            # minus free over capacity; host_pages is the tree's marker
            # count (== capacity - free_slots while only the tree and
            # in-flight restores hold slots).
            hp = self._host_pool
            out["host_capacity"] = hp.capacity
            out["host_free_slots"] = hp.free_slots
            if self._pcache is not None:
                out["host_pages"] = self._pcache.host_pages
            out["host_occupancy"] = (
                (hp.capacity - hp.free_slots) / hp.capacity
            )
            if self._long:
                # Residency gauges (inference.long_context): host slots
                # held by live REQUESTS (engine-owned refs, not tree
                # markers) over the tier's capacity.
                held = sum(
                    len(r.host_pages)
                    for r in itertools.chain(self.slots, self.waiting)
                    if r is not None
                )
                out["request_host_pages"] = held
                out["residency_occupancy"] = held / hp.capacity
        return out

    @contextlib.contextmanager
    def _device_span(self, path: str, bucket: str = "_dev_span"):
        """The ONE dispatch-timing primitive every device call site shares
        (previously four copy-pasted ``t_dev = time.perf_counter()``
        blocks): wraps dispatch + token fetch, accumulating the elapsed
        wall time into the step's device/prefill bucket and emitting a
        tracer span over the same window. On an exception the bucket is
        NOT credited (the pre-refactor behavior: a failed step's partial
        span never lands in the timing split) but the tracer span still
        records — a postmortem wants to see the dispatch that died."""
        tags = {"step": self.step_no}
        if self._tracer.enabled:
            # Dispatch spans carry the trace ids of every live slot they
            # computed for (ISSUE 14): a request's correlated track in
            # the merged timeline includes the device work that advanced
            # it, not just its lifecycle instants. Built only when the
            # tracer is on — the untraced host path is unchanged.
            tags["tids"] = [
                r.trace_id if r.trace_id is not None else r.rid
                for r in self.slots if r is not None and not r.done
            ]
        t0 = time.perf_counter()
        with self._tracer.span("dispatch/" + path, **tags):
            yield
        setattr(self, bucket, getattr(self, bucket) + time.perf_counter() - t0)

    def _flight_dump(self, reason: str, **context) -> None:
        """Write a flight-recorder postmortem (no-op without
        inference.flight_dir); best-effort — a failed dump degrades the
        artifact, never the engine (FlightRecorder.try_dump)."""
        if self._flight is not None:
            self._flight.try_dump(reason, step=self.step_no, **context)

    def _flight_note(self, kind: str, **fields) -> None:
        """Stamp one event into the postmortem ring (no-op without
        inference.flight_dir) — the single guard every fault path shares."""
        if self._flight is not None:
            self._flight.note(kind, step=self.step_no, **fields)

    def export_trace(self, path: str) -> int:
        """Export the span ring as Chrome trace-event JSON (Perfetto);
        returns the number of events written (0 when tracing is off)."""
        return self._tracer.export_chrome(path)

    @property
    def tracer(self):
        """The engine's span tracer (NULL_TRACER when obs is off) — the
        router reads it to merge this replica's ring into the fleet
        timeline (obs.merge_chrome)."""
        return self._tracer

    # -- dispatch + degradation ladder (infer/executor.py) ----------------

    def _jit_program(self, name: str, mcfg, mesh):
        """Delegate to the executor's program factory (the one factory
        both primary and XLA-fallback builds share)."""
        return self._executor.jit_program(name, mcfg, mesh)

    def _fallback_program(self, name: str):
        return self._executor.fallback_program(name)

    def _run_dispatch(self, path: str, name: str, *args, **kwargs):
        """Run one device dispatch under the executor's fault-tolerance
        envelope (injection points, XLA-fallback retry ladder with
        ``inference.dispatch_retries`` jittered-backoff attempts); raises
        DispatchFault when every path is exhausted — the engine fails the
        step, not the process."""
        return self._executor.run(path, name, *args, **kwargs)

    def _note_spec_fault(self, e: Exception) -> None:
        """Degradation ladder rung 2: count a verify-path PRIMARY dispatch
        fault (whether or not the XLA fallback then absorbed it); past
        inference.spec_fault_limit, speculation auto-disables for the
        engine's lifetime (SpecDecodeStats.disabled_reason) and decoding
        continues on the plain window."""
        self._spec_faults += 1
        log.warning(
            "speculative verify dispatch fault %d/%d: %s",
            self._spec_faults, self.icfg.spec_fault_limit, e,
        )
        if (
            self._spec_faults >= self.icfg.spec_fault_limit
            and not self._spec_disabled
        ):
            self._spec_disabled = True
            self.spec_stats.disabled_reason = (
                f"auto-disabled after {self._spec_faults} verify "
                f"dispatch faults"
            )
            log.error(
                "speculative decoding %s", self.spec_stats.disabled_reason
            )
            self._flight_dump(
                "spec_auto_disable", spec_faults=self._spec_faults
            )

    def _maybe_inject_nan(self) -> None:
        """FaultSpec kind="nan": poison the victim's newest attended
        PRIVATE page with NaN. The poison flows through the real attention
        into exactly that slot's logits (no other slot reads its pages);
        the nan_guard quarantine is then exercised end-to-end."""
        inj = self._injector
        if inj is None:
            return
        spec = inj.take("nan", self.step_no)
        if spec is None:
            return
        cands = [
            r for r in self.slots
            if r is not None and not r.done
            and (spec.rid is None or r.rid == spec.rid)
        ]
        if not cands:
            log.warning("nan injection at step %d found no victim",
                        self.step_no)
            return
        req = min(cands, key=lambda r: r.admit_seq)
        # Walk back from the cursor's page: the newest written position is
        # always attended, and shared (refcount > 1) prefix pages must stay
        # clean — they are other requests' data.
        pos = max(int(self.seq_lens[req.slot]) - 1, 0)
        for i in range(min(pos // self.psz, len(req.pages) - 1), -1, -1):
            p = req.pages[i]
            if p is not None and self.alloc.refcount(p) == 1:
                log.warning(
                    "injecting NaN into page %d of request %d (step %d)",
                    p, req.rid, self.step_no,
                )
                self.cache = self._poison(self.cache, jnp.int32(p))
                return
        log.warning("nan injection: request %d has no private page",
                    req.rid)

    def _quarantine(self, req: Request, reason: str) -> None:
        """Contain a poisoned slot: the request errors with a typed
        outcome, its private pages are SCRUBBED (stale NaNs must not leak
        to the page's next tenant) and released with NO prefix-cache
        donation; neighbors never read its pages, so their outputs stay
        byte-identical to a fault-free run."""
        log.error("quarantining request %d (%s)", req.rid, reason)
        priv = [
            p for p in req.pages
            if p is not None and self.alloc.refcount(p) == 1
        ]
        if priv:
            pad = priv + [0] * (self.pages_per_seq - len(priv))
            self.cache = self._scrub(
                self.cache, jnp.asarray(pad, jnp.int32)
            )
        req.done = True
        req.outcome = f"error:{reason}"
        self.robust.quarantined += 1
        self._teardown_slot(req, 0)   # n_cached=0: donate nothing
        self._just_finished.append(req)
        self._flight_dump(f"{reason}_quarantine", rid=req.rid)

    def _reap_expired(self) -> None:
        """Step-boundary deadline sweep: expired requests — waiting or
        active, mid-prefill or mid-decode — terminate with outcome
        "expired"; active ones release pages with prefix-cache donation
        exactly as preemption does (the _reap path)."""
        now = time.monotonic()
        for r in self.waiting.sweep_expired(now):
            r.done = True
            r.outcome = "expired"
            self.robust.expired += 1
            self._drop_host_pages(r)
            self._just_finished.append(r)
        for r in self.slots:
            if (
                r is not None and not r.done
                and r.deadline is not None and now >= r.deadline
            ):
                log.info("request %d deadline expired (slot %d)",
                         r.rid, r.slot)
                r.done = True
                r.outcome = "expired"
                self.robust.expired += 1

    # -- public API --------------------------------------------------------

    def submit(
        self,
        prompt: Sequence[int],
        max_new_tokens: Optional[int] = None,
        *,
        temperature: Optional[float] = None,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        deadline_s: Optional[float] = None,
        priority: int = 0,
        constraint: Optional[Any] = None,
    ) -> int:
        """Queue a request; returns its id.

        ``deadline_s`` (seconds from now; default
        inference.default_deadline_s) bounds the request's life: once past
        it, the request is reaped at the next step boundary with outcome
        "expired". ``priority`` (higher = more important) orders admission,
        page-pressure preemption (low classes evict first) and overload
        shedding. With inference.queue_limit set, an over-limit submit
        SHEDS the lowest-priority / nearest-deadline / newest candidate —
        possibly this very request — with outcome "shed" instead of
        queueing unboundedly; the shed request still surfaces from the
        next step().

        ``constraint`` (a ``orion_tpu.constrain.ConstraintSpec``) asks
        for grammar-constrained output: the emission is guaranteed to
        match the spec's regex / JSON schema token-for-token. Needs
        ``inference.constrained=true`` (the flag builds the verify
        programs constrained slots decode through); the spec compiles at
        submit (memoized across requests by constraint hash) and a
        pattern this vocab can never satisfy raises here, typed.

        Note: any non-None sampling override switches the WHOLE decode batch
        to the sort-based sampling program (a [B, V] sort per token for every
        co-scheduled slot, plus a one-time second decode compile) until no
        overriding request remains active — overrides cost throughput for the
        batch, not just this request. Greedy-default traffic stays on the
        sort-free specialized program.
        """
        return self.submit_request(
            prompt, max_new_tokens, temperature=temperature, top_k=top_k,
            top_p=top_p, deadline_s=deadline_s, priority=priority,
            constraint=constraint,
        ).rid

    def submit_request(
        self,
        prompt: Sequence[int],
        max_new_tokens: Optional[int] = None,
        *,
        temperature: Optional[float] = None,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        deadline_s: Optional[float] = None,
        priority: int = 0,
        trace_id: Optional[int] = None,
        attempt: int = 0,
        constraint: Optional[Any] = None,
    ) -> Request:
        """submit() returning the live Request object instead of its id —
        the CLI/bench/driver surface: callers poll ``.generated`` for
        incremental tokens and read the typed ``.outcome`` at the end.
        Same arguments and validation as submit(). ``trace_id`` /
        ``attempt`` are the fleet trace context (ISSUE 14): the router
        stamps its request id and failover attempt number here so this
        replica's lifecycle instants correlate in the merged timeline;
        bare-engine callers leave them defaulted (tid falls back to the
        engine rid)."""
        if not len(prompt):
            raise ValueError("empty prompt")
        if temperature is not None and temperature < 0.0:
            raise ValueError(f"temperature must be >= 0, got {temperature}")
        if top_k is not None and not 0 <= top_k <= self.mcfg.vocab_size:
            raise ValueError(
                f"top_k must be in [0, vocab_size={self.mcfg.vocab_size}], "
                f"got {top_k} (0 disables the top-k filter)"
            )
        if top_p is not None and not 0.0 < top_p <= 1.0:
            raise ValueError(f"top_p must be in (0, 1], got {top_p}")
        cstate = None
        if constraint is not None:
            # Cross-field check lives here per the config lint rule
            # (dotted overrides apply one field at a time).
            if not self.constrained:
                raise ValueError(
                    "constraint= needs inference.constrained=true (the "
                    "flag builds the verify programs constrained slots "
                    "decode through)"
                )
            from orion_tpu.constrain import (
                ConstraintSpec,
                ConstraintState,
                compile_constraint,
            )

            if not isinstance(constraint, ConstraintSpec):
                raise ValueError(
                    f"constraint must be a ConstraintSpec, got "
                    f"{type(constraint).__name__}"
                )
            t0 = time.perf_counter()
            dfa, hit = compile_constraint(
                constraint, self.mcfg.vocab_size,
                max_states=self.icfg.constraint_max_states,
                cache_size=self.icfg.constraint_cache,
            )
            cs = self.constraint_stats
            cs.requests += 1
            cs.compiles += 1
            if hit:
                cs.compile_hits += 1
            else:
                cs.compile_s += time.perf_counter() - t0
            cstate = ConstraintState(dfa, self.eos_id)
        # Normalize overrides equal to the engine defaults back to None: a
        # request that explicitly passes the default values is sampling-
        # identical to one passing nothing, and must not push the batch onto
        # the sort-based decode program.
        if temperature is not None and temperature == self.icfg.temperature:
            temperature = None
        if top_k is not None and top_k == self.icfg.top_k:
            top_k = None
        if top_p is not None and top_p == self.icfg.top_p:
            top_p = None
        limit = self.icfg.max_seq_len
        if len(prompt) >= limit:
            raise ValueError(f"prompt length {len(prompt)} >= max_seq_len {limit}")
        max_new = (
            max_new_tokens
            if max_new_tokens is not None
            else self.icfg.max_new_tokens
        )
        # The pool must be able to hold this request ALONE at its largest
        # footprint (preemption can always shrink the batch to one, and a
        # grown request re-prefills at its context's bucket length) plus one
        # spare page — this makes mid-decode pool exhaustion unreachable for
        # admitted requests. The footprint includes the decode window's
        # pre-provisioned pages: the device may write up to W-1 positions
        # past the host's final accepted token (see runner.decode_window).
        max_context = min(len(prompt) + max(max_new, 0), limit)
        # Worst admission demand over every context the request could
        # (re-)prefill at — with a sliding window the peak sits at a
        # prefill-bucket bottom, not at max_context (see
        # _worst_admission_need).
        needed = self._worst_admission_need(len(prompt), max_context)
        usable = self.icfg.num_pages - 1
        shed_kind = None
        if needed > usable:
            if self._lazy and self._long_admission_need() <= usable:
                # Over-pool long context (inference.long_context + SWA +
                # chunked prefill): the LAZY working set fits — pages
                # materialize per chunk and die behind the window, so the
                # pool never holds the O(context) footprint at once.
                pass
            elif self._long:
                # Long-context mode refuses infeasible work with a TYPED
                # outcome instead of a raw raise: the caller/router sees
                # "shed:context_too_long" surface from step() exactly
                # like an overload shed (RobustnessStats.shed_context).
                shed_kind = "context_too_long"
            else:
                raise ValueError(
                    f"request needs up to {needed} KV pages but the pool "
                    f"only has {usable}; raise inference.num_pages or "
                    f"lower max_new_tokens"
                )
        if deadline_s is None:
            deadline_s = self.icfg.default_deadline_s
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        req = Request(
            rid=next(self._rid),
            prompt=list(map(int, prompt)),
            max_new_tokens=max_new,
            temperature=temperature,
            top_k=top_k,
            top_p=top_p,
            priority=int(priority),
            deadline=(
                time.monotonic() + deadline_s
                if deadline_s is not None else None
            ),
            trace_id=trace_id,
            attempt=int(attempt),
            constraint=cstate,
        )
        if self._tracer.enabled:
            self._tracer.instant(
                "submit", rid=req.rid, priority=req.priority,
                prompt_tokens=len(req.prompt),
                max_new_tokens=req.max_new_tokens,
                deadline_s=deadline_s, **self._trace_ctx(req),
            )
        if shed_kind is not None:
            self._shed(
                req,
                f"context needs up to {needed} KV pages, pool has "
                f"{usable} and the lazy working set does not fit",
                kind=shed_kind,
            )
            return req
        if self.draining:
            # Admission is stopped (SIGTERM drain): typed shed, never
            # queued — the caller still sees the request surface.
            self._shed(req, "draining")
            return req
        qlim = self.icfg.queue_limit
        if qlim is not None and len(self.waiting) >= qlim:
            # Overload: shed the least defensible candidate — lowest
            # priority first, then the nearest (most infeasible) deadline,
            # then the newest arrival — which may be the incoming request.
            # In-flight requests (admitted once, or carrying generated
            # tokens — see _in_flight) are never victims: "shed" means
            # never admitted (RobustnessStats contract).
            victim = self.waiting.shed_victim(req)
            self._shed(victim, f"queue full ({qlim})")
            if victim is not req:
                self.waiting.remove(victim)
                self.waiting.append(req)
            return req
        self.waiting.append(req)
        return req

    # In-flight test (scheduler face): admitted at least once, or carrying
    # generated tokens — exempt from overload shedding, finished (not
    # shed) by drain(). See infer/scheduler.py.
    _in_flight = staticmethod(in_flight)

    def _shed(
        self, req: Request, why: str, kind: Optional[str] = None
    ) -> None:
        log.warning("shedding request %d (priority %d): %s",
                    req.rid, req.priority, why)
        req.done = True
        req.outcome = "shed" if kind is None else f"shed:{kind}"
        self.robust.shed += 1
        if kind == "context_too_long":
            self.robust.shed_context += 1
        self._drop_host_pages(req)
        self._just_finished.append(req)

    def cancel(self, rid: int) -> bool:
        """Cancel a request by id; returns False when it is unknown or
        already terminal. A waiting request terminates immediately; an
        active one is reaped at the next step boundary — pages released,
        full pages donated to the prefix cache, any speculative
        provisioning rolled back — exactly like a finished request."""
        for i, r in enumerate(self.waiting):
            if r.rid == rid:
                del self.waiting[i]
                r.done = True
                r.outcome = "cancelled"
                self.robust.cancelled += 1
                self._drop_host_pages(r)
                self._just_finished.append(r)
                return True
        for r in self.slots:
            if r is not None and r.rid == rid and not r.done:
                r.done = True
                r.outcome = "cancelled"
                self.robust.cancelled += 1
                return True
        return False

    def step(self) -> list[Request]:
        """Admit + prefill new requests, then run one decode WINDOW
        (``self.decode_window`` fused token steps, one host round-trip)
        for all active slots; returns the requests that finished.

        Each step's wall time is split into ``timing`` (see reset_timing):
        the decode device span (dispatch through the [W, B] token fetch),
        the prefill span (admission-burst dispatch through the first-token
        fetch — its own bucket, so host_share stays meaningful on churny
        workloads), and the host remainder — the observability needed to
        tune the decode window from data rather than assertion.
        """
        t0 = time.perf_counter()
        m0 = time.monotonic() if self._tracer.enabled else 0.0
        if self._watchdog is not None and self._watchdog.armed:
            # Refresh at step START so idle gaps between caller-driven
            # steps never read as stalls — only time INSIDE a step does.
            # Arming stays with the step-END heartbeat (Watchdog's
            # first-completed-step contract): the first step's unbounded
            # jit compile must not trip a false stall.
            self._watchdog.heartbeat()
        self._dev_span = 0.0
        self._mixed_span = 0.0
        self._prefill_span = 0.0
        self._spill_span = 0.0
        self._restore_span = 0.0
        self._pagein_span = 0.0
        self._spec_step = False
        self._reap_expired()
        # Reap expired/cancelled slots BEFORE admission so their pages are
        # already donated/free when this step's admission pass budgets.
        self._reap()
        mixed = False
        try:
            self._admit()
            self._maybe_inject_nan()
            mixed = self.chunked and any(
                r is not None and r.prefill_pending and not r.done
                for r in self.slots
            )
            decoded = self._mixed_decode() if mixed else self._decode_all()
            self._consec_failed = 0
        except (DispatchFault, MemoryError) as e:
            # Every dispatch path failed (or the page allocator did, at
            # grow time): the step is abandoned with engine state
            # consistent — injected dispatch faults fire before the device
            # call, prefill faults unwind their admissions, grow faults
            # leave pages owned — so fail the step, not the process. A
            # persistent fault is not transient: re-raise after
            # max_step_faults consecutive losses.
            if isinstance(e, MemoryError):
                self.robust.pool_faults += 1
            self.robust.failed_steps += 1
            self._consec_failed += 1
            log.error(
                "engine step %d failed (%s); continuing (%d/%d consecutive)",
                self.step_no, e, self._consec_failed,
                self.icfg.max_step_faults,
            )
            self._flight_note(
                "failed_step", consecutive=self._consec_failed,
                error=f"{type(e).__name__}: {e}",
            )
            if self._consec_failed >= self.icfg.max_step_faults:
                self._flight_dump(
                    "max_step_faults",
                    consecutive=self._consec_failed, error=str(e),
                )
                raise
            decoded = False
        total = time.perf_counter() - t0
        # device_s keeps its historical meaning (every decode-facing
        # dispatch, mixed chunk+decode included); the per-phase split
        # rides alongside so the router's ITL-proxy tiebreak can read
        # PURE decode time — a replica grinding a long prompt through
        # mixed steps no longer looks "slow to decode" (ISSUE 20
        # load-gauge satellite).
        self.timing["device_s"] += self._dev_span + self._mixed_span
        self.timing["decode_device_s"] += self._dev_span
        self.timing["mixed_device_s"] += self._mixed_span
        self.timing["prefill_s"] += self._prefill_span
        # Host-tier copy spans get their own buckets (the bench derives
        # real d2h/h2d bandwidth from them); they are neither decode
        # device time nor scheduler host time.
        self.timing["spill_s"] += self._spill_span
        self.timing["restore_s"] += self._restore_span
        self.timing["page_in_s"] += self._pagein_span
        self.timing["host_s"] += (
            total - self._dev_span - self._mixed_span - self._prefill_span
            - self._spill_span - self._restore_span - self._pagein_span
        )
        self.timing["steps"] += 1
        if decoded:
            self.timing["windows"] += 1
            # While chunked prefill is in flight the decode window is
            # clamped to 1 (the mixed step); autotune only reads clean
            # decode-window timings, so mixed steps never resize it.
            # Speculative verify steps are held out the same way: their
            # dispatch is the static verify shape, not the [W, B] decode
            # window, so their split says nothing about the window.
            if (
                self.icfg.decode_window_autotune
                and not mixed and not self._spec_step
            ):
                if self._autotune_skip:
                    # First decode-window step at a freshly-resized [W, B]
                    # shape: its spans carry the retrace/recompile cost,
                    # not steady-state timing — excluded from the tuner
                    # (see _autotune_window).
                    self._autotune_skip = False
                else:
                    self._autotune_window(total)
        if self.mcfg.debug_asserts:
            from orion_tpu.runtime.asserts import raise_if_failed

            # The token fetch synced the device work, but not the async
            # callback thread — the barrier orders it before the check.
            jax.effects_barrier()
            raise_if_failed()
        if self._watchdog is not None:
            if self._watchdog.stalled:
                # The watchdog fired DURING this step (a wedged/slow
                # dispatch): the step is marked stalled and counted; the
                # process carries on, deadline expiry handles the SLO
                # consequences at the next boundary.
                self.robust.stalled_steps += 1
                self._flight_dump("watchdog_stall", step_wall_s=total)
            self._watchdog.heartbeat()
        if self._tracer.enabled:
            # Request-lifecycle instants, swept at the step boundary where
            # every token-emitting path has already run: first_token fires
            # once per request (TTFT), outcome exactly once at the end.
            # The wait queue is in the sweep too: a request preempted in
            # the very step that produced its first token sits there, and
            # skipping it would stamp its TTFT steps late.
            for r in itertools.chain(
                self.slots, self.waiting, self._just_finished
            ):
                if (
                    r is not None and r.generated
                    and r.rid not in self._ttft_seen
                ):
                    self._ttft_seen.add(r.rid)
                    self._tracer.instant(
                        "first_token", rid=r.rid, step=self.step_no,
                        **self._trace_ctx(r),
                    )
            for r in self._just_finished:
                self._ttft_seen.discard(r.rid)
                self._tracer.instant(
                    "outcome", rid=r.rid, outcome=r.outcome,
                    tokens=len(r.generated), step=self.step_no,
                    **self._trace_ctx(r),
                )
            self._tracer.record_span(
                "step", m0, time.monotonic(), step=self.step_no,
                decoded=bool(decoded),
            )
        self.step_no += 1
        done, self._just_finished = self._just_finished, []
        return done

    @staticmethod
    def _zero_timing() -> dict:
        return {
            "device_s": 0.0, "host_s": 0.0, "prefill_s": 0.0,
            # Per-phase device split (ISSUE 20 load-gauge satellite):
            # decode_device_s covers pure decode-phase dispatches
            # (decode windows, verify, draft compaction) and pairs with
            # decode_slot_steps for a phase-pure ITL proxy;
            # mixed_device_s covers chunk-carrying mixed dispatches
            # whose wall time fuses prompt and decode work.
            # device_s == decode_device_s + mixed_device_s, unchanged.
            "decode_device_s": 0.0, "mixed_device_s": 0.0,
            "windows": 0, "steps": 0,
            # Decode-waste accounting: slot_steps counts (active slot x
            # inner decode step) work the device performed; wasted_steps
            # the share discarded because the slot finished mid-window.
            # decode_slot_steps is the pure decode-window/verify subset
            # (mixed steps' decode rows excluded, matching
            # decode_device_s's numerator).
            "slot_steps": 0, "wasted_steps": 0, "decode_slot_steps": 0,
            # Chunked-prefill accounting: mixed_steps counts unified
            # dispatches, chunk_tokens the real prompt tokens they carried,
            # chunk_pad_tokens the padded-out chunk positions (the chunk-
            # side waste analog of wasted_steps — budget tuning reads both
            # instead of guessing).
            "mixed_steps": 0, "prefill_chunks": 0,
            "chunk_tokens": 0, "chunk_pad_tokens": 0,
            # Host-tier copy time: spill_s wraps the batched d2h of each
            # eviction sweep, restore_s the batched h2d of each restore
            # (inference.host_tier_bytes; both 0.0 with the tier off).
            # page_in_s is the per-request paging h2d (inference.
            # long_context): restores of a live request's own host-
            # resident pages ahead of the dispatch that reads them.
            "spill_s": 0.0, "restore_s": 0.0, "page_in_s": 0.0,
            # Cross-replica KV migration copy time (ISSUE 20): the
            # batched gather on the export side / scatter on the import
            # side. Both run OUTSIDE step() (router-driven) and flush
            # directly, like offload_prefix_cache's spill span.
            "migrate_out_s": 0.0, "migrate_in_s": 0.0,
        }

    def reset_timing(self) -> dict:
        """Return and zero the accumulated step timing split: device_s
        (decode dispatch -> token fetch, including mixed chunk+decode
        dispatches), prefill_s (admission bursts), host_s (scheduler
        remainder), windows/steps counters, the slot_steps/wasted_steps
        decode-waste tally, the mixed_steps/prefill_chunks/chunk_tokens/
        chunk_pad_tokens chunked-prefill tally, the CURRENT decode_window
        (after any autotune growth/shrink — a snapshot, not zeroed), with
        inference.prefix_cache the prefix-cache counters
        (prefix_hits/misses/hit_rate, cached_tokens, inserted/evicted/cow
        pages), and with inference.speculative the speculation counters
        (spec_drafted/accepted/rolled_back/emitted, spec_acceptance_rate,
        verify_steps, verify_slot_steps, spec_tokens_per_verify, and
        spec_gated_steps — steps the draft-density gate sent back to the
        plain window), and with inference.constrained the grammar
        counters (constrain_* — compiles/cache hits, masked dispatch
        volume, forced-run draft/accept tally, completions/dead ends)."""
        out, self.timing = self.timing, self._zero_timing()
        out["decode_window"] = self.decode_window
        # prefix_stats also carries the per-request paging counters
        # (request_paged_out/in), which exist without a prefix tree.
        if self._pcache is not None or self._long:
            out.update(self.prefix_stats.as_timing())
            self.prefix_stats = PrefixCacheStats()
        if self._spec is not None:
            out.update(self.spec_stats.as_timing())
            old = self.spec_stats
            self.spec_stats = SpecDecodeStats(
                # Disablement is engine-lifetime state, not a window
                # counter: the reason survives the drain.
                disabled_reason=old.disabled_reason,
            )
        if self.constrained:
            # Constrained-decoding counters (metrics.ConstraintStats):
            # compiles/cache hits, masked dispatch volume, and the
            # forced-run draft/accept tally — drained like spec_stats.
            out.update(self.constraint_stats.as_timing())
            self.constraint_stats = ConstraintStats()
        # Robustness counters (metrics.RobustnessStats): typed request
        # outcomes + fault episodes, always present.
        out.update(self.robust.as_timing())
        self.robust = RobustnessStats()
        if self.icfg.metrics_jsonl or self.icfg.metrics_prom:
            # The registry exporters ride the drain point: one JSONL
            # time-series row / one Prometheus textfile rewrite per drain
            # window, carrying the drained counters plus the live gauges.
            row = {f"serve.{k}": v for k, v in out.items()}
            row.update(self.registry.snapshot(sections=("pool", "hbm")))
            try:
                if self.icfg.metrics_jsonl:
                    self.registry.export_jsonl(
                        self.icfg.metrics_jsonl, snapshot=row
                    )
                if self.icfg.metrics_prom:
                    self.registry.export_prometheus(
                        self.icfg.metrics_prom, snapshot=row
                    )
            except OSError as e:
                log.error("metrics export failed: %s", e)
        return out

    def _autotune_window(self, step_total: float) -> None:
        """Resize the decode window from the step's measured device/host
        split (see InferenceConfig.decode_window_autotune): double while
        the per-step host share exceeds the target; halve when it falls
        below a quarter of the target (hysteresis band [target/4, target]
        is stable), so a load drop is not stuck with a doubled window's
        ITL forever. Floors at the configured inference.decode_window,
        caps at decode_window_max. Uses the step's own measured split, so
        one outlier pass (e.g. a compile) moves the window at most one
        notch.

        Every resize changes the [W, B] decode shape and forces a full
        retrace/recompile of the fused decode program on the NEXT decode
        dispatch; that compile lands inside that step's device span and
        would distort the very split this tuner reads, so step() excludes
        the first post-resize decode-window step from tuning
        (_autotune_skip) — the recompile cost is paid once per resize
        either way, but it can no longer cascade into a second, spurious
        resize."""
        host = (
            step_total - self._dev_span - self._prefill_span
            - self._spill_span - self._restore_span - self._pagein_span
        )
        denom = step_total if step_total > 0 else 1.0
        target = self.icfg.decode_host_share_target
        if (
            host / denom > target
            and self.decode_window * 2 <= self.icfg.decode_window_max
        ):
            self.decode_window *= 2
            self._autotune_skip = True
            log.info(
                "decode_window autotune: host share %.2f > %.2f, window -> %d",
                host / denom, target, self.decode_window,
            )
        elif (
            host / denom < target / 4
            and self.decode_window // 2 >= self.icfg.decode_window
        ):
            self.decode_window //= 2
            self._autotune_skip = True
            log.info(
                "decode_window autotune: host share %.2f < %.2f, window -> %d",
                host / denom, target / 4, self.decode_window,
            )

    def clear_prefix_cache(self) -> int:
        """Drop every cached prefix (idle cached pages return to the free
        list); returns the number of pages released. Live requests keep
        their shared pages through their own refs. No-op when
        inference.prefix_cache is off."""
        if self._pcache is None:
            return 0
        return self._pcache.clear()

    def has_work(self) -> bool:
        return (
            bool(self.waiting)
            or bool(self._just_finished)
            or any(r is not None for r in self.slots)
        )

    # -- router-facing scheduler face (infer/router.py) --------------------

    @property
    def consec_failed_steps(self) -> int:
        """Consecutive failed step() calls (0 after any successful step) —
        the router's primary liveness signal for this replica; the engine
        itself re-raises at inference.max_step_faults."""
        return self._consec_failed

    def prefix_match_tokens(self, context: Sequence[int]) -> int:
        """Tokens of ``context`` this replica could serve from its radix
        prefix index right now — the router's prefix-affinity placement
        signal. Read-only (PrefixCache.peek: no locks, no LRU stamps, no
        edge splits), so probing N replicas never perturbs any tree. 0
        with the prefix cache off.

        Mirrors _match_prefix's USABILITY gates, not just its cap: a
        match below prefix_cache_min_pages, or shallower than the SWA
        dead-page boundary, is one admission would reject — advertising
        it would affinity-pin placements that then prefill cold."""
        if self._pcache is None:
            return 0
        cap = len(context) // self.psz
        if self.page_window is not None:
            # Mirror _match_prefix's SWA cap: a full-context match is
            # never usable there, so do not advertise it.
            cap = (len(context) - 1) // self.psz
        pages, host, first_host = self._pcache.peek_tiered(context, cap)
        if host and (
            self._host_pool is None
            or host * self.psz < self._host_min_tokens
        ):
            # Host-resident span admission would send to recompute (gate
            # below threshold, or a stale tier with no pool): advertise
            # only the usable device prefix. Above the threshold the FULL
            # match advertises — a host-warm replica must beat a cold one
            # at placement even though its hit pays one h2d.
            pages = first_host
        if pages < max(self.icfg.prefix_cache_min_pages, 1):
            return 0
        if self.page_window is not None and (
            pages < self._first_live_page(len(context))
        ):
            return 0
        return pages * self.psz

    def drain(self) -> list[Request]:
        """Graceful shutdown (the SIGTERM path, wired in generate.py via
        PreemptionHandler): stop admission, shed the wait queue with typed
        outcomes, finish every LIVE request — donating their pages to the
        prefix cache exactly as normal completion does — and return every
        request that terminated during the drain. Leaves the pool fully
        accounted (assert_page_accounting)."""
        self.draining = True
        keep: AdmissionQueue = AdmissionQueue()
        while self.waiting:
            r = self.waiting.popleft()
            if in_flight(r):
                # Preempted back into the queue after running: in-flight
                # work the drain contract finishes, not sheds.
                keep.append(r)
            else:
                self._shed(r, "draining")
        self.waiting = keep
        drained: list[Request] = []
        while self.has_work():
            drained.extend(self.step())
        self.assert_page_accounting()
        return drained

    def close(self) -> None:
        """Stop the serving watchdog thread, flush the metrics exporters
        and export the Chrome trace when inference.trace_path is set.
        Idempotent: the flush/export half runs once — a second close must
        not append a spurious all-zero row to the metrics time series.

        Admission stops permanently: a submit() after close() yields a
        typed "shed" outcome exactly like one after drain() — it must
        never queue work no step loop will ever run (ISSUE 12 lifecycle
        hardening; the router leans on this when retiring replicas)."""
        self.draining = True
        if not self._closed:
            self._closed = True
            if self.icfg.metrics_jsonl or self.icfg.metrics_prom:
                # Final drain so a short-lived serve (the CLI path, which
                # never calls reset_timing itself) still flushes its tail
                # window through the exporters.
                self.reset_timing()
            export_chrome_safe(self._tracer, self.icfg.trace_path)
        if self._watchdog is not None:
            self._watchdog.stop()

    def assert_page_accounting(self) -> None:
        """The drain-time pool invariant (bugfix-sweep guard for the shared
        release path): every pool page's allocator refcount equals its live
        owner count — one per page mapped by a request plus one per
        prefix-cache node holding it — and the free list holds exactly the
        rest. A double-release or leak in ANY teardown path (reap, preempt,
        expiry, cancel, quarantine, shed) trips this immediately."""
        n = self.icfg.num_pages
        refs = [0] * n
        owners = [r for r in self.slots if r is not None]
        owners += list(self.waiting) + list(self._just_finished)
        owners += list(self._importing.values())
        for req in owners:
            for p in req.pages:
                if p is not None:
                    refs[p] += 1
        if self._pcache is not None:
            for p in self._pcache.held_pages():
                refs[p] += 1
        actual = [self.alloc.refcount(p) for p in range(n)]
        bad = [
            (p, refs[p], actual[p])
            for p in range(1, n) if refs[p] != actual[p]
        ]
        assert not bad, (
            f"page refcount mismatch (page, owners, refcount): {bad[:8]}"
        )
        live = sum(1 for p in range(1, n) if refs[p] > 0)
        assert self.alloc.free_pages == n - 1 - live, (
            f"free-list size {self.alloc.free_pages} != "
            f"{n - 1 - live} (pool {n}, live {live})"
        )
        if self._host_pool is not None:
            # Host-tier half of the invariant: at a quiescent point host
            # slots are owned by the tree's HostPage markers plus live
            # requests' host_pages maps (inference.long_context — one
            # ENGINE-owned ref each; in-flight restore refs exist only
            # inside the restore envelope), so each held slot's refcount
            # is its owner count and the free list holds exactly the rest.
            hp = self._host_pool
            hrefs = [0] * hp.capacity
            tlive = 0
            if self._pcache is not None:
                for h in self._pcache.held_host_pages():
                    hrefs[h] += 1
                tlive = sum(1 for h in range(hp.capacity) if hrefs[h] > 0)
            for req in owners:
                for h in req.host_pages.values():
                    hrefs[h] += 1
            hbad = [
                (h, hrefs[h], hp.refcount(h))
                for h in range(hp.capacity) if hrefs[h] != hp.refcount(h)
            ]
            assert not hbad, (
                f"host slot refcount mismatch (slot, owners, refcount): "
                f"{hbad[:8]}"
            )
            hlive = sum(1 for h in range(hp.capacity) if hrefs[h] > 0)
            assert hp.free_slots == hp.capacity - hlive, (
                f"host free-list size {hp.free_slots} != "
                f"{hp.capacity - hlive} (capacity {hp.capacity}, "
                f"live {hlive})"
            )
            if self._pcache is not None:
                assert self._pcache.host_pages == tlive, (
                    f"host_pages counter {self._pcache.host_pages} != "
                    f"walked marker count {tlive}"
                )

    def generate(
        self,
        prompts: Sequence[Sequence[int]],
        max_new_tokens: Optional[int] = None,
    ) -> list[list[int]]:
        """Convenience drain loop: returns generated tokens per prompt, in
        submission order."""
        rids = [self.submit(p, max_new_tokens) for p in prompts]
        results: dict[int, list[int]] = {}
        while self.has_work():
            for req in self.step():
                results[req.rid] = req.generated
        return [results[rid] for rid in rids]

    def stream(
        self,
        prompts: Sequence[Sequence[int]],
        max_new_tokens: Optional[int] = None,
    ):
        """Incremental drain loop: yields ``(rid, new_tokens)`` as tokens
        are accepted, one tuple per advanced request per engine step.

        Granularity is the engine step (``inference.decode_window`` fused
        token steps per host round-trip): lowering the window trades
        latency-to-first-yield against throughput. Requests still waiting
        for pool admission simply yield nothing until admitted.
        """
        reqs = [self.submit_request(p, max_new_tokens) for p in prompts]
        emitted = [0] * len(reqs)
        pending = set(range(len(reqs)))
        while pending:
            self.step()
            for i in sorted(pending):
                req = reqs[i]
                if len(req.generated) > emitted[i]:
                    yield req.rid, req.generated[emitted[i]:]
                    emitted[i] = len(req.generated)
                if req.done and emitted[i] == len(req.generated):
                    if emitted[i] == 0:
                        # Zero-token completion (e.g. max_new_tokens=0
                        # scoring): still announce the rid exactly once so
                        # consumers see every request they submitted.
                        yield req.rid, []
                    pending.discard(i)

    # -- scheduler internals ----------------------------------------------

    def _bucket_len(self, n: int) -> int:
        chunk = self.icfg.prefill_chunk
        return min(-(-n // chunk) * chunk, self.icfg.max_seq_len)

    def _admission_need(self, context_len: int) -> tuple[int, int, int]:
        """(n_pages, first_live, need): the pool demand of admitting a
        request whose context is ``context_len`` tokens.

        ``need`` covers the prefill's real (live) pages plus the first
        decode window's pre-provisioning — the exact check _admit applies;
        submit() maxes it over every context the request could re-prefill
        at so the pool-holds-this-request-alone invariant stays true.

        Chunked prefill allocates EVERY logical page (first_live = 0, even
        under SWA): a later chunk's queries read window-distant positions
        from the POOL (the prefix-page gather), so pages behind the
        window of the full context are still live for the chunks that
        attend them; _roll_window frees them as the chunk cursor — not
        the whole prompt — advances. Chunked SWA admission is therefore
        O(context) pages, traded for the bounded ITL.
        """
        n_pages = self._bucket_len(context_len) // self.psz
        first_live = (
            0 if self.chunked else self._first_live_page(context_len)
        )
        n_real = n_pages - first_live
        last = min(
            context_len + self._provision_window - 1,
            self.icfg.max_seq_len - 1,
        )
        first_window = min(last // self.psz + 1, self.pages_per_seq)
        # +1 spare on both branches: mid-decode pool exhaustion must stay
        # unreachable for a request the pool holds alone.
        need = max(n_real + 1, first_window - first_live + 1)
        return n_pages, first_live, need

    def _worst_admission_need(self, min_ctx: int, max_ctx: int) -> int:
        """Max admission need over every context in [min_ctx, max_ctx].

        Exact vectorized sweep: with a sliding window the demand is not
        monotone in context (bucket size is a step function while the
        dead-page count advances every page_size tokens), and the peak
        sits at a prefill-bucket bottom — not at max_ctx, where a
        candidate-point check would look.
        """
        icfg = self.icfg
        W, Wd, psz = self.page_window, self._provision_window, self.psz
        ctxs = np.arange(min_ctx, max_ctx + 1, dtype=np.int64)
        chunk = icfg.prefill_chunk
        bucket = np.minimum(-(-ctxs // chunk) * chunk, icfg.max_seq_len)
        first_live = (
            np.maximum(ctxs - W + 1, 0) // psz
            if W is not None and not self.chunked
            else np.zeros_like(ctxs)
        )
        n_real = bucket // psz - first_live
        last = np.minimum(ctxs + Wd - 1, icfg.max_seq_len - 1)
        first_window = np.minimum(last // psz + 1, self.pages_per_seq)
        need = np.maximum(n_real + 1, first_window - first_live + 1)
        return int(need.max())

    def _available(self) -> int:
        """Pool headroom the scheduler may count on: free pages plus every
        cached page no live request has pinned — the cache is reclaimable
        headroom, not a separate budget (one pool, one invariant)."""
        ev = self._pcache.evictable_pages() if self._pcache is not None else 0
        return self.alloc.free_pages + ev

    def _alloc_pages(self, n: int) -> list[int]:
        """Allocate n pages, evicting LRU prefix-cache pages as needed.

        EVERY engine page allocation routes through here — it is the
        injection point for FaultSpec kind="pool" (a simulated allocator
        exhaustion), which the admit path absorbs by deferring the request
        and the grow path by failing the step, never the process."""
        if self._injector is not None and (
            self._injector.take("pool", self.step_no) is not None
        ):
            raise MemoryError(
                f"injected pool exhaustion (step {self.step_no})"
            )
        short = n - self.alloc.free_pages
        if short > 0 and self._pcache is not None:
            self.prefix_stats.evicted_pages += self._pcache.evict(short)
        return self.alloc.alloc(n)

    # -- host tier (inference.host_tier_bytes; README "Tiered prefix
    #    cache"): the two batched copy envelopes + the break-even gate ---

    def _spill_pages(
        self, pages: list[int], *, tree: bool = True
    ) -> Optional[list[int]]:
        """PrefixCache's spill callback: copy the victim pages' KV bytes
        (every cache array — int8 scale pools ride along) into host
        slots. ONE batched d2h serves the whole eviction sweep: one
        gather dispatch over all victims, one device_get. Returns the
        host slot ids (one engine-owned ref each, which demote hands to
        the tree), or None when the tier cannot take them — the caller
        falls back to discarding, so a spill failure degrades the cache,
        never the step."""
        hp = self._host_pool
        try:
            hids = hp.alloc(len(pages))
        except MemoryError:
            return None
        n = len(pages)
        npad = 1 << (n - 1).bit_length()
        padded = np.zeros(npad, np.int32)
        padded[:n] = pages
        try:
            with self._device_span("spill", "_spill_span"), \
                    self._tracer.annotation("orion/spill"):
                blocks = self._gather_pages(self.cache, jnp.asarray(padded))
                # orion: allow[host-sync] the ONE batched d2h per eviction sweep — the host copy IS the operation
                blocks = jax.device_get(blocks)
        # orion: allow[fault-except] spill envelope: ANY copy failure degrades to discard eviction, never a failed step
        except Exception as e:
            hp.free(hids)
            self.robust.dispatch_faults += 1
            self._flight_note(
                "dispatch_fault", path="spill",
                error=f"{type(e).__name__}: {e}",
            )
            log.error("host-tier spill failed (%s); discarding instead", e)
            return None
        hp.store(hids, blocks, n)
        if tree:
            # tree=False is the per-request paging caller (_page_out /
            # _preempt_to_host): those slots never transit the radix
            # tree, so they count as request_paged_out, not
            # evicted_to_host.
            self.prefix_stats.evicted_to_host += n
        return hids

    def _restore_pages(self, pages: list, node, host_idx: list[int]) -> None:
        """Restore a matched path's host-resident entries into fresh pool
        pages with ONE batched h2d, then promote the tree markers to the
        new device ids — after which the caller maps the match exactly as
        a warm HBM hit. Runs under the match's lock (the path cannot
        mutate) with one engine ref per host slot in flight (the slots
        cannot be reclaimed).

        Failure containment: pool exhaustion while allocating the fresh
        pages propagates as MemoryError (the admission path defers, as
        any warm admission does); a fault inside the copy envelope —
        injected (FaultSpec kind="restore") or real — unwinds BOTH sides
        completely (fresh pages freed, in-flight refs dropped, tree
        markers untouched and unpromoted) and raises a typed
        DispatchFault: a torn restore can never leave a half-promoted
        path or leak a page on either tier."""
        hp = self._host_pool
        hids = [pages[i].hid for i in host_idx]
        for h in hids:
            hp.retain(h)
        n = len(hids)
        try:
            fresh = self._alloc_pages(n)
        except MemoryError:
            hp.free(hids)
            raise
        try:
            if self._injector is not None and (
                self._injector.take("restore", self.step_no) is not None
            ):
                raise InjectedFault(
                    f"injected restore fault (step {self.step_no})"
                )
            npad = 1 << (n - 1).bit_length()
            padded = np.zeros(npad, np.int32)
            padded[:n] = fresh
            blocks = hp.load(hids)
            if npad > n:
                blocks = {
                    k: np.concatenate(
                        [v, np.zeros((npad - n,) + v.shape[1:], v.dtype)]
                    )
                    for k, v in blocks.items()
                }
            with self._device_span("restore", "_restore_span"), \
                    self._tracer.annotation("orion/restore"):
                self.cache = self._scatter_pages(
                    self.cache, jnp.asarray(padded),
                    {k: jnp.asarray(v) for k, v in blocks.items()},
                )
                # orion: allow[host-sync] the ONE batched h2d per restore — a torn copy must surface BEFORE any marker promotes
                jax.block_until_ready(self.cache)
        # orion: allow[fault-except] restore envelope: unwind both tiers fully, typed DispatchFault, no torn pages
        except Exception as e:
            self.alloc.free(fresh)
            hp.free(hids)
            self.robust.dispatch_faults += 1
            self._flight_note(
                "dispatch_fault", path="restore",
                error=f"{type(e).__name__}: {e}",
            )
            raise DispatchFault(
                "restore", f"{type(e).__name__}: {e}"
            ) from e
        self._pcache.promote_path(node, dict(zip(host_idx, fresh)))
        hp.free(hids)
        for i, p in zip(host_idx, fresh):
            pages[i] = p
        self.prefix_stats.host_hits += 1
        self.prefix_stats.host_restored_pages += n

    def _resolve_host_match(self, context, cap: int, pages: list, node):
        """A match() result containing host-resident entries is not yet
        mappable: either restore the whole match (break-even says the h2d
        beats recomputing the host span) or re-match truncated at the
        FIRST host entry (prefill needs a contiguous device prefix —
        entries past a gap are unusable even if device-resident). The
        binary choice is exact: restores are all-or-prefix, and the gate
        compares the host span's token count against the measured
        threshold."""
        host_idx = [
            i for i, p in enumerate(pages) if not isinstance(p, int)
        ]
        if (
            self._host_pool is not None
            and len(host_idx) * self.psz >= self._host_min_tokens
        ):
            try:
                self._restore_pages(pages, node, host_idx)
                return pages, node
            except MemoryError as e:
                # Pool too tight for the restore right now: fall back to
                # the device prefix rather than deferring the admission —
                # recompute always works.
                log.warning(
                    "host-tier restore deferred to recompute (%s)", e
                )
            except DispatchFault:
                # The envelope unwound both pools; balance the match
                # lock too before the typed fault fails the step —
                # retry re-matches from scratch.
                self._pcache.unlock(node)
                raise
        self.prefix_stats.host_recompute_skips += 1
        self._pcache.unlock(node)
        first_host = host_idx[0]
        if first_host == 0:
            return [], None
        return self._pcache.match(context, first_host)

    def offload_prefix_cache(self) -> int:
        """Demote every evictable device-resident cached page to the host
        tier (one batched d2h) — the fleet warm-start control: a replica
        about to scale down / hand off its traffic parks its working set
        in host RAM, and the router's affinity probe still advertises the
        prefixes, so the replica wins placement over a cold one and
        restores on first hit. Also the bench's phase control
        (tools/prefix_cache_bench.py --capacity-sweep). Returns device
        pages demoted; 0 with the tier (or the cache) off."""
        if self._pcache is None or self._host_pool is None:
            return 0
        # Runs OUTSIDE step() (step's span flush won't see this), so the
        # spill span flushes straight into the timing bucket here.
        self._spill_span = 0.0
        n = self._pcache.demote(self._pcache.evictable_pages())
        self.prefix_stats.evicted_pages += n
        self.timing["spill_s"] += self._spill_span
        self._spill_span = 0.0
        return n

    def _match_prefix(self, context: list[int]):
        """(n_match, pages, node): longest usable cached prefix of
        ``context``, page-granular, LOCKED against eviction (the caller
        owns the unlock). Always leaves at least the final token to
        recompute — a full-page-multiple full match is allowed (the COW
        admission path recomputes the last token via decode)."""
        if self._pcache is None:
            return 0, [], None
        cap = len(context) // self.psz
        if self.page_window is not None:
            # SWA: never take the COW full-match path, and only accept
            # matches at least as deep as the cold dead-page boundary —
            # a shallower match would have to ALLOCATE live prefix pages
            # for the tail prefill to read, pages a cold admission never
            # materializes, breaking the pool-holds-this-request-alone
            # accounting submit() checked against.
            cap = (len(context) - 1) // self.psz
        pages, node = self._pcache.match(context, cap)
        if node is not None and any(not isinstance(p, int) for p in pages):
            # Host-resident entries in the match: restore them (break-
            # even permitting) or fall back to the pure-device prefix.
            # Either way `pages` below holds only mappable device ids.
            pages, node = self._resolve_host_match(
                context, cap, pages, node
            )
        n_match = len(pages)
        ok = n_match >= max(self.icfg.prefix_cache_min_pages, 1)
        if ok and self.page_window is not None:
            ok = n_match >= self._first_live_page(len(context))
        if not ok:
            if node is not None:
                self._pcache.unlock(node)
            return 0, [], None
        return n_match, pages, node

    def _admission_need_warm(
        self, context_len: int, n_match: int, full: bool
    ) -> tuple[int, int, int, int]:
        """(n_pages, first_live, n_alloc, need) for a prefix-matched
        admission: ``n_alloc`` fresh pool pages (the uncached tail — exact
        page count, no bucket padding — or the single COW page on a full
        match), ``need`` the same live-prefill + first-decode-window
        demand _admission_need computes for cold admissions. Always
        <= the cold need submit() validated the pool against."""
        psz = self.psz
        if full:
            # Whole context cached: decode restarts at position len-1,
            # rewriting the final token's KV slot in a COW'd private copy
            # of the last matched page.
            n_pages, n_alloc = n_match, 1
            last = min(
                context_len - 1 + self._provision_window - 1,
                self.icfg.max_seq_len - 1,
            )
        else:
            n_pages = -(-context_len // psz)
            n_alloc = n_pages - n_match
            last = min(
                context_len + self._provision_window - 1,
                self.icfg.max_seq_len - 1,
            )
        first_window = min(last // psz + 1, self.pages_per_seq)
        first_live = (
            self._first_live_page(n_match * psz) if not full else 0
        )
        need = max(n_alloc + 1, n_alloc + first_window - n_pages + 1)
        return n_pages, first_live, n_alloc, need

    @property
    def _provision_window(self) -> int:
        """The decode window the pool must budget for: with auto-tune on,
        the cap the window may grow to — admission/submit checks against
        this, so growth never strands an admitted request. With
        speculation on, also at least speculate_tokens+1: a verify step
        writes draft KV that far past the cursor, and its page
        provisioning must never preempt a request admission promised to
        hold."""
        base = (
            self.icfg.decode_window_max
            if self.icfg.decode_window_autotune else self.decode_window
        )
        if self.icfg.speculative:
            base = max(base, self.icfg.speculate_tokens + 1)
        return base

    def _first_live_page(self, context_len: int) -> int:
        """First logical page a sequence at ``context_len`` can still read.

        With sliding-window attention the next decode query (position
        ``context_len``) attends kv positions > context_len - window; pages
        wholly before that are dead — never allocated at admission, and
        freed as the window rolls past them (_roll_window). 0 without SWA.
        """
        W = self.page_window
        if W is None:
            return 0
        return max(context_len - W + 1, 0) // self.psz

    def _roll_window(self) -> None:
        """Return dead pages (behind the sliding window) to the pool.

        The decode mask and the paged kernel's index clamp both exclude
        them, so a windowed sequence's steady-state footprint is
        O(window), not O(context). Freed logical slots keep a None
        placeholder so page indices stay position-aligned; their table
        entries point at scratch page 0 (never read)."""
        if self.page_window is None:
            return
        for req in self.slots:
            if req is None or req.slot is None:
                continue
            first = min(
                self._first_live_page(int(self.seq_lens[req.slot])),
                len(req.pages),
            )
            if first <= req.freed_until:
                continue  # nothing newly dead since the last pass
            dead = [
                p for p in req.pages[req.freed_until:first] if p is not None
            ]
            for j in range(req.freed_until, first):
                req.pages[j] = None
            self.page_table[req.slot, req.freed_until:first] = 0
            req.freed_until = first
            if dead:
                self.alloc.free(dead)
            if req.host_pages:
                # SWA rolled past a host-resident page: its KV will never
                # be read again — drop the host slot instead of ever
                # paying the h2d to restore a dead page.
                rolled = [j for j in req.host_pages if j < first]
                if rolled:
                    self._host_pool.free(
                        [req.host_pages.pop(j) for j in rolled]
                    )

    # -- per-request KV paging (inference.long_context; README "Long
    #    context"): lazy chunk provisioning + host-tier demote/restore --

    def _long_admission_need(self) -> int:
        """Worst-instant pool demand of the LAZY chunked-prefill path
        (the over-pool admission bound): pages spanned by
        [cursor - W + 1, cursor + X - 1] for any page-aligned cursor —
        the live window behind plus the larger of one chunk and the
        decode provisioning window ahead — plus one page of span
        misalignment and the +1 spare every admission carries. O(window),
        independent of context length: that independence IS the
        long-context admission story (PERF.md "Long context")."""
        W = self.page_window
        X = max(self.icfg.prefill_chunk_tokens, self._provision_window)
        return (W + X - 2) // self.psz + 3

    def _drop_host_pages(self, req: Request) -> None:
        """Release every host slot a request holds (terminal paths and
        recompute-from-scratch preemption — stale KV must not occupy the
        tier)."""
        if req.host_pages:
            self._host_pool.free(list(req.host_pages.values()))
            req.host_pages.clear()
        req.host_cursor = 0

    def _page_out(self, req: Request) -> None:
        """Residency-cap demotion (inference.request_resident_pages):
        after a long request's chunk, demote its OLDEST live private
        pages beyond the cap to host slots — one batched d2h — freeing
        device pages for co-tenants between this request's turns. The
        pages come back through _page_in_request before the next chunk
        that reads them. Spill failure (full tier / copy fault) degrades
        to staying resident, never a failed step."""
        cap = self.icfg.request_resident_pages
        if not cap or not self._long or req.slot is None:
            return
        live = [
            j for j in range(req.freed_until, len(req.pages))
            if req.pages[j] is not None and j >= req.n_prefix
        ]
        excess = len(live) - cap
        if excess <= 0:
            return
        victims = live[:excess]
        pages = [req.pages[j] for j in victims]
        hids = self._spill_pages(pages, tree=False)
        if hids is None:
            return
        for j, h in zip(victims, hids):
            req.host_pages[j] = h
            req.pages[j] = None
        self.page_table[req.slot, victims] = 0
        self.alloc.free(pages)
        self.prefix_stats.request_paged_out += len(pages)
        if self._tracer.enabled:
            self._tracer.instant(
                "page_out", rid=req.rid, pages=len(pages),
                step=self.step_no, **self._trace_ctx(req),
            )

    def _page_in_request(self, req: Request) -> None:
        """Restore a live request's host-resident pages into fresh pool
        pages with ONE batched h2d, ahead of the chunk/decode dispatch
        that reads them (every still-held slot is live: _roll_window
        already dropped the rolled-dead ones).

        Failure containment mirrors _restore_pages: pool exhaustion
        propagates as MemoryError (the step fails and retries — the
        request keeps its host refs); a fault inside the copy envelope —
        injected (FaultSpec kind="restore") or real — unwinds the DEVICE
        side completely (fresh pages freed) while the HOST side keeps
        every slot, so the request stays resumable and a retry next step
        pages in from scratch. No torn page on either tier."""
        if not req.host_pages:
            return
        hp = self._host_pool
        due = sorted(req.host_pages)
        hids = [req.host_pages[j] for j in due]
        n = len(hids)
        fresh = self._alloc_pages(n)
        try:
            if self._injector is not None and (
                self._injector.take("restore", self.step_no) is not None
            ):
                raise InjectedFault(
                    f"injected restore fault (step {self.step_no})"
                )
            npad = 1 << (n - 1).bit_length()
            padded = np.zeros(npad, np.int32)
            padded[:n] = fresh
            blocks = hp.load(hids)
            if npad > n:
                blocks = {
                    k: np.concatenate(
                        [v, np.zeros((npad - n,) + v.shape[1:], v.dtype)]
                    )
                    for k, v in blocks.items()
                }
            with self._device_span("page_in", "_pagein_span"), \
                    self._tracer.annotation("orion/page_in"):
                self.cache = self._scatter_pages(
                    self.cache, jnp.asarray(padded),
                    {k: jnp.asarray(v) for k, v in blocks.items()},
                )
                # orion: allow[host-sync] the ONE batched h2d per page-in — a torn copy must surface BEFORE any page maps
                jax.block_until_ready(self.cache)
        # orion: allow[fault-except] page-in envelope: free the fresh device pages, keep every host ref, typed DispatchFault
        except Exception as e:
            self.alloc.free(fresh)
            self.robust.dispatch_faults += 1
            self._flight_note(
                "dispatch_fault", path="page_in",
                error=f"{type(e).__name__}: {e}",
            )
            raise DispatchFault(
                "page_in", f"{type(e).__name__}: {e}"
            ) from e
        for j, p in zip(due, fresh):
            req.pages[j] = p
            del req.host_pages[j]
        hp.free(hids)
        self.page_table[req.slot, due] = fresh
        self.prefix_stats.request_paged_in += n
        if self._tracer.enabled:
            self._tracer.instant(
                "page_in", rid=req.rid, pages=n, step=self.step_no,
                **self._trace_ctx(req),
            )

    def _provision_chunk_pages(self, req: Request, k: int) -> None:
        """Lazy page materialization for the next chunk (the over-pool
        admission path allocates NOTHING up front): extend the request's
        page list to cover [cursor, cursor + k). Pool exhaustion raises
        MemoryError out of _alloc_pages — the step fails with pages
        owned, exactly the _grow_pages contract."""
        n_need = -(-(req.prefill_done + k) // self.psz)
        while len(req.pages) < n_need:
            page = self._alloc_pages(1)[0]
            self.page_table[req.slot, len(req.pages)] = page
            req.pages.append(page)

    def _preempt_to_host(self, req: Request, cursor: int) -> bool:
        """Preempt-to-host (inference.long_context): spill the victim's
        live private pages to host slots instead of discarding and
        re-prefilling from scratch — for a long request the O(context)
        chunked re-prefill is exactly the cost the tier exists to dodge.
        Gated by the same measured break-even the tree restores use
        (host_tier_min_tokens / the PERF.md arithmetic): below it,
        recompute wins and the plain preempt path runs. Returns True
        when the request left the slot host-resident."""
        if not self._long or self._host_pool is None:
            return False
        if req.n_prefix:
            # Shared prefix pages are tree-owned and immutable — the
            # radix tier already covers them; mixed ownership is not
            # worth the accounting.
            return False
        live = [
            j for j in range(req.freed_until, len(req.pages))
            if req.pages[j] is not None
        ]
        span = (len(live) + len(req.host_pages)) * self.psz
        if span < self._host_min_tokens:
            return False
        hids = None
        if live:
            hids = self._spill_pages(
                [req.pages[j] for j in live], tree=False
            )
            if hids is None:
                return False   # tier full / copy fault: plain preempt
        slot = req.slot
        if hids is not None:
            req.host_pages.update(zip(live, hids))
            self.prefix_stats.request_paged_out += len(live)
        req.host_cursor = cursor
        req.host_last_token = int(self.last_token[slot])
        self.alloc.free([req.pages[j] for j in live])
        req.pages = []
        if req.prefix_node is not None:   # unreachable (n_prefix == 0)
            self._pcache.unlock(req.prefix_node)
            req.prefix_node = None
        req.slot = None
        self.slots[slot] = None
        self.page_table[slot] = 0
        self.seq_lens[slot] = 0
        self.last_token[slot] = 0
        if self._spec is not None:
            self._spec.drop(req.rid)
        self.waiting.appendleft(req)
        if self._tracer.enabled:
            self._tracer.instant(
                "preempt_to_host", rid=req.rid, pages=len(live),
                cursor=cursor, step=self.step_no, **self._trace_ctx(req),
            )
        return True

    def _readmit_host(
        self, req: Request, slot: int, reserved: int
    ) -> Optional[int]:
        """Re-admit a host-resident request (preempt-to-host's other
        half): allocate fresh device pages for every spilled logical
        page, batched-restore them, and resume at the spill-time cursor
        — no re-prefill at all. Returns the claimed-but-unallocated page
        count (the caller's ``reserved`` delta), or None (head-of-line
        block) when the pool lacks the restore + first-window headroom;
        raises DispatchFault out of the copy envelope with the admission
        fully unwound (the request re-queues at the head, still
        host-resident, and retries next step)."""
        n = len(req.host_pages)
        last = min(
            req.host_cursor + self._provision_window - 1,
            self.icfg.max_seq_len - 1,
        )
        first_window = min(last // self.psz + 1, self.pages_per_seq)
        n_logical = max(
            max(req.host_pages) + 1 if req.host_pages else 0,
            -(-req.host_cursor // self.psz),
        )
        need = max(n + 1, n + first_window - n_logical + 1)
        if self._available() - reserved < need:
            return None
        req.slot = slot
        req.admit_seq = next(self._admit_seq)
        req.pages = [None] * n_logical
        self.slots[slot] = req
        self.page_table[slot] = 0
        try:
            self._page_in_request(req)
        except (MemoryError, DispatchFault):
            # Unwind the claim completely; host refs survive inside the
            # envelope, so the request re-queues resumable either way.
            req.pages = []
            req.slot = None
            self.slots[slot] = None
            self.waiting.appendleft(req)
            raise
        icfg = self.icfg
        self.slot_temp[slot] = (
            icfg.temperature if req.temperature is None
            else req.temperature
        )
        self.slot_top_k[slot] = (
            icfg.top_k if req.top_k is None else req.top_k
        )
        self.slot_top_p[slot] = (
            icfg.top_p if req.top_p is None else req.top_p
        )
        self.seq_lens[slot] = req.host_cursor
        self.last_token[slot] = req.host_last_token
        req.prefill_done = req.host_cursor
        req.prefill_pending = req.host_cursor < len(req.context)
        req.host_cursor = 0
        if self._tracer.enabled:
            self._tracer.instant(
                "admit", rid=req.rid, slot=slot, step=self.step_no,
                priority=req.priority, host_restored=n,
                **self._trace_ctx(req),
            )
        return need - n

    # -- cross-replica KV-page migration (ISSUE 20; infer/router.py
    #    drives these between steps for role-split fleets) ----------------
    #
    # Export half (the prefill replica): migration_ready /
    # migration_full_pages gate the handoff, export_migration_state
    # snapshots the host-side request state, export_migration_pages runs
    # the batched gather (the spill envelope's read half — int8 scale
    # pools ride the cache dict), finish_migration tears the slot down
    # WITHOUT a typed outcome once the destination committed (fleet-level
    # exactly-once surfacing moves with the request; full context pages
    # still donate to the source prefix tree on the way out).
    #
    # Import half (the decode replica): import_begin stages a Request
    # with no slot, import_pages allocates fresh pool pages and scatters
    # migrated blocks into them (the restore envelope's write half, same
    # unwind discipline), import_commit claims a slot and resumes decode
    # at the source cursor — a zero-prefill warm start, byte-identical
    # greedy continuation — and import_abort unwinds a torn handoff.
    # Staged requests are page owners (assert_page_accounting walks
    # them); a commit deferred on a full batch leaves the request WHOLLY
    # arrived, just unscheduled.

    def _active_request(self, rid: int) -> Optional[Request]:
        for r in self.slots:
            if r is not None and r.rid == rid and not r.done:
                return r
        return None

    def migration_ready(self, rid: int) -> bool:
        """Whole-request handoff can run: the prompt is fully prefilled
        and the first token sampled (both prefill paths sample it at
        prompt completion), so the destination resumes in pure decode."""
        req = self._active_request(rid)
        return (
            req is not None
            and not req.prefill_pending
            and bool(req.generated)
        )

    def migration_in_prefill(self, rid: int) -> bool:
        """The request is mid-chunked-prefill on a live slot — the
        per-chunk streaming mode (router.migrate_per_chunk) can open its
        stream and ship completed full pages ahead of the final commit."""
        req = self._active_request(rid)
        return req is not None and req.prefill_pending

    def migration_full_pages(self, rid: int) -> int:
        """Leading logical pages whose KV is final (wholly covered by the
        prefill chunk cursor / decode cursor): the per-chunk streaming
        watermark — a full page never mutates, so pages below this index
        ship once and stay valid."""
        req = self._active_request(rid)
        if req is None:
            return 0
        cursor = (
            req.prefill_done if req.prefill_pending
            else int(self.seq_lens[req.slot])
        )
        return min(cursor // self.psz, len(req.pages))

    def export_migration_state(self, rid: int) -> dict:
        """Host-side snapshot of everything the destination needs beyond
        the KV bytes: identity + sampling overrides, the decode cursor
        and in-flight token, the SWA rolling mark, and the grammar
        ``ConstraintState`` walk (pure host state — it moves with the
        request). No device work; call at commit time so the snapshot
        matches the shipped pages."""
        req = self._active_request(rid)
        if req is None:
            raise ValueError(f"no active request {rid} to export")
        slot = req.slot
        return {
            "prompt": list(req.prompt),
            "generated": list(req.generated),
            "max_new_tokens": req.max_new_tokens,
            "temperature": req.temperature,
            "top_k": req.top_k,
            "top_p": req.top_p,
            "priority": req.priority,
            "deadline": req.deadline,
            "trace_id": req.trace_id,
            "attempt": req.attempt,
            "constraint": req.constraint,
            "cursor": int(self.seq_lens[slot]),
            "last_token": int(self.last_token[slot]),
            "prefill_pending": req.prefill_pending,
            "prefill_done": req.prefill_done,
            "freed_until": req.freed_until,
            "n_logical": len(req.pages),
            "page_size": self.psz,
        }

    def export_migration_pages(
        self, rid: int, start: int = 0, stop: Optional[int] = None
    ):
        """Batched gather of the live pages in logical span [start, stop)
        — ONE dispatch + the blocks as DEVICE arrays (``[npad, L, ...]``
        per cache array, int8 scale pools included), so the router can
        convert topology through ``parallel/reshard.py`` (or
        ``jax.device_get`` for the universal host hop) before the
        destination scatter. Host-tier-resident pages page in FIRST
        (restore-before-migrate): the gather needs device bytes, and the
        page-in envelope's unwind already covers its faults. Returns
        ``(live, blocks)`` with ``live`` the absolute logical indices
        gathered. The source request is untouched — gather is a pure pool
        read, so a failed handoff leaves it serving colocated."""
        req = self._active_request(rid)
        if req is None:
            raise ValueError(f"no active request {rid} to export")
        if req.host_pages:
            self._page_in_request(req)
        if stop is None:
            stop = len(req.pages)
        live = [
            j for j in range(start, min(stop, len(req.pages)))
            if req.pages[j] is not None
        ]
        if not live:
            return [], {}
        n = len(live)
        npad = 1 << (n - 1).bit_length()
        padded = np.zeros(npad, np.int32)
        padded[:n] = [req.pages[j] for j in live]
        self._migrate_span = 0.0
        try:
            with self._device_span("migrate_out", "_migrate_span"), \
                    self._tracer.annotation("orion/migrate_out"):
                blocks = self._gather_pages(self.cache, jnp.asarray(padded))
                jax.block_until_ready(blocks)  # orion: allow[host-sync] a torn gather must surface HERE, not inside the destination scatter
        # orion: allow[fault-except] migrate-out envelope: pure read — nothing to unwind; typed DispatchFault, source request intact
        except Exception as e:
            self.robust.dispatch_faults += 1
            self._flight_note(
                "dispatch_fault", path="migrate_out",
                error=f"{type(e).__name__}: {e}",
            )
            raise DispatchFault(
                "migrate_out", f"{type(e).__name__}: {e}"
            ) from e
        # Runs OUTSIDE step() (same contract as offload_prefix_cache):
        # flush the copy span straight into the timing bucket.
        self.timing["migrate_out_s"] += self._migrate_span
        self._migrate_span = 0.0
        return live, blocks

    def finish_migration(self, rid: int) -> None:
        """Source-side commit: the destination holds the whole request —
        tear the slot down with NO typed outcome (the request surfaces
        exactly once, from the destination), donating full context pages
        to the source prefix tree exactly like a reap would, so the
        source stays warm for affinity-matched followers."""
        req = self._active_request(rid)
        if req is None:
            return
        cursor = int(self.seq_lens[req.slot])
        self._teardown_slot(req, cursor)
        req.done = True
        self._ttft_seen.discard(req.rid)
        if self._tracer.enabled:
            self._tracer.instant(
                "migrate_out", rid=req.rid, cursor=cursor,
                step=self.step_no, **self._trace_ctx(req),
            )

    def import_begin(self, state: dict) -> int:
        """Stage an incoming migration: a Request with no slot, owning
        pages as they arrive (import_pages). Returns the engine rid the
        router uses as the stream token. Validates the ONE layout
        parameter the page copy cannot convert — page geometry; pool
        sizes, shardings and dtypes convert in transit."""
        if state["page_size"] != self.psz:
            raise ValueError(
                f"migration page_size {state['page_size']} != "
                f"destination page_size {self.psz} (page-granular copies "
                f"cannot re-chunk; match inference.page_size across roles)"
            )
        req = Request(
            rid=next(self._rid),
            prompt=list(state["prompt"]),
            max_new_tokens=state["max_new_tokens"],
            temperature=state["temperature"],
            top_k=state["top_k"],
            top_p=state["top_p"],
            priority=state["priority"],
            deadline=state["deadline"],
            trace_id=state["trace_id"],
            attempt=state["attempt"],
            constraint=state["constraint"],
        )
        self._importing[req.rid] = req
        return req.rid

    def import_pages(self, token: int, live: list, blocks: dict) -> None:
        """Scatter one batch of migrated page blocks into fresh pool
        pages at the staged request's logical indices ``live``. The write
        half of the restore envelope with the same unwind: a fault frees
        the fresh pages and raises a typed DispatchFault with the staged
        request unchanged — the router aborts or retries; no torn page
        either way."""
        req = self._importing[token]
        n = len(live)
        fresh = self._alloc_pages(n)
        try:
            npad = 1 << (n - 1).bit_length()
            padded = np.zeros(npad, np.int32)
            padded[:n] = fresh
            self._migrate_span = 0.0
            with self._device_span("migrate_in", "_migrate_span"), \
                    self._tracer.annotation("orion/migrate_in"):
                self.cache = self._scatter_pages(
                    self.cache, jnp.asarray(padded),
                    {k: jnp.asarray(v) for k, v in blocks.items()},
                )
                jax.block_until_ready(self.cache)  # orion: allow[host-sync] the ONE sync per migrate-in batch — a torn copy must surface BEFORE the commit
        # orion: allow[fault-except] migrate-in envelope: free the fresh pages, keep the staged request, typed DispatchFault
        except Exception as e:
            self.alloc.free(fresh)
            self.robust.dispatch_faults += 1
            self._flight_note(
                "dispatch_fault", path="migrate_in",
                error=f"{type(e).__name__}: {e}",
            )
            raise DispatchFault(
                "migrate_in", f"{type(e).__name__}: {e}"
            ) from e
        self.timing["migrate_in_s"] += self._migrate_span
        self._migrate_span = 0.0
        if live and max(live) >= len(req.pages):
            req.pages.extend([None] * (max(live) + 1 - len(req.pages)))
        for j, p in zip(live, fresh):
            req.pages[j] = p

    def import_commit(self, token: int, state: dict) -> Optional[Request]:
        """Admit the staged request as a zero-prefill warm start: claim a
        free slot, mirror the source's page layout and cursors, resume
        decode on the in-flight token. Returns the live Request, or None
        when no slot (or no first-window page headroom) is free — the
        request stays staged, WHOLLY arrived, and the router retries the
        commit next step. Mirrors _readmit_host's slot restore exactly;
        the decode stream continues byte-identical to a colocated serve
        for greedy requests (argmax is key-independent — sampled streams
        draw from the destination engine's key lineage, the same caveat
        as the prefix cache's zero-prefill path)."""
        req = self._importing[token]
        slot = next(
            (i for i, r in enumerate(self.slots) if r is None), None
        )
        if slot is None:
            return None
        n_logical = max(state["n_logical"], len(req.pages))
        cursor = state["cursor"]
        last = min(
            cursor + self._provision_window - 1, self.icfg.max_seq_len - 1
        )
        first_window = min(last // self.psz + 1, self.pages_per_seq)
        headroom = max(first_window - n_logical, 0) + 1
        if self._available() < headroom:
            return None
        del self._importing[token]
        if len(req.pages) < n_logical:
            req.pages.extend([None] * (n_logical - len(req.pages)))
        req.generated = list(state["generated"])
        req.constraint = state["constraint"]
        req.freed_until = state["freed_until"]
        # The source's SWA window may have rolled past pages shipped
        # earlier in a per-chunk stream: they are dead at commit — free
        # them now, exactly as the source's _roll_window did.
        stale = [
            j for j in range(min(req.freed_until, len(req.pages)))
            if req.pages[j] is not None
        ]
        if stale:
            self.alloc.free([req.pages[j] for j in stale])
            for j in stale:
                req.pages[j] = None
        req.slot = slot
        req.admit_seq = next(self._admit_seq)
        self.slots[slot] = req
        icfg = self.icfg
        self.slot_temp[slot] = (
            icfg.temperature if req.temperature is None
            else req.temperature
        )
        self.slot_top_k[slot] = (
            icfg.top_k if req.top_k is None else req.top_k
        )
        self.slot_top_p[slot] = (
            icfg.top_p if req.top_p is None else req.top_p
        )
        self.page_table[slot] = 0
        self.page_table[slot, :len(req.pages)] = [
            0 if p is None else p for p in req.pages
        ]
        self.seq_lens[slot] = cursor
        self.last_token[slot] = state["last_token"]
        req.prefill_done = state["prefill_done"]
        req.prefill_pending = state["prefill_pending"]
        if self._tracer.enabled:
            self._tracer.instant(
                "migrate_in", rid=req.rid, slot=slot, cursor=cursor,
                step=self.step_no, **self._trace_ctx(req),
            )
        return req

    def import_abort(self, token: int) -> None:
        """Unwind a torn/abandoned migration stream: free every staged
        page and drop the staged request. Idempotent (a commit already
        consumed the token -> no-op), so the router's failure paths can
        call it unconditionally."""
        req = self._importing.pop(token, None)
        if req is None:
            return
        self.alloc.free([p for p in req.pages if p is not None])
        req.pages = []

    def migration_block_shardings(self) -> Optional[dict]:
        """Target shardings for migrated-in page blocks, one per cache
        array: this pool's own sharding with the leading pool-row dim
        replaced by the block batch dims (``[rows, ...] -> [n, L, ...]``)
        so `parallel/reshard.py` can move a source replica's gathered
        blocks straight onto this replica's layout — the manifest-style
        per-array redistribution, without a host bounce when source and
        destination share a platform. Returns None when any pool array
        carries no usable sharding (the router then falls back to the
        universal jax.device_get hop)."""
        out = {}
        for name, arr in self.cache.items():
            sh = getattr(arr, "sharding", None)
            if sh is None:
                return None
            if isinstance(sh, jax.sharding.NamedSharding):
                spec = jax.sharding.PartitionSpec(None, None, *sh.spec[1:])
                out[name] = jax.sharding.NamedSharding(sh.mesh, spec)
            else:
                # Single-device pool: place blocks on the same device.
                out[name] = sh
        return out

    def _admit(self) -> None:
        # Pass 1 (host): claim slots + pages for every admissible request,
        # highest priority class first, arrival order within a class
        # (with all-default priorities this IS arrival order, exactly the
        # pre-priority behavior) and head-of-line blocking on resources.
        # No draining gate here: while draining, submit() sheds on arrival
        # and drain()'s entry pass sheds queued never-started requests, so
        # anything still in the queue is in-flight work (preempted, or
        # unwound by a fault) that MUST re-admit to finish — gating it
        # would livelock the drain loop.
        admitted: list[tuple[Request, int]] = []
        # Headroom pages claimed by this burst's earlier admissions but not
        # yet allocated (they materialize in _grow_pages): without carrying
        # this across the loop, N admissions each pass the check against the
        # same free pool and the burst over-commits — _grow_pages then
        # preempts an OLDER request in the same step, discarding its
        # just-done prefill.
        reserved = 0
        while self.waiting:
            idx = max(
                range(len(self.waiting)),
                key=lambda i: (self.waiting[i].priority, -i),
            )
            req = self.waiting[idx]
            slot = next(
                (i for i, r in enumerate(self.slots) if r is None), None
            )
            if slot is None:
                break
            if req.host_pages:
                # Host-resident re-admission (preempt-to-host's other
                # half): restore the spilled pages and resume at the
                # spill-time cursor — no re-prefill. A DispatchFault out
                # of the copy envelope has already unwound the claim and
                # re-queued the request; let it fail the step.
                del self.waiting[idx]
                delta = self._readmit_host(req, slot, reserved)
                if delta is None:
                    self.waiting.insert(idx, req)
                    break   # head-of-line blocking, as below
                reserved += delta
                continue
            context = req.context
            # Prefix cache: map the longest cached prefix (shared,
            # refcount++) and prefill only the uncached tail. The matched
            # path is locked (evict-proof) from here until release.
            n_match, m_pages, m_node = self._match_prefix(context)
            full = bool(n_match) and n_match * self.psz >= len(context)
            if full:
                temp = (
                    self.icfg.temperature
                    if req.temperature is None else req.temperature
                )
                if temp != 0.0:
                    # Sampled request: the zero-prefill path would draw its
                    # first token from the decode key stream where the cold
                    # engine draws it from the prefill stream — breaking
                    # sampled cache-on/off byte-equivalence. Fall back to a
                    # one-page tail re-prefill (still n_match-1 pages
                    # shared); greedy requests keep the zero-prefill path
                    # (argmax is key-independent).
                    full = False
                    n_match = (len(context) - 1) // self.psz
                    if n_match < max(self.icfg.prefix_cache_min_pages, 1):
                        self._pcache.unlock(m_node)
                        n_match, m_pages, m_node = 0, [], None
                    else:
                        m_pages = m_pages[:n_match]
            if n_match and self._lazy and self._admission_need_warm(
                len(context), n_match, full
            )[3] > self.icfg.num_pages - 1:
                # Over-pool long request with a prefix match: the warm
                # path's eager tail allocation can NEVER fit — drop the
                # match and take the lazy cold branch below.
                self._pcache.unlock(m_node)
                n_match, m_pages, m_node = 0, [], None
                full = False
            if n_match:
                n_pages, first_live, n_alloc, need = (
                    self._admission_need_warm(len(context), n_match, full)
                )
                s_pad = self._bucket_len(len(context) - n_match * self.psz)
            else:
                # Sliding window: logical pages wholly behind the window are
                # dead on arrival (decode will never read them) — their table
                # entries point at scratch page 0 and no pool page is spent.
                # `need` also reserves the first decode window's
                # pre-provisioning: admitting on the prefill footprint alone
                # would let _grow_pages preempt the request right back out in
                # the same step when decode_window > page_size.
                n_pages, first_live, need = self._admission_need(len(context))
                n_alloc = n_pages - first_live
                s_pad = self._bucket_len(len(context))
                if self._lazy and need > self.icfg.num_pages - 1:
                    # Over-pool long-context admission (inference.
                    # long_context): the eager footprint can never fit —
                    # admit on the O(window) lazy working set instead.
                    # NO pages allocate here: chunks materialize their
                    # own (_provision_chunk_pages) and _roll_window
                    # frees behind the window, so the pool never holds
                    # the O(context) footprint at once.
                    first_live = 0
                    n_alloc = 0
                    need = self._long_admission_need()
            if self._available() - reserved < need:
                if m_node is not None:
                    self._pcache.unlock(m_node)
                break  # head-of-line blocking: keep class/arrival order
            reserved += need - n_alloc
            del self.waiting[idx]
            req.slot = slot
            req.admit_seq = next(self._admit_seq)
            req.prefix_node = m_node
            # Fresh pages allocate FIRST in every branch: _alloc_pages is
            # the only fallible op (injected/real pool exhaustion), so a
            # MemoryError here leaves nothing to unwind beyond the claim.
            try:
                if full:
                    # Whole context cached (exact page multiple): no
                    # prefill at all. Copy-on-write the final matched page
                    # — the first decode step rewrites the last token's KV
                    # slot, and shared pages are immutable — then restart
                    # decode from position len-1 with the last context
                    # token in flight.
                    cow = self._alloc_pages(1)[0]
                    self.cache = self._cow(
                        self.cache, jnp.int32(m_pages[-1]), jnp.int32(cow)
                    )
                    for p in m_pages[:-1]:
                        self.alloc.retain(p)
                    req.pages = list(m_pages[:-1]) + [cow]
                    req.n_prefix = n_match - 1
                    req.freed_until = 0
                    self.prefix_stats.hits += 1
                    self.prefix_stats.cached_tokens += len(context) - 1
                    self.prefix_stats.cow_pages += 1
                elif n_match:
                    fresh = self._alloc_pages(n_alloc)
                    live = m_pages[first_live:]
                    for p in live:
                        self.alloc.retain(p)
                    req.pages = [None] * first_live + list(live) + fresh
                    req.n_prefix = n_match
                    req.freed_until = first_live
                    self.prefix_stats.hits += 1
                    self.prefix_stats.cached_tokens += n_match * self.psz
                else:
                    req.pages = (
                        [None] * first_live + self._alloc_pages(n_alloc)
                    )
                    req.n_prefix = 0
                    req.freed_until = first_live
                    if self._pcache is not None:
                        self.prefix_stats.misses += 1
            except MemoryError as e:
                # Pool exhaustion at admit (injected, or an allocator/
                # accounting fault): un-claim and retry next step instead
                # of crashing the engine mid-admission.
                self.robust.pool_faults += 1
                log.warning(
                    "admission of request %d hit pool exhaustion (%s); "
                    "deferred", req.rid, e,
                )
                if m_node is not None:
                    self._pcache.unlock(m_node)
                req.prefix_node = None
                req.slot = None
                # Un-claim completely: admit_seq >= 0 marks in-flight work
                # (shed/drain-exempt), and this request never ran.
                req.admit_seq = -1
                self.waiting.appendleft(req)
                break
            self.slots[slot] = req
            if self._tracer.enabled:
                self._tracer.instant(
                    "admit", rid=req.rid, slot=slot, step=self.step_no,
                    priority=req.priority,
                    cached_tokens=(
                        len(context) - 1 if full
                        else n_match * self.psz
                    ),
                    **self._trace_ctx(req),
                )
            icfg = self.icfg
            self.slot_temp[slot] = (
                icfg.temperature if req.temperature is None
                else req.temperature
            )
            self.slot_top_k[slot] = (
                icfg.top_k if req.top_k is None else req.top_k
            )
            self.slot_top_p[slot] = (
                icfg.top_p if req.top_p is None else req.top_p
            )
            # len(req.pages) == n_pages on every eager branch; the lazy
            # branch admitted with NO pages (they materialize per chunk).
            self.page_table[slot, :len(req.pages)] = [
                0 if p is None else p for p in req.pages
            ]
            if full:
                self.seq_lens[slot] = len(context) - 1
                self.last_token[slot] = context[-1]
                if req.max_new_tokens <= 0:
                    # Scoring request with its whole context cached:
                    # nothing to compute; reap re-donates the pages.
                    req.done = True
            else:
                self.seq_lens[slot] = len(context)
                admitted.append((req, s_pad))

        # Pass 2. Chunked prefill (inference.chunked_prefill): NO eager
        # prefill dispatch at all — admitted prompts only set their chunk
        # cursor (past any cached prefix) and ride the next mixed steps,
        # so a long-prompt admission can never stall in-flight decodes by
        # more than one chunk budget.
        if admitted and self.chunked:
            for req, _ in admitted:
                req.prefill_done = req.n_prefix * self.psz
                req.prefill_pending = True
                self.seq_lens[req.slot] = req.prefill_done
            return
        # Unchunked pass 2 (device). On the pallas path: ONE ragged
        # prefill dispatch for the WHOLE burst, regardless of length mix
        # (VERDICT r3 item 7) — rows pad to the burst's largest bucket,
        # but the flash kernel SKIPS blocks whose rows/columns are all
        # padding (segment id 0), so each row's attention pays ~its own
        # length (the quadratic term; the linear ops still run at the
        # shared width). On the xla path no block skip exists — a short
        # row would pay the burst-max O(S^2) attention — so keep one
        # dispatch per bucket there. Rows are padded up to a power-of-two
        # batch so jit specializations stay bounded.
        if admitted:
            from orion_tpu.ops._dispatch import resolve_impl

            if resolve_impl(self.mcfg.kernels)[0]:
                self._prefill_bucket(
                    [r for r, _ in admitted], max(s for _, s in admitted)
                )
            else:
                by_bucket: dict[int, list[Request]] = {}
                for req, s_pad in admitted:
                    by_bucket.setdefault(s_pad, []).append(req)
                items = list(by_bucket.items())
                for bi, (s_pad, reqs) in enumerate(items):
                    try:
                        self._prefill_bucket(reqs, s_pad)
                    except DispatchFault:
                        # The faulted bucket unwound its own admissions;
                        # the not-yet-dispatched buckets are admitted but
                        # unprefilled — unwind them too before failing
                        # the step.
                        for _, later in items[bi + 1:]:
                            for r in reversed(later):
                                self._teardown_slot(r, 0)
                                r.freed_until = 0
                                self.waiting.appendleft(r)
                        raise

    def _prefill_bucket(self, reqs: list[Request], s_pad: int) -> None:
        """Prefill a group of admitted requests in one dispatch; rows may
        be shorter than ``s_pad`` (their tail positions write to the
        scratch page and their compute blocks skip via segment ids).
        Prefix-matched rows carry only their uncached TAIL here — the
        prefix page ids ride along for the mid-sequence attention gather
        (runner.prefill_step), padded to the burst's max match (power of
        two, so jit specializations stay bounded)."""
        n_pages = s_pad // self.psz
        nb = 1 << (len(reqs) - 1).bit_length()   # next power of two
        tokens = np.zeros((nb, s_pad), np.int32)
        lengths = np.ones(nb, np.int32)          # pad rows: length 1
        pages = np.zeros((nb, n_pages), np.int32)  # pad rows: scratch page 0
        max_pre = max(r.n_prefix for r in reqs)
        p_pre = 1 << (max_pre - 1).bit_length() if max_pre > 0 else 0
        pre_lens = np.zeros(nb, np.int32)
        pre_pages = np.zeros((nb, p_pre), np.int32)
        for i, req in enumerate(reqs):
            npre = req.n_prefix
            tail = req.context[npre * self.psz:]
            tokens[i, : len(tail)] = tail
            lengths[i] = len(tail)
            pre_lens[i] = npre * self.psz
            if npre:
                # Dead (behind-window) matched pages point at scratch 0 —
                # behind every tail query's window, never attended.
                pre_pages[i, :npre] = [
                    0 if p is None else p for p in req.pages[:npre]
                ]
            # Dead (behind-window) logical pages write to scratch page 0;
            # those positions are never read back (sliding-window mask).
            # Positions past this row's own bucket (shorter than the
            # burst's) go to scratch too.
            tail_pg = req.pages[npre:]
            pages[i, : len(tail_pg)] = [
                0 if p is None else p for p in tail_pg
            ]
        with self._device_span("prefill", "_prefill_span"):
            try:
                logits, self.cache = self._run_dispatch(
                    "prefill", "prefill",
                    self.params,
                    self.cache,
                    jnp.asarray(tokens),
                    jnp.asarray(lengths),
                    jnp.asarray(pages),
                    jnp.asarray(pre_lens),
                    jnp.asarray(pre_pages),
                )
            except DispatchFault:
                # Unwind this burst's admissions: their slots are claimed
                # but NO KV was written, so tear down with nothing donated
                # (n_cached=0 — donating would insert garbage pages into
                # the prefix cache) and re-queue at the head for the next
                # step's re-prefill.
                for r in reversed(reqs):
                    self._teardown_slot(r, 0)
                    r.freed_until = 0
                    self.waiting.appendleft(r)
                raise
            firsts = self._sample(logits, reqs)  # blocks on the fetch
        for i, req in enumerate(reqs):
            if req.done:
                continue   # quarantined during mask build (_sample_masks)
            if req.max_new_tokens <= 0:
                req.done = True   # prefill-only (scoring) request
                continue
            first = int(firsts[i])
            self.last_token[req.slot] = first
            req.generated.append(first)
            self._maybe_finish(req, first)

    def _release_request(self, req: Request, n_cached: int) -> None:
        """Release a leaving request's pages. With prefix caching, the
        contiguous full pages of its context (``n_cached`` tokens hold
        valid KV) are donated to the radix tree first — on reap AND
        preempt, so a preempted request re-matches its own pages and
        re-prefills only what the cache lost. insert() retains what it
        keeps; the request then drops its own refs uniformly (shared
        pages decrement, private duplicates free)."""
        if self._pcache is not None and req.pages:
            n_full = min(n_cached // self.psz, len(req.pages))
            k = 0
            while k < n_full and req.pages[k] is not None:
                k += 1
            if k:
                self.prefix_stats.inserted_pages += self._pcache.insert(
                    req.context[: k * self.psz], req.pages[:k]
                )
        if req.prefix_node is not None:
            self._pcache.unlock(req.prefix_node)
            req.prefix_node = None
        self.alloc.free([p for p in req.pages if p is not None])
        req.pages = []
        req.n_prefix = 0
        # Host-resident pages are stale the moment the device side drops
        # (terminal exit, or a recompute-from-scratch preemption — the
        # preempt-to-host path never reaches here): release the slots.
        self._drop_host_pages(req)
        if self._spec is not None:
            # Adaptive draft-length state dies with the slot; a preempted
            # request restarts adaptation cold on re-admission.
            self._spec.drop(req.rid)

    def _teardown_slot(self, req: Request, n_cached: int) -> None:
        """The ONE slot-teardown path every exit shares: reap (completion,
        expiry, cancel), preemption and quarantine all release pages
        (donating the first ``n_cached`` tokens' full pages to the prefix
        cache via _release_request) and clear the slot's scheduler arrays
        HERE, so the pool invariant (assert_page_accounting) has a single
        code path to hold instead of three hand-rolled variants."""
        slot = req.slot
        self._release_request(req, n_cached)
        req.slot = None
        self.slots[slot] = None
        self.page_table[slot] = 0
        self.seq_lens[slot] = 0
        self.last_token[slot] = 0

    def _preempt(self, req: Request) -> None:
        """Evict an active request, returning its pages; it re-enters at the
        head of the queue and resumes from its full context on re-prefill
        (cheaply, when the prefix cache kept its pages)."""
        log.info("preempting request %d (pool pressure)", req.rid)
        self.preemptions += 1
        cursor = int(self.seq_lens[req.slot])
        # Preempt-to-host (inference.long_context): for a long request
        # past the restore break-even, spill live pages to host slots and
        # resume at the cursor on re-admission — replacing the O(context)
        # recompute-from-scratch below.
        if self._preempt_to_host(req, cursor):
            return
        # Mid-prefill preemption: seq_lens is the chunk cursor, so exactly
        # the completed chunks' full pages donate to the prefix cache and
        # re-admission resumes from whatever the cache kept.
        self._teardown_slot(req, cursor)
        req.freed_until = 0
        req.prefill_pending = False
        req.prefill_done = 0
        self.waiting.appendleft(req)

    def _grow_pages(self, window: Optional[int] = None) -> None:
        """Pre-provision every active slot with pages covering the whole
        upcoming decode window (the device writes up to W positions ahead of
        the host's view, including past mid-window EOS), preempting the
        lowest-priority youngest-admitted request under pool pressure
        (high classes and older requests keep making progress; no
        mid-decode crash). ``window`` overrides the
        span for verify steps (speculate_tokens+1 write positions per
        slot — always within _provision_window, which admission budgeted
        for)."""
        W = self.decode_window if window is None else window
        # Provisioning rank: high priority classes first, oldest first
        # within a class — so the preemption victim (the LAST ranked
        # request below) is the lowest class's youngest member, honoring
        # the submit() contract that low classes evict first. With
        # all-default priorities this is exactly the pre-priority
        # youngest-admitted order.
        by_age = sorted(
            (r for r in self.slots if r is not None and not r.done),
            key=lambda r: (-r.priority, r.admit_seq),
        )
        # Batched pre-evict: compute the whole pass's page shortfall and
        # reclaim it in ONE eviction sweep, so a host-tier demotion pays
        # one batched d2h instead of one per page (the per-page evict(1)
        # below remains as the fallback for preemption-donated pages).
        # Tier-off this frees the identical LRU page set the lazy loop
        # would have, just up front.
        if self._pcache is not None:
            need_total = 0
            for req in by_age:
                if req.slot is None:
                    continue
                pos = int(self.seq_lens[req.slot])
                last = min(pos + W - 1, self.icfg.max_seq_len - 1)
                n_need = min(last // self.psz + 1, self.pages_per_seq)
                need_total += max(n_need - len(req.pages), 0)
            short = need_total - self.alloc.free_pages
            if short > 0:
                self.prefix_stats.evicted_pages += self._pcache.evict(
                    short
                )
        for req in by_age:
            if req.slot is None:
                continue  # preempted earlier in this pass
            pos = int(self.seq_lens[req.slot])
            last = min(pos + W - 1, self.icfg.max_seq_len - 1)
            n_need = min(last // self.psz + 1, self.pages_per_seq)
            while req.slot is not None and len(req.pages) < n_need:
                while self.alloc.free_pages < 1:
                    # Reclaim cached pages before touching live requests:
                    # the prefix cache is headroom, not a tenant. (A
                    # preemption below may DONATE pages to the cache, which
                    # this branch then reclaims on the next iteration.)
                    if self._pcache is not None and self._pcache.evict(1):
                        self.prefix_stats.evicted_pages += 1
                        continue
                    victims = [
                        r for r in by_age
                        if r.slot is not None and r is not req
                        and r.priority <= req.priority
                    ]
                    if not victims:
                        if not any(
                            r.slot is not None and r is not req
                            for r in by_age
                        ):
                            raise MemoryError(
                                "KV pool too small for a single request; "
                                "raise inference.num_pages"
                            )
                        # Only HIGHER-priority tenants hold pages: a
                        # low-priority request must never grow at their
                        # expense — evict the requester itself instead.
                        self._preempt(req)
                        break
                    self._preempt(victims[-1])
                if req.slot is None:
                    break   # self-preempted above
                # Through _alloc_pages for the pool-fault injection point
                # (free_pages >= 1 here, so no second eviction pass runs).
                page = self._alloc_pages(1)[0]
                self.page_table[req.slot, len(req.pages)] = page
                req.pages.append(page)

    def _propose_drafts(
        self, cands: list[Request]
    ) -> Optional[dict[int, list[int]]]:
        """Host-side drafting pass (inference.speculative): an n-gram
        draft per candidate slot, keyed by slot. None when NOTHING was
        drafted — the caller falls back to the plain decode window, so a
        non-repetitive workload pays only the proposal scan. The draft
        length is capped per request by the adaptive state, the context
        window (write positions must stay below max_seq_len) and the
        request's remaining token budget (drafting past max_new_tokens
        is guaranteed rollback).

        Draft-density gate (inference.spec_min_draft_slots): a verify
        step costs every NON-drafting co-tenant its multi-step decode
        window (one host round-trip per token on that step), so when
        fewer than the threshold of live slots drafted — clamped to the
        live count, a fully-drafting batch always verifies — the step is
        gated back to the plain window (counted: spec_gated_steps). The
        discarded drafts were free to produce and are re-proposed next
        step if the repetition persists."""
        if not cands:
            return None
        self._constraint_forced = {}   # no forced prefixes on this path
        extra = (
            self._pcache.token_paths() if self._pcache is not None else ()
        )
        drafts: dict[int, list[int]] = {}
        n_drafted = 0
        for r in cands:
            if r.host_pages:
                # Long-context hold: part of this slot's KV is host-
                # resident (a page-in fault left residue), so a
                # multi-token verify would read pages the page-in pass
                # has not restored yet. Hold to a plain 1-token row this
                # step; the restore runs before dispatch and the slot
                # drafts again next step.
                drafts[r.slot] = None if self._tree else []
                continue
            pos = int(self.seq_lens[r.slot])
            limit = min(
                self.icfg.max_seq_len - 1 - pos,
                r.max_new_tokens - len(r.generated) - 1,
            )
            if limit <= 0:
                d = None if self._tree else []
            elif self._tree:
                # Token-tree drafting: up to spec_tree_width distinct
                # n-gram continuations merged into a trie (DraftTree).
                d = self._spec.propose_tree(r.rid, r.context, limit, extra)
            else:
                d = self._spec.propose(r.rid, r.context, limit, extra)
            drafts[r.slot] = d
            n_drafted += bool(d)
        if not n_drafted:
            return None
        if n_drafted < min(self.icfg.spec_min_draft_slots, len(cands)):
            self.spec_stats.gated_steps += 1
            return None
        return drafts

    def _propose_constrained_drafts(
        self, cands: list[Request]
    ) -> dict[int, Any]:
        """Drafting pass for a decode batch that contains constrained
        slots (these never ride the fused multi-token window: the next
        mask depends on the device-side sample, but along a KNOWN draft
        every per-position mask is host-precomputable — the verify
        layout). Never returns None: zero-draft constrained slots still
        verify at lens=1 — a masked single-token decode.

        Constrained slots draft their FSM FORCED RUN — single-choice
        states emit their only legal continuation, whose masked target
        probability is exactly 1.0, so acceptance is guaranteed under
        the standard rejection/greedy rule with NO new acceptance math
        (free tokens). Speculation composes: with inference.speculative
        the run extends with the n-gram continuation truncated to its
        FSM-legal prefix; in tree mode an ambiguous state after the run
        becomes a branch point — up to spec_tree_width legal tokens,
        each extended by its own forced tail, merged by
        spec_decode.build_tree. Unconstrained co-tenants draft exactly
        as _propose_drafts would (or not at all when speculation is
        off: their lens-1 rows ride the same verify dispatch)."""
        spec_on = self._spec is not None and not self._spec_disabled
        extra = (
            self._pcache.token_paths()
            if spec_on and self._pcache is not None else ()
        )
        tree = self._tree
        if tree:
            from orion_tpu.infer.spec_decode import build_tree
        drafts: dict[int, Any] = {}
        cs = self.constraint_stats
        self._constraint_forced = {}
        for r in cands:
            pos = int(self.seq_lens[r.slot])
            limit = min(
                self.icfg.max_seq_len - 1 - pos,
                r.max_new_tokens - len(r.generated) - 1,
                self.icfg.speculate_tokens,
            )
            c = r.constraint
            if c is None:
                if spec_on and limit > 0:
                    d = (
                        self._spec.propose_tree(
                            r.rid, r.context, limit, extra
                        ) if tree
                        else self._spec.propose(
                            r.rid, r.context, limit, extra
                        )
                    )
                else:
                    d = None if tree else []
                drafts[r.slot] = d
                continue
            if limit <= 0:
                drafts[r.slot] = None if tree else []
                continue
            forced = c.forced_run(limit)
            cs.forced_drafted += len(forced)
            self._constraint_forced[r.slot] = len(forced)
            end = c.walk(forced)
            if tree:
                chains = [forced] if forced else []
                if (
                    end >= 0 and len(forced) < limit
                    and c.mask_choices(end) > 1
                ):
                    # FSM branch point: the grammar itself names the
                    # candidate children — no n-gram statistics needed.
                    branches = c.branch_tokens(
                        self.icfg.spec_tree_width, end
                    )
                    if len(branches) > 1:
                        cs.branch_points += 1
                    bc = []
                    for b in branches:
                        nxt = c.peek(int(b), end)
                        tail = (
                            c.forced_run(limit - len(forced) - 1, nxt)
                            if nxt >= 0 else []
                        )
                        bc.append(forced + [int(b)] + tail)
                    chains = bc or chains
                t = build_tree(chains, limit) if chains else None
                drafts[r.slot] = t if t is not None and len(t) else None
            else:
                d = list(forced)
                if spec_on and end >= 0 and len(d) < limit:
                    cont = self._spec.propose(
                        r.rid, r.context + d, limit - len(d), extra
                    ) or []
                    for tok in cont:
                        nxt = c.peek(int(tok), end)
                        if nxt < 0:
                            break   # keep only the FSM-legal prefix
                        d.append(int(tok))
                        end = nxt
                drafts[r.slot] = d
        return drafts

    def _verify_masks(
        self,
        active: list[Request],
        tokens: np.ndarray,
        lens: np.ndarray,
        parents: Optional[np.ndarray] = None,
    ) -> Optional[np.ndarray]:
        """Per-position legal-token masks [B, W, V] for one verify
        dispatch: column j of a constrained slot carries the FSM mask
        AFTER consuming its (chain-prefix or tree-ancestor) draft path —
        column 0 is the current state (its token, the pending last
        token, already advanced the walk at emission time). Padding
        columns, unconstrained slots, and columns past an FSM-illegal
        draft token (unreachable: the masked parent logits give the
        illegal draft probability 0, so it is always rejected) stay
        all-True. None when no active slot is constrained — the
        ``legal_mask=None`` jit specialization keeps unconstrained
        verify dispatches byte-identical."""
        if not any(r.constraint is not None for r in active):
            return None
        B, W = tokens.shape
        m = np.ones((B, W, self.mcfg.vocab_size), bool)
        masked = 0
        for r in active:
            c = r.constraint
            if c is None:
                continue
            s = r.slot
            states = np.full(W, -1, np.int64)
            states[0] = c.state
            m[s, 0] = c.mask_row()
            for j in range(1, int(lens[s])):
                p = int(parents[s, j]) if parents is not None else j - 1
                ps = int(states[p])
                nxt = c.peek(int(tokens[s, j]), ps) if ps >= 0 else -1
                states[j] = nxt
                if nxt >= 0:
                    m[s, j] = c.mask_row(nxt)
            masked += 1
        self.constraint_stats.masked_steps += 1
        self.constraint_stats.masked_rows += masked
        return m

    def _build_verify_rows(
        self, reqs: list[Request], drafts: dict[int, list[int]]
    ) -> tuple[np.ndarray, np.ndarray]:
        """The [B, speculate_tokens+1] verify-row layout BOTH dispatch
        paths (_verify_all, _mixed_decode) feed the device and
        _accept_and_rollback later walks: column 0 the pending last
        token, columns 1..1+len(d) the drafts, ``lens`` the per-slot real
        width. Rows without a request stay (zeros, len 1) — masked onto
        scratch by the device side."""
        W = self.icfg.speculate_tokens + 1
        tokens = np.zeros((self.max_batch, W), np.int32)
        lens = np.ones(self.max_batch, np.int32)
        for r in reqs:
            d = drafts.get(r.slot, [])
            tokens[r.slot, 0] = self.last_token[r.slot]
            if d:
                tokens[r.slot, 1:1 + len(d)] = d
            lens[r.slot] = 1 + len(d)
        return tokens, lens

    def _build_verify_tree_rows(
        self, reqs: list[Request], drafts: dict[int, Any]
    ) -> tuple[np.ndarray, ...]:
        """Tree-mode verify layout (inference.spec_tree_width > 1): the
        chain row layout plus the flattened DraftTree structure arrays —
        per-column tree depths, parent columns, and packed ancestor mask
        words. Columns without a node (padding, and whole rows without a
        tree) carry CHAIN-shaped defaults (depth j, parent j-1, causal
        prefix words), so a chain-shaped tree feeds the device arrays a
        pure chain would — the degenerate case is bitwise today's
        verify."""
        W = self.icfg.speculate_tokens + 1
        B = self.max_batch
        steps = np.arange(W, dtype=np.int64)
        tokens = np.zeros((B, W), np.int32)
        lens = np.ones(B, np.int32)
        depths = np.tile(steps.astype(np.int32), (B, 1))
        parents = np.tile(
            np.maximum(steps - 1, 0).astype(np.int32), (B, 1)
        )
        words = np.tile(
            ((np.int64(1) << (steps + 1)) - 1).astype(np.int32), (B, 1)
        )
        for r in reqs:
            s = r.slot
            t = drafts.get(s)
            tokens[s, 0] = self.last_token[s]
            if t:
                n = len(t)
                tokens[s, 1:1 + n] = t.tokens
                lens[s] = 1 + n
                depths[s, :1 + n] = t.depths()
                parents[s, 1:1 + n] = t.parents
                words[s, :1 + n] = np.asarray(
                    t.mask_words(), np.int64
                ).astype(np.int32)
        return tokens, lens, depths, parents, words

    def _verify_all(self, drafts: dict[int, list[int]]) -> bool:
        """One verify dispatch for every live decode slot: K drafts + the
        pending last token per slot, scored in a single pass over the
        weights (runner.verify_step); accept the matched prefix + one
        bonus/correction token, then rewind the rejected tail."""
        self._grow_pages(self.icfg.speculate_tokens + 1)
        # Recompute AFTER provisioning: pool pressure may have preempted
        # a drafted slot (its drafts entry simply goes unread).
        active = [r for r in self.slots if r is not None and not r.done]
        if not active:
            self._reap()
            return False
        if not any(drafts.get(r.slot) for r in active) and not any(
            r.constraint is not None for r in active
        ):
            # Every drafted slot was preempted by the provisioning pass:
            # a verify dispatch would be all padding. Run the plain
            # window instead (it re-provisions to the decode window).
            # Constrained slots are exempt: even draftless they must
            # decode through the masked verify program (lens-1 rows).
            self._spec_step = False
            return self._decode_window_all()
        if self._tree:
            tokens, lens, depths, parents, words = (
                self._build_verify_tree_rows(active, drafts)
            )
            tree_kw = dict(
                depths=jnp.asarray(depths),
                parents=jnp.asarray(parents),
                tree_mask=jnp.asarray(words),
            )
            vmask = self._verify_masks(active, tokens, lens, parents)
        else:
            tokens, lens = self._build_verify_rows(active, drafts)
            tree_kw = {}
            vmask = self._verify_masks(active, tokens, lens)
        if vmask is not None:
            tree_kw["legal_mask"] = jnp.asarray(vmask)
        mask = np.zeros(self.max_batch, bool)
        for r in active:
            mask[r.slot] = True
        self._key, sub = jax.random.split(self._key)
        common = (
            self.params,
            self.cache,
            jnp.asarray(tokens),
            jnp.asarray(self.seq_lens),
            jnp.asarray(lens),
            jnp.asarray(self.page_table),
            jnp.asarray(mask),
            sub,
        )
        with self._device_span("verify"):
            if all(
                r.temperature is None and r.top_k is None and r.top_p is None
                for r in active
            ):
                out = self._run_dispatch(
                    "verify", "verify_defaults", *common, **tree_kw
                )
            else:
                out = self._run_dispatch(
                    "verify", "verify", *common,
                    jnp.asarray(self.slot_temp),
                    jnp.asarray(self.slot_top_k),
                    jnp.asarray(self.slot_top_p),
                    **tree_kw,
                )
            if self._guard:
                acc, alt, ok, self.cache = out
                acc, alt, okh = jax.device_get((acc, alt, ok))  # orion: allow[host-sync] the verify step's ONE documented fetch
            else:
                acc, alt, self.cache = out
                acc, alt = jax.device_get((acc, alt))   # orion: allow[host-sync] the verify step's ONE documented fetch
                okh = None
        self.timing["slot_steps"] += len(active)
        self.timing["decode_slot_steps"] += len(active)
        if okh is not None:
            for req in active:
                if not okh[req.slot]:
                    self._quarantine(req, "nan")
            active = [r for r in active if r.slot is not None]
        if self._tree:
            self._accept_and_rollback_tree(active, tokens, lens, drafts,
                                           acc, alt)
        else:
            self._accept_and_rollback(active, tokens, lens, acc, alt)
        self._reap()
        return True

    def _accept_and_rollback(
        self,
        active: list[Request],
        tokens: np.ndarray,
        lens: np.ndarray,
        acc: np.ndarray,
        alt: np.ndarray,
    ) -> None:
        """Walk each slot's verify verdicts: emit the accepted draft
        prefix plus alt at the first rejection (the correction) or at the
        row's end (the bonus), then rewind — cursor stays at the last
        emitted token (it only ever advanced by emissions) and pages
        covering only rejected positions go back to the pool
        (kv_cache.rollback_pages), leaving exactly the page footprint a
        non-speculative window=1 step would have left. Rejected KV beyond
        the cursor is dead by the seq_lens masking invariant, the same
        way decode-window overshoot is."""
        st = self.spec_stats
        st.verify_steps += 1
        st.verify_slot_steps += len(active)
        for r in active:
            s = r.slot
            k = int(lens[s]) - 1
            a = 0
            while a < k and acc[s, a]:
                a += 1
            emit = [int(t) for t in tokens[s, 1:1 + a]] + [int(alt[s, a])]
            n_emit = 0
            for tok in emit:
                if r.done:
                    break
                self.seq_lens[s] += 1
                self.last_token[s] = tok
                r.generated.append(tok)
                n_emit += 1
                self._maybe_finish(r, tok)
            kept = min(n_emit, a)       # draft tokens that reached the stream
            st.drafted += k
            st.accepted += kept
            st.rolled_back += k - kept
            st.emitted += n_emit
            fr = self._constraint_forced.get(s, 0)
            if fr:
                self.constraint_stats.forced_accepted += min(kept, fr)
            if self._spec is not None:
                # Constrained-only engines verify without a proposer —
                # there is no adaptive draft length to steer.
                self._spec.state(r.rid).update(
                    k, kept, self.icfg.speculate_tokens
                )
            if not r.done:
                # Finished slots skip this: _reap releases everything and
                # donates only full pages below the (rewound) cursor.
                self._rollback_slot(r)

    def _rollback_slot(self, req: Request) -> None:
        """Release the pages a verify step provisioned beyond the
        accepted cursor (speculative rollback, kv_cache.rollback_pages)."""
        n_keep = (int(self.seq_lens[req.slot]) - 1) // self.psz + 1
        if len(req.pages) > n_keep:
            rollback_pages(self.alloc, req.pages, n_keep)
            self.page_table[req.slot, n_keep:] = 0

    def _plan_emission(self, req: Request, emit: list[int]) -> int:
        """How many of ``emit``'s tokens this request will actually
        accept — a side-effect-free mirror of the emission loop's
        ``_maybe_finish`` stop conditions, so tree acceptance can size
        the KV compaction BEFORE any engine state mutates (a failed
        compaction dispatch then fails the step with nothing emitted,
        the same containment contract every other dispatch has)."""
        n = 0
        gen = len(req.generated)
        pos = int(self.seq_lens[req.slot])
        for tok in emit:
            n += 1
            gen += 1
            pos += 1
            if (
                (self.eos_id is not None and tok == self.eos_id)
                or pos >= self.icfg.max_seq_len
                or gen >= req.max_new_tokens
            ):
                break
        return n

    def _accept_and_rollback_tree(
        self,
        active: list[Request],
        tokens: np.ndarray,
        lens: np.ndarray,
        drafts: dict[int, Any],
        acc: np.ndarray,
        alt: np.ndarray,
    ) -> None:
        """Tree-mode acceptance: walk each slot's DraftTree root-down,
        descending into the first accepted child in sibling (insertion-
        priority) order — greedy rows can match at most one sibling
        (tokens are distinct), sampled rows' verdicts are the
        sequential multi-branch rejection scheme of
        ``sampling.spec_verify_sample_tree`` — and emit the verified
        path plus the final node's bonus/correction token.

        An accepted path that is not the tree's primary chain lives at
        non-contiguous verify columns; its KV is MOVED into
        cursor-contiguous slots in one batched compaction dispatch
        (kv_cache.compact_draft_kv) before anything else runs — the
        primary-chain case (and all chain-shaped traffic) needs no
        dispatch at all. Then the cursor advances by emissions exactly
        as the chain walk's does, and rollback releases every page
        covering only losing-branch positions, restoring the window=1
        footprint."""
        st = self.spec_stats
        st.verify_steps += 1
        st.verify_slot_steps += len(active)
        W = self.icfg.speculate_tokens + 1
        src = np.tile(np.arange(W, dtype=np.int32), (self.max_batch, 1))
        plans: list[tuple[Request, Any, list[int], list[int]]] = []
        moves = 0
        for r in active:
            s = r.slot
            t = drafts.get(s) or None
            path: list[int] = []
            cur = 0
            if t is not None:
                children = t.children()
                while True:
                    nxt = next(
                        (c for c in children[cur] if acc[s, c]), None
                    )
                    if nxt is None:
                        break
                    path.append(nxt)
                    cur = nxt
            emit = [int(tokens[s, c]) for c in path] + [int(alt[s, cur])]
            plans.append((r, t, path, emit))
            kept = min(self._plan_emission(r, emit), len(path))
            off = [i for i in range(kept) if path[i] != i + 1]
            if off:
                src[s, 1:1 + kept] = path[:kept]
                moves += len(off)
        if moves:
            try:
                with self._device_span("compact"), \
                        self._tracer.annotation("orion/compact"):
                    self.cache = self._compact(
                        self.cache,
                        jnp.asarray(self.page_table),
                        jnp.asarray(self.seq_lens),
                        jnp.asarray(src),
                    )
                    # orion: allow[host-sync] compaction must surface device errors BEFORE any token is emitted
                    jax.block_until_ready(self.cache)
            # orion: allow[fault-except] dispatch envelope: ANY compaction failure becomes a failed step, never an emission
            except Exception as e:
                self.robust.dispatch_faults += 1
                self._flight_note(
                    "dispatch_fault", path="compact",
                    error=f"{type(e).__name__}: {e}",
                )
                # A broken compaction program is a speculation-path
                # fault: count it toward the auto-disable ladder so a
                # persistent failure turns speculation off instead of
                # escalating to the max_step_faults re-raise.
                self._note_spec_fault(e)
                raise DispatchFault(
                    "compact", f"{type(e).__name__}: {e}"
                ) from e
            st.compactions += 1
            st.compacted_tokens += moves
        for r, t, path, emit in plans:
            s = r.slot
            n_emit = 0
            for tok in emit:
                if r.done:
                    break
                self.seq_lens[s] += 1
                self.last_token[s] = tok
                r.generated.append(tok)
                n_emit += 1
                self._maybe_finish(r, tok)
            kept = min(n_emit, len(path))
            k = int(lens[s]) - 1
            depth = t.max_depth if t is not None else 0
            st.drafted += k
            st.accepted += kept
            st.rolled_back += k - kept
            st.emitted += n_emit
            st.tree_nodes += k
            st.tree_branch_nodes += max(k - depth, 0)
            fr = self._constraint_forced.get(s, 0)
            if fr:
                self.constraint_stats.forced_accepted += min(kept, fr)
            if self._spec is not None:
                # The adaptive controller steers DEPTH (the chain-
                # equivalent draft length): drafted = the tree's primary
                # depth, accepted = the verified path length. Width fills
                # whatever budget the depth leaves
                # (spec_decode.NgramProposer.propose_tree). Constrained-
                # only engines verify without a proposer.
                self._spec.state(r.rid).update(
                    depth, kept, self.icfg.speculate_tokens
                )
            if not r.done:
                self._rollback_slot(r)

    def _decode_all(self) -> bool:
        self._roll_window()
        live = [r for r in self.slots if r is not None and not r.done]
        if self._long:
            # Host-resident residue on a decode slot (a page-in fault
            # retrying, per the keep-host-refs envelope): restore before
            # any dispatch reads the pages.
            for r in live:
                if r.host_pages:
                    self._page_in_request(r)
        if self.constrained and any(
            r.constraint is not None for r in live
        ):
            # Constrained slots decode through the masked verify path
            # unconditionally (the fused window cannot carry FSM masks);
            # forced runs make the step multi-token whenever the grammar
            # allows, and unconstrained co-tenants draft normally.
            self._spec_step = True
            return self._verify_all(self._propose_constrained_drafts(live))
        if self._spec is not None and not self._spec_disabled:
            drafts = self._propose_drafts(live)
            if drafts is not None:
                self._spec_step = True
                return self._verify_all(drafts)
        return self._decode_window_all()

    def _decode_window_all(self) -> bool:
        """The plain fused decode window over all live slots (the
        non-speculative step body; also the verify path's fallback when
        preemption strips every drafted slot)."""
        self._grow_pages()
        active = [r for r in self.slots if r is not None and not r.done]
        if not active:
            self._reap()
            return False
        W = self.decode_window
        mask = np.array(
            [r is not None and not r.done for r in self.slots], bool
        )
        self._key, sub = jax.random.split(self._key)
        common = (
            self.params,
            self.cache,
            jnp.asarray(self.last_token),
            jnp.asarray(self.seq_lens),
            jnp.asarray(self.page_table),
            jnp.asarray(mask),
            jax.random.split(sub, W),
        )
        with self._device_span("decode"):
            if all(
                r.temperature is None and r.top_k is None and r.top_p is None
                for r in active
            ):
                out = self._run_dispatch("decode", "decode_defaults", *common)
            else:
                out = self._run_dispatch(
                    "decode", "decode", *common,
                    jnp.asarray(self.slot_temp),
                    jnp.asarray(self.slot_top_k),
                    jnp.asarray(self.slot_top_p),
                )
            if self._guard:
                toks, ok, self.cache = out
                tokens, okh = jax.device_get((toks, ok))   # orion: allow[host-sync] the decode window's ONE documented fetch
                tokens = np.asarray(tokens)
            else:
                toks, self.cache = out
                tokens = np.asarray(jax.device_get(toks))  # orion: allow[host-sync] [W, B] — the decode window's ONE documented fetch
                okh = None
        self.timing["slot_steps"] += W * len(active)
        self.timing["decode_slot_steps"] += W * len(active)
        if okh is not None:
            for req in active:
                if not okh[req.slot]:
                    # Non-finite logits in this slot's window: the whole
                    # window's tokens for it are suspect — drop them all
                    # and quarantine (neighbors' tokens are unaffected;
                    # no slot ever reads another's pages).
                    self._quarantine(req, "nan")
            active = [r for r in active if r.slot is not None]
        for j in range(W):
            for req in active:
                if req.done:
                    # Finished mid-window: the device still decoded this
                    # slot; the discarded overshoot is the tunable waste.
                    self.timing["wasted_steps"] += 1
                    continue
                tok = int(tokens[j, req.slot])
                self.seq_lens[req.slot] += 1
                self.last_token[req.slot] = tok
                req.generated.append(tok)
                self._maybe_finish(req, tok)
        self._reap()
        return True

    def _mixed_decode(self) -> bool:
        """One UNIFIED mixed prefill+decode step (inference.chunked_prefill,
        runner.mixed_step): a single-token decode for every live slot plus
        up to prefill_chunk_tokens of prompt tail, in ONE dispatch — the
        stall any in-flight decode observes under a prompt burst is
        bounded by the chunk budget, never the whole quadratic prompt.
        Returns True iff any decode slot advanced.

        Speculation composes here (runner.mixed_verify_step): decode-phase
        slots draft and verify up to speculate_tokens per mixed step while
        prompt-phase slots skip drafting — their prompts ARE the chunk
        rows — so a prompt burst and a speculation streak share one
        dispatch."""
        self._roll_window()
        drafts = None
        dec_cands = [
            r for r in self.slots
            if r is not None and not r.done and not r.prefill_pending
        ]
        if self.constrained and any(
            r.constraint is not None for r in dec_cands
        ):
            # Constrained decode-phase slots force the mixed VERIFY
            # program (masked rows; forced runs as free drafts), exactly
            # as _decode_all forces the pure verify path.
            drafts = self._propose_constrained_drafts(dec_cands)
        elif self._spec is not None and not self._spec_disabled:
            drafts = self._propose_drafts(dec_cands)
        if self._long:
            # Decode-phase host residue (a failed page-in retrying):
            # restore AFTER drafting — _propose_drafts held non-resident
            # slots to a 1-token row, so this pass never races a
            # multi-token verify against pages it is still copying.
            for r in dec_cands:
                if r.host_pages:
                    self._page_in_request(r)
        self._grow_pages(
            self.icfg.speculate_tokens + 1 if drafts is not None else None
        )
        psz = self.psz
        S = self.icfg.prefill_chunk_tokens
        # Chunk assembly: pending prompts in admission order (head-of-line
        # fairness matches unchunked admission), each contributing its
        # next page-aligned chunk until the token budget is spent. The
        # final chunk of a prompt may be shorter than a page; mid-prompt
        # chunks end page-aligned so the NEXT chunk resumes page-aligned
        # (the prefix-gather contract of runner.prefill_step).
        pending = sorted(
            (
                r for r in self.slots
                if r is not None and not r.done and r.prefill_pending
            ),
            key=lambda r: r.admit_seq,
        )
        budget = S
        chunks: list[tuple[Request, int]] = []
        for r in pending:
            if budget < 1:
                break
            rem = len(r.context) - r.prefill_done
            k = min(rem, budget)
            if k < rem:
                k = k // psz * psz
                if k == 0:
                    break
            budget -= k
            chunks.append((r, k))
        if self._long:
            # Long-context page passes, restore-then-provision per chunk
            # getter: host-resident pages this chunk's window reads come
            # back in ONE batched h2d (inference.request_resident_pages
            # demoted them after the previous chunk), then the lazy
            # admission path materializes the chunk's own pages (over-pool
            # admission allocated NONE up front). Either raise
            # (DispatchFault / MemoryError) fails the step with both
            # tiers consistent.
            for r, k in chunks:
                if r.host_pages:
                    self._page_in_request(r)
                try:
                    self._provision_chunk_pages(r, k)
                except MemoryError:
                    # Chunk provisioning has no grow-time preemption
                    # valve (_grow_pages only serves decode spans), so
                    # pool exhaustion HERE would fail the step forever.
                    # Park THIS request instead — preempt-to-host past
                    # the break-even, plain preempt below it — and let
                    # co-tenants drain the pressure.
                    self.robust.pool_faults += 1
                    self._preempt(r)
            chunks = [(r, k) for r, k in chunks if r.slot is not None]
        nb = 1 << max(len(chunks) - 1, 0).bit_length()
        n_pages = S // psz
        tokens = np.zeros((nb, S), np.int32)
        lengths = np.ones(nb, np.int32)          # pad rows: length 1
        pages = np.zeros((nb, n_pages), np.int32)  # pad rows: scratch 0
        max_pre = max((r.prefill_done // psz for r, _ in chunks), default=0)
        p_pre = 1 << (max_pre - 1).bit_length() if max_pre > 0 else 0
        pre_lens = np.zeros(nb, np.int32)
        pre_pages = np.zeros((nb, p_pre), np.int32)
        for i, (r, k) in enumerate(chunks):
            start = r.prefill_done
            tokens[i, :k] = r.context[start:start + k]
            lengths[i] = k
            pre_lens[i] = start
            npre = start // psz
            if npre:
                # Rolled-dead (behind-window) pages point at scratch 0 —
                # behind every chunk query's window, never attended.
                pre_pages[i, :npre] = [
                    0 if p is None else p for p in r.pages[:npre]
                ]
            pg = r.pages[npre:npre - (-k // psz)]
            pages[i, :len(pg)] = [0 if p is None else p for p in pg]

        # Decode side: mid-prefill slots mask onto scratch page 0, so the
        # decode sub-body's fused write (which fires for every slot) can
        # never clobber a page their chunks are filling this very step.
        d_pt = self.page_table
        if pending:
            d_pt = self.page_table.copy()
            for r in pending:
                if r.slot is not None:   # provisioning may have preempted
                    d_pt[r.slot] = 0
        dec = [
            r for r in self.slots
            if r is not None and not r.done and not r.prefill_pending
        ]
        if (
            drafts is not None
            and not any(drafts.get(r.slot) for r in dec)
            and not any(r.constraint is not None for r in dec)
        ):
            # The drafted slot(s) were preempted by this step's page
            # provisioning: nothing left to verify — take the plain
            # 1-token mixed step instead of a padding-only verify.
            # Constrained decode slots are exempt: draftless or not,
            # they must ride the masked verify rows.
            drafts = None
        mask = np.array(
            [
                r is not None and not r.done and not r.prefill_pending
                for r in self.slots
            ],
            bool,
        )
        if dec:
            self._key, sub = jax.random.split(self._key)
            # Same key derivation as _decode_all's W-window (split(sub, W),
            # here W=1): at equal engine PRNG state a mixed decode step
            # samples with exactly the key a decode_window=1 step would.
            sub = jax.random.split(sub, 1)[0]
        else:
            # No live decode: do NOT advance the engine PRNG stream —
            # sampled chunked-vs-unchunked equivalence needs one split
            # per SAMPLING event, not per dispatch.
            sub = self._null_key
        chunk_args = (
            jnp.asarray(tokens),
            jnp.asarray(lengths),
            jnp.asarray(pages),
            jnp.asarray(pre_lens),
            jnp.asarray(pre_pages),
        )
        defaults = all(
            r.temperature is None and r.top_k is None and r.top_p is None
            for r in dec
        )
        override_args = (
            jnp.asarray(self.slot_temp),
            jnp.asarray(self.slot_top_k),
            jnp.asarray(self.slot_top_p),
        )
        if drafts is not None:
            # Speculative mixed step: verify rows replace the 1-token
            # decode rows (runner.mixed_verify_step); prompt-phase slots
            # are plain chunk rows, exactly as without speculation.
            self._spec_step = True
            if self._tree:
                vtok, vlens, vdepths, vparents, vwords = (
                    self._build_verify_tree_rows(dec, drafts)
                )
                tree_kw = dict(
                    depths=jnp.asarray(vdepths),
                    parents=jnp.asarray(vparents),
                    tree_mask=jnp.asarray(vwords),
                )
                vmask = self._verify_masks(dec, vtok, vlens, vparents)
            else:
                vtok, vlens = self._build_verify_rows(dec, drafts)
                tree_kw = {}
                vmask = self._verify_masks(dec, vtok, vlens)
            if vmask is not None:
                tree_kw["legal_mask"] = jnp.asarray(vmask)
            common = (
                self.params,
                self.cache,
                jnp.asarray(vtok),
                jnp.asarray(self.seq_lens),
                jnp.asarray(vlens),
                jnp.asarray(d_pt),
                jnp.asarray(mask),
                sub,
            ) + chunk_args
            with self._device_span("mixed_verify", "_mixed_span"):
                if defaults:
                    out = self._run_dispatch(
                        "mixed_verify", "mixed_verify_defaults", *common,
                        **tree_kw
                    )
                else:
                    out = self._run_dispatch(
                        "mixed_verify", "mixed_verify", *common,
                        *override_args, **tree_kw
                    )
                if self._guard:
                    acc, alt, ok, p_logits, self.cache = out
                    acc, alt, okh = jax.device_get((acc, alt, ok))  # orion: allow[host-sync] the mixed-verify step's ONE documented fetch
                else:
                    acc, alt, p_logits, self.cache = out
                    acc, alt = jax.device_get((acc, alt))   # orion: allow[host-sync] the verify step's ONE documented fetch
                    okh = None
        else:
            common = (
                self.params,
                self.cache,
                jnp.asarray(self.last_token),
                jnp.asarray(self.seq_lens),
                jnp.asarray(d_pt),
                jnp.asarray(mask),
                sub,
            ) + chunk_args
            with self._device_span("mixed", "_mixed_span"):
                if defaults:
                    out = self._run_dispatch(
                        "mixed", "mixed_defaults", *common
                    )
                else:
                    out = self._run_dispatch(
                        "mixed", "mixed", *common, *override_args
                    )
                if self._guard:
                    d_toks, ok, p_logits, self.cache = out
                    d_out, okh = jax.device_get((d_toks, ok))   # orion: allow[host-sync] the mixed step's ONE documented fetch
                    d_out = np.asarray(d_out)
                else:
                    d_toks, p_logits, self.cache = out
                    d_out = np.asarray(jax.device_get(d_toks))  # orion: allow[host-sync] [B] — the mixed step's ONE documented fetch
                    okh = None
        real = sum(k for _, k in chunks)
        self.timing["mixed_steps"] += 1
        self.timing["prefill_chunks"] += len(chunks)
        self.timing["chunk_tokens"] += real
        self.timing["chunk_pad_tokens"] += nb * S - real

        # Chunk bookkeeping: advance cursors (seq_lens tracks the cursor,
        # so preemption donates exactly the completed pages and SWA page
        # rolling follows the chunks); prompts that just completed sample
        # their next token off the unified step's logits — fetched only
        # now, so non-finishing steps never pay the [Nc, V] transfer.
        finishing: list[tuple[int, Request]] = []
        for i, (r, k) in enumerate(chunks):
            r.prefill_done += k
            self.seq_lens[r.slot] = r.prefill_done
            if r.prefill_done >= len(r.context):
                finishing.append((i, r))
        if finishing:
            rows = jnp.asarray([i for i, _ in finishing])
            firsts = self._sample(p_logits[rows], [r for _, r in finishing])
            # orion: allow[host-sync] finishing prompts need their sampled first token on the host this step
            for (_, r), first in zip(finishing, np.asarray(firsts)):
                r.prefill_pending = False
                if r.done:
                    continue   # quarantined during mask build
                if r.max_new_tokens <= 0:
                    r.done = True   # prefill-only (scoring) request
                    continue
                tok = int(first)
                self.last_token[r.slot] = tok
                r.generated.append(tok)
                self._maybe_finish(r, tok)
        if self._long and self.icfg.request_resident_pages:
            # Residency demotion between a long request's turns: roll the
            # window first (never demote a page the window already passed
            # — _page_out picks the OLDEST live pages, exactly the
            # about-to-roll ones), then spill still-mid-prefill chunk
            # getters past the cap. Demotion failure degrades to staying
            # resident, so this pass cannot fail the step.
            self._roll_window()
            for r, _k in chunks:
                if r.prefill_pending and not r.done:
                    self._page_out(r)

        # Decode bookkeeping. Speculative: accepted prefix + bonus per
        # slot, then rollback (same walk as the pure verify step).
        # Otherwise W = 1, so no mid-window waste by construction.
        self.timing["slot_steps"] += len(dec)
        if okh is not None:
            # NaN quarantine (decode rows only — the guard rides the
            # decode/verify half of the mixed program; prompt-phase rows
            # are not sampled from this step).
            for r in dec:
                if not okh[r.slot]:
                    self._quarantine(r, "nan")
            dec = [r for r in dec if r.slot is not None]
        if drafts is not None:
            if self._tree:
                self._accept_and_rollback_tree(
                    dec, vtok, vlens, drafts, acc, alt
                )
            else:
                self._accept_and_rollback(dec, vtok, vlens, acc, alt)
        else:
            for r in dec:
                tok = int(d_out[r.slot])
                self.seq_lens[r.slot] += 1
                self.last_token[r.slot] = tok
                r.generated.append(tok)
                self._maybe_finish(r, tok)
        self._reap()
        return bool(dec)

    def _sample_masks(
        self, reqs: list[Request], nb: int
    ) -> Optional[jax.Array]:
        """Host-built legal-token masks for one single-token sampling
        dispatch: row i constrains reqs[i]'s next token to its FSM's
        legal set (all-True for unconstrained slots). Returns None when
        no live request is constrained — the ``legal_mask=None``
        specialization keeps unconstrained dispatches byte-identical to
        a build without this subsystem."""
        if not any(
            r.constraint is not None and not r.done for r in reqs
        ):
            return None
        rows = np.ones((nb, self.mcfg.vocab_size), bool)
        masked = 0
        for i, r in enumerate(reqs):
            if r.constraint is None or r.done or i >= nb:
                continue
            row = r.constraint.mask_row()
            if not row.any():
                # Defense in depth — unreachable through the engine
                # (dead/complete states finish at advance time, dead
                # START states are rejected at submit): an all-masked
                # row would fail the whole dispatch
                # (sampling.check_legal_mask), so contain just this
                # slot and leave its row permissive; neighbors sample
                # exactly what they would have.
                self.constraint_stats.dead_ends += 1
                self._quarantine(r, "constraint_all_masked")
                continue
            rows[i] = row
            masked += 1
        if not masked:
            return None
        self.constraint_stats.masked_steps += 1
        self.constraint_stats.masked_rows += masked
        return jnp.asarray(rows)

    def _sample(
        self, logits: jax.Array, reqs: Optional[list[Request]] = None
    ) -> np.ndarray:
        icfg = self.icfg
        self._key, sub = jax.random.split(self._key)
        legal = (
            self._sample_masks(reqs, logits.shape[0])
            if self.constrained and reqs else None
        )
        if not any(
            r.temperature is not None or r.top_k is not None
            or r.top_p is not None
            for r in (reqs or [])
        ):
            # All-defaults: python scalars keep the greedy short-circuit.
            toks = sample(
                logits, sub, temperature=icfg.temperature,
                top_k=icfg.top_k, top_p=icfg.top_p, legal_mask=legal,
            )
            return np.asarray(jax.device_get(toks))
        # Requests here are admitted (slots assigned), and _admit already
        # resolved the None-means-default rule into the slot arrays — gather
        # from there so the resolution lives in exactly one place.
        nb = logits.shape[0]
        temp = np.full(nb, icfg.temperature, np.float32)
        top_k = np.full(nb, icfg.top_k, np.int32)
        top_p = np.full(nb, icfg.top_p, np.float32)
        for i, req in enumerate(reqs or []):
            temp[i] = self.slot_temp[req.slot]
            top_k[i] = self.slot_top_k[req.slot]
            top_p[i] = self.slot_top_p[req.slot]
        toks = sample(
            logits,
            sub,
            temperature=jnp.asarray(temp),
            top_k=jnp.asarray(top_k),
            top_p=jnp.asarray(top_p),
            legal_mask=legal,
        )
        return np.asarray(jax.device_get(toks))

    def _maybe_finish(self, req: Request, tok: int) -> None:
        # Grammar walk: every emission site funnels through here (the
        # append + _maybe_finish invariant), so this is the single point
        # where a constrained request's FSM consumes the token.
        if req.constraint is not None and not req.done:
            c = req.constraint
            t0 = time.perf_counter()
            # Replay safety: a failover/resubmission may have rebuilt
            # ``generated`` without walking the FSM — re-sync before the
            # incremental advance (no-op when the counts agree; ``tok``
            # is already the last element of ``generated``).
            ok = c.sync(req.generated[:-1]) and c.advance(int(tok))
            self.constraint_stats.advance_s += time.perf_counter() - t0
            if not ok:
                # Only reachable when something upstream bypassed the
                # mask — contain like any poisoned slot; neighbors'
                # outputs stay byte-identical.
                self.constraint_stats.dead_ends += 1
                self._quarantine(req, "constraint_illegal_token")
                return
            if c.is_dead():
                # Non-accepting, no legal continuation: the vocab can't
                # spell the rest of the pattern from here.
                self.constraint_stats.dead_ends += 1
                self._quarantine(req, "constraint_dead_end")
                return
            if c.is_complete():
                # Accepting with no continuation: the only legal move is
                # to stop — finish now instead of burning a step to
                # sample the forced eos.
                self.constraint_stats.completed += 1
                req.done = True
                return
            if self.eos_id is not None and tok == self.eos_id:
                # eos only passes the mask in accepting states: a closed
                # constrained walk is a completion.
                self.constraint_stats.completed += 1
        hit_eos = self.eos_id is not None and tok == self.eos_id
        # seq_lens counts tokens whose KV is cached; the just-sampled token
        # is not yet written, and its write position (== seq_lens) must stay
        # inside the context window.
        ctx_full = int(self.seq_lens[req.slot]) >= self.icfg.max_seq_len
        if hit_eos or ctx_full or len(req.generated) >= req.max_new_tokens:
            req.done = True

    def _reap(self) -> None:
        for i, req in enumerate(self.slots):
            if req is not None and req.done:
                if not req.outcome:
                    req.outcome = "completed"
                # seq_lens counts tokens whose KV is actually in the pool
                # (decode-window overshoot lands beyond it): the full pages
                # below it are what _release_request donates to the cache.
                self._teardown_slot(req, int(self.seq_lens[i]))
                self._just_finished.append(req)
