"""Multi-replica serving router (ISSUE 12 tentpole).

One engine is 100% of capacity; N engines behind this router make any
single wedged, poisoned or killed replica 1/N with automatic failover.
The router is a pure *scheduler-face* consumer (infer/scheduler.py): a
replica is "somewhere requests can be admitted, with typed outcomes and
registry gauges" — it never reaches into KV pools or dispatch programs.

Three mechanisms, each riding substrate earlier PRs built:

  - **Prefix-affinity placement.** Every placement probes each routable
    replica's radix prefix index read-only (``PrefixCache.peek`` via
    ``engine.prefix_match_tokens``); the longest match >=
    ``router.affinity_min_tokens`` pins the replica (warm KV pages beat a
    cold prefill), ties and cold requests break on LOAD read from the
    replica's metrics registry — pool occupancy, queue depth, per-window
    device-seconds-per-slot-step (the ITL proxy) — never ad-hoc counters.
  - **Health circuit breaker.** A per-replica breaker driven by the
    engine's own robustness signals: consecutive failed steps, watchdog
    stalls and NaN-quarantine storms observed per router step, plus hard
    escalations (an engine ``step()`` that RAISES DispatchFault /
    MemoryError). Tripping OPENs the breaker — no new placements, the
    replica's in-flight work fails over — and after
    ``router.probe_after_steps`` the breaker goes HALF_OPEN: the next
    eligible request is routed as a probe; a completed probe closes the
    breaker, any failure re-opens it.
  - **Failover.** Requests on a dead/broken replica are re-queued on
    survivors under ``router.retry_budget`` with jittered step-count
    backoff. Every request still ends in EXACTLY one typed outcome —
    retried-then-completed, or shed when the budget/survivors run out;
    never a silent drop, never a double emission (``stream()`` dedups on
    a high-water mark, and greedy regeneration is deterministic, so a
    retried request's stream is the uninterrupted stream).

Router decisions (route / retry / break / probe) are emitted as tracer
instants and flight-recorder events, exactly like the engine's own
request lifecycle. ``router.replicas=1`` is a pass-through: byte-identical
greedy streams to the bare engine (pinned in tests/test_router.py).
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import random
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Optional, Sequence

from orion_tpu.config import Config
from orion_tpu.infer.engine import InferenceEngine
from orion_tpu.infer.scheduler import Request
from orion_tpu.metrics import RouterStats
from orion_tpu.obs import MetricsRegistry, export_chrome_safe, init_obs
from orion_tpu.runtime.fault import (
    DispatchFault,
    FaultInjector,
    FaultSpec,
)

log = logging.getLogger("orion_tpu.router")

# Circuit-breaker states (the canonical three-state breaker): CLOSED =
# healthy and routable, OPEN = broken (no placements), HALF_OPEN = one
# probe request allowed through to test recovery.
CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


@dataclass
class RouterRequest:
    """One request as the router sees it: the durable identity (prompt +
    sampling params + SLO class) that survives failover, pointing at the
    CURRENT engine-side attempt. ``outcome`` is set exactly once."""

    rid: int
    prompt: list[int]
    max_new_tokens: Optional[int]
    temperature: Optional[float] = None
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    priority: int = 0
    # Absolute monotonic deadline carried ACROSS attempts: a failover
    # re-placement passes the remaining budget, not a fresh window.
    deadline: Optional[float] = None
    outcome: str = ""           # "" while live; exactly one typed outcome
    retries: int = 0            # failover re-queues consumed
    replica: Optional[int] = None
    attempt: Optional[Request] = None   # live engine-side request
    due_step: int = 0           # backoff gate: no placement before this
    emitted: int = 0            # stream() high-water mark (dedup)
    placed: bool = False        # ever admitted to some engine

    @property
    def generated(self) -> list[int]:
        """Tokens of the CURRENT attempt (a failover restarts from the
        prompt; greedy regeneration reproduces the lost prefix)."""
        return self.attempt.generated if self.attempt is not None else []

    @property
    def done(self) -> bool:
        return bool(self.outcome)


class ReplicaHandle:
    """One replica: the engine, its dedicated fault injector (the funnel
    replica-scoped fault specs forward through) and the breaker state."""

    def __init__(self, idx: int, engine: InferenceEngine,
                 injector: FaultInjector):
        self.idx = idx
        self.engine = engine
        self.injector = injector
        self.state = CLOSED
        self.dead = False           # killed: never stepped again
        self.opened_at = 0          # router step of the last OPEN trip
        self.unhealthy = 0          # consecutive unhealthy health sweeps
        self.probe_rid: Optional[int] = None   # engine rid of the probe
        # engine rid -> RouterRequest for everything placed here
        self.inflight: dict[int, RouterRequest] = {}
        # Absolute robust-counter watermarks for delta-based health signals
        # (clamped re-base survives an engine reset_timing mid-flight).
        self.seen = {"stalled": 0, "quarantined": 0}

    @property
    def routable(self) -> bool:
        return not self.dead and (
            self.state == CLOSED
            or (self.state == HALF_OPEN and self.probe_rid is None)
        )


class Router:
    """Fan ``submit()`` across N InferenceEngine replicas (see module
    docstring). The public surface mirrors the engine's scheduler face —
    submit/submit_request, step, has_work, drain, close, generate,
    stream, reset_timing — so callers written against one engine port by
    construction."""

    def __init__(
        self,
        cfg: Config,
        params: Any,
        *,
        eos_id: Optional[int] = None,
        seed: int = 0,
        fault_injector: Optional[FaultInjector] = None,
    ):
        self.cfg = cfg
        self.rcfg = cfg.router
        self.icfg = cfg.inference
        # Replica engines own no export targets: the ROUTER exports the
        # trace/metrics (N engines rewriting one trace_path/prom file
        # would clobber each other); flight dumps stay per-engine (file
        # names are unique) so a replica postmortem is still written.
        rep_icfg = dataclasses.replace(
            cfg.inference,
            trace_path=None, metrics_jsonl=None, metrics_prom=None,
        )
        rep_cfg = dataclasses.replace(cfg, inference=rep_icfg)
        self.handles: list[ReplicaHandle] = []
        for i in range(self.rcfg.replicas):
            inj = FaultInjector()
            eng = InferenceEngine(
                rep_cfg, params, eos_id=eos_id, seed=seed + i,
                fault_injector=inj,
            )
            self.handles.append(ReplicaHandle(i, eng, inj))
        self._injector = fault_injector
        self.stats = RouterStats()
        self.step_no = 0
        self.draining = False
        self._closed = False
        self.waiting: deque[RouterRequest] = deque()
        self._just_finished: list[RouterRequest] = []
        self._rid = itertools.count()
        self._rng = random.Random(self.rcfg.seed)
        self.registry = MetricsRegistry()
        self.registry.register("router", self._router_metrics)
        self._tracer, self._flight = init_obs(
            trace=self.icfg.trace,
            trace_ring=self.icfg.trace_ring,
            flight_dir=self.icfg.flight_dir,
            trace_path=self.icfg.trace_path,
            snapshot=self.registry.snapshot,
            injector=fault_injector,
        )

    # -- observability -----------------------------------------------------

    def _router_metrics(self) -> dict:
        by_state = {CLOSED: 0, OPEN: 0, HALF_OPEN: 0}
        for h in self.handles:
            by_state[h.state] += 1
        return {
            **self.stats.as_timing(),
            "replicas": len(self.handles),
            "replicas_closed": by_state[CLOSED],
            "replicas_open": by_state[OPEN],
            "replicas_half_open": by_state[HALF_OPEN],
            "replicas_dead": sum(1 for h in self.handles if h.dead),
            "queue_depth": len(self.waiting),
            "step_no": self.step_no,
        }

    def _flight_note(self, kind: str, **fields) -> None:
        if self._flight is not None:
            self._flight.note(kind, step=self.step_no, **fields)

    def export_trace(self, path: str) -> int:
        """Export the router's span ring (route/retry/break/probe plus
        request lifecycle) as Chrome trace-event JSON."""
        return self._tracer.export_chrome(path)

    def reset_timing(self) -> dict:
        """Drain the router-level counters (RouterStats) plus breaker/
        queue gauges. Per-replica serving windows stay with each engine's
        own ``reset_timing`` — the router never aggregates them away."""
        out = self._router_metrics()
        self.stats = RouterStats()
        return out

    # -- public API --------------------------------------------------------

    def submit(self, prompt: Sequence[int],
               max_new_tokens: Optional[int] = None, **kw) -> int:
        """Queue a request; returns its router-level id (engine-side rids
        are per-replica and change across failover)."""
        return self.submit_request(prompt, max_new_tokens, **kw).rid

    def submit_request(
        self,
        prompt: Sequence[int],
        max_new_tokens: Optional[int] = None,
        *,
        temperature: Optional[float] = None,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        deadline_s: Optional[float] = None,
        priority: int = 0,
    ) -> RouterRequest:
        """Admit one request to the fleet. Placement is immediate when a
        routable replica exists (engine-side validation errors raise here
        exactly as the bare engine's would); with every breaker OPEN the
        request waits at the router, and with every replica DEAD (or the
        router draining) it is SHED with a typed outcome — surfacing from
        the next ``step()``, never silently dropped."""
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        if deadline_s is None:
            # Resolve the config default HERE so the absolute deadline is
            # carried across failover attempts (each re-placement passes
            # the REMAINING budget) — leaving it to the engine would hand
            # every retry a fresh default window.
            deadline_s = self.icfg.default_deadline_s
        rr = RouterRequest(
            rid=next(self._rid),
            prompt=list(map(int, prompt)),
            max_new_tokens=max_new_tokens,
            temperature=temperature,
            top_k=top_k,
            top_p=top_p,
            priority=int(priority),
            deadline=(
                time.monotonic() + deadline_s
                if deadline_s is not None else None
            ),
        )
        if self._tracer.enabled:
            self._tracer.instant(
                "submit", rid=rr.rid, priority=rr.priority,
                prompt_tokens=len(rr.prompt), deadline_s=deadline_s,
            )
        if self.draining:
            self._shed(rr, "draining", self._just_finished)
            return rr
        if all(h.dead for h in self.handles):
            self._shed(rr, "all replicas down", self._just_finished)
            return rr
        placed = self._try_place(rr, self._just_finished,
                                 raise_errors=True)
        if not placed and not rr.done:
            self.waiting.append(rr)
        return rr

    def cancel(self, rid: int) -> bool:
        """Cancel a router request by id; returns False when unknown or
        already terminal. A router-queued request terminates immediately;
        a placed one cancels on its replica and surfaces at the next
        step boundary with outcome "cancelled"."""
        for i, rr in enumerate(self.waiting):
            if rr.rid == rid:
                del self.waiting[i]
                self._finalize(rr, "cancelled", self._just_finished)
                return True
        for h in self.handles:
            for erid, rr in h.inflight.items():
                if rr.rid == rid:
                    return h.engine.cancel(erid)
        return False

    def has_work(self) -> bool:
        return (
            bool(self.waiting)
            or bool(self._just_finished)
            or any(h.inflight for h in self.handles)
            or any(
                not h.dead and h.engine.has_work() for h in self.handles
            )
        )

    def step(self) -> list[RouterRequest]:
        """One router step: fire replica-scoped fault specs, sweep
        health (breaker trips + failover), advance OPEN breakers toward
        HALF_OPEN, place due queued requests, then step every live
        replica with work and surface finished requests — each with
        exactly one typed outcome."""
        done: list[RouterRequest] = self._just_finished
        self._just_finished = []
        self._fire_replica_faults(done)
        self._sweep_health(done)
        self._open_to_half_open()
        self._dispatch_queue(done)
        for h in self.handles:
            if h.dead or not h.engine.has_work():
                continue
            try:
                finished = h.engine.step()
            except (DispatchFault, MemoryError) as e:
                # The engine's own containment gave up (max_step_faults
                # consecutive losses, or an unrecoverable pool fault):
                # that is a broken replica, not a broken fleet.
                self._break(
                    h, done,
                    f"step raised {type(e).__name__}: {e}",
                )
                continue
            for er in finished:
                rr = h.inflight.pop(er.rid, None)
                if rr is None:
                    continue    # failed over / cancelled by the router
                self._finish(h, rr, er, done)
        self.step_no += 1
        return done

    def drain(self) -> list[RouterRequest]:
        """Graceful fleet shutdown: stop admission, shed never-placed
        queue entries with typed outcomes, finish (or fail over) every
        in-flight request, and return everything that terminated during
        the drain."""
        self.draining = True
        keep: deque[RouterRequest] = deque()
        drained: list[RouterRequest] = []
        while self.waiting:
            rr = self.waiting.popleft()
            if rr.placed:
                # Failover work the drain contract finishes, not sheds.
                keep.append(rr)
            else:
                self._shed(rr, "draining", drained)
        self.waiting = keep
        while self.has_work():
            drained.extend(self.step())
        return drained

    def close(self) -> None:
        """Close every live replica (dead replicas model a killed process
        — only their watchdog thread is reaped) and export the router's
        trace when inference.trace_path is set. Idempotent; admission
        stays stopped afterwards."""
        self.draining = True
        if self._closed:
            return
        self._closed = True
        for h in self.handles:
            if h.dead:
                if h.engine._watchdog is not None:
                    h.engine._watchdog.stop()
            else:
                h.engine.close()
        export_chrome_safe(self._tracer, self.icfg.trace_path)

    def generate(
        self,
        prompts: Sequence[Sequence[int]],
        max_new_tokens: Optional[int] = None,
    ) -> list[list[int]]:
        """Convenience drain loop: generated tokens per prompt, in
        submission order (shed requests yield [])."""
        reqs = [self.submit_request(p, max_new_tokens) for p in prompts]
        while self.has_work():
            self.step()
        return [list(r.generated) for r in reqs]

    def stream(
        self,
        prompts: Sequence[Sequence[int]],
        max_new_tokens: Optional[int] = None,
    ):
        """Incremental drain loop: yields ``(rid, new_tokens)`` per
        advanced request per router step. Emission is high-water-marked
        per request, so a failover NEVER double-emits: the new attempt's
        regenerated prefix is swallowed up to what was already yielded
        (greedy regeneration reproduces it exactly; sampled retries may
        diverge from the lost tail — the distribution, not the bytes, is
        the sampled contract)."""
        reqs = [self.submit_request(p, max_new_tokens) for p in prompts]
        pending = set(range(len(reqs)))
        while pending:
            self.step()
            for i in sorted(pending):
                rr = reqs[i]
                gen = rr.generated
                if len(gen) > rr.emitted:
                    yield rr.rid, gen[rr.emitted:]
                    rr.emitted = len(gen)
                if rr.done and rr.emitted >= len(gen):
                    if rr.emitted == 0:
                        # Zero-token terminal (shed, scoring): announce
                        # the rid exactly once, like the engine does.
                        yield rr.rid, []
                    pending.discard(i)

    # -- breaker + failover internals --------------------------------------

    def _fire_replica_faults(self, done: list[RouterRequest]) -> None:
        """Replica-scoped FaultSpec kinds (runtime/fault.py): kill is a
        router-level event (sudden process death); stall and poison
        forward into the victim engine's own injector so the fault flows
        through the REAL engine code paths the health sweep then reads."""
        inj = self._injector
        if inj is None:
            return
        for kind in FaultSpec.REPLICA_KINDS:
            while True:
                spec = inj.take(kind, self.step_no)
                if spec is None:
                    break
                if spec.replica >= len(self.handles):
                    log.warning("%s: no replica %d", kind, spec.replica)
                    continue
                h = self.handles[spec.replica]
                if kind == "replica_kill":
                    if not h.dead:
                        self._break(h, done, "killed (injected)",
                                    kill=True)
                elif kind == "replica_stall":
                    h.injector.specs.append(FaultSpec(
                        "stall", step=h.engine.step_no,
                        stall_s=spec.stall_s,
                    ))
                else:   # replica_poison
                    h.injector.specs.append(FaultSpec(
                        "nan", step=h.engine.step_no, rid=spec.rid,
                    ))

    def _delta(self, h: ReplicaHandle, key: str, current: int) -> int:
        """Clamped watermark delta over an engine robust counter: an
        engine-side reset_timing (which swaps the stats object) re-bases
        instead of producing a negative delta."""
        d = max(current - h.seen[key], 0)
        h.seen[key] = current
        return d

    def _sweep_health(self, done: list[RouterRequest]) -> None:
        """Per-step health read of every live replica off its OWN
        signals: consecutive failed steps, watchdog stalls and NaN
        quarantines since the last sweep. Only CLOSED replicas are judged
        (an OPEN/HALF_OPEN replica's stale counters must not pre-empt its
        probe), but watermarks advance for all so recovery starts with a
        clean slate."""
        rcfg = self.rcfg
        for h in self.handles:
            if h.dead:
                continue
            r = h.engine.robust
            stalled_d = self._delta(h, "stalled", r.stalled_steps)
            quar_d = self._delta(h, "quarantined", r.quarantined)
            if h.state != CLOSED:
                continue
            unhealthy = (
                h.engine.consec_failed_steps >= rcfg.break_failed_steps
                or stalled_d > 0
                or quar_d >= rcfg.break_quarantined
            )
            if not unhealthy:
                h.unhealthy = 0
                continue
            h.unhealthy += 1
            if h.unhealthy >= rcfg.break_after:
                self._break(
                    h, done,
                    f"unhealthy: consec_failed="
                    f"{h.engine.consec_failed_steps} stalled+={stalled_d} "
                    f"quarantined+={quar_d}",
                )

    def _break(
        self,
        h: ReplicaHandle,
        done: list[RouterRequest],
        reason: str,
        kill: bool = False,
    ) -> None:
        """Trip the breaker OPEN (or mark the replica dead) and fail over
        everything in flight there. On a soft break the engine is still
        alive: its requests are cancelled so their pages free at its next
        step; a killed replica is never touched again."""
        log.error("replica %d circuit-break OPEN: %s", h.idx, reason)
        h.state = OPEN
        h.opened_at = self.step_no
        h.unhealthy = 0
        h.probe_rid = None
        self.stats.breaks += 1
        if kill:
            h.dead = True
            self.stats.kills += 1
        if self._tracer.enabled:
            self._tracer.instant(
                "break", replica=h.idx, reason=reason, killed=kill,
                step=self.step_no,
            )
        self._flight_note(
            "router_break", replica=h.idx, reason=reason, killed=kill,
        )
        victims = list(h.inflight.values())
        h.inflight.clear()
        for rr in victims:
            if rr.attempt is not None and rr.attempt.outcome:
                # Typed-terminal before the break surfaced it (e.g.
                # reaped as expired in the very step that then raised):
                # honor the engine's outcome instead of regenerating.
                self._finalize(rr, rr.attempt.outcome, done)
                continue
            if not h.dead and rr.attempt is not None:
                h.engine.cancel(rr.attempt.rid)
            rr.attempt = None
            rr.replica = None
            self._requeue(rr, done, f"replica {h.idx}: {reason}")

    def _open_to_half_open(self) -> None:
        for h in self.handles:
            if h.dead or h.state != OPEN:
                continue
            if self.step_no - h.opened_at >= self.rcfg.probe_after_steps:
                h.state = HALF_OPEN
                self.stats.probes += 1
                log.warning(
                    "replica %d breaker HALF_OPEN: probing", h.idx
                )
                if self._tracer.enabled:
                    self._tracer.instant(
                        "probe", replica=h.idx, step=self.step_no
                    )
                self._flight_note("router_probe", replica=h.idx)

    def _requeue(
        self, rr: RouterRequest, done: list[RouterRequest], why: str
    ) -> None:
        """Failover: re-queue ``rr`` on the survivors under the retry
        budget with jittered exponential step-count backoff — or shed it,
        typed, when the budget (or the fleet) is exhausted."""
        survivors = [x for x in self.handles if not x.dead]
        if rr.retries >= self.rcfg.retry_budget or not survivors:
            self._shed(
                rr,
                f"{why}; retries={rr.retries}/{self.rcfg.retry_budget}, "
                f"survivors={len(survivors)}",
                done,
            )
            return
        rr.retries += 1
        self.stats.retries += 1
        delay = self.rcfg.retry_backoff_steps * (1 << (rr.retries - 1))
        if self.rcfg.retry_backoff_jitter:
            delay += self._rng.randint(0, self.rcfg.retry_backoff_jitter)
        rr.due_step = self.step_no + delay
        log.warning(
            "request %d failing over (%s): retry %d/%d after %d steps",
            rr.rid, why, rr.retries, self.rcfg.retry_budget, delay,
        )
        if self._tracer.enabled:
            self._tracer.instant(
                "retry", rid=rr.rid, attempt=rr.retries,
                backoff_steps=delay, reason=why, step=self.step_no,
            )
        self._flight_note(
            "router_retry", rid=rr.rid, attempt=rr.retries, reason=why,
        )
        self.waiting.append(rr)

    def _shed(
        self, rr: RouterRequest, why: str, done: list[RouterRequest]
    ) -> None:
        log.warning("router shedding request %d: %s", rr.rid, why)
        self.stats.router_shed += 1
        self._finalize(rr, "shed", done)

    def _finalize(
        self, rr: RouterRequest, outcome: str, done: list[RouterRequest]
    ) -> None:
        """Stamp the one typed outcome and surface the request. The
        lifecycle instant carries the ``retried`` tag — how many failover
        attempts this request consumed on its way to the outcome."""
        assert not rr.done, (rr.rid, rr.outcome, outcome)
        rr.outcome = outcome
        if self._tracer.enabled:
            self._tracer.instant(
                "outcome", rid=rr.rid, outcome=outcome,
                retried=rr.retries, tokens=len(rr.generated),
                step=self.step_no,
            )
        done.append(rr)

    def _finish(
        self,
        h: ReplicaHandle,
        rr: RouterRequest,
        er: Request,
        done: list[RouterRequest],
    ) -> None:
        """An engine attempt reached its typed outcome. Engine-level
        sheds of an admitted-then-evicted request re-enter the failover
        path (another replica may have room); everything else is final.
        A HALF_OPEN probe's outcome decides the breaker: completed ->
        CLOSED; replica-fault outcomes (error:*, shed) -> re-OPEN;
        client-driven terminals (cancelled, expired) are NEUTRAL — they
        say nothing about replica health, so the breaker stays HALF_OPEN
        and the next eligible request becomes the new probe."""
        was_probe = h.probe_rid == er.rid
        if was_probe:
            h.probe_rid = None
        if er.outcome == "shed" and not self.draining:
            rr.attempt = None
            rr.replica = None
            self._requeue(rr, done, f"replica {h.idx} shed")
        else:
            rr.attempt = er
            self._finalize(rr, er.outcome, done)
        if was_probe and h.state == HALF_OPEN:
            if er.outcome == "completed":
                h.state = CLOSED
                h.unhealthy = 0
                self.stats.recoveries += 1
                log.warning(
                    "replica %d breaker CLOSED (probe completed)", h.idx
                )
                if self._tracer.enabled:
                    self._tracer.instant(
                        "recover", replica=h.idx, step=self.step_no
                    )
                self._flight_note("router_recover", replica=h.idx)
            elif er.outcome not in ("cancelled", "expired"):
                h.state = OPEN
                h.opened_at = self.step_no

    # -- placement ---------------------------------------------------------

    def _load_key(self, h: ReplicaHandle) -> tuple:
        """Load order for placement tiebreaks, read from the replica's
        metrics registry (never ad-hoc counters): queue depth + active
        slots first, then pool occupancy, then the current window's
        device-seconds-per-slot-step (the per-class ITL proxy — a replica
        grinding through slow verify windows ranks below an idle one at
        equal occupancy). Replica index last for determinism."""
        g = h.engine.registry.snapshot(sections=("engine", "pool"))
        queued = g.get("engine.waiting", 0) + g.get("engine.active", 0)
        occupancy = g.get("pool.occupancy", 0.0)
        itl = g.get("engine.device_s", 0.0) / max(
            g.get("engine.slot_steps", 0), 1
        )
        return (queued, occupancy, itl, h.idx)

    def _place(self, rr: RouterRequest):
        """(handle, affinity, match_tokens) for the best placement right
        now, or None when no replica is routable. Longest radix match >=
        affinity_min_tokens wins (load breaks ties among equal matches);
        otherwise least-loaded."""
        cands = [h for h in self.handles if h.routable]
        if not cands:
            return None
        matches = {
            h.idx: h.engine.prefix_match_tokens(rr.prompt) for h in cands
        }
        best = max(matches.values())
        affinity = best >= self.rcfg.affinity_min_tokens
        pool = (
            [h for h in cands if matches[h.idx] == best]
            if affinity else cands
        )
        h = min(pool, key=self._load_key)
        return h, affinity, matches[h.idx]

    def _try_place(
        self,
        rr: RouterRequest,
        done: list[RouterRequest],
        raise_errors: bool = False,
    ) -> bool:
        """Place ``rr`` on the best routable replica; returns True when
        it was admitted somewhere (or reached a terminal outcome trying).
        ``raise_errors`` propagates engine validation errors (the
        synchronous submit path); the queue path converts them to a typed
        error outcome instead of killing the step loop."""
        picked = self._place(rr)
        if picked is None:
            return False
        h, affinity, match = picked
        deadline_s = None
        if rr.deadline is not None:
            deadline_s = rr.deadline - time.monotonic()
            if deadline_s <= 0:
                self._finalize(rr, "expired", done)
                return True
        try:
            er = h.engine.submit_request(
                rr.prompt, rr.max_new_tokens,
                temperature=rr.temperature, top_k=rr.top_k,
                top_p=rr.top_p, deadline_s=deadline_s,
                priority=rr.priority,
            )
        except ValueError:
            if raise_errors:
                raise
            self._finalize(rr, "error:submit", done)
            return True
        if er.done:
            # Shed on arrival (bounded queue / replica draining): spend a
            # retry on the rest of the fleet instead of giving up.
            self._requeue(rr, done, f"replica {h.idx} shed on admit")
            return True
        rr.attempt = er
        rr.replica = h.idx
        rr.placed = True
        h.inflight[er.rid] = rr
        self.stats.routed += 1
        if affinity:
            self.stats.affinity_routes += 1
        else:
            self.stats.cold_routes += 1
        probe = h.state == HALF_OPEN
        if probe:
            h.probe_rid = er.rid
        if self._tracer.enabled:
            self._tracer.instant(
                "route", rid=rr.rid, replica=h.idx, match_tokens=match,
                affinity=affinity, probe=probe, retried=rr.retries,
                step=self.step_no,
            )
        return True

    def _dispatch_queue(self, done: list[RouterRequest]) -> None:
        """Place every due queued request (backoff gates failover
        re-placements); requests that cannot be placed wait — unless the
        whole fleet is dead, which sheds them typed."""
        if not self.waiting:
            return
        still: deque[RouterRequest] = deque()
        all_dead = all(h.dead for h in self.handles)
        now = time.monotonic()
        while self.waiting:
            rr = self.waiting.popleft()
            if all_dead:
                self._shed(rr, "all replicas down", done)
                continue
            if rr.deadline is not None and now >= rr.deadline:
                # Router-queued requests expire at step boundaries too —
                # waiting out a backoff (or an all-open fleet) does not
                # suspend the SLO clock.
                self._finalize(rr, "expired", done)
                continue
            if rr.due_step > self.step_no:
                still.append(rr)
                continue
            if not self._try_place(rr, done):
                still.append(rr)
        self.waiting = still
