"""Multi-replica serving router (ISSUE 12 tentpole).

One engine is 100% of capacity; N engines behind this router make any
single wedged, poisoned or killed replica 1/N with automatic failover.
The router is a pure *scheduler-face* consumer (infer/scheduler.py): a
replica is "somewhere requests can be admitted, with typed outcomes and
registry gauges" — it never reaches into KV pools or dispatch programs.

Three mechanisms, each riding substrate earlier PRs built:

  - **Prefix-affinity placement.** Every placement probes each routable
    replica's radix prefix index read-only (``PrefixCache.peek`` via
    ``engine.prefix_match_tokens``); the longest match >=
    ``router.affinity_min_tokens`` pins the replica (warm KV pages beat a
    cold prefill), ties and cold requests break on LOAD read from the
    replica's metrics registry — pool occupancy, queue depth, per-window
    device-seconds-per-slot-step (the ITL proxy) — never ad-hoc counters.
  - **Health circuit breaker.** A per-replica breaker driven by the
    engine's own robustness signals: consecutive failed steps, watchdog
    stalls and NaN-quarantine storms observed per router step, plus hard
    escalations (an engine ``step()`` that RAISES DispatchFault /
    MemoryError). Tripping OPENs the breaker — no new placements, the
    replica's in-flight work fails over — and after
    ``router.probe_after_steps`` the breaker goes HALF_OPEN: the next
    eligible request is routed as a probe; a completed probe closes the
    breaker, any failure re-opens it.
  - **Failover.** Requests on a dead/broken replica are re-queued on
    survivors under ``router.retry_budget`` with jittered step-count
    backoff. Every request still ends in EXACTLY one typed outcome —
    retried-then-completed, or shed when the budget/survivors run out;
    never a silent drop, never a double emission (``stream()`` dedups on
    a high-water mark, and greedy regeneration is deterministic, so a
    retried request's stream is the uninterrupted stream).

Router decisions (route / retry / break / probe) are emitted as tracer
instants and flight-recorder events, exactly like the engine's own
request lifecycle. ``router.replicas=1`` is a pass-through: byte-identical
greedy streams to the bare engine (pinned in tests/test_router.py).

Fleet observability plane (ISSUE 14): the router is the fleet's ONE
obs surface. Every request carries its router rid as a **trace id**
through every engine attempt (engine lifecycle instants + dispatch spans
tag ``tid``), so route -> admit -> chunks -> verify -> failover ->
re-queue -> outcome is a single correlated track across processes.
Replica engines export to **namespaced sinks**
(``trace.replica-k.json``, metrics JSONL/prom likewise — PR 11 stripped
their targets instead) and ``close()`` additionally merges the router's
ring plus all N replica rings into ONE Perfetto timeline on a shared
monotonic clock (``obs.merge_chrome``). The router registry snapshots
every replica registry under ``replica<k>.*`` sections plus ``fleet``
rollups (aggregate typed outcomes, total pool occupancy, breaker-state
gauges) behind the same Prometheus/JSONL exporters — one scrape surface
for the fleet — and an **SLO monitor** (obs/slo.py, ``cfg.slo``) judges
per-priority-class TTFT/ITL burn rates, emitting typed ``slo_breach``
events into the flight recorder and burn gauges into the registry.
"""

from __future__ import annotations

import dataclasses
import itertools
import logging
import random
import time
from collections import deque
from dataclasses import dataclass
from typing import Any, Optional, Sequence

import jax

from orion_tpu.config import Config, parse_roles
from orion_tpu.infer.engine import InferenceEngine
from orion_tpu.infer.scheduler import Request
from orion_tpu.metrics import RouterStats
from orion_tpu.obs import (
    MetricsRegistry,
    SLOMonitor,
    init_obs,
    merge_chrome,
    merge_chrome_safe,
    namespaced_path,
)
from orion_tpu.parallel.reshard import reshard
from orion_tpu.runtime.fault import (
    DispatchFault,
    FaultInjector,
    FaultSpec,
    InjectedFault,
)

log = logging.getLogger("orion_tpu.router")

# Circuit-breaker states (the canonical three-state breaker): CLOSED =
# healthy and routable, OPEN = broken (no placements), HALF_OPEN = one
# probe request allowed through to test recovery.
CLOSED = "closed"
OPEN = "open"
HALF_OPEN = "half_open"


@dataclass
class RouterRequest:
    """One request as the router sees it: the durable identity (prompt +
    sampling params + SLO class) that survives failover, pointing at the
    CURRENT engine-side attempt. ``outcome`` is set exactly once."""

    rid: int
    prompt: list[int]
    max_new_tokens: Optional[int]
    temperature: Optional[float] = None
    top_k: Optional[int] = None
    top_p: Optional[float] = None
    priority: int = 0
    # Absolute monotonic deadline carried ACROSS attempts: a failover
    # re-placement passes the remaining budget, not a fresh window.
    deadline: Optional[float] = None
    outcome: str = ""           # "" while live; exactly one typed outcome
    retries: int = 0            # failover re-queues consumed
    replica: Optional[int] = None
    attempt: Optional[Request] = None   # live engine-side request
    due_step: int = 0           # backoff gate: no placement before this
    emitted: int = 0            # stream() high-water mark (dedup)
    placed: bool = False        # ever admitted to some engine
    # SLO observation state (obs/slo.py; router-side host wall clock):
    # t_first/t_last stamp token arrivals as the router's step loop sees
    # them, slo_seen is the observation high-water mark — a failover's
    # regenerated prefix (generated drops back to []) re-observes nothing
    # until it passes the mark, mirroring stream()'s dedup: the SLO clock
    # measures the CLIENT-VISIBLE wait, which kept running through the
    # failover.
    t_submit: float = 0.0
    t_first: Optional[float] = None
    t_last: Optional[float] = None
    slo_seen: int = 0
    # Grammar constraint (orion_tpu.constrain.ConstraintSpec): part of
    # the durable identity — every placement hands the SPEC to the
    # engine, which compiles it (memoized by pattern hash) and builds a
    # fresh per-attempt walk; a failover's regenerated prefix re-walks
    # the FSM from the start, so the walk always matches the attempt.
    constraint: Optional[Any] = None

    @property
    def generated(self) -> list[int]:
        """Tokens of the CURRENT attempt (a failover restarts from the
        prompt; greedy regeneration reproduces the lost prefix)."""
        return self.attempt.generated if self.attempt is not None else []

    @property
    def done(self) -> bool:
        return bool(self.outcome)


@dataclass
class MigrationStream:
    """One in-flight prefill->decode KV handoff (ISSUE 20): the staged
    request on the destination (``token`` is its engine rid) plus the
    full-page watermark already shipped (``router.migrate_per_chunk``
    streams pages during chunked prefill; whole-request mode ships once
    at commit). The stream dies with either endpoint — the destination
    staging is aborted and, when the SOURCE died, the request re-queues
    with a typed ``retried`` tag: never half a context."""

    src: int                    # prefill replica index
    dst: int                    # decode replica index
    token: int                  # destination engine rid (staging key)
    t0: float                   # perf_counter at stream open (latency)
    shipped: int = 0            # logical pages already on the destination
    pages: int = 0              # total pages shipped (metrics)


class ReplicaHandle:
    """One replica: the engine, its dedicated fault injector (the funnel
    replica-scoped fault specs forward through) and the breaker state."""

    def __init__(self, idx: int, engine: InferenceEngine,
                 injector: FaultInjector):
        self.idx = idx
        self.engine = engine
        self.injector = injector
        # Replica role (router.roles; ISSUE 20): None on a symmetric
        # fleet. "prefill" replicas take new placements and hand decode
        # work off; "decode" replicas accept only migrated-in requests.
        self.role: Optional[str] = None
        self.state = CLOSED
        self.dead = False           # killed: never stepped again
        self.opened_at = 0          # router step of the last OPEN trip
        self.unhealthy = 0          # consecutive unhealthy health sweeps
        self.probe_rid: Optional[int] = None   # engine rid of the probe
        # engine rid -> RouterRequest for everything placed here
        self.inflight: dict[int, RouterRequest] = {}
        # Absolute robust-counter watermarks for delta-based health signals
        # (clamped re-base survives an engine reset_timing mid-flight).
        self.seen = {"stalled": 0, "quarantined": 0}

    @property
    def routable(self) -> bool:
        return not self.dead and (
            self.state == CLOSED
            or (self.state == HALF_OPEN and self.probe_rid is None)
        )


class Router:
    """Fan ``submit()`` across N InferenceEngine replicas (see module
    docstring). The public surface mirrors the engine's scheduler face —
    submit/submit_request, step, has_work, drain, close, generate,
    stream, reset_timing — so callers written against one engine port by
    construction."""

    def __init__(
        self,
        cfg: Config,
        params: Any,
        *,
        eos_id: Optional[int] = None,
        seed: int = 0,
        fault_injector: Optional[FaultInjector] = None,
    ):
        self.cfg = cfg
        self.rcfg = cfg.router
        self.icfg = cfg.inference
        # Replica engines export to NAMESPACED sinks (ISSUE 14; PR 11
        # stripped their targets so N engines wouldn't clobber one
        # trace_path/prom file): inference.trace_path=/x/trace.json gives
        # replica k /x/trace.replica-k.json (metrics JSONL/prom
        # likewise), while the ROUTER owns the configured paths — the
        # merged fleet timeline at trace_path, the aggregated registry at
        # metrics_*. Flight dumps were always per-engine-unique file
        # names and stay as they were.
        self.handles: list[ReplicaHandle] = []
        for i in range(self.rcfg.replicas):
            tag = f"replica-{i}"
            rep_icfg = dataclasses.replace(
                cfg.inference,
                trace_path=(
                    namespaced_path(self.icfg.trace_path, tag)
                    if self.icfg.trace_path else None
                ),
                metrics_jsonl=(
                    namespaced_path(self.icfg.metrics_jsonl, tag)
                    if self.icfg.metrics_jsonl else None
                ),
                metrics_prom=(
                    namespaced_path(self.icfg.metrics_prom, tag)
                    if self.icfg.metrics_prom else None
                ),
            )
            rep_cfg = dataclasses.replace(cfg, inference=rep_icfg)
            inj = FaultInjector()
            eng = InferenceEngine(
                rep_cfg, params, eos_id=eos_id, seed=seed + i,
                fault_injector=inj,
            )
            self.handles.append(ReplicaHandle(i, eng, inj))
        # Role-split fleet (router.roles; ISSUE 20): assign roles in spec
        # order — "prefill:1,decode:2" marks replica 0 prefill, 1-2
        # decode. Unset = today's symmetric fleet, byte-identical.
        self._roles = (
            parse_roles(self.rcfg.roles) if self.rcfg.roles else None
        )
        if self._roles is not None:
            order = [
                role for role, k in self._roles.items() for _ in range(k)
            ]
            for h, role in zip(self.handles, order):
                h.role = role
        # Live prefill->decode handoffs, keyed by ROUTER rid; plus the
        # per-request failure tally and the give-up set (a request whose
        # handoff failed past retry_budget decodes colocated on its
        # prefill replica — still exactly one typed outcome).
        self._migrations: dict[int, MigrationStream] = {}
        self._mig_failures: dict[int, int] = {}
        self._mig_exhausted: set[int] = set()
        # Committed handoff wall-times (begin -> commit), for benches;
        # cleared by reset_timing() with the rest of the counters.
        self.migration_latencies: list[float] = []
        self._injector = fault_injector
        self.stats = RouterStats()
        self.step_no = 0
        self.draining = False
        self._closed = False
        self.waiting: deque[RouterRequest] = deque()
        self._just_finished: list[RouterRequest] = []
        self._rid = itertools.count()
        self._rng = random.Random(self.rcfg.seed)
        # Last-K routing decisions (router.decision_log): attached to the
        # flight note a breaker trip writes, so a postmortem shows why
        # traffic was where it was when the breaker opened.
        self._decisions: deque[dict] = deque(maxlen=self.rcfg.decision_log)
        self.registry = MetricsRegistry()
        self.registry.register("router", self._router_metrics)
        # Aggregated fleet registry (ISSUE 14): every replica's registry
        # snapshots under its own namespaced section plus fleet rollups —
        # one scrape surface. Providers are lazy (priced at export/dump
        # time only), so registering N sections costs nothing per step.
        for h in self.handles:
            self.registry.register(
                f"replica{h.idx}",
                lambda h=h: h.engine.registry.snapshot(),
            )
        self.registry.register("fleet", self._fleet_metrics)
        self._tracer, self._flight = init_obs(
            trace=self.icfg.trace,
            trace_ring=self.icfg.trace_ring,
            flight_dir=self.icfg.flight_dir,
            trace_path=self.icfg.trace_path,
            snapshot=self.registry.snapshot,
            injector=fault_injector,
        )
        if self._tracer.enabled:
            self.registry.register("trace", self._tracer.metrics)
        # SLO burn-rate monitor (obs/slo.py; cfg.slo): None when no
        # objective is configured — the step loop then skips observation
        # entirely (obs-off serving stays byte-identical).
        self._slo = SLOMonitor.from_config(cfg.slo)
        if self._slo is not None:
            self.registry.register("slo", self._slo.metrics)

    # -- observability -----------------------------------------------------

    def _router_metrics(self) -> dict:
        by_state = {CLOSED: 0, OPEN: 0, HALF_OPEN: 0}
        for h in self.handles:
            by_state[h.state] += 1
        out = {
            **self.stats.as_timing(),
            "replicas": len(self.handles),
            "replicas_closed": by_state[CLOSED],
            "replicas_open": by_state[OPEN],
            "replicas_half_open": by_state[HALF_OPEN],
            "replicas_dead": sum(1 for h in self.handles if h.dead),
            "queue_depth": len(self.waiting),
            "step_no": self.step_no,
        }
        if self._roles is not None:
            # Per-role breaker/load view (ISSUE 20): prefill saturation
            # and decode saturation are DIFFERENT bottlenecks — a scrape
            # must see each role's routable count and inflight depth as
            # its own autoscale signal, not a fleet blur.
            for role in self._roles:
                hs = [h for h in self.handles if h.role == role]
                out[f"{role}_replicas"] = len(hs)
                out[f"{role}_routable"] = sum(
                    1 for h in hs if h.routable
                )
                out[f"{role}_dead"] = sum(1 for h in hs if h.dead)
                out[f"{role}_inflight"] = sum(
                    len(h.inflight) for h in hs
                )
            out["migrations_inflight"] = len(self._migrations)
        return out

    def _fleet_metrics(self) -> dict:
        """Fleet rollups (the ``fleet`` registry section): aggregate
        queue/slot gauges, total pool + radix occupancy, and the summed
        per-replica typed-outcome/fault counters (RobustnessStats) across
        LIVE replicas — a dead replica models a killed process, whose
        state no scrape could read. Breaker-state gauges live in the
        ``router`` section (``replicas_closed``/`open`/...)."""
        gauges = {"waiting": 0, "active": 0, "preemptions": 0}
        pool = {
            "num_pages": 0, "free_pages": 0, "cached_pages": 0,
            "evictable_pages": 0,
        }
        robust: dict[str, float] = {}
        live = 0
        for h in self.handles:
            if h.dead:
                continue
            live += 1
            snap = h.engine.registry.snapshot(
                sections=("engine", "pool", "robust")
            )
            for k in gauges:
                gauges[k] += snap.get(f"engine.{k}", 0)
            for k in pool:
                pool[k] += snap.get(f"pool.{k}", 0)
            for k, v in snap.items():
                if k.startswith("robust.") and isinstance(v, (int, float)):
                    key = k[len("robust."):]
                    robust[key] = robust.get(key, 0) + v
        # Page 0 is each replica's reserved scratch page (engine
        # _pool_metrics contract), so the fleet's usable pool is
        # num_pages minus one per live replica. Zero live replicas have
        # zero pool: occupancy 0.0, not the 1.0 the degenerate division
        # would report (an alert keyed on this gauge must read a total
        # outage as "no pool", never "pool full").
        usable = max(pool["num_pages"] - live, 1)
        return {
            "live_replicas": live,
            **gauges,
            **pool,
            "pool_occupancy": (
                (usable - pool["free_pages"]) / usable if live else 0.0
            ),
            **robust,
        }

    def _flight_note(self, kind: str, **fields) -> None:
        if self._flight is not None:
            self._flight.note(kind, step=self.step_no, **fields)

    def export_trace(self, path: str) -> int:
        """Export the MERGED fleet timeline — the router's span ring
        (route/retry/break/probe plus request lifecycle) and every
        replica engine's ring as one Perfetto trace on a shared clock,
        one process per source (obs.merge_chrome). Killed replicas'
        rings are still in-process, so their final spans appear too.
        Returns events written (0 when tracing is off everywhere)."""
        # Raises on a write failure (unlike close()'s merge_chrome_safe):
        # this is the explicit-export path — generate.py --trace catches
        # OSError and reports the failure honestly instead of pointing
        # the user at a file that was never written.
        sources = self._trace_sources()
        if not any(tr.enabled for _, tr in sources):
            return 0
        return merge_chrome(path, sources)

    def _trace_sources(self) -> list:
        return [("router", self._tracer)] + [
            (f"replica-{h.idx}", h.engine.tracer) for h in self.handles
        ]

    def reset_timing(self) -> dict:
        """Drain the router-level counters (RouterStats) plus breaker/
        queue gauges, and — when inference.metrics_jsonl/_prom are set —
        flush the AGGREGATED fleet snapshot (router + fleet rollups + SLO
        + every replica section) through the exporters: one scrape
        surface for the fleet, exactly like the engine's own drain-point
        export. Per-replica serving windows stay with each engine's own
        ``reset_timing`` — the router never aggregates them away."""
        out = self._router_metrics()
        if self.icfg.metrics_jsonl or self.icfg.metrics_prom:
            # Snapshot BEFORE the drain zeroes RouterStats, so the
            # exported row carries the window being drained, not zeros.
            row = self.registry.snapshot()
        self.stats = RouterStats()
        self.migration_latencies = []
        if self.icfg.metrics_jsonl or self.icfg.metrics_prom:
            try:
                if self.icfg.metrics_jsonl:
                    self.registry.export_jsonl(
                        self.icfg.metrics_jsonl, snapshot=row
                    )
                if self.icfg.metrics_prom:
                    self.registry.export_prometheus(
                        self.icfg.metrics_prom, snapshot=row
                    )
            except OSError as e:
                log.error("router metrics export failed: %s", e)
        return out

    # -- public API --------------------------------------------------------

    def submit(self, prompt: Sequence[int],
               max_new_tokens: Optional[int] = None, **kw) -> int:
        """Queue a request; returns its router-level id (engine-side rids
        are per-replica and change across failover)."""
        return self.submit_request(prompt, max_new_tokens, **kw).rid

    def submit_request(
        self,
        prompt: Sequence[int],
        max_new_tokens: Optional[int] = None,
        *,
        temperature: Optional[float] = None,
        top_k: Optional[int] = None,
        top_p: Optional[float] = None,
        deadline_s: Optional[float] = None,
        priority: int = 0,
        constraint: Optional[Any] = None,
    ) -> RouterRequest:
        """Admit one request to the fleet. Placement is immediate when a
        routable replica exists (engine-side validation errors raise here
        exactly as the bare engine's would); with every breaker OPEN the
        request waits at the router, and with every replica DEAD (or the
        router draining) it is SHED with a typed outcome — surfacing from
        the next ``step()``, never silently dropped."""
        if deadline_s is not None and deadline_s <= 0:
            raise ValueError(f"deadline_s must be > 0, got {deadline_s}")
        if deadline_s is None:
            # Resolve the config default HERE so the absolute deadline is
            # carried across failover attempts (each re-placement passes
            # the REMAINING budget) — leaving it to the engine would hand
            # every retry a fresh default window.
            deadline_s = self.icfg.default_deadline_s
        rr = RouterRequest(
            rid=next(self._rid),
            prompt=list(map(int, prompt)),
            max_new_tokens=max_new_tokens,
            temperature=temperature,
            top_k=top_k,
            top_p=top_p,
            priority=int(priority),
            deadline=(
                time.monotonic() + deadline_s
                if deadline_s is not None else None
            ),
            t_submit=time.monotonic(),
            constraint=constraint,
        )
        if self._tracer.enabled:
            self._tracer.instant(
                "submit", rid=rr.rid, tid=rr.rid, priority=rr.priority,
                prompt_tokens=len(rr.prompt), deadline_s=deadline_s,
            )
        if self.draining:
            self._shed(rr, "draining", self._just_finished)
            return rr
        if all(h.dead for h in self.handles):
            self._shed(rr, "all replicas down", self._just_finished)
            return rr
        placed = self._try_place(rr, self._just_finished,
                                 raise_errors=True)
        if not placed and not rr.done:
            self.waiting.append(rr)
        return rr

    def cancel(self, rid: int) -> bool:
        """Cancel a router request by id; returns False when unknown or
        already terminal. A router-queued request terminates immediately;
        a placed one cancels on its replica and surfaces at the next
        step boundary with outcome "cancelled"."""
        for i, rr in enumerate(self.waiting):
            if rr.rid == rid:
                del self.waiting[i]
                self._finalize(rr, "cancelled", self._just_finished)
                return True
        for h in self.handles:
            for erid, rr in h.inflight.items():
                if rr.rid == rid:
                    return h.engine.cancel(erid)
        return False

    def has_work(self) -> bool:
        return (
            bool(self.waiting)
            or bool(self._just_finished)
            or any(h.inflight for h in self.handles)
            or any(
                not h.dead and h.engine.has_work() for h in self.handles
            )
        )

    def step(self) -> list[RouterRequest]:
        """One router step: fire replica-scoped fault specs, sweep
        health (breaker trips + failover), advance OPEN breakers toward
        HALF_OPEN, place due queued requests, then step every live
        replica with work and surface finished requests — each with
        exactly one typed outcome."""
        done: list[RouterRequest] = self._just_finished
        self._just_finished = []
        self._fire_replica_faults(done)
        self._sweep_health(done)
        self._open_to_half_open()
        self._dispatch_queue(done)
        for h in self.handles:
            if h.dead or not h.engine.has_work():
                continue
            try:
                finished = h.engine.step()
            except (DispatchFault, MemoryError) as e:
                # The engine's own containment gave up (max_step_faults
                # consecutive losses, or an unrecoverable pool fault):
                # that is a broken replica, not a broken fleet.
                self._break(
                    h, done,
                    f"step raised {type(e).__name__}: {e}",
                )
                continue
            for er in finished:
                rr = h.inflight.pop(er.rid, None)
                if rr is None:
                    continue    # failed over / cancelled by the router
                self._finish(h, rr, er, done)
        if self._roles is not None:
            self._drive_migrations(done)
        if self._slo is not None:
            self._observe_slo(done)
        self.step_no += 1
        return done

    # -- SLO monitoring (obs/slo.py; cfg.slo) ------------------------------

    def _observe_slo(self, done: list[RouterRequest]) -> None:
        """Per-step SLO observation + window sweep: record TTFT/ITL for
        every request that grew tokens this step (in flight anywhere, or
        surfacing now), then let the monitor judge any window that
        closed. A breach is a typed event: tracer instant + flight note
        AND dump (throttled like every other postmortem trigger) +
        RouterStats counter; the burn gauges ride the ``slo`` registry
        section."""
        now = time.monotonic()
        for h in self.handles:
            for rr in h.inflight.values():
                self._slo_track(rr, now)
        for rr in done:
            self._slo_track(rr, now)
        self._handle_breaches(self._slo.sweep(now))

    def _handle_breaches(self, breaches: list[dict]) -> None:
        for breach in breaches:
            self.stats.slo_breaches += 1
            log.error("SLO breach: %s", breach)
            if self._tracer.enabled:
                self._tracer.instant(
                    "slo_breach", step=self.step_no, **breach
                )
            # router_-prefixed like every other router flight kind:
            # note() mirrors into the tracer, and a second bare
            # "slo_breach" instant would double-count the breach in
            # obs_report's burn panel and fleet timeline.
            self._flight_note("router_slo_breach", **breach)
            if self._flight is not None:
                self._flight.try_dump(
                    "slo_breach", step=self.step_no, **breach
                )

    def _slo_track(self, rr: RouterRequest, now: float) -> None:
        """Observe one request's token progress on the router's host
        clock: TTFT at the first token past submit, one ITL gap per
        step that grew tokens (same-step extras arrive together — gap
        0.0, matching the bench collectors' convention). ``slo_seen`` is
        a high-water mark, so a failover's regenerated prefix is not
        re-observed — the client-visible clock never restarted."""
        n = len(rr.generated)
        if n <= rr.slo_seen:
            return
        new = n - rr.slo_seen
        if rr.t_first is None:
            rr.t_first = now
            self._slo.observe("ttft", rr.priority, now - rr.t_submit, now)
        else:
            self._slo.observe(
                "itl", rr.priority, now - rr.t_last, now
            )
        for _ in range(new - 1):
            self._slo.observe("itl", rr.priority, 0.0, now)
        rr.t_last = now
        rr.slo_seen = n

    def drain(self) -> list[RouterRequest]:
        """Graceful fleet shutdown: stop admission, shed never-placed
        queue entries with typed outcomes, finish (or fail over) every
        in-flight request, and return everything that terminated during
        the drain."""
        self.draining = True
        keep: deque[RouterRequest] = deque()
        drained: list[RouterRequest] = []
        while self.waiting:
            rr = self.waiting.popleft()
            if rr.placed:
                # Failover work the drain contract finishes, not sheds.
                keep.append(rr)
            else:
                self._shed(rr, "draining", drained)
        self.waiting = keep
        while self.has_work():
            drained.extend(self.step())
        return drained

    def close(self) -> None:
        """Close every live replica (dead replicas model a killed process
        — only their watchdog thread is reaped; their per-replica
        namespaced trace file is never written, but their ring is still
        in-process and lands in the merge), flush the aggregated metrics
        exporters, and write the MERGED fleet timeline to
        inference.trace_path (live replicas also exported their own
        namespaced traces in engine.close()). Idempotent; admission
        stays stopped afterwards."""
        self.draining = True
        if self._closed:
            return
        self._closed = True
        if self._slo is not None:
            # Final FORCED sweep: a serve shorter than slo.window_s still
            # gets one verdict over its partial tail window before the
            # gauges are exported below.
            self._handle_breaches(
                self._slo.sweep(time.monotonic(), force=True)
            )
        for h in self.handles:
            if h.dead:
                if h.engine._watchdog is not None:
                    h.engine._watchdog.stop()
            else:
                h.engine.close()
        if self.icfg.metrics_jsonl or self.icfg.metrics_prom:
            # Final fleet drain, mirroring engine.close(): a short-lived
            # serve that never called reset_timing still flushes its tail
            # window through the aggregated exporters (reset_timing is
            # where the export actually happens, and it is now a no-op
            # window — replicas already flushed their own sinks above).
            self.reset_timing()
        merge_chrome_safe(self.icfg.trace_path, self._trace_sources())

    def generate(
        self,
        prompts: Sequence[Sequence[int]],
        max_new_tokens: Optional[int] = None,
    ) -> list[list[int]]:
        """Convenience drain loop: generated tokens per prompt, in
        submission order (shed requests yield [])."""
        reqs = [self.submit_request(p, max_new_tokens) for p in prompts]
        while self.has_work():
            self.step()
        return [list(r.generated) for r in reqs]

    def stream(
        self,
        prompts: Sequence[Sequence[int]],
        max_new_tokens: Optional[int] = None,
    ):
        """Incremental drain loop: yields ``(rid, new_tokens)`` per
        advanced request per router step. Emission is high-water-marked
        per request, so a failover NEVER double-emits: the new attempt's
        regenerated prefix is swallowed up to what was already yielded
        (greedy regeneration reproduces it exactly; sampled retries may
        diverge from the lost tail — the distribution, not the bytes, is
        the sampled contract)."""
        reqs = [self.submit_request(p, max_new_tokens) for p in prompts]
        pending = set(range(len(reqs)))
        while pending:
            self.step()
            for i in sorted(pending):
                rr = reqs[i]
                gen = rr.generated
                if len(gen) > rr.emitted:
                    yield rr.rid, gen[rr.emitted:]
                    rr.emitted = len(gen)
                if rr.done and rr.emitted >= len(gen):
                    if rr.emitted == 0:
                        # Zero-token terminal (shed, scoring): announce
                        # the rid exactly once, like the engine does.
                        yield rr.rid, []
                    pending.discard(i)

    # -- breaker + failover internals --------------------------------------

    def _fire_replica_faults(self, done: list[RouterRequest]) -> None:
        """Replica-scoped FaultSpec kinds (runtime/fault.py): kill is a
        router-level event (sudden process death); stall and poison
        forward into the victim engine's own injector so the fault flows
        through the REAL engine code paths the health sweep then reads."""
        inj = self._injector
        if inj is None:
            return
        for kind in FaultSpec.REPLICA_KINDS:
            while True:
                spec = inj.take(kind, self.step_no)
                if spec is None:
                    break
                if spec.replica >= len(self.handles):
                    log.warning("%s: no replica %d", kind, spec.replica)
                    continue
                h = self.handles[spec.replica]
                if kind == "replica_kill":
                    if not h.dead:
                        self._break(h, done, "killed (injected)",
                                    kill=True)
                elif kind == "replica_stall":
                    h.injector.specs.append(FaultSpec(
                        "stall", step=h.engine.step_no,
                        stall_s=spec.stall_s,
                    ))
                else:   # replica_poison
                    h.injector.specs.append(FaultSpec(
                        "nan", step=h.engine.step_no, rid=spec.rid,
                    ))

    def _delta(self, h: ReplicaHandle, key: str, current: int) -> int:
        """Clamped watermark delta over an engine robust counter: an
        engine-side reset_timing (which swaps the stats object) re-bases
        instead of producing a negative delta."""
        d = max(current - h.seen[key], 0)
        h.seen[key] = current
        return d

    def _sweep_health(self, done: list[RouterRequest]) -> None:
        """Per-step health read of every live replica off its OWN
        signals: consecutive failed steps, watchdog stalls and NaN
        quarantines since the last sweep. Only CLOSED replicas are judged
        (an OPEN/HALF_OPEN replica's stale counters must not pre-empt its
        probe), but watermarks advance for all so recovery starts with a
        clean slate."""
        rcfg = self.rcfg
        for h in self.handles:
            if h.dead:
                continue
            r = h.engine.robust
            stalled_d = self._delta(h, "stalled", r.stalled_steps)
            quar_d = self._delta(h, "quarantined", r.quarantined)
            if h.state != CLOSED:
                continue
            unhealthy = (
                h.engine.consec_failed_steps >= rcfg.break_failed_steps
                or stalled_d > 0
                or quar_d >= rcfg.break_quarantined
            )
            if not unhealthy:
                h.unhealthy = 0
                continue
            h.unhealthy += 1
            if h.unhealthy >= rcfg.break_after:
                self._break(
                    h, done,
                    f"unhealthy: consec_failed="
                    f"{h.engine.consec_failed_steps} stalled+={stalled_d} "
                    f"quarantined+={quar_d}",
                )

    def _break(
        self,
        h: ReplicaHandle,
        done: list[RouterRequest],
        reason: str,
        kill: bool = False,
    ) -> None:
        """Trip the breaker OPEN (or mark the replica dead) and fail over
        everything in flight there. On a soft break the engine is still
        alive: its requests are cancelled so their pages free at its next
        step; a killed replica is never touched again."""
        log.error("replica %d circuit-break OPEN: %s", h.idx, reason)
        h.state = OPEN
        h.opened_at = self.step_no
        h.unhealthy = 0
        h.probe_rid = None
        self.stats.breaks += 1
        if kill:
            h.dead = True
            self.stats.kills += 1
        if self._tracer.enabled:
            self._tracer.instant(
                "break", replica=h.idx, reason=reason, killed=kill,
                step=self.step_no,
            )
        self._flight_note(
            "router_break", replica=h.idx, reason=reason, killed=kill,
            # The last K routing decisions (replica, match_tokens, load
            # gauges at placement): the postmortem shows WHY traffic was
            # where it was when the breaker opened (ISSUE 14 satellite).
            recent_routes=list(self._decisions),
        )
        # Migration streams touching the broken replica die with it
        # (ISSUE 20): as the DESTINATION, the staged pages are aborted
        # (or gone with the process) and the source keeps serving — a
        # later step re-opens a stream to a surviving decode replica. As
        # the SOURCE, the victim loop below re-queues the request with a
        # typed ``retried`` tag, counted in migrations_requeued: the
        # decode side never admits half a context (commit is atomic).
        for rid, st in list(self._migrations.items()):
            if st.dst == h.idx:
                if not h.dead:
                    h.engine.import_abort(st.token)
                del self._migrations[rid]
        victims = list(h.inflight.values())
        h.inflight.clear()
        for rr in victims:
            if rr.attempt is not None and rr.attempt.outcome:
                # Typed-terminal before the break surfaced it (e.g.
                # reaped as expired in the very step that then raised):
                # honor the engine's outcome instead of regenerating.
                self._finalize(rr, rr.attempt.outcome, done)
                continue
            if not h.dead and rr.attempt is not None:
                h.engine.cancel(rr.attempt.rid)
            rr.attempt = None
            rr.replica = None
            st = self._migrations.pop(rr.rid, None)
            if st is not None:
                dsth = self.handles[st.dst]
                if not dsth.dead:
                    dsth.engine.import_abort(st.token)
                self.stats.migrations_requeued += 1
                self._requeue(
                    rr, done, f"replica {h.idx} died mid-migration",
                    exhausted_outcome="error:migration",
                )
                continue
            self._requeue(rr, done, f"replica {h.idx}: {reason}")

    def _open_to_half_open(self) -> None:
        for h in self.handles:
            if h.dead or h.state != OPEN:
                continue
            if self.step_no - h.opened_at >= self.rcfg.probe_after_steps:
                h.state = HALF_OPEN
                self.stats.probes += 1
                log.warning(
                    "replica %d breaker HALF_OPEN: probing", h.idx
                )
                if self._tracer.enabled:
                    self._tracer.instant(
                        "probe", replica=h.idx, step=self.step_no
                    )
                self._flight_note("router_probe", replica=h.idx)

    def _requeue(
        self,
        rr: RouterRequest,
        done: list[RouterRequest],
        why: str,
        *,
        exhausted_outcome: Optional[str] = None,
    ) -> None:
        """Failover: re-queue ``rr`` on the survivors under the retry
        budget with jittered exponential step-count backoff — or shed it,
        typed, when the budget (or the fleet) is exhausted.
        ``exhausted_outcome`` overrides the terminal outcome past the
        budget ("error:migration" for a handoff-interrupted request, so
        the migration failure mode is distinguishable from overload)."""
        survivors = [
            x for x in self.handles if not x.dead and x.role != "decode"
        ]
        if rr.retries >= self.rcfg.retry_budget or not survivors:
            why = (
                f"{why}; retries={rr.retries}/{self.rcfg.retry_budget}, "
                f"survivors={len(survivors)}"
            )
            if exhausted_outcome is not None:
                log.warning(
                    "request %d: %s -> %s", rr.rid, why, exhausted_outcome
                )
                self._finalize(rr, exhausted_outcome, done)
            else:
                self._shed(rr, why, done)
            return
        rr.retries += 1
        self.stats.retries += 1
        delay = self.rcfg.retry_backoff_steps * (1 << (rr.retries - 1))
        if self.rcfg.retry_backoff_jitter:
            delay += self._rng.randint(0, self.rcfg.retry_backoff_jitter)
        rr.due_step = self.step_no + delay
        log.warning(
            "request %d failing over (%s): retry %d/%d after %d steps",
            rr.rid, why, rr.retries, self.rcfg.retry_budget, delay,
        )
        if self._tracer.enabled:
            self._tracer.instant(
                "retry", rid=rr.rid, tid=rr.rid, attempt=rr.retries,
                backoff_steps=delay, reason=why, step=self.step_no,
            )
        self._flight_note(
            "router_retry", rid=rr.rid, attempt=rr.retries, reason=why,
        )
        self.waiting.append(rr)

    def _shed(
        self, rr: RouterRequest, why: str, done: list[RouterRequest]
    ) -> None:
        log.warning("router shedding request %d: %s", rr.rid, why)
        self.stats.router_shed += 1
        self._finalize(rr, "shed", done)

    def _finalize(
        self, rr: RouterRequest, outcome: str, done: list[RouterRequest]
    ) -> None:
        """Stamp the one typed outcome and surface the request. The
        lifecycle instant carries the ``retried`` tag — how many failover
        attempts this request consumed on its way to the outcome."""
        assert not rr.done, (rr.rid, rr.outcome, outcome)
        rr.outcome = outcome
        self._mig_exhausted.discard(rr.rid)
        self._mig_failures.pop(rr.rid, None)
        if self._tracer.enabled:
            self._tracer.instant(
                "outcome", rid=rr.rid, tid=rr.rid, outcome=outcome,
                retried=rr.retries, tokens=len(rr.generated),
                step=self.step_no,
            )
        done.append(rr)

    def _finish(
        self,
        h: ReplicaHandle,
        rr: RouterRequest,
        er: Request,
        done: list[RouterRequest],
    ) -> None:
        """An engine attempt reached its typed outcome. Engine-level
        sheds of an admitted-then-evicted request re-enter the failover
        path (another replica may have room); everything else is final.
        A HALF_OPEN probe's outcome decides the breaker: completed ->
        CLOSED; replica-fault outcomes (error:*, shed) -> re-OPEN;
        client-driven terminals (cancelled, expired) are NEUTRAL — they
        say nothing about replica health, so the breaker stays HALF_OPEN
        and the next eligible request becomes the new probe."""
        st = self._migrations.pop(rr.rid, None)
        if st is not None:
            # The source attempt reached a terminal outcome while its
            # handoff was still staging (completed/expired/cancelled
            # before the commit): drop the half-shipped staging — the
            # outcome below is the request's one surfacing.
            dsth = self.handles[st.dst]
            if not dsth.dead:
                dsth.engine.import_abort(st.token)
        was_probe = h.probe_rid == er.rid
        if was_probe:
            h.probe_rid = None
        if er.outcome == "shed" and not self.draining:
            rr.attempt = None
            rr.replica = None
            self._requeue(rr, done, f"replica {h.idx} shed")
        else:
            rr.attempt = er
            self._finalize(rr, er.outcome, done)
        if was_probe and h.state == HALF_OPEN:
            if er.outcome == "completed":
                h.state = CLOSED
                h.unhealthy = 0
                self.stats.recoveries += 1
                log.warning(
                    "replica %d breaker CLOSED (probe completed)", h.idx
                )
                if self._tracer.enabled:
                    self._tracer.instant(
                        "recover", replica=h.idx, step=self.step_no
                    )
                self._flight_note("router_recover", replica=h.idx)
            elif er.outcome not in ("cancelled", "expired"):
                h.state = OPEN
                h.opened_at = self.step_no

    # -- prefill -> decode KV-page migration (ISSUE 20) --------------------

    def _drive_migrations(self, done: list[RouterRequest]) -> None:
        """Advance every live handoff after the replica steps: open a
        stream when a prefill replica finishes a prompt (or, under
        router.migrate_per_chunk, as soon as its first full page lands),
        ship page batches, and commit the decode-side admission. Failures
        are contained per request: the envelope's unwind leaves the
        source serving colocated, migrations_failed counts the attempt,
        and past router.retry_budget the request is left alone (typed
        outcome still guaranteed — it completes on its prefill replica)."""
        for h in self.handles:
            if h.role != "prefill" or h.dead:
                continue
            for erid, rr in list(h.inflight.items()):
                if rr.rid in self._mig_exhausted or h.probe_rid == erid:
                    # A HALF_OPEN prefill probe must complete on its own
                    # replica — migrating it away would starve the
                    # breaker of its verdict.
                    continue
                try:
                    self._advance_migration(h, erid, rr, done)
                except (DispatchFault, MemoryError, InjectedFault) as e:
                    self._migration_failed(rr, e)

    def _advance_migration(
        self,
        h: ReplicaHandle,
        erid: int,
        rr: RouterRequest,
        done: list[RouterRequest],
    ) -> None:
        eng = h.engine
        st = self._migrations.get(rr.rid)
        if st is not None:
            dsth = self.handles[st.dst]
            if dsth.dead or dsth.state == OPEN:
                # Destination broke since the stream opened: the staging
                # died with it (_break aborted live ones). Re-open
                # against a survivor below.
                self._migrations.pop(rr.rid, None)
                st = None
        ready = eng.migration_ready(erid)
        if st is None:
            streaming = (
                self.rcfg.migrate_per_chunk
                and eng.migration_in_prefill(erid)
                and eng.migration_full_pages(erid) > 0
            )
            if not (ready or streaming):
                return
            dst = self._pick_decode()
            if dst is None:
                # No routable decode replica: decode colocated on the
                # prefill replica (graceful degradation, not an error).
                return
            token = dst.engine.import_begin(
                eng.export_migration_state(erid)
            )
            st = MigrationStream(
                src=h.idx, dst=dst.idx, token=token,
                t0=time.perf_counter(),
            )
            self._migrations[rr.rid] = st
        dst = self.handles[st.dst]
        # Ship [shipped, stop): the immutable-full-page watermark while
        # streaming, everything (partial cursor page included) at commit.
        stop = None if ready else eng.migration_full_pages(erid)
        if stop is None or stop > st.shipped:
            self._inject_migration("gather")
            live, blocks = eng.export_migration_pages(
                erid, st.shipped, stop
            )
            if live:
                blocks = self._convert_blocks(blocks, dst)
                self._inject_migration("scatter")
                dst.engine.import_pages(st.token, live, blocks)
                st.pages += len(live)
                st.shipped = max(st.shipped, max(live) + 1)
        if not ready:
            return
        # Atomic commit: re-export the host-side state (the source kept
        # decoding while the commit waited) and admit as a zero-prefill
        # warm start. A full destination defers — the request is WHOLLY
        # arrived, just unscheduled, and the source keeps serving.
        state = eng.export_migration_state(erid)
        er_new = dst.engine.import_commit(st.token, state)
        if er_new is None:
            return
        del self._migrations[rr.rid]
        self._mig_failures.pop(rr.rid, None)
        h.inflight.pop(erid, None)
        eng.finish_migration(erid)
        rr.attempt = er_new
        rr.replica = dst.idx
        dst.inflight[er_new.rid] = rr
        self.stats.migrations += 1
        latency = time.perf_counter() - st.t0
        self.migration_latencies.append(latency)
        if dst.state == HALF_OPEN and dst.probe_rid is None:
            # A migrated-in request is the decode role's probe: its
            # typed outcome drives the breaker exactly like a routed
            # probe on a prefill replica.
            dst.probe_rid = er_new.rid
        if self._tracer.enabled:
            self._tracer.instant(
                "migrate", rid=rr.rid, tid=rr.rid, src=h.idx,
                dst=dst.idx, pages=st.pages, cursor=state["cursor"],
                latency_s=round(latency, 6), step=self.step_no,
            )
        self._flight_note(
            "router_migrate", rid=rr.rid, src=h.idx, dst=dst.idx,
            pages=st.pages, latency_s=round(latency, 6),
        )

    def _migration_failed(self, rr: RouterRequest, err: Exception) -> None:
        """A handoff envelope failed with the SOURCE intact (export is a
        pure pool read; a torn import freed its fresh pages): abort the
        stream, count it, retry on a later step — and past
        router.retry_budget stop trying, leaving the request to complete
        colocated with its normal typed outcome. Source DEATH mid-stream
        is _break's path, which re-queues with the typed ``retried`` tag
        instead."""
        st = self._migrations.pop(rr.rid, None)
        if st is not None:
            dsth = self.handles[st.dst]
            if not dsth.dead:
                dsth.engine.import_abort(st.token)
        self.stats.migrations_failed += 1
        fails = self._mig_failures.get(rr.rid, 0) + 1
        self._mig_failures[rr.rid] = fails
        exhausted = fails > self.rcfg.retry_budget
        if exhausted:
            self._mig_exhausted.add(rr.rid)
            self._mig_failures.pop(rr.rid, None)
        log.warning(
            "request %d migration failed (%s): attempt %d/%d%s",
            rr.rid, err, fails, self.rcfg.retry_budget + 1,
            ", decoding colocated" if exhausted else "",
        )
        if self._tracer.enabled:
            self._tracer.instant(
                "migrate_fail", rid=rr.rid, tid=rr.rid,
                error=f"{type(err).__name__}: {err}", attempt=fails,
                exhausted=exhausted, step=self.step_no,
            )
        self._flight_note(
            "router_migrate_fail", rid=rr.rid,
            error=f"{type(err).__name__}: {err}", attempt=fails,
            exhausted=exhausted,
        )

    def _pick_decode(self) -> Optional[ReplicaHandle]:
        """Least-loaded routable decode replica (phase-aware _load_key),
        or None when the whole decode role is down/open — migration then
        skips and the prefill replica decodes colocated."""
        cands = [
            h for h in self.handles
            if h.role == "decode" and h.routable
        ]
        if not cands:
            return None
        return min(cands, key=self._load_key)

    def _convert_blocks(self, blocks: dict, dst: ReplicaHandle) -> dict:
        """Topology conversion for a page-block batch: redistribute
        straight onto the destination pool's per-array shardings through
        parallel/reshard.py (block shapes are pool-size independent, so
        mismatched pool layouts convert naturally); when the destination
        exposes no usable sharding, fall back to the universal host hop
        (device_get -> numpy; import_pages re-places on the destination)."""
        tgt = dst.engine.migration_block_shardings()
        if tgt is not None:
            return reshard(blocks, {k: tgt[k] for k in blocks})
        return jax.device_get(blocks)

    def _inject_migration(self, point: str) -> None:
        """Consume a router-level "migration" FaultSpec at this envelope
        stage ("gather" before the source read, "scatter" before the
        destination write; spec.path restricts the stage). Raises
        InjectedFault INSIDE the handoff, exercising the
        whole-or-requeued guarantee through the real unwind paths."""
        inj = self._injector
        if inj is None:
            return
        spec = inj.take("migration", self.step_no, point)
        if spec is not None:
            raise InjectedFault(
                f"injected migration fault at {point} "
                f"(router step {self.step_no})"
            )

    # -- placement ---------------------------------------------------------

    def _load_key(self, h: ReplicaHandle) -> tuple:
        """Load order for placement tiebreaks, read from the replica's
        metrics registry (never ad-hoc counters): queue depth + active
        slots first, then pool occupancy, then the current window's
        PURE-DECODE device-seconds-per-decode-slot-step (the per-class
        ITL proxy — phase-aware, so a replica grinding through a long
        prompt no longer looks "slow to decode"; mixed chunk+decode
        dispatches land in their own registry bucket), then the all-phase
        gauge as the residual tiebreak (it still sees prefill/mixed
        grind when the pure-decode gauge is empty or tied). Replica index
        last for determinism."""
        g = h.engine.registry.snapshot(sections=("engine", "pool"))
        queued = g.get("engine.waiting", 0) + g.get("engine.active", 0)
        occupancy = g.get("pool.occupancy", 0.0)
        itl = g.get("engine.decode_device_s", 0.0) / max(
            g.get("engine.decode_slot_steps", 0), 1
        )
        itl_all = g.get("engine.device_s", 0.0) / max(
            g.get("engine.slot_steps", 0), 1
        )
        return (queued, occupancy, itl, itl_all, h.idx)

    def _place(self, rr: RouterRequest):
        """(handle, affinity, match_tokens) for the best placement right
        now, or None when no replica is routable. Longest radix match >=
        affinity_min_tokens wins (load breaks ties among equal matches);
        otherwise least-loaded."""
        # Decode-role replicas accept only migrated-in work (ISSUE 20):
        # new submissions and failover re-placements go to prefill
        # replicas, whose radix trees the affinity probe is restricted to.
        cands = [
            h for h in self.handles if h.routable and h.role != "decode"
        ]
        if not cands:
            return None
        matches = {
            h.idx: h.engine.prefix_match_tokens(rr.prompt) for h in cands
        }
        best = max(matches.values())
        affinity = best >= self.rcfg.affinity_min_tokens
        pool = (
            [h for h in cands if matches[h.idx] == best]
            if affinity else cands
        )
        h = min(pool, key=self._load_key)
        return h, affinity, matches[h.idx]

    def _try_place(
        self,
        rr: RouterRequest,
        done: list[RouterRequest],
        raise_errors: bool = False,
    ) -> bool:
        """Place ``rr`` on the best routable replica; returns True when
        it was admitted somewhere (or reached a terminal outcome trying).
        ``raise_errors`` propagates engine validation errors (the
        synchronous submit path); the queue path converts them to a typed
        error outcome instead of killing the step loop."""
        picked = self._place(rr)
        if picked is None:
            return False
        h, affinity, match = picked
        # Load gauges for the decision log, read at the moment of the
        # CHOICE — after admission the snapshot would include the very
        # request being placed, and the postmortem would show the router
        # picking an already-loaded replica that was actually idle.
        load_key = (
            self._load_key(h) if self._flight is not None else None
        )
        deadline_s = None
        if rr.deadline is not None:
            deadline_s = rr.deadline - time.monotonic()
            if deadline_s <= 0:
                self._finalize(rr, "expired", done)
                return True
        try:
            er = h.engine.submit_request(
                rr.prompt, rr.max_new_tokens,
                temperature=rr.temperature, top_k=rr.top_k,
                top_p=rr.top_p, deadline_s=deadline_s,
                priority=rr.priority, constraint=rr.constraint,
                # Trace context (ISSUE 14): the router rid is the fleet
                # trace id; the replica's lifecycle instants and dispatch
                # spans tag it, so this attempt correlates with the
                # router track (and any prior attempt) in the merge.
                trace_id=rr.rid, attempt=rr.retries,
            )
        except ValueError:
            if raise_errors:
                raise
            self._finalize(rr, "error:submit", done)
            return True
        if er.done:
            # Shed on arrival (bounded queue / replica draining): spend a
            # retry on the rest of the fleet instead of giving up.
            self._requeue(rr, done, f"replica {h.idx} shed on admit")
            return True
        rr.attempt = er
        rr.replica = h.idx
        rr.placed = True
        h.inflight[er.rid] = rr
        self.stats.routed += 1
        if affinity:
            self.stats.affinity_routes += 1
        else:
            self.stats.cold_routes += 1
        probe = h.state == HALF_OPEN
        if probe:
            h.probe_rid = er.rid
        if load_key is not None:
            # Decision log (router.decision_log): the placement plus the
            # load gauges it read, ringed for the breaker-trip
            # postmortem note. Recorded only when the flight recorder —
            # its sole consumer — exists, so an obs-off fleet pays no
            # extra registry read per placement.
            queued, occupancy, itl, itl_all, _ = load_key
            self._decisions.append({
                "step": self.step_no, "rid": rr.rid, "replica": h.idx,
                "match_tokens": match, "affinity": affinity,
                "retried": rr.retries, "queued": queued,
                "occupancy": round(float(occupancy), 4),
                "itl_proxy_s": round(float(itl), 6),
                "itl_all_s": round(float(itl_all), 6),
            })
        if self._tracer.enabled:
            self._tracer.instant(
                "route", rid=rr.rid, tid=rr.rid, replica=h.idx,
                match_tokens=match, affinity=affinity, probe=probe,
                retried=rr.retries, step=self.step_no,
            )
        return True

    def _dispatch_queue(self, done: list[RouterRequest]) -> None:
        """Place every due queued request (backoff gates failover
        re-placements); requests that cannot be placed wait — unless the
        whole fleet is dead, which sheds them typed."""
        if not self.waiting:
            return
        still: deque[RouterRequest] = deque()
        all_dead = all(h.dead for h in self.handles)
        now = time.monotonic()
        while self.waiting:
            rr = self.waiting.popleft()
            if all_dead:
                self._shed(rr, "all replicas down", done)
                continue
            if rr.deadline is not None and now >= rr.deadline:
                # Router-queued requests expire at step boundaries too —
                # waiting out a backoff (or an all-open fleet) does not
                # suspend the SLO clock.
                self._finalize(rr, "expired", done)
                continue
            if rr.due_step > self.step_no:
                still.append(rr)
                continue
            if not self._try_place(rr, done):
                still.append(rr)
        self.waiting = still
