"""orion-tpu: a TPU-native LLM training and inference framework.

Brand-new implementation with the capabilities of the reference CUDA/NCCL
stack ``DatCorno/orion`` (see SURVEY.md), re-designed for TPU: XLA collectives
over ICI/DCN on a named ``jax.sharding.Mesh`` instead of NCCL process groups;
DP/FSDP/TP/PP/SP/EP as mesh axes and sharding rules instead of wrapper
modules; Pallas kernels instead of CUDA; a single jit-compiled train step with
optax + Orbax instead of an eager step loop; and a paged-KV continuous
batching engine for inference.
"""

__version__ = "0.1.0"
