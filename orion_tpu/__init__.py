"""orion-tpu: a TPU-native LLM training and inference framework.

Brand-new implementation with the capabilities of the reference CUDA/NCCL
stack ``DatCorno/orion`` (see SURVEY.md), re-designed for TPU: XLA collectives
over ICI/DCN on a named ``jax.sharding.Mesh`` instead of NCCL process groups;
DP/FSDP/TP/PP/SP/EP as mesh axes and sharding rules instead of wrapper
modules; Pallas kernels instead of CUDA; a single jit-compiled train step with
optax + Orbax instead of an eager step loop; and a paged-KV continuous
batching engine for inference.
"""

__version__ = "0.1.0"

# -- jax API compatibility ---------------------------------------------------
# The codebase targets the current jax surface (``jax.shard_map`` with
# ``check_vma``); on older runtimes where shard_map still lives under
# jax.experimental (and the flag is called check_rep), install an adapter at
# the same spot so every call site — and tests importing ``jax.shard_map`` —
# runs unchanged. No-op on new jax.
import jax as _jax

if not hasattr(_jax, "shard_map"):
    from jax.experimental.shard_map import shard_map as _esm

    def _shard_map_compat(
        f, mesh=None, in_specs=None, out_specs=None, check_vma=None,
        axis_names=None, **kw
    ):
        if check_vma is not None:
            kw["check_rep"] = check_vma
        if axis_names is not None:
            # New-jax partial-manual selection; old spelling is the
            # complementary ``auto`` axis set.
            kw["auto"] = frozenset(mesh.axis_names) - frozenset(axis_names)
        return _esm(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, **kw
        )

    # Marker consumed by parallel/pipeline.py: the old runtime's SPMD
    # partitioner cannot lower a partial-auto (axis_names-subset) region
    # that uses axis_index / ppermute, or the transposed while loop
    # jax.grad makes of a scanned one — the pipeline switches to its
    # compat formulation (stage-id inputs, one-hot reduce-scatter ring
    # hops, unrolled tick loops) when it sees this.
    _shard_map_compat._orion_compat = True
    _jax.shard_map = _shard_map_compat

if not hasattr(_jax.lax, "axis_size"):
    def _axis_size(name):
        # psum of a literal constant-folds to the static axis size.
        return _jax.lax.psum(1, name)

    _jax.lax.axis_size = _axis_size

if not hasattr(_jax.lax, "pcast"):
    def _pcast(x, *args, **kwargs):
        # pcast only annotates replication for the new check_vma machinery;
        # under the old shard_map (check_rep=False) identity is correct.
        return x

    _jax.lax.pcast = _pcast

# The `name` primitive (jax.ad_checkpoint.checkpoint_name — the
# remat="names" annotation in models/transformer.py) has no shard_map
# replication rule on this jax version, so a rep-checked shard_map region
# (the pipeline loop) raises "No replication rule for name" for ANY model
# whose block body carries annotations. checkpoint_name is an identity:
# the standard check (output replication = input replication) and the
# no-rewrite rule are exact. No-op where jax already registers them.
try:
    from jax._src.ad_checkpoint import name_p as _name_p
    from jax.experimental import shard_map as _sm_mod

    if _name_p not in _sm_mod._check_rules:
        _sm_mod.register_standard_check(_name_p)
    if _name_p not in _sm_mod._rewrite_rules:
        _sm_mod.register_norewrite(_name_p)
except (ImportError, AttributeError):
    pass
