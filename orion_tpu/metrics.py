"""Step metrics, throughput and MFU accounting, structured logging.

The judged metric is tokens/sec/chip + MFU for Llama-3-8B (BASELINE.json:2);
this module owns that math (SURVEY.md §6 "Metrics / logging"): MFU = achieved
model FLOP/s ÷ (chips × peak bf16 FLOP/s), with model FLOPs from the
6·N·tokens estimate plus the attention term (ModelConfig.flops_per_token).
Sinks: console, JSONL, and in-memory history for tests. The Stats
dataclasses below double as metrics-registry providers (orion_tpu/obs/
registry.py): their as_timing()/summary() dicts are what the registry
snapshots and the Prometheus/JSONL exporters serialize.
"""

from __future__ import annotations

import json
import time
from dataclasses import dataclass, field
from typing import Any, Optional

import jax

# Peak bf16 FLOP/s per chip by device kind; used for MFU. The dev chip is a
# v5e (197 TF), the judged target a v5p (459 TF) — keep both so MFU is right
# on either (SURVEY.md §8).
PEAK_FLOPS_BF16: dict[str, float] = {
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5p": 459e12,
    "TPU v5": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
    "cpu": 1e12,  # nominal, keeps MFU finite in CPU tests
}


def peak_flops_per_device(device: Optional[jax.Device] = None) -> float:
    d = device if device is not None else jax.devices()[0]
    kind = getattr(d, "device_kind", "cpu")
    for key, val in PEAK_FLOPS_BF16.items():
        if key.lower() in kind.lower():
            return val
    return PEAK_FLOPS_BF16.get(kind, 1e12)


@dataclass
class StepMetrics:
    step: int
    loss: float
    grad_norm: float = 0.0
    learning_rate: float = 0.0
    step_time_s: float = 0.0
    tokens: int = 0
    tokens_per_sec: float = 0.0
    tokens_per_sec_per_device: float = 0.0
    model_flops: float = 0.0
    mfu: float = 0.0
    extras: dict[str, float] = field(default_factory=dict)

    def to_dict(self) -> dict[str, Any]:
        d = {
            "step": self.step,
            "loss": self.loss,
            "grad_norm": self.grad_norm,
            "lr": self.learning_rate,
            "step_time_s": self.step_time_s,
            "tokens": self.tokens,
            "tokens_per_sec": self.tokens_per_sec,
            "tokens_per_sec_per_device": self.tokens_per_sec_per_device,
            "mfu": self.mfu,
        }
        d.update(self.extras)
        return d


@dataclass
class PrefixCacheStats:
    """Serving-side prefix-cache counters (SURVEY.md §6 metrics).

    Owned by InferenceEngine and surfaced through ``reset_timing`` (the
    serving metrics drain point, like the device/host split): hits/misses
    count admissions, cached_tokens the prompt tokens served from shared
    pages instead of prefill FLOPs, evicted/inserted/cow pages the pool
    churn the cache itself causes.

    Host-tier counters (inference.host_tier_bytes > 0):
    ``evicted_to_host`` pages demoted to host RAM instead of discarded (a
    subset of ``evicted_pages``), ``host_hits`` admissions that restored a
    host-resident path, ``host_restored_pages`` the pages those restores
    copied back, ``host_recompute_skips`` host-resident matches the
    break-even gate (or a full host pool / restore failure) sent to
    recompute instead.
    """

    hits: int = 0
    misses: int = 0
    cached_tokens: int = 0
    inserted_pages: int = 0
    evicted_pages: int = 0
    cow_pages: int = 0
    evicted_to_host: int = 0
    host_hits: int = 0
    host_restored_pages: int = 0
    host_recompute_skips: int = 0
    # Per-request paging (inference.long_context): pages a live request
    # demoted to host slots (residency cap / preempt-to-host) and pages
    # restored ahead of the dispatch that reads them. Distinct from the
    # tree's evicted_to_host/host_restored_pages: these carry
    # engine-owned refs and never transit the radix tree.
    request_paged_out: int = 0
    request_paged_in: int = 0

    @property
    def hit_rate(self) -> float:
        total = self.hits + self.misses
        return self.hits / total if total else 0.0

    def as_timing(self) -> dict[str, float]:
        """Flatten into the engine's reset_timing dict."""
        return {
            "prefix_hits": self.hits,
            "prefix_misses": self.misses,
            "prefix_hit_rate": self.hit_rate,
            "cached_tokens": self.cached_tokens,
            "inserted_pages": self.inserted_pages,
            "evicted_pages": self.evicted_pages,
            "cow_pages": self.cow_pages,
            "evicted_to_host": self.evicted_to_host,
            "host_hits": self.host_hits,
            "host_restored_pages": self.host_restored_pages,
            "host_recompute_skips": self.host_recompute_skips,
            "request_paged_out": self.request_paged_out,
            "request_paged_in": self.request_paged_in,
        }


@dataclass
class SpecDecodeStats:
    """Speculative-decoding counters (inference.speculative), owned by
    InferenceEngine and drained through ``reset_timing``.

    ``drafted``/``accepted``/``rolled_back`` count DRAFT tokens (proposed /
    matched-and-emitted / rejected-and-rewound; rolled_back == drafted -
    accepted by construction). ``verify_steps`` counts verify dispatches,
    ``verify_slot_steps`` (verify dispatches x live decode slots) the
    per-slot dispatch opportunities, and ``emitted`` every token a verify
    step emitted (accepted drafts + the per-slot bonus/correction token) —
    so ``emitted / verify_slot_steps`` is the decode tokens-per-dispatch
    the speculation bought (1.0 means it bought nothing). ``gated_steps``
    counts steps where SOMETHING drafted but fewer slots than
    ``inference.spec_min_draft_slots``, so the engine ran the plain
    decode window instead of a whole-batch verify step (the
    draft-density gate; drafts discarded there are not in ``drafted``)."""

    drafted: int = 0
    accepted: int = 0
    rolled_back: int = 0
    emitted: int = 0
    verify_steps: int = 0
    verify_slot_steps: int = 0
    gated_steps: int = 0
    # Token-tree speculation (inference.spec_tree_width > 1):
    # ``tree_nodes`` counts drafted tree nodes (a subset of ``drafted``),
    # ``tree_branch_nodes`` the nodes OUTSIDE the primary chain (the
    # extra breadth a single-path draft could not carry),
    # ``compactions``/``compacted_tokens`` the KV-compaction dispatches
    # and moved tokens when an accepted path was not the primary chain
    # (zero on chain-shaped traffic — the layout is already contiguous).
    tree_nodes: int = 0
    tree_branch_nodes: int = 0
    compactions: int = 0
    compacted_tokens: int = 0
    # Why the engine auto-disabled speculation (degradation ladder: repeated
    # verify-path dispatch faults), or None while speculation is live.
    # Carried across reset_timing drains — disablement is engine-lifetime
    # state, not a per-window counter.
    disabled_reason: Optional[str] = None

    @property
    def acceptance_rate(self) -> float:
        return self.accepted / self.drafted if self.drafted else 0.0

    @property
    def tokens_per_verify(self) -> float:
        if not self.verify_slot_steps:
            return 0.0
        return self.emitted / self.verify_slot_steps

    def as_timing(self) -> dict[str, float]:
        """Flatten into the engine's reset_timing dict."""
        return {
            "spec_drafted": self.drafted,
            "spec_accepted": self.accepted,
            "spec_rolled_back": self.rolled_back,
            "spec_emitted": self.emitted,
            "spec_acceptance_rate": self.acceptance_rate,
            "verify_steps": self.verify_steps,
            "verify_slot_steps": self.verify_slot_steps,
            "spec_tokens_per_verify": self.tokens_per_verify,
            "spec_gated_steps": self.gated_steps,
            "spec_tree_nodes": self.tree_nodes,
            "spec_tree_branch_nodes": self.tree_branch_nodes,
            "spec_compactions": self.compactions,
            "spec_compacted_tokens": self.compacted_tokens,
            "spec_disabled_reason": self.disabled_reason or "",
        }


@dataclass
class ConstraintStats:
    """Grammar-constrained-decoding counters (inference.constrained;
    ISSUE 16), owned by InferenceEngine and drained through
    ``reset_timing`` like the speculation stats.

    Compile side: ``compiles``/``compile_hits`` count constraint-DFA
    compilations requested at submit and the memo-cache hits among them
    (``compile_s`` is the cumulative MISS cost — hits are free by
    construction). Runtime side: ``masked_rows`` counts logits rows a
    legal-token mask was applied to (per slot per dispatch position),
    ``masked_steps`` the engine steps that carried at least one
    constrained row, ``advance_s`` the cumulative host-side FSM-advance
    time. Speculation coupling: ``forced_drafted``/``forced_accepted``
    count draft tokens emitted from single-legal-continuation FSM states
    (the free drafts — accepted/drafted should sit at ~1.0),
    ``branch_points`` tree branch-outs taken at ambiguous FSM states.
    Terminals: ``completed`` constraints satisfied to acceptance,
    ``dead_ends`` runtime walks into a state no vocab token leaves
    (typed quarantine, neighbors unaffected).
    """

    requests: int = 0
    compiles: int = 0
    compile_hits: int = 0
    compile_s: float = 0.0
    advance_s: float = 0.0
    masked_steps: int = 0
    masked_rows: int = 0
    forced_drafted: int = 0
    forced_accepted: int = 0
    branch_points: int = 0
    completed: int = 0
    dead_ends: int = 0

    @property
    def forced_acceptance_rate(self) -> float:
        if not self.forced_drafted:
            return 0.0
        return self.forced_accepted / self.forced_drafted

    def as_timing(self) -> dict[str, float]:
        """Flatten into the engine's reset_timing dict."""
        return {
            "constrain_requests": self.requests,
            "constrain_compiles": self.compiles,
            "constrain_compile_hits": self.compile_hits,
            "constrain_compile_s": self.compile_s,
            "constrain_advance_s": self.advance_s,
            "constrain_masked_steps": self.masked_steps,
            "constrain_masked_rows": self.masked_rows,
            "constrain_forced_drafted": self.forced_drafted,
            "constrain_forced_accepted": self.forced_accepted,
            "constrain_forced_acceptance_rate":
                self.forced_acceptance_rate,
            "constrain_branch_points": self.branch_points,
            "constrain_completed": self.completed,
            "constrain_dead_ends": self.dead_ends,
        }


@dataclass
class RobustnessStats:
    """Fault-tolerance counters (ISSUE 6), owned by InferenceEngine and
    drained through ``reset_timing`` like the cache/speculation stats.

    Request outcomes: ``shed`` (bounded-queue overload or drain — never
    admitted), ``expired`` (deadline passed; reaped at a step boundary),
    ``cancelled`` (cancel(rid)), ``quarantined`` (non-finite logits; the
    request errored, neighbors unaffected). Every terminal request carries
    exactly one typed outcome — there are no silent drops.

    Fault episodes: ``dispatch_faults`` counts dispatch attempts that
    raised (injected or real), ``dispatch_retries`` the XLA-fallback
    retry attempts started (``inference.dispatch_retries`` per episode),
    ``dispatch_fallbacks`` the retries that SUCCEEDED on the XLA
    reference path, ``failed_steps`` engine steps abandoned after every
    path failed (the engine continues; state untouched),
    ``stalled_steps`` steps the watchdog flagged as stalled, and
    ``pool_faults`` page-allocation failures absorbed at admit/grow.
    ``shed_context`` counts the "shed:context_too_long" subset of
    ``shed`` — requests the long-context feasibility check refused with
    a typed outcome instead of a raw raise (inference.long_context).
    """

    shed: int = 0
    shed_context: int = 0
    expired: int = 0
    cancelled: int = 0
    quarantined: int = 0
    dispatch_faults: int = 0
    dispatch_retries: int = 0
    dispatch_fallbacks: int = 0
    failed_steps: int = 0
    stalled_steps: int = 0
    pool_faults: int = 0

    def as_timing(self) -> dict[str, float]:
        """Flatten into the engine's reset_timing dict."""
        return {
            "shed_requests": self.shed,
            "shed_context_requests": self.shed_context,
            "expired_requests": self.expired,
            "cancelled_requests": self.cancelled,
            "quarantined_requests": self.quarantined,
            "dispatch_faults": self.dispatch_faults,
            "dispatch_retries": self.dispatch_retries,
            "dispatch_fallbacks": self.dispatch_fallbacks,
            "failed_steps": self.failed_steps,
            "stalled_steps": self.stalled_steps,
            "pool_faults": self.pool_faults,
        }


@dataclass
class RouterStats:
    """Multi-replica router counters (infer/router.py; ISSUE 12), the
    router-level twin of ``RobustnessStats`` — drained through
    ``Router.reset_timing`` and registered as the ``router`` section of
    the router's metrics registry.

    Placement: ``routed`` counts engine placements (including failover
    re-placements and half-open probes), split into ``affinity_routes``
    (longest radix match >= router.affinity_min_tokens pinned the
    replica) and ``cold_routes`` (no usable match — least-loaded replica
    by registry gauges). Failover: ``retries`` counts re-queues of
    in-flight requests off a dead/broken replica, ``router_shed``
    requests the ROUTER shed (retry budget exhausted, or no survivors) —
    engine-level sheds stay in the engine's own stats. Breaker:
    ``breaks`` OPEN trips (health sweep or a step() escalation),
    ``kills`` the replica_kill subset, ``probes`` OPEN->HALF_OPEN
    transitions, ``recoveries`` probes that closed the breaker. SLO
    (ISSUE 14): ``slo_breaches`` counts typed ``slo_breach`` events the
    burn-rate monitor (obs/slo.py) fired this window. Disaggregation
    (ISSUE 20): ``migrations`` counts completed prefill->decode KV-page
    handoffs, ``migrations_failed`` envelopes that faulted (gather/
    convert/scatter), ``migrations_requeued`` the subset whose request
    was re-queued on a prefill replica with a ``retried`` tag (the rest
    of the failures stayed resident on their source replica).
    """

    routed: int = 0
    affinity_routes: int = 0
    cold_routes: int = 0
    retries: int = 0
    router_shed: int = 0
    breaks: int = 0
    kills: int = 0
    probes: int = 0
    recoveries: int = 0
    slo_breaches: int = 0
    migrations: int = 0
    migrations_failed: int = 0
    migrations_requeued: int = 0

    def as_timing(self) -> dict[str, float]:
        return {
            "routed": self.routed,
            "affinity_routes": self.affinity_routes,
            "cold_routes": self.cold_routes,
            "retries": self.retries,
            "router_shed": self.router_shed,
            "breaks": self.breaks,
            "kills": self.kills,
            "probes": self.probes,
            "recoveries": self.recoveries,
            "slo_breaches": self.slo_breaches,
            "migrations": self.migrations,
            "migrations_failed": self.migrations_failed,
            "migrations_requeued": self.migrations_requeued,
        }


@dataclass
class TrainRobustnessStats:
    """Training-side fault-tolerance counters (ISSUE 8), owned by the
    Trainer — the twin of the serving engine's ``RobustnessStats``.

    ``anomalous_steps`` counts compiled-step skips by the gradient anomaly
    guard (``train.anomaly_guard``), split into ``nonfinite_steps`` (NaN/Inf
    in the loss or any grad leaf) and ``spike_steps`` (finite but the global
    grad norm exceeded ``train.anomaly_spike_factor`` x the running EMA); a
    skipped step leaves params/optimizer bit-identical to pre-step.
    ``rollbacks`` counts auto-rollback episodes (``train.anomaly_limit``
    consecutive anomalies -> restore newest intact checkpoint + skip the
    poisoned batch window), ``skipped_batches`` the data-cursor fast-forward
    those episodes applied. ``emergency_saves`` counts preemption/crash
    force-saves, ``corrupt_checkpoints`` the checkpoints restore quarantined
    with a typed reason before finding an intact one, ``restarts`` the
    supervisor attempt number this fit is running under
    (``run_with_restarts``), and ``last_fault_reason`` why the previous
    attempt died (carried into the step log).
    """

    anomalous_steps: int = 0
    nonfinite_steps: int = 0
    spike_steps: int = 0
    rollbacks: int = 0
    skipped_batches: int = 0
    emergency_saves: int = 0
    corrupt_checkpoints: int = 0
    restarts: int = 0
    last_fault_reason: Optional[str] = None

    def as_extras(self) -> dict[str, float]:
        """Flatten into MetricsLogger extras (floats only; the reason
        string rides the log line, not the JSONL row)."""
        return {
            "anomalous_steps": float(self.anomalous_steps),
            "rollbacks": float(self.rollbacks),
            "restarts": float(self.restarts),
        }

    def as_timing(self) -> dict[str, Any]:
        """The FULL counter set, for the metrics registry / Prometheus
        export (as_extras keeps its lean step-log subset)."""
        return {
            "anomalous_steps": self.anomalous_steps,
            "nonfinite_steps": self.nonfinite_steps,
            "spike_steps": self.spike_steps,
            "rollbacks": self.rollbacks,
            "skipped_batches": self.skipped_batches,
            "emergency_saves": self.emergency_saves,
            "corrupt_checkpoints": self.corrupt_checkpoints,
            "restarts": self.restarts,
            "last_fault_reason": self.last_fault_reason or "",
        }


@dataclass
class LatencyStats:
    """Streaming latency collector for the serving benches (SURVEY.md §6
    metrics): record per-event wall times (TTFT, inter-token gaps), report
    percentiles. The serving SLO quantities — p50/p99 ITL under prompt
    bursts, max decode stall — are wall-clock host-side measurements, so
    they live with the bench driver (tools/serving_latency_bench.py), not
    inside the engine; the engine exposes the counters (reset_timing) this
    class turns into a distribution summary."""

    samples: list[float] = field(default_factory=list)

    def record(self, seconds: float) -> None:
        self.samples.append(float(seconds))

    def percentile(self, p: float) -> float:
        """Nearest-rank percentile (p in [0, 100]); 0.0 when empty."""
        if not self.samples:
            return 0.0
        s = sorted(self.samples)
        rank = max(int(-(-p / 100.0 * len(s) // 1)) - 1, 0)  # ceil - 1
        return s[min(rank, len(s) - 1)]

    def summary(self) -> dict[str, float]:
        n = len(self.samples)
        return {
            "count": n,
            "mean": sum(self.samples) / n if n else 0.0,
            "p50": self.percentile(50),
            "p95": self.percentile(95),
            "p99": self.percentile(99),
            "max": max(self.samples) if n else 0.0,
        }


class MetricsLogger:
    """Accumulates per-step metrics; writes console lines and optional JSONL."""

    def __init__(
        self,
        flops_per_token: float,
        num_devices: int,
        peak_flops: Optional[float] = None,
        jsonl_path: Optional[str] = None,
        log_interval: int = 10,
    ):
        self.flops_per_token = flops_per_token
        self.num_devices = max(num_devices, 1)
        self.peak_flops = peak_flops if peak_flops else peak_flops_per_device()
        self.jsonl_path = jsonl_path
        self.log_interval = max(log_interval, 1)
        self.history: list[StepMetrics] = []
        self._jsonl_file = None
        if jsonl_path:
            self._jsonl_file = open(jsonl_path, "a")

    def record(
        self,
        step: int,
        loss: float,
        tokens: int,
        step_time_s: float,
        grad_norm: float = 0.0,
        learning_rate: float = 0.0,
        **extras: float,
    ) -> StepMetrics:
        tps = tokens / step_time_s if step_time_s > 0 else 0.0
        model_flops = self.flops_per_token * tokens
        achieved = model_flops / step_time_s if step_time_s > 0 else 0.0
        mfu = achieved / (self.num_devices * self.peak_flops)
        m = StepMetrics(
            step=step,
            loss=float(loss),
            grad_norm=float(grad_norm),
            learning_rate=float(learning_rate),
            step_time_s=step_time_s,
            tokens=tokens,
            tokens_per_sec=tps,
            tokens_per_sec_per_device=tps / self.num_devices,
            model_flops=model_flops,
            mfu=mfu,
            extras=dict(extras),
        )
        self.history.append(m)
        if self._jsonl_file is not None:
            self._jsonl_file.write(json.dumps(m.to_dict()) + "\n")
            self._jsonl_file.flush()
        if step % self.log_interval == 0 or "eval_loss" in extras:
            line = (
                f"step {step:>6d}  loss {m.loss:8.4f}  "
                f"gnorm {m.grad_norm:7.3f}  lr {m.learning_rate:.2e}  "
                f"{m.step_time_s * 1e3:7.1f} ms/step  "
                f"{m.tokens_per_sec_per_device:9.0f} tok/s/dev  "
                f"MFU {m.mfu * 100:5.2f}%"
            )
            if "eval_loss" in extras:
                line += f"  eval {extras['eval_loss']:8.4f}"
            print(line)
        return m

    def close(self) -> None:
        if self._jsonl_file is not None:
            self._jsonl_file.close()
            self._jsonl_file = None


class Stopwatch:
    """Wall-clock timer for step timing (blocks on device completion)."""

    def __init__(self):
        self._t = time.perf_counter()

    def lap(self, sync_on: Any = None) -> float:
        if sync_on is not None:
            jax.block_until_ready(sync_on)
        now = time.perf_counter()
        dt = now - self._t
        self._t = now
        return dt
