"""Data pipeline: per-host sharded LM batches.

Reference equivalent: the tokenized data loading a ``train.py`` needs
(SURVEY.md §3 "data pipeline"). TPU-native design decision: a loader is a
*pure function of the step number* — ``batch_at(step)`` — so the data-iterator
state that the reference checkpoints alongside model state collapses to the
step counter already in the train state, making resume exact by construction.
"""

from orion_tpu.data.loader import Loader, make_loader

__all__ = ["Loader", "make_loader"]
