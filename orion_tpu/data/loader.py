"""Stateless-resume LM data loaders (synthetic + memmapped token shards).

``batch_at(step)`` returns this host's shard of the global batch as numpy
arrays; the trainer assembles a global device array via
``jax.make_array_from_process_local_data``. Every loader is deterministic in
(seed, step, process), so checkpoint/resume needs no iterator state.

The memmap path reads flat token files (uint16/uint32); a native C++ reader
with readahead lives in orion_tpu/data/native (used when available and
``DataConfig.use_native_loader``), with a numpy fallback.
"""

from __future__ import annotations

import abc
from typing import Mapping

import jax
import numpy as np

from orion_tpu.config import DataConfig

Batch = Mapping[str, np.ndarray]


class Loader(abc.ABC):
    """Per-host view of a deterministic global batch stream."""

    def __init__(self, cfg: DataConfig, process_index: int, process_count: int):
        if cfg.batch_size % process_count:
            raise ValueError(
                f"global batch {cfg.batch_size} not divisible by "
                f"{process_count} processes"
            )
        self.cfg = cfg
        self.process_index = process_index
        self.process_count = process_count
        self.host_batch = cfg.batch_size // process_count

    @abc.abstractmethod
    def batch_at(self, step: int) -> Batch:
        """Host-local shard: inputs/targets [host_batch, seq_len] int32."""


class SyntheticLoader(Loader):
    """Deterministic pseudo-random tokens with a learnable structure.

    Tokens follow a noisy modular progression so that a real model can drive
    the loss well below log(vocab) — giving integration tests a 'loss goes
    down' signal (SURVEY.md §5) without any dataset on disk.
    """

    def __init__(self, cfg: DataConfig, process_index: int, process_count: int,
                 vocab_size: int):
        super().__init__(cfg, process_index, process_count)
        self.vocab_size = vocab_size

    def batch_at(self, step: int) -> Batch:
        b, s = self.host_batch, self.cfg.seq_len
        rng = np.random.default_rng(
            (self.cfg.shuffle_seed, step, self.process_index)
        )
        start = rng.integers(0, self.vocab_size, size=(b, 1))
        ramp = np.arange(s + 1, dtype=np.int64)[None, :]
        noise = rng.integers(0, 2, size=(b, s + 1))
        seq = (start + 3 * ramp + noise) % self.vocab_size
        seq = seq.astype(np.int32)
        return {"inputs": seq[:, :-1], "targets": seq[:, 1:]}


class MemmapLoader(Loader):
    """Flat binary token file; samples length-(S+1) windows deterministically.

    Window offsets are a pseudo-random but step-indexed permutation, so every
    (seed, step) pair maps to a fixed set of windows across restarts.
    """

    def __init__(self, cfg: DataConfig, process_index: int, process_count: int,
                 vocab_size: int):
        super().__init__(cfg, process_index, process_count)
        if cfg.path is None:
            raise ValueError("memmap loader needs data.path")
        self.reader = _open_reader(cfg)
        self.n_tokens = len(self.reader)
        need = cfg.seq_len + 1
        if self.n_tokens < need * cfg.batch_size:
            raise ValueError(
                f"token file too small: {self.n_tokens} tokens for "
                f"batch {cfg.batch_size} x seq {cfg.seq_len}"
            )
        self.n_windows = self.n_tokens - need + 1

    def _offsets_at(self, step: int) -> np.ndarray:
        rng = np.random.default_rng(
            (self.cfg.shuffle_seed, step, self.process_index)
        )
        return rng.integers(0, self.n_windows, size=self.host_batch)

    def batch_at(self, step: int) -> Batch:
        s = self.cfg.seq_len
        rows = self.reader.gather(self._offsets_at(step), s + 1)
        if hasattr(self.reader, "prefetch"):
            # Deterministic stream: page in the next step's windows while
            # this step trains (native reader issues MADV_WILLNEED).
            self.reader.prefetch(self._offsets_at(step + 1), s + 1)
        rows = rows.astype(np.int32)
        return {"inputs": rows[:, :-1], "targets": rows[:, 1:]}


class _NumpyReader:
    def __init__(self, path: str, dtype: np.dtype):
        self.mm = np.memmap(path, dtype=dtype, mode="r")

    def __len__(self) -> int:
        return len(self.mm)

    def gather(self, offsets: np.ndarray, width: int) -> np.ndarray:
        return np.stack([np.asarray(self.mm[o : o + width]) for o in offsets])


def _token_dtype(path: str) -> np.dtype:
    # .u16/.u32 suffix convention; default uint16 (vocab < 65536).
    if path.endswith(".u32") or path.endswith(".bin32"):
        return np.dtype(np.uint32)
    return np.dtype(np.uint16)


def _open_reader(cfg: DataConfig):
    dtype = _token_dtype(cfg.path)
    if cfg.use_native_loader:
        try:
            from orion_tpu.data.native import NativeReader

            return NativeReader(cfg.path, dtype)
        except (ImportError, OSError):
            pass
    return _NumpyReader(cfg.path, dtype)


def make_loader(cfg: DataConfig, vocab_size: int) -> Loader:
    pi, pc = jax.process_index(), jax.process_count()
    if cfg.source == "synthetic":
        return SyntheticLoader(cfg, pi, pc, vocab_size)
    if cfg.source == "memmap":
        return MemmapLoader(cfg, pi, pc, vocab_size)
    raise ValueError(f"unknown data source {cfg.source!r}")
