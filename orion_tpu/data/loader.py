"""Stateless-resume LM data loaders (synthetic + memmapped token shards).

``batch_at(step)`` returns this host's shard of the global batch as numpy
arrays; the trainer assembles a global device array via
``jax.make_array_from_process_local_data``. Every loader is deterministic in
(seed, step) GLOBALLY — the host shard is a row slice of the same global
batch, never a per-process stream — so checkpoint/resume needs no iterator
state AND the data stream is invariant across process counts (elastic
resume on fewer/more hosts continues the identical trajectory,
SURVEY.md §6 "Failure detection / elastic recovery"). Each host pays the
small cost of materializing the full global batch before slicing.

The memmap path reads flat token files (uint16/uint32); a native C++ reader
with readahead lives in orion_tpu/data/native (used when available and
``DataConfig.use_native_loader``), with a numpy fallback.
"""

from __future__ import annotations

import abc
import logging
from typing import Mapping

import jax
import numpy as np

from orion_tpu.config import DataConfig

Batch = Mapping[str, np.ndarray]

log = logging.getLogger("orion_tpu.data")

# Data-stream format version. Bump whenever the (seed, step) -> batch
# mapping changes, because the stream is otherwise SILENT about it: resume
# replays a different token order with no error. History:
#   1 — per-process streams (seed included process_index).
#   2 — global (seed, step)-deterministic batch sliced per host (round 4,
#       for elastic resume). A checkpoint written under format 1 that
#       resumes under format 2 continues training on a DIFFERENT shuffle
#       of the data — loss-equivalent in expectation, but not the same
#       trajectory. Checkpoints carry no stream state (stateless resume),
#       so this constant and the log line at loader construction are the
#       record.
STREAM_FORMAT = 2

# Observability for pack_rows' bounded token loss (see its docstring): a
# crossing document's carried tail is dropped at every carry-group reset.
# Module-level tally (host-side code, single-threaded per process).
pack_stats = {"dropped_tokens": 0}


class Loader(abc.ABC):
    """Per-host view of a deterministic global batch stream.

    The stream is a pure function of ``(shuffle_seed, data_step)`` where
    ``data_step = step + offset``: the ``offset`` cursor (default 0) is the
    loader's ONLY mutable state, serialized into every checkpoint manifest
    (``state_dict``/``load_state_dict``) so resume replays the identical
    token order bitwise. ``skip_batches`` advances the cursor without
    advancing the optimizer step — the auto-rollback path uses it to
    fast-forward past a poisoned batch window (the skipped optimizer steps
    then draw fresh batches instead of replaying the poison).
    """

    def __init__(self, cfg: DataConfig, process_index: int, process_count: int):
        if cfg.batch_size % process_count:
            raise ValueError(
                f"global batch {cfg.batch_size} not divisible by "
                f"{process_count} processes"
            )
        self.cfg = cfg
        self.process_index = process_index
        self.process_count = process_count
        self.host_batch = cfg.batch_size // process_count
        self.offset = 0
        if process_index == 0:
            log.info("data stream format v%d (seed=%s): resuming a "
                     "checkpoint written under an older format replays a "
                     "different token order (see loader.STREAM_FORMAT)",
                     STREAM_FORMAT, cfg.shuffle_seed)

    @abc.abstractmethod
    def batch_at(self, step: int) -> Batch:
        """Host-local shard: inputs/targets [host_batch, seq_len] int32."""

    # -- serializable cursor (checkpoint manifest "loader" entry) ---------

    def data_step(self, step: int) -> int:
        """The stream index a trainer step maps to (cursor applied)."""
        return step + self.offset

    def skip_batches(self, n: int) -> None:
        """Advance the cursor by ``n`` global batches (auto-rollback's
        poison-window fast-forward). Negative n is rejected: the stream
        never rewinds — resume-equivalence owns replay, not the cursor."""
        if n < 0:
            raise ValueError(f"skip_batches({n}): cursor never rewinds")
        self.offset += n

    def state_dict(self) -> dict:
        return {
            "version": 1,
            "offset": self.offset,
            "stream_format": STREAM_FORMAT,
            "shuffle_seed": self.cfg.shuffle_seed,
        }

    def load_state_dict(self, state: Mapping) -> None:
        fmt = state.get("stream_format")
        if fmt is not None and fmt != STREAM_FORMAT:
            log.warning(
                "loader state was written under data-stream format %s but "
                "this build uses format %d: resume continues on a "
                "DIFFERENT token order", fmt, STREAM_FORMAT,
            )
        seed = state.get("shuffle_seed")
        if seed is not None and seed != self.cfg.shuffle_seed:
            log.warning(
                "loader state was written under shuffle_seed=%s but this "
                "run uses %s: resume continues on a different token order",
                seed, self.cfg.shuffle_seed,
            )
        self.offset = int(state.get("offset", 0))


def pack_rows(
    docs_per_row: list[list[np.ndarray]],
    seq_len: int,
    carry_group: Optional[int] = None,
) -> Batch:
    """Pack variable-length documents into fixed [B, S] packed batches.

    Each document contributes its (input, target) pairs independently —
    targets never cross a document boundary, attention is confined to the
    document via ``segment_ids``, RoPE restarts via ``positions``, and the
    padding tail is excluded via ``loss_mask``. This is the host-side half
    of the packed path; the device half is the flash kernel's segment
    masking (ops/pallas/flash_attention.py) + position-aware RoPE.

    Segment id 0 is reserved for padding (matches the kernel's convention
    that distinct ids never attend to each other; padding rows also carry
    loss_mask 0 so their nll is dropped).

    A document that crosses a row boundary is split, not truncated: the
    untrained remainder (from the last consumed input token onward, so no
    pair is dropped or duplicated) carries over to the front of the next
    row. ``carry_group`` bounds how far: the carry resets at every
    multiple-of-``carry_group`` row (dropping that overhang like a final
    row). Loaders use a FIXED group so the packed stream is a pure
    function of (seed, step) — independent of process count — while each
    host only has to materialize group-aligned row ranges, not the whole
    global batch. None = carry across all rows.
    """
    B = len(docs_per_row)
    inputs = np.zeros((B, seq_len), np.int32)
    targets = np.zeros((B, seq_len), np.int32)
    segments = np.zeros((B, seq_len), np.int32)
    positions = np.zeros((B, seq_len), np.int32)
    mask = np.zeros((B, seq_len), np.float32)
    carry: list[np.ndarray] = []  # docs (or tails) displaced into the next row
    for b, docs in enumerate(docs_per_row):
        if carry_group is not None and b % carry_group == 0:
            if carry:
                # Bounded, silent-by-design token loss (docstring); tally
                # it so the loss is observable at scale (pack_stats).
                dropped = sum(max(len(d) - 1, 0) for d in carry)
                pack_stats["dropped_tokens"] += dropped
                log.debug("pack_rows: dropped %d tokens at carry-group "
                          "boundary (row %d)", dropped, b)
            carry = []            # fixed reset boundary (see docstring)
        at, seg = 0, 0
        queue, carry = carry + list(docs), []
        for doc in queue:
            doc = np.asarray(doc)
            if len(doc) < 2:
                continue       # degenerate doc: skip, keep packing the rest
            if at >= seq_len:
                carry.append(doc)  # row already full: displace whole doc
                continue
            n = min(len(doc) - 1, seq_len - at)  # pairs, not tokens
            seg += 1
            inputs[b, at : at + n] = doc[:n]
            targets[b, at : at + n] = doc[1 : n + 1]
            segments[b, at : at + n] = seg
            positions[b, at : at + n] = np.arange(n)
            mask[b, at : at + n] = 1.0
            at += n
            if n < len(doc) - 1:
                # Truncated mid-document: resume at token n so the next row
                # trains the pair (doc[n] -> doc[n+1]) and nothing is lost.
                carry.append(doc[n:])
    return {
        "inputs": inputs,
        "targets": targets,
        "segment_ids": segments,
        "positions": positions,
        "loss_mask": mask,
    }


class SyntheticLoader(Loader):
    """Deterministic pseudo-random tokens with a learnable structure.

    Tokens follow a noisy modular progression so that a real model can drive
    the loss well below log(vocab) — giving integration tests a 'loss goes
    down' signal (SURVEY.md §5) without any dataset on disk.
    """

    def __init__(self, cfg: DataConfig, process_index: int, process_count: int,
                 vocab_size: int):
        super().__init__(cfg, process_index, process_count)
        self.vocab_size = vocab_size

    def _doc(self, rng, length: int) -> np.ndarray:
        start = rng.integers(0, self.vocab_size)
        ramp = np.arange(length, dtype=np.int64)
        noise = rng.integers(0, 2, size=length)
        return ((start + 3 * ramp + noise) % self.vocab_size).astype(np.int32)

    def _slice(self, batch: Batch) -> Batch:
        lo = self.process_index * self.host_batch
        return {k: v[lo : lo + self.host_batch] for k, v in batch.items()}

    def batch_at(self, step: int) -> Batch:
        # Generate the GLOBAL batch (seeded by the cursor-adjusted step
        # only), then slice this host's rows — the stream is process-count
        # invariant by design.
        gb, s = self.cfg.batch_size, self.cfg.seq_len
        rng = np.random.default_rng((self.cfg.shuffle_seed, self.data_step(step)))
        if self.cfg.packed:
            rows = []
            for _ in range(gb):
                docs, filled = [], 0
                while filled < s:
                    length = int(rng.integers(8, max(9, s // 2)))
                    docs.append(self._doc(rng, length + 1))
                    filled += length
                rows.append(docs)
            return self._slice(
                pack_rows(rows, s, carry_group=self.cfg.pack_carry_group)
            )
        start = rng.integers(0, self.vocab_size, size=(gb, 1))
        ramp = np.arange(s + 1, dtype=np.int64)[None, :]
        noise = rng.integers(0, 2, size=(gb, s + 1))
        seq = (start + 3 * ramp + noise) % self.vocab_size
        seq = seq.astype(np.int32)
        return self._slice({"inputs": seq[:, :-1], "targets": seq[:, 1:]})


class MemmapLoader(Loader):
    """Flat binary token file; samples length-(S+1) windows deterministically.

    Window offsets are a pseudo-random but step-indexed permutation, so every
    (seed, step) pair maps to a fixed set of windows across restarts.
    """

    def __init__(self, cfg: DataConfig, process_index: int, process_count: int,
                 vocab_size: int):
        super().__init__(cfg, process_index, process_count)
        if cfg.path is None:
            raise ValueError("memmap loader needs data.path")
        self.reader = _open_reader(cfg)
        self.n_tokens = len(self.reader)
        need = cfg.seq_len + 1
        if self.n_tokens < need * cfg.batch_size:
            raise ValueError(
                f"token file too small: {self.n_tokens} tokens for "
                f"batch {cfg.batch_size} x seq {cfg.seq_len}"
            )
        self.n_windows = self.n_tokens - need + 1

    def _offsets_at(self, step: int) -> np.ndarray:
        # Global offsets (seeded by the cursor-adjusted step only): every
        # host draws the same window set and slices its rows —
        # process-count invariant.
        rng = np.random.default_rng(
            (self.cfg.shuffle_seed, self.data_step(step))
        )
        return rng.integers(0, self.n_windows, size=self.cfg.batch_size)

    def batch_at(self, step: int) -> Batch:
        s = self.cfg.seq_len
        lo = self.process_index * self.host_batch
        hi = lo + self.host_batch
        sl = slice(lo, hi)
        if self.cfg.packed:
            # Carry crosses rows only within fixed global groups
            # (pack_carry_group), so this host needs exactly the
            # group-ALIGNED row range covering its slice — bounded extra
            # reads (< one group), never the whole global batch.
            G = self.cfg.pack_carry_group
            g0 = (lo // G) * G
            g1 = min(-(-hi // G) * G, self.cfg.batch_size)
            fetch = slice(g0, g1)
        else:
            fetch = sl
        rows = self.reader.gather(self._offsets_at(step)[fetch], s + 1)
        if hasattr(self.reader, "prefetch"):
            # Deterministic stream: page in the next step's windows while
            # this step trains (native reader issues MADV_WILLNEED).
            self.reader.prefetch(self._offsets_at(step + 1)[fetch], s + 1)
        rows = rows.astype(np.int32)
        if self.cfg.packed:
            eos = self.cfg.eos_token_id
            docs_per_row = []
            for row in rows:
                cuts = np.flatnonzero(row == eos)
                bounds = [0, *(int(c) + 1 for c in cuts), len(row)]
                docs = [
                    row[a:b]
                    for a, b in zip(bounds[:-1], bounds[1:])
                    if b - a >= 2
                ]
                # If no span has >=2 tokens (e.g. a run of EOS), emit an
                # empty doc list: pack_rows leaves the row fully masked
                # rather than training attention/loss across EOS boundaries.
                docs_per_row.append(docs)
            # g0 is a group multiple, so reset boundaries computed relative
            # to the fetched range coincide with the global ones.
            packed = pack_rows(docs_per_row, s, carry_group=G)
            return {k: v[lo - g0 : hi - g0] for k, v in packed.items()}
        return {"inputs": rows[:, :-1], "targets": rows[:, 1:]}


class _NumpyReader:
    def __init__(self, path: str, dtype: np.dtype):
        self.mm = np.memmap(path, dtype=dtype, mode="r")

    def __len__(self) -> int:
        return len(self.mm)

    def gather(self, offsets: np.ndarray, width: int) -> np.ndarray:
        return np.stack([np.asarray(self.mm[o : o + width]) for o in offsets])


def _token_dtype(path: str) -> np.dtype:
    # .u16/.u32 suffix convention; default uint16 (vocab < 65536).
    if path.endswith(".u32") or path.endswith(".bin32"):
        return np.dtype(np.uint32)
    return np.dtype(np.uint16)


def _open_reader(cfg: DataConfig):
    dtype = _token_dtype(cfg.path)
    if cfg.use_native_loader:
        try:
            from orion_tpu.data.native import NativeReader

            return NativeReader(cfg.path, dtype)
        except (ImportError, OSError):
            pass
    return _NumpyReader(cfg.path, dtype)


def make_loader(cfg: DataConfig, vocab_size: int) -> Loader:
    pi, pc = jax.process_index(), jax.process_count()
    if cfg.source == "synthetic":
        return SyntheticLoader(cfg, pi, pc, vocab_size)
    if cfg.source == "memmap":
        return MemmapLoader(cfg, pi, pc, vocab_size)
    raise ValueError(f"unknown data source {cfg.source!r}")
