// Native token-shard reader: mmap + multithreaded strided gather + readahead.
//
// TPU-native counterpart of the reference's native data loader (SURVEY.md §3
// "data pipeline"): the hot operation is gathering B windows of (S+1) tokens
// from a memmapped flat token file into one contiguous host batch buffer,
// which then feeds jax.make_array_from_process_local_data. The gather is
// memcpy-bound, so it fans out over threads; prefetch() issues
// MADV_WILLNEED for the *next* step's (deterministic) windows so page-ins
// overlap with the current train step.
//
// Exposed as a plain C ABI for ctypes (no pybind11 in this toolchain).

#include <algorithm>
#include <cstdint>
#include <cstring>
#include <fcntl.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <thread>
#include <unistd.h>
#include <vector>

namespace {

struct Handle {
  int fd;
  size_t bytes;
  const uint8_t* base;
};

}  // namespace

extern "C" {

// Returns an opaque handle, or nullptr on failure.
void* otn_open(const char* path) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0 || st.st_size == 0) {
    close(fd);
    return nullptr;
  }
  void* p = mmap(nullptr, static_cast<size_t>(st.st_size), PROT_READ,
                 MAP_SHARED, fd, 0);
  if (p == MAP_FAILED) {
    close(fd);
    return nullptr;
  }
  // Window sampling is random-access; disable kernel sequential readahead.
  madvise(p, static_cast<size_t>(st.st_size), MADV_RANDOM);
  return new Handle{fd, static_cast<size_t>(st.st_size),
                    static_cast<const uint8_t*>(p)};
}

long long otn_len_bytes(void* hv) {
  return static_cast<long long>(static_cast<Handle*>(hv)->bytes);
}

// Copy n windows of `width` elements (elem_size bytes each), window i
// starting at element offsets[i], into out (contiguous [n, width]).
// Returns 0 on success, -1 if any window is out of bounds.
int otn_gather(void* hv, const long long* offsets, int n, int width,
               int elem_size, void* out, int nthreads) {
  Handle* h = static_cast<Handle*>(hv);
  const size_t row_bytes = static_cast<size_t>(width) * elem_size;
  for (int i = 0; i < n; i++) {
    if (offsets[i] < 0 ||
        static_cast<size_t>(offsets[i]) * elem_size + row_bytes > h->bytes) {
      return -1;
    }
  }
  uint8_t* dst = static_cast<uint8_t*>(out);
  auto worker = [&](int a, int b) {
    for (int i = a; i < b; i++) {
      memcpy(dst + static_cast<size_t>(i) * row_bytes,
             h->base + static_cast<size_t>(offsets[i]) * elem_size, row_bytes);
    }
  };
  int nt = std::max(1, nthreads);
  if (nt == 1 || n < 2 * nt) {
    worker(0, n);
    return 0;
  }
  std::vector<std::thread> threads;
  int per = (n + nt - 1) / nt;
  for (int t = 0; t < nt; t++) {
    int a = t * per, b = std::min(n, a + per);
    if (a >= b) break;
    threads.emplace_back(worker, a, b);
  }
  for (auto& th : threads) th.join();
  return 0;
}

// Hint the kernel to page in the given windows (the next step's batch).
void otn_prefetch(void* hv, const long long* offsets, int n, int width,
                  int elem_size) {
  Handle* h = static_cast<Handle*>(hv);
  const long page = sysconf(_SC_PAGESIZE);
  for (int i = 0; i < n; i++) {
    if (offsets[i] < 0) continue;
    size_t start = static_cast<size_t>(offsets[i]) * elem_size;
    size_t end = start + static_cast<size_t>(width) * elem_size;
    if (end > h->bytes) continue;
    size_t aligned = start & ~static_cast<size_t>(page - 1);
    madvise(const_cast<uint8_t*>(h->base) + aligned, end - aligned,
            MADV_WILLNEED);
  }
}

void otn_close(void* hv) {
  Handle* h = static_cast<Handle*>(hv);
  munmap(const_cast<uint8_t*>(h->base), h->bytes);
  close(h->fd);
  delete h;
}

}  // extern "C"
