"""ctypes bindings for the native (C++) token-shard reader.

Compiled on first use with g++ into this package directory (no network, no
pybind11 — plain C ABI + ctypes, per the toolchain constraints). Callers
treat ImportError/OSError as "native unavailable" and fall back to the numpy
memmap reader (orion_tpu.data.loader._open_reader).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

import numpy as np

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "native_loader.cpp")
_SO = os.path.join(_DIR, "libnative_loader.so")
_BUILD_LOCK = threading.Lock()


def _build() -> str:
    with _BUILD_LOCK:
        if (
            os.path.exists(_SO)
            and os.path.getmtime(_SO) >= os.path.getmtime(_SRC)
        ):
            return _SO
        tmp = _SO + f".tmp.{os.getpid()}"
        cmd = [
            "g++", "-O3", "-shared", "-fPIC", "-pthread", "-std=c++17",
            _SRC, "-o", tmp,
        ]
        try:
            subprocess.run(cmd, check=True, capture_output=True, text=True)
        except (subprocess.CalledProcessError, FileNotFoundError) as e:
            detail = getattr(e, "stderr", str(e))
            raise ImportError(f"native loader build failed: {detail}") from e
        os.replace(tmp, _SO)  # atomic: concurrent processes race safely
        return _SO


def _load() -> ctypes.CDLL:
    lib = ctypes.CDLL(_build())
    lib.otn_open.argtypes = [ctypes.c_char_p]
    lib.otn_open.restype = ctypes.c_void_p
    lib.otn_len_bytes.argtypes = [ctypes.c_void_p]
    lib.otn_len_bytes.restype = ctypes.c_longlong
    lib.otn_gather.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_longlong), ctypes.c_int,
        ctypes.c_int, ctypes.c_int, ctypes.c_void_p, ctypes.c_int,
    ]
    lib.otn_gather.restype = ctypes.c_int
    lib.otn_prefetch.argtypes = [
        ctypes.c_void_p, ctypes.POINTER(ctypes.c_longlong), ctypes.c_int,
        ctypes.c_int, ctypes.c_int,
    ]
    lib.otn_prefetch.restype = None
    lib.otn_close.argtypes = [ctypes.c_void_p]
    lib.otn_close.restype = None
    return lib


_lib: ctypes.CDLL | None = None


def _get_lib() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        _lib = _load()
    return _lib


class NativeReader:
    """Reader over a flat token file: len() in elements, gather(), prefetch().

    Drop-in for the numpy reader in orion_tpu.data.loader, with a
    multithreaded native gather and MADV_WILLNEED readahead for the next
    (deterministic) batch.
    """

    def __init__(self, path: str, dtype: np.dtype, num_threads: int = 0):
        self._lib = _get_lib()
        self._h = self._lib.otn_open(os.fsencode(path))
        if not self._h:
            raise OSError(f"native loader could not open {path!r}")
        self.dtype = np.dtype(dtype)
        self.path = path
        self._nthreads = num_threads or min(8, os.cpu_count() or 1)

    def __len__(self) -> int:
        return self._lib.otn_len_bytes(self._h) // self.dtype.itemsize

    def _offsets_arg(self, offsets: np.ndarray):
        offs = np.ascontiguousarray(offsets, dtype=np.int64)
        return offs, offs.ctypes.data_as(ctypes.POINTER(ctypes.c_longlong))

    def gather(self, offsets: np.ndarray, width: int) -> np.ndarray:
        offs, ptr = self._offsets_arg(offsets)
        out = np.empty((len(offs), width), self.dtype)
        rc = self._lib.otn_gather(
            self._h, ptr, len(offs), width, self.dtype.itemsize,
            out.ctypes.data_as(ctypes.c_void_p), self._nthreads,
        )
        if rc != 0:
            raise IndexError(
                f"gather window out of bounds (file has {len(self)} tokens)"
            )
        return out

    def prefetch(self, offsets: np.ndarray, width: int) -> None:
        offs, ptr = self._offsets_arg(offsets)
        self._lib.otn_prefetch(
            self._h, ptr, len(offs), width, self.dtype.itemsize
        )

    def close(self) -> None:
        if self._h:
            self._lib.otn_close(self._h)
            self._h = None

    def __del__(self):
        try:
            self.close()
        except Exception:
            pass
